"""The fleet router's metric surface — one canonical table.

Every metric the router publishes is declared here, name -> (kind,
labelnames, help). ``docs/observability.md`` documents the same set in a
table fenced by ``<!-- router-metrics:begin/end -->`` and
``tools/check_metrics_docs.py`` enforces the two directions (a rename
here orphans the docs loudly; a new gauge can't ship undocumented) —
the same contract the engine gauge table has.

The registry is the process-wide one from ``obs/metrics.py``: when the
router runs in its own process these are simply its ``/metrics``; when
tests or the fleet bench run router + N replicas in ONE process, the
``router_*`` prefix keeps them distinct from the replicas' chain/engine
metrics, and the replica-labeled children tell the replicas apart.
"""

from __future__ import annotations

from ..obs import metrics as obs_metrics

#: name -> (kind, labelnames, help). The checker keys off the names; the
#: accessors below key off the whole row, so the two can never drift.
ROUTER_METRICS: dict[str, tuple[str, tuple[str, ...], str]] = {
    "router_replicas_healthy": (
        "gauge", (),
        "replicas currently placeable: reachable, not draining, breaker "
        "not open"),
    "router_replicas_total": (
        "gauge", (), "replicas in the table, placeable or not"),
    "router_placed_total": (
        "counter", ("replica",),
        "requests placed on each replica (post-retry final placement)"),
    "router_affinity_hits": (
        "counter", (),
        "placements whose chosen replica matched >= 1 prefix block in "
        "its affinity sketch"),
    "router_retries_total": (
        "counter", ("reason",),
        "forward attempts abandoned and retried on another replica, by "
        "reason: connect (connect-phase failure), draining (replica "
        "429'd as draining), breaker_open (placement raced a breaker "
        "trip)"),
    "router_drain_in_flight": (
        "gauge", (),
        "in-flight streams still running on DRAINING replicas, summed "
        "from heartbeats — a rollout waits for this to reach 0"),
    "router_kv_transfer_hints_total": (
        "counter", (),
        "placements forwarded with an X-KV-Transfer-From donor hint: "
        "the chosen replica missed the prompt's prefix but a sibling's "
        "affinity sketch covers it, so the replica fetches the prefix "
        "pages from the sibling instead of re-prefilling "
        "(docs/kv-tiering.md)"),
    "router_replica_queue_depth": (
        "gauge", ("replica",),
        "per-replica engine dispatch queue depth from the last "
        "heartbeat"),
    "router_replica_in_flight": (
        "gauge", ("replica",),
        "per-replica in-flight /generate streams from the last "
        "heartbeat"),
    "router_replica_rejected_total": (
        "gauge", ("replica",),
        "per-replica cumulative engine admission rejections "
        "(queue-full + deadline queue drops) from the last heartbeat — "
        "the router diffs consecutive heartbeats into a recent shed "
        "rate for the load score"),
    "router_replica_prefix_hit_rate": (
        "gauge", ("replica",),
        "per-replica engine prefix-cache hit rate from the last "
        "heartbeat — fleet-wide cache health at a glance"),
}


def _get(name: str):
    kind, labelnames, help_txt = ROUTER_METRICS[name]
    reg = obs_metrics.REGISTRY
    factory = reg.counter if kind == "counter" else reg.gauge
    return factory(name, help_txt, labelnames=labelnames)


def counter(name: str, *labels: str):
    m = _get(name)
    return m.labels(*labels) if labels else m


def gauge(name: str, *labels: str):
    m = _get(name)
    return m.labels(*labels) if labels else m


def record_replica_load(name: str, load: dict) -> None:
    """Mirror one replica's heartbeat ``load`` block into the
    replica-labeled gauges (obs/metrics stays scrape-shaped: the router
    polls, the gauges hold the last observation)."""
    if "queue_depth" in load:
        gauge("router_replica_queue_depth", name).set(
            float(load["queue_depth"]))
    if "in_flight" in load:
        gauge("router_replica_in_flight", name).set(
            float(load["in_flight"]))
    if "rejected_total" in load:
        gauge("router_replica_rejected_total", name).set(
            float(load["rejected_total"]))
    if "prefix_hit_rate" in load:
        gauge("router_replica_prefix_hit_rate", name).set(
            float(load["prefix_hit_rate"]))
