"""Fleet router: the asyncio HTTP front that turns N chain-server/engine
replicas into one serving endpoint.

Request path (``POST /generate``, ``/documentSearch``, and the
OpenAI-compat ``/v1/*`` surfaces):

1. read the JSON body once, hash the prompt head into chained affinity
   blocks (``table.affinity_blocks``);
2. place via :class:`~.table.ReplicaTable` (affinity + load + health —
   docs/router.md has the policy);
3. forward the raw body with the caller's correlation headers
   (``X-Request-ID``, ``X-Deadline-Ms``, ``traceparent``) intact;
4. stream the replica's response back byte-for-byte.

Failure semantics (the part routers get wrong):

- **Connect-phase failures only are retried on the next replica** —
  the PR-5 ``is_connect_failure`` contract: if the connection was never
  established, the replica cannot have started generating, so a replay
  cannot double-run a generation. One bounded budget
  (``ROUTER_RETRY_ATTEMPTS``) across replicas; each failed attempt
  feeds that replica's breaker.
- A **429 ``draining``** answer is also safe to retry (the replica
  refused before doing any work) and additionally marks the replica
  draining immediately — the router need not wait for the next
  heartbeat to stop placing on it.
- **Mid-stream replica loss is RESUMED, not retried** (docs/
  robustness.md): a replay of the whole request could double-run the
  generation, but the router holds the full generation transcript
  (every byte it forwarded, held to clean UTF-8 boundaries —
  ``flight.Transcript``), so it re-places on a sibling (dead replica
  excluded, DRAINING siblings eligible — a resume is the continuation
  of an already-accepted stream) and re-submits the original body plus
  the transcript as a ``resume`` continuation block. The sibling admits
  it as prompt + generated prefix and streams only what comes AFTER the
  transcript — the transcript is the dedupe boundary; the caller sees
  no error frame, no duplicated and no dropped token. Bounded by
  ``ROUTER_RESUME_ATTEMPTS`` (default 1; 0 restores the classic
  behavior byte-for-byte). Exhausted budget / no sibling / sibling
  rejection falls back to the classic machine-readable error-frame
  contract (``\\n[error] ...`` + ``event: error`` JSON with
  ``type=replica_lost``) so clients parse a real failure instead of
  seeing a silent truncation. Either way the dead replica's breaker
  records the failure and it is marked unreachable so the NEXT request
  places elsewhere at once.
- Any other upstream HTTP status is relayed as-is — the replica's 429 /
  503 / 504 taxonomy (docs/robustness.md) already says the right thing;
  the router adds only ``503 no_replicas`` (nothing placeable) and
  ``502 replica_error`` (retry budget exhausted).

A background **heartbeat** polls each replica's ``GET /health`` every
``ROUTER_HEARTBEAT_S``: the chain server's truthful readiness body
(drain state, breaker state, the ``load`` block, and — since PR 12 —
the round-telemetry / KV-tier / capacity blocks) is the router's
entire fleet view — no engine or metrics-scrape coupling. Fault points
``router.forward`` / ``replica.heartbeat`` (tag = replica name) let
chaos plans fail or partition individual replicas (docs/robustness.md).

**Fleet observability spine** (PR 12, docs/observability.md): every
routed request gets a flight timeline (``router/flight.py`` — the
placement decision with scored candidates, each connect/retry attempt,
the first upstream byte as router-observed TTFT, stream end or
mid-stream loss) behind ``GET /debug/requests``, joinable to the
replica/engine records by the forwarded ``X-Request-ID``; outcomes feed
a rolling per-replica SLO window; and ``GET /debug/fleet``
(``router/fleet.py``) folds heartbeat state, round aggregates, KV-tier
counters, the SLO window, and a step-cost-model capacity-headroom
estimate into the one snapshot an autoscaler or operator reads.

**Disaggregated prefill/decode** (docs/disaggregation.md): when the
fleet advertises a ``prefill``-role replica, long ``/generate`` prompts
(>= ``ROUTER_DISAGG_MIN_PROMPT_BYTES``, no retrieval) take a two-leg
path the router conducts: leg 1 POSTs the body to the prefill replica's
``/control/prefill`` with ``X-KV-Push-To`` naming the already-chosen
decode replica, which prefills and pushes the finished prefix pages
host-to-host; leg 2 forwards the request pinned to that decode replica
with ``X-KV-Transfer-From`` as the pull fallback, so it admits as a
near-full prefix-cache hit. The handoff is priced first
(``table.handoff_beats_prefill`` against the decode replica's
heartbeat-advertised step-cost model) and every leg-1 failure falls
back to normal in-place placement — recompute, never an error frame.
A role-less fleet never enters this path.
"""

from __future__ import annotations

import asyncio
import functools
import json
import os
import random
import time
from typing import Optional, Sequence

import aiohttp
from aiohttp import web

from ..obs import alerts as obs_alerts
from ..obs import flight as obs_flight
from ..obs import history as obs_history
from ..obs import incidents as obs_incidents
from ..obs import metrics as obs_metrics
from ..utils import faults
from ..utils.logging import get_logger
from . import autoscale as router_autoscale
from . import fleet as router_fleet
from . import metrics as router_metrics
from .flight import RouterFlightRecorder, Transcript
from .table import ReplicaTable, handoff_beats_prefill

logger = get_logger(__name__)

#: Paths the router forwards, mapped to how the affinity text is pulled
#: out of the JSON body. The affinity text is the PROMPT HEAD as the
#: replica will see it lead — context/system first, then the question —
#: so a multi-turn session keeps hashing to the same leading blocks.
FORWARD_PATHS = ("/generate", "/documentSearch", "/v1/completions",
                 "/v1/chat/completions", "/v1/embeddings")

#: Correlation/robustness headers forwarded verbatim to the replica.
_FORWARD_HEADERS = ("X-Request-ID", "X-Deadline-Ms", "traceparent",
                    "Content-Type", "Accept")

#: Replica response headers relayed back to the caller.
_RELAY_HEADERS = ("Content-Type", "X-Request-ID", "Retry-After",
                  "Cache-Control")


def affinity_text(path: str, body: dict) -> str:
    """The text whose head determines placement, per forwarded route."""
    if path == "/generate":
        context = str(body.get("context", "") or "")
        question = str(body.get("question", "") or "")
        return f"{context}\n{question}" if context else question
    if path == "/v1/completions":
        prompt = body.get("prompt", "")
        return "\n".join(map(str, prompt)) if isinstance(prompt, list) \
            else str(prompt)
    if path == "/v1/chat/completions":
        msgs = body.get("messages") or []
        return "\n".join(str(m.get("content", "")) for m in msgs
                         if isinstance(m, dict))
    if path == "/v1/embeddings":
        inp = body.get("input", "")
        return "\n".join(map(str, inp)) if isinstance(inp, list) \
            else str(inp)
    return str(body.get("content", ""))  # /documentSearch


def is_connect_failure(exc: BaseException) -> bool:
    """aiohttp twin of ``serving.client.is_connect_failure``: True only
    when the failure happened ESTABLISHING the connection, so the
    request cannot have executed replica-side. ``ServerDisconnectedError``
    and payload errors arrive after the connection existed — the replica
    may have done the work; never replayed."""
    if isinstance(exc, (aiohttp.ClientConnectorError,
                        ConnectionRefusedError)):
        return True
    if isinstance(exc, ConnectionError):
        # exact builtin type only (incl. injected faults): subclasses
        # Reset/Aborted/BrokenPipe mean bytes were in flight
        return type(exc) is ConnectionError
    return False


def _error_response(status: int, err_type: str, message: str, rid: str,
                    retry_after_s: Optional[float] = None) -> web.Response:
    headers = {"X-Request-ID": rid}
    if retry_after_s is not None:
        headers["Retry-After"] = str(max(1, int(retry_after_s + 0.999)))
    return web.json_response(
        {"error": {"type": err_type, "message": message},
         "request_id": rid},
        status=status, headers=headers)


class FleetRouter:
    """Owns the table, the client session, and the heartbeat task."""

    def __init__(self, table: ReplicaTable, *,
                 heartbeat_s: float = 2.0,
                 heartbeat_timeout_s: float = 2.0,
                 retry_attempts: int = 3,
                 connect_timeout_s: float = 5.0,
                 forward_timeout_s: float = 300.0,
                 kv_transfer: bool = False,
                 kv_transfer_min_blocks: int = 2,
                 disagg_min_prompt_bytes: int = 4096,
                 disagg_prefill_timeout_s: float = 30.0,
                 heartbeat_jitter: float = 0.2,
                 resume_attempts: int = 1,
                 heartbeat_max_backoff_s: float = 30.0,
                 flight: Optional[RouterFlightRecorder] = None,
                 surge: Optional[router_autoscale.SurgeGate] = None):
        self.table = table
        # Router flight recorder + rolling SLO window (router/flight.py):
        # per-router instance, so the fleet bench's per-arm routers and
        # parallel test routers never interleave timelines or windows.
        self.flight = flight or RouterFlightRecorder()
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.retry_attempts = max(1, int(retry_attempts))
        self.connect_timeout_s = float(connect_timeout_s)
        self.forward_timeout_s = float(forward_timeout_s)
        # Cross-replica KV-page transfer (docs/kv-tiering.md): on a
        # placement whose replica misses the prompt's prefix while a
        # sibling's sketch covers it, forward an X-KV-Transfer-From
        # donor hint so the replica pulls the pages instead of
        # re-prefilling. Requires tiering (KV_HOST_POOL_TOKENS>0) on
        # the replicas; the hint is ignored where tiering is off.
        self.kv_transfer = bool(kv_transfer)
        self.kv_transfer_min_blocks = max(1, int(kv_transfer_min_blocks))
        # Disaggregated prefill/decode (docs/disaggregation.md): the
        # enable gate is the FLEET — the handoff path only triggers
        # when a prefill-role replica is placeable, so a role-less
        # fleet routes byte-for-byte as before. These knobs only tune
        # when a role-ful fleet bothers with the two-leg dance.
        self.disagg_min_prompt_bytes = max(1, int(disagg_min_prompt_bytes))
        self.disagg_prefill_timeout_s = float(disagg_prefill_timeout_s)
        # Sweep desynchronization: each heartbeat cycle sleeps
        # heartbeat_s * U(1-j, 1+j), so N routers polling one fleet (or
        # one router's restarts) never phase-lock their probe bursts.
        self.heartbeat_jitter = min(0.9, max(0.0, float(heartbeat_jitter)))
        # Mid-stream failover (docs/robustness.md): how many times ONE
        # request's stream may be resumed on a sibling after its replica
        # died on a 200. 0 = off (classic replica_lost error frame,
        # byte-for-byte — no transcript is even kept).
        self.resume_attempts = max(0, int(resume_attempts))
        # Heartbeat crash-loop backoff: consecutive probe failures to
        # one replica space its probes out exponentially (cap below)
        # instead of hammering a dead host every sweep. Router-side
        # state, not table state: the table's heartbeat_failures counter
        # is CUMULATIVE by contract (the doc-fenced metric mirrors it)
        # and must not reset on recovery.
        self.heartbeat_max_backoff_s = max(
            0.0, float(heartbeat_max_backoff_s))
        self._hb_fail_streak: dict[str, int] = {}
        self._hb_next_t: dict[str, float] = {}
        # Surge admission (router/autoscale.py): counts in-flight
        # forwards always; gates only while the autoscaler (or an
        # operator) flips it active.
        self.surge = surge or router_autoscale.SurgeGate()
        #: The attached AutoscaleController, if any (create_router_app).
        self.autoscale: Optional[router_autoscale.AutoscaleController] = \
            None
        self._session: Optional[aiohttp.ClientSession] = None
        self._hb_task: Optional[asyncio.Task] = None
        self._as_task: Optional[asyncio.Task] = None
        self._fleet: Optional[dict] = None   # last refresh_fleet() result

    # ---------------------------------------------------------- lifecycle

    async def start(self, run_heartbeat: bool = True,
                    run_autoscale: bool = True) -> None:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        if run_heartbeat and self._hb_task is None:
            self._hb_task = asyncio.create_task(self._heartbeat_loop())
        if run_autoscale and self.autoscale is not None \
                and self._as_task is None:
            self._as_task = asyncio.create_task(self.autoscale.run())

    async def stop(self) -> None:
        for attr in ("_hb_task", "_as_task"):
            task = getattr(self, attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
                setattr(self, attr, None)
        if self._session is not None:
            await self._session.close()
            self._session = None

    # ---------------------------------------------------------- heartbeat

    async def _heartbeat_loop(self) -> None:
        while True:
            try:
                await self.heartbeat_once()
                # Background fleet aggregation: fold the fresh heartbeat
                # state + SLO window into the cached snapshot and push
                # the window/headroom gauges — /metrics stays live even
                # when nobody reads /debug/fleet.
                self.refresh_fleet()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("router heartbeat cycle failed")
            await asyncio.sleep(self._next_heartbeat_delay())

    def _next_heartbeat_delay(self) -> float:
        """Jittered sweep period: ``heartbeat_s * U(1-j, 1+j)``."""
        j = self.heartbeat_jitter
        return self.heartbeat_s * random.uniform(1.0 - j, 1.0 + j)

    async def heartbeat_once(self, force: bool = False) -> None:
        """Probe every DUE replica's /health concurrently. Each probe is
        bounded by its OWN timeout (the HTTP client timeout plus slack
        for injected stalls), so one wedged replica costs the sweep at
        most that bound — its siblings' health lands the moment their
        probes return, never behind the straggler's.

        A replica whose probes keep failing is in exponential backoff
        (``_hb_update_backoff``) and is skipped until its next-probe
        time arrives; ``force=True`` (the ``/control/heartbeat``
        endpoint — an operator asking NOW) probes everyone regardless."""
        reps = self.table.replicas()
        if not reps:
            return
        now = time.monotonic()
        due = [r for r in reps
               if force or self._hb_next_t.get(r.name, 0.0) <= now]
        if not due:
            return
        await asyncio.gather(*(self._probe_bounded(r) for r in due))
        for r in due:
            self._hb_update_backoff(r)

    def _hb_update_backoff(self, rep) -> None:
        """Crash-loop backoff bookkeeping after one probe: a failure
        doubles the spacing to this replica (``heartbeat_s * 2^(n-1)``,
        capped at ``heartbeat_max_backoff_s``); any successful probe
        resets it to the normal sweep cadence. Skipped sweeps do NOT
        advance ``last_heartbeat_t``, so ``router_heartbeat_age_seconds``
        keeps growing for a backed-off replica — the age gauge's
        semantics (seconds since the last OBSERVATION) are unchanged."""
        if rep.reachable:
            self._hb_fail_streak.pop(rep.name, None)
            self._hb_next_t.pop(rep.name, None)
            return
        streak = self._hb_fail_streak.get(rep.name, 0) + 1
        self._hb_fail_streak[rep.name] = streak
        backoff = min(self.heartbeat_max_backoff_s,
                      self.heartbeat_s * (2 ** (streak - 1)))
        self._hb_next_t[rep.name] = time.monotonic() + backoff

    async def _probe_bounded(self, rep) -> None:
        try:
            await asyncio.wait_for(self._probe(rep),
                                   timeout=self.heartbeat_timeout_s + 1.0)
        except asyncio.TimeoutError:
            logger.debug("heartbeat to %s exceeded the poll bound",
                         rep.name)
            self.table.update_health(rep.name, ok=False, ready=False)

    async def _probe(self, rep) -> None:
        try:
            # Injected faults run OFF the event loop: a delay/hang plan
            # on one replica's heartbeat must stall that one probe's
            # thread, not the loop every sibling's probe shares.
            if faults.active():
                await asyncio.get_running_loop().run_in_executor(
                    None, functools.partial(
                        faults.inject, "replica.heartbeat", tag=rep.name))
            assert self._session is not None
            async with self._session.get(
                    rep.url + "/health",
                    timeout=aiohttp.ClientTimeout(
                        total=self.heartbeat_timeout_s)) as resp:
                try:
                    body = await resp.json()
                except Exception:  # noqa: BLE001 — non-JSON health answer
                    body = None
                self.table.update_health(
                    rep.name, ok=True, ready=resp.status == 200, body=body)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 — any probe failure
            logger.debug("heartbeat to %s failed: %s", rep.name, exc)
            self.table.update_health(rep.name, ok=False, ready=False)

    # --------------------------------------------------------- membership

    async def remove_replica(self, name: str, *, drain: bool = True,
                             wait_s: float = 30.0,
                             poll_s: float = 0.1) -> bool:
        """Remove a replica from the table — the scale-down/rollout
        path. With ``drain`` (the default), placement stops IMMEDIATELY
        (the table marks it draining), the replica's own admission is
        closed via ``POST /control/drain``, and the removal waits up to
        ``wait_s`` for its in-flight streams to finish — a streaming
        replica is never dropped mid-token. The replica's SLO-window
        rows are forgotten with it, so a later re-add under the same
        name starts with clean attainment (and a fresh sketch + breaker,
        via ``table.add``'s reset semantics)."""
        rep = self.table.get(name)
        if rep is None:
            return False
        if drain:
            self.table.mark_draining(name)
            assert self._session is not None
            try:
                async with self._session.post(
                        rep.url + "/control/drain",
                        timeout=aiohttp.ClientTimeout(
                            total=self.heartbeat_timeout_s)) as resp:
                    await resp.read()
            except Exception as exc:  # noqa: BLE001 — dead replica: done
                logger.info("drain of %s unreachable (%s); removing",
                            name, exc)
            else:
                deadline = time.monotonic() + max(0.0, float(wait_s))
                while time.monotonic() < deadline:
                    in_flight = await self._drain_in_flight(rep)
                    if in_flight is None or in_flight <= 0:
                        break
                    await asyncio.sleep(poll_s)
                else:
                    logger.warning(
                        "drain of %s still has streams in flight after "
                        "%.1fs budget; removing anyway", name, wait_s)
        self.table.remove(name)
        self.flight.slo.forget(name)
        self._hb_fail_streak.pop(name, None)
        self._hb_next_t.pop(name, None)
        return True

    async def _drain_in_flight(self, rep) -> Optional[int]:
        """The draining replica's in-flight stream count from /health
        (a drained replica answers 503 — the BODY is the signal)."""
        try:
            assert self._session is not None
            async with self._session.get(
                    rep.url + "/health",
                    timeout=aiohttp.ClientTimeout(
                        total=self.heartbeat_timeout_s)) as resp:
                body = await resp.json()
            return int((body.get("load") or {}).get("in_flight", 0))
        except Exception:  # noqa: BLE001 — unreachable: nothing to wait on
            return None

    # -------------------------------------------------------------- fleet

    def refresh_fleet(self) -> dict:
        """Build the fleet snapshot (``GET /debug/fleet``) from the
        table's heartbeat-carried state + the SLO window, and publish
        the derived gauges. Pure local fold — cheap enough to also run
        on demand for the endpoint, so the view is never staler than
        the last heartbeat."""
        self.flight.slo.publish(
            [r.name for r in self.table.replicas()])
        self.table.publish_heartbeat_ages()
        snap = router_fleet.build_fleet_snapshot(
            self.table, self.flight.slo, heartbeat_s=self.heartbeat_s)
        router_fleet.publish_fleet_gauges(snap)
        self._fleet = snap
        return snap

    # ------------------------------------------------------------ forward

    async def forward(self, request: web.Request) -> web.StreamResponse:
        # Router flight timeline (router/flight.py): keyed by the SAME
        # X-Request-ID forwarded below, so the router's record joins the
        # replica's /debug/requests timeline and the engine's round
        # grants by one ID. Begun BEFORE surge admission so a surge 429
        # still has a timeline and an SLO-window row.
        tl = self.flight.begin_request(request.headers, request.path)
        # Surge admission (docs/autoscaling.md): while the autoscaler
        # holds the gate active (fleet at max and overloaded), a bounded
        # wait queue fronts placement and the rejections are honest
        # backpressure — Retry-After from the measured queue-wait
        # estimate, fast 429 for deadlines the queue would eat whole.
        try:
            ticket, rejection = await self.surge.enter(
                deadline_ms=tl.meta.get("deadline_ms"))
        except asyncio.CancelledError:
            # Caller hung up while QUEUED in the surge gate (the
            # overload case exactly): the gate cleaned its own slot up;
            # the timeline must still retire or the in-flight map leaks
            # one entry per impatient caller.
            self.flight.complete_request(tl, outcome="disconnect")
            raise
        except BaseException:
            self.flight.complete_request(tl, outcome="error")
            raise
        if rejection is not None:
            err_type, est_wait_ms = rejection
            self.flight.complete_request(tl, outcome="shed", status=429)
            return _error_response(
                429, err_type,
                f"fleet is at capacity ({err_type}); estimated queue "
                f"wait {est_wait_ms:.0f} ms", tl.request_id,
                retry_after_s=est_wait_ms / 1e3)
        try:
            raw = await request.read()
            try:
                body = json.loads(raw) if raw else {}
            except (ValueError, UnicodeDecodeError):
                body = {}
            blocks = self.table.affinity_blocks(
                affinity_text(request.path, body if isinstance(body, dict)
                              else {}))
            handed = await self._try_disagg(request, raw, body, blocks,
                                            tl)
            if handed is not None:
                return handed
            return await self._forward_attempts(request, raw, blocks, tl)
        except asyncio.CancelledError:
            # Caller hung up while we were placing/connecting/streaming:
            # retire the timeline (idempotent — a relay that already
            # completed it wins) so the in-flight map can never leak.
            self.flight.complete_request(tl, outcome="disconnect")
            raise
        except BaseException:
            self.flight.complete_request(tl, outcome="error")
            raise
        finally:
            self.surge.exit(ticket)

    async def _try_disagg(self, request: web.Request, raw: bytes,
                          body, blocks: Sequence[bytes],
                          tl) -> Optional[web.StreamResponse]:
        """The disaggregated prefill/decode handoff, or None to take
        the normal path (docs/disaggregation.md).

        Eligibility: a ``/generate`` body at least
        ``disagg_min_prompt_bytes`` long, no retrieval (the replica
        augments the prompt server-side, so the router cannot pre-run
        it on a different chip), a placeable prefill-role replica, and
        the priced rule saying moving the finished pages beats
        re-prefilling on the decode replica. The decode replica is
        chosen FIRST — the prefill replica pushes straight to it — and
        every leg-1 failure degrades to plain placement on that same
        replica: recompute costs TTFT, never correctness."""
        if request.path != "/generate" or not isinstance(body, dict):
            return None
        if body.get("use_knowledge_base"):
            return None
        if len(raw) < self.disagg_min_prompt_bytes:
            return None
        prefill = self.table.prefill_candidate()
        if prefill is None:
            return None
        rep, decision = self.table.place_explained(blocks)
        if rep is None:
            return None
        pinned = (rep, decision)
        if not handoff_beats_prefill(rep.capacity, len(raw)):
            # Priced out (tiny pages / fast prefill): same placement,
            # no handoff leg. Reuse the decision — re-placing would
            # double-count the selection.
            return await self._forward_attempts(request, raw, blocks,
                                                tl, pinned=pinned)
        reason = ""
        t0 = time.monotonic()
        try:
            assert self._session is not None
            async with self._session.post(
                    prefill.url + "/control/prefill", data=raw,
                    headers={"X-KV-Push-To": rep.url,
                             "X-Request-ID": tl.request_id,
                             "Content-Type": "application/json"},
                    timeout=aiohttp.ClientTimeout(
                        total=self.disagg_prefill_timeout_s)) as up:
                if up.status == 200:
                    try:
                        info = await up.json()
                    except Exception:  # noqa: BLE001 — not the contract
                        info = {}
                    if int(info.get("blocks", 0) or 0) > 0 \
                            and info.get("pushed"):
                        prefill.breaker.record_success()
                    else:
                        reason = "no_pages"
                else:
                    reason = "prefill_error"
        except asyncio.CancelledError:
            raise
        except asyncio.TimeoutError:
            reason = "prefill_timeout"
        except Exception as exc:  # noqa: BLE001 — any leg-1 failure
            logger.info("disagg prefill leg via %s failed (%s); "
                        "falling back to recompute", prefill.name, exc)
            reason = "prefill_error"
        tl.stage("router_disagg_prefill", time.monotonic() - t0)
        if reason:
            router_metrics.counter(
                "router_disagg_fallbacks_total", reason).inc()
            tl.event("disagg_fallback", f"{prefill.name}:{reason}")
            return await self._forward_attempts(request, raw, blocks,
                                                tl, pinned=pinned)
        router_metrics.counter("router_disagg_handoffs_total").inc()
        tl.event("disagg_handoff", prefill.name)
        return await self._forward_attempts(
            request, raw, blocks, tl, pinned=pinned,
            donor_override=prefill.url)

    async def _forward_attempts(self, request: web.Request, raw: bytes,
                                blocks: Sequence[bytes],
                                tl, *,
                                pinned: Optional[tuple] = None,
                                donor_override: Optional[str] = None
                                ) -> web.StreamResponse:
        rid = tl.request_id
        fwd_headers = {"X-Request-ID": rid}
        for h in _FORWARD_HEADERS:
            if h in request.headers and h not in fwd_headers:
                fwd_headers[h] = request.headers[h]

        tried: list[str] = []
        last_err: Optional[str] = None
        fallback: Optional[web.Response] = None
        fallback_rep = ""
        for _ in range(self.retry_attempts):
            t_place = time.monotonic()
            if pinned is not None:
                # Disagg handoff (docs/disaggregation.md): the decode
                # replica was chosen BEFORE the prefill leg so the pages
                # could be pushed to it — first attempt lands there;
                # retries fall back to normal placement.
                rep, decision = pinned
                pinned = None
            else:
                rep, decision = self.table.place_explained(blocks,
                                                           exclude=tried)
            if rep is None:
                break
            tried.append(rep.name)
            # Fleet-wide cache: a placement miss with a covering sibling
            # carries a donor hint — recomputed per attempt, since the
            # donor depends on who was chosen.
            fwd_headers.pop("X-KV-Transfer-From", None)
            donor: Optional[str] = None
            if donor_override is not None:
                # The handoff's pull fallback: if the prefill replica's
                # push raced admission, the decode replica fetches the
                # pages from it by the ordinary transfer leg.
                donor = donor_override
                fwd_headers["X-KV-Transfer-From"] = donor
                donor_override = None
            elif self.kv_transfer and blocks:
                donor = self.table.transfer_donor(
                    blocks, chosen=rep.name,
                    min_blocks=self.kv_transfer_min_blocks)
                if donor is not None:
                    fwd_headers["X-KV-Transfer-From"] = donor
                    router_metrics.counter(
                        "router_kv_transfer_hints_total").inc()
            self.flight.placement(
                tl, replica=rep.name,
                affinity_blocks=int(decision.get("affinity_blocks", 0)),
                candidates=decision.get("candidates", []),
                t_start=t_place, kv_donor=donor)
            t_conn = time.monotonic()
            try:
                faults.inject("router.forward", tag=rep.name)
                assert self._session is not None
                upstream = await self._session.post(
                    rep.url + request.path, data=raw, headers=fwd_headers,
                    timeout=aiohttp.ClientTimeout(
                        total=self.forward_timeout_s,
                        sock_connect=self.connect_timeout_s))
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — classified below
                if not is_connect_failure(exc):
                    # The connection existed; the replica may have run
                    # the request. Never replayed (PR-5 semantics).
                    rep.breaker.record_failure()
                    logger.warning("forward to %s failed post-connect: %s",
                                   rep.name, exc)
                    self.flight.attempt_failed(
                        tl, replica=rep.name, reason="post_connect",
                        retried=False)
                    self.flight.complete_request(
                        tl, outcome="error", replica=rep.name, status=502)
                    return _error_response(
                        502, "replica_error",
                        f"replica {rep.name} failed: {exc}", rid)
                rep.breaker.record_failure()
                router_metrics.counter(
                    "router_retries_total", "connect").inc()
                self.flight.attempt_failed(
                    tl, replica=rep.name, reason="connect", retried=True)
                last_err = f"{rep.name}: {exc}"
                logger.info("connect to replica %s failed (%s); trying "
                            "next", rep.name, exc)
                continue
            # Connect + time-to-upstream-headers (for /generate the
            # replica pulls the first chunk before committing to a 200,
            # so this stage absorbs the replica-side TTFT work).
            tl.stage("router_connect", time.monotonic() - t_conn)
            try:
                return await self._relay(request, rep, upstream, rid,
                                         blocks, tried, tl,
                                         raw=raw, fwd_headers=fwd_headers)
            except _RetryNextReplica as retry:
                last_err = f"{rep.name}: {retry.reason}"
                fallback = retry.response
                fallback_rep = rep.name
                self.flight.attempt_failed(
                    tl, replica=rep.name, reason=retry.reason,
                    retried=True)
                continue
        if fallback is not None:
            # Every placeable replica refused as draining: relay the 429
            # — a rollout must look like backpressure to callers
            # (Retry-After and all), never a hard 502.
            self.flight.complete_request(
                tl, outcome="shed", replica=fallback_rep,
                status=fallback.status)
            return fallback
        if not tried:
            self.flight.complete_request(tl, outcome="shed", status=503)
            return _error_response(
                503, "no_replicas",
                "no placeable replica (all draining, unreachable, or "
                "breaker-open)", rid, retry_after_s=self.heartbeat_s)
        self.flight.complete_request(tl, outcome="error", status=502)
        return _error_response(
            502, "replica_error",
            f"all forward attempts failed (tried {', '.join(tried)}); "
            f"last: {last_err}", rid, retry_after_s=self.heartbeat_s)

    async def _relay(self, request: web.Request, rep,
                     upstream: aiohttp.ClientResponse, rid: str,
                     blocks: Sequence[bytes],
                     tried: list,
                     tl=None, *,
                     raw: bytes = b"",
                     fwd_headers: Optional[dict] = None
                     ) -> web.StreamResponse:
        """Stream one upstream answer back; raises _RetryNextReplica for
        the one retry-safe HTTP answer (429 draining, pre-work). ``tl``
        is the request's router timeline — first upstream body byte
        stamps the router-observed TTFT, and the terminal transition
        (stream end / mid-stream loss / caller disconnect / relayed
        error status) retires it into the SLO window.

        With failover on (``resume_attempts > 0``) a ``/generate``
        stream keeps a :class:`~.flight.Transcript` of every byte
        forwarded; on mid-stream loss the stream is resumed on a sibling
        (``_attempt_resume``) and the caller never sees the seam —
        ``raw``/``fwd_headers`` are kept for exactly that re-submission.
        A resumed request that completes is an ``ok`` outcome attributed
        to the FINISHING replica, not a ``midstream_loss`` (the dead
        replica still pays breaker + unreachable)."""
        try:
            if upstream.status == 429:
                data = await upstream.read()
                err_type = ""
                try:
                    err_type = json.loads(data)["error"]["type"]
                except Exception:  # noqa: BLE001 — not the JSON contract
                    pass
                if err_type == "draining":
                    # The replica refused BEFORE doing any work, so a
                    # sibling can safely take it; stop placing here now
                    # instead of at the next heartbeat. The rendered 429
                    # rides along as the fallback answer for when no
                    # sibling remains.
                    self.table.mark_draining(rep.name)
                    rep.breaker.record_success()  # alive — just draining
                    router_metrics.counter(
                        "router_retries_total", "draining").inc()
                    raise _RetryNextReplica(
                        "draining",
                        response=self._relay_body(upstream, data))
                # Genuine backpressure (queue_full, deadline_unmeetable):
                # relay — the Retry-After hint is the replica's to give.
                self.flight.complete_request(
                    tl, outcome="shed", replica=rep.name, status=429)
                return self._relay_body(upstream, data)
            rep.breaker.record_success()
            if upstream.status >= 400:
                # 503/504 are backpressure/deadline sheds in the replica
                # taxonomy (docs/robustness.md); everything else relayed
                # at >= 400 is an error outcome.
                self.flight.complete_request(
                    tl, outcome=("shed" if upstream.status in (503, 504)
                                 else "error"),
                    replica=rep.name, status=upstream.status)
                return self._relay_body(upstream, await upstream.read())
            # 2xx: commit the placement (the sketch learns this prompt)
            # and stream the body through as it arrives.
            self.table.record_placement(rep, blocks)
            resp = web.StreamResponse(status=upstream.status)
            for h in _RELAY_HEADERS:
                if h in upstream.headers:
                    resp.headers[h] = upstream.headers[h]
            resp.headers["X-Routed-Replica"] = rep.name
            await resp.prepare(request)
            # Generation transcript (docs/robustness.md): every byte
            # forwarded downstream, held to clean UTF-8 boundaries —
            # the resume continuation AND its dedupe boundary. Only
            # kept when failover could use it; with resume off the
            # stream path below is byte-for-byte the classic one.
            transcript = (Transcript()
                          if (self.resume_attempts > 0
                              and request.path == "/generate")
                          else None)
            resume_attempt = 0
            # Upstream reads and downstream writes fail for OPPOSITE
            # reasons and must not share an except: a read failure is
            # the REPLICA dying (breaker + unreachable + error frame); a
            # write failure is the CALLER hanging up, which says nothing
            # about the replica's health — misfiling it would let a few
            # impatient clients trip a healthy replica's breaker.
            t_stream = time.monotonic()
            outcome = "ok"
            chunks = upstream.content.iter_any()
            while True:
                try:
                    chunk = await chunks.__anext__()
                except StopAsyncIteration:
                    break
                except (aiohttp.ClientError, ConnectionError,
                        asyncio.TimeoutError) as exc:
                    # Replica died mid-stream: tokens already went out
                    # on a 200, so NO replay of the whole request. The
                    # dead replica pays either way: breaker failure +
                    # unreachable, so the NEXT request places elsewhere
                    # immediately.
                    rep.breaker.record_failure()
                    self.table.mark_unreachable(rep.name)
                    logger.warning("replica %s lost mid-stream: %s",
                                   rep.name, exc)
                    if tl is not None:
                        tl.event("midstream_loss", rep.name)
                    # Failover (docs/robustness.md): resume the stream
                    # on a sibling from the transcript. On success the
                    # caller's stream simply continues — swap upstream
                    # and keep relaying.
                    if transcript is not None:
                        resume_attempt += 1
                        new_up, new_rep = await self._attempt_resume(
                            rep, rid, raw, fwd_headers or {}, blocks,
                            tried, transcript, resume_attempt, tl)
                        if new_up is not None:
                            upstream.release()
                            upstream, rep = new_up, new_rep
                            chunks = upstream.content.iter_any()
                            continue
                    # No resume: degrade with the machine-readable
                    # error frame (chat_client parses it into
                    # last_error), flushing the transcript's held-back
                    # tail first — the caller gets every byte the dead
                    # replica generated, then the failure.
                    outcome = "midstream_loss"
                    tail = (transcript.flush() if transcript is not None
                            else b"")
                    frame = (f"\n[error] replica {rep.name} lost "
                             f"mid-stream"
                             + "\n\nevent: error\ndata: " + json.dumps(
                                 {"error": "replica_lost",
                                  "message": f"replica {rep.name} lost "
                                             f"mid-stream: {exc}",
                                  "replica": rep.name,
                                  "request_id": rid}) + "\n\n")
                    try:
                        await resp.write(tail + frame.encode("utf-8"))
                    except (ConnectionError, ConnectionResetError):
                        pass  # caller gone too
                    break
                # First upstream body byte = the router-observed TTFT
                # (idempotent; only the first chunk stamps it).
                self.flight.first_byte(tl)
                if transcript is not None:
                    # Forward only up to a clean UTF-8 boundary; the
                    # held-back tail (<= 3 bytes) goes out on EOF.
                    chunk = transcript.push(chunk)
                    if not chunk:
                        continue
                try:
                    await resp.write(chunk)
                except (ConnectionError, ConnectionResetError) as exc:
                    logger.debug("caller disconnected mid-stream: %s",
                                 exc)
                    # Abort the upstream stream (don't drain it): the
                    # replica sees the disconnect and cancels the
                    # generation instead of decoding to a dead socket.
                    upstream.close()
                    outcome = "disconnect"
                    break
            if transcript is not None and outcome == "ok":
                tail = transcript.flush()
                if tail:
                    try:
                        await resp.write(tail)
                    except (ConnectionError, ConnectionResetError):
                        outcome = "disconnect"
            try:
                await resp.write_eof()
            except (ConnectionError, ConnectionResetError):
                pass
            if tl is not None:
                tl.stage("router_stream", time.monotonic() - t_stream)
            self.flight.complete_request(
                tl, outcome=outcome, replica=rep.name,
                status=upstream.status)
            return resp
        finally:
            upstream.release()

    async def _attempt_resume(self, dead_rep, rid: str, raw: bytes,
                              fwd_headers: dict, blocks: Sequence[bytes],
                              tried: list, transcript: Transcript,
                              attempt: int, tl
                              ) -> tuple[
                                  Optional[aiohttp.ClientResponse],
                                  Optional[object]]:
        """One mid-stream resume attempt: place a sibling (draining
        included — a resume continues an already-accepted stream, the
        PR-7 rollout contract), re-submit the original body plus the
        transcript as a ``resume`` continuation block, and return the
        new 200 upstream to keep relaying from. ``(None, None)`` means
        the caller falls back to the classic error frame. Every attempt
        lands a ``router_resume_total{outcome=}`` count and a ``resume``
        timeline event — the failure legs are observable, never
        silent."""
        def _fail(outcome: str, **extra) -> tuple[None, None]:
            router_metrics.counter("router_resume_total", outcome).inc()
            if tl is not None:
                tl.event("resume", dict(extra, outcome=outcome,
                                        attempt=attempt,
                                        **{"from": dead_rep.name}))
            logger.info("resume of %s after %s died mid-stream: %s",
                        rid, dead_rep.name, outcome)
            return None, None

        if attempt > self.resume_attempts:
            return _fail("budget_exhausted")
        if transcript.overflowed:
            return _fail("overflow")
        rep, decision = self.table.place_explained(
            blocks, exclude=tried, include_draining=True)
        if rep is None:
            return _fail("no_replica")
        tried.append(rep.name)
        try:
            body = json.loads(raw) if raw else {}
        except (ValueError, UnicodeDecodeError):
            body = {}
        if not isinstance(body, dict):
            body = {}
        body["resume"] = {"text": transcript.text, "attempt": attempt}
        headers = dict(fwd_headers)
        headers["Content-Type"] = "application/json"
        # Deadline carried over, not restarted: the sibling gets what
        # is LEFT of the caller's budget.
        deadline_ms = (tl.meta.get("deadline_ms")
                       if tl is not None else None)
        if deadline_ms is not None:
            elapsed_ms = (time.monotonic() - tl.t_start) * 1e3
            headers["X-Deadline-Ms"] = str(
                max(1, int(deadline_ms - elapsed_ms)))
        # Donor hint recomputed for the NEW placement (the dead replica
        # can't serve pulls): a warm sibling makes the replayed prefix
        # a priced page fetch instead of a re-prefill.
        headers.pop("X-KV-Transfer-From", None)
        if self.kv_transfer and blocks:
            donor = self.table.transfer_donor(
                blocks, chosen=rep.name,
                min_blocks=self.kv_transfer_min_blocks)
            if donor is not None:
                headers["X-KV-Transfer-From"] = donor
        t0 = time.monotonic()
        try:
            assert self._session is not None
            upstream = await self._session.post(
                rep.url + "/generate",
                data=json.dumps(body).encode("utf-8"), headers=headers,
                timeout=aiohttp.ClientTimeout(
                    total=self.forward_timeout_s,
                    sock_connect=self.connect_timeout_s))
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 — any resume-leg failure
            rep.breaker.record_failure()
            return _fail("connect_fail", to=rep.name, error=str(exc))
        if upstream.status != 200:
            reason = ""
            try:
                reason = json.loads(
                    await upstream.read())["error"]["type"]
            except Exception:  # noqa: BLE001 — not the JSON contract
                pass
            upstream.release()
            return _fail("rejected", to=rep.name, status=upstream.status,
                         reason=reason)
        rep.breaker.record_success()
        self.table.record_placement(rep, blocks)
        if tl is not None:
            tl.stage("router_resume", time.monotonic() - t0)
        replayed = 0
        try:
            replayed = int(upstream.headers.get("X-Resume-Replayed", 0))
        except ValueError:
            pass
        router_metrics.counter("router_resume_total", "ok").inc()
        router_metrics.gauge("router_resume_replay_tokens").set(
            float(replayed))
        if tl is not None:
            tl.event("resume", {"outcome": "ok", "from": dead_rep.name,
                                "to": rep.name, "attempt": attempt,
                                "replayed_tokens": replayed})
            tl.annotate(resumed=attempt, resume_to=rep.name)
        # The held-back tail belongs to a token the sibling regenerates
        # (it replays from the transcript, which never included it).
        transcript.discard_pending()
        logger.info("resumed %s on %s after %s died mid-stream "
                    "(%d chars replayed as %d tokens)", rid, rep.name,
                    dead_rep.name, len(transcript.text), replayed)
        return upstream, rep

    @staticmethod
    def _relay_body(upstream: aiohttp.ClientResponse,
                    data: Optional[bytes] = None) -> web.Response:
        headers = {h: upstream.headers[h] for h in _RELAY_HEADERS
                   if h in upstream.headers}
        # web.Response sets Content-Type via its own keyword; passing it
        # in headers too raises.
        ctype = headers.pop("Content-Type", "application/octet-stream")
        return web.Response(status=upstream.status, body=data or b"",
                            content_type=ctype.split(";")[0],
                            headers=headers)


class _RetryNextReplica(Exception):
    def __init__(self, reason: str,
                 response: Optional[web.Response] = None):
        super().__init__(reason)
        self.reason = reason
        self.response = response  # relayed if no sibling can take it


try:  # typed app-state key (aiohttp >= 3.9); tests reach the router by it
    ROUTER = web.AppKey("fleet_router", FleetRouter)
except AttributeError:  # older aiohttp: plain string key
    ROUTER = "fleet_router"  # type: ignore[assignment]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def create_router_app(replicas: Sequence[tuple[str, str]] = (), *,
                      table: Optional[ReplicaTable] = None,
                      policy: Optional[str] = None,
                      heartbeat_s: Optional[float] = None,
                      retry_attempts: Optional[int] = None,
                      kv_transfer: Optional[bool] = None,
                      resume_attempts: Optional[int] = None,
                      run_heartbeat: bool = True,
                      autoscale: Optional[
                          "router_autoscale.AutoscaleController"] = None,
                      autoscale_factory: Optional[callable] = None,
                      run_autoscale: bool = True) -> web.Application:
    """Build the router app. ``replicas`` is (name, url) pairs; pass a
    pre-built ``table`` instead to control scoring knobs. Env defaults:
    ``ROUTER_POLICY``, ``ROUTER_HEARTBEAT_S`` /
    ``ROUTER_HEARTBEAT_JITTER``, ``ROUTER_RETRY_ATTEMPTS``,
    ``ROUTER_AFFINITY_BLOCK_BYTES`` / ``ROUTER_AFFINITY_HEAD_BYTES`` /
    ``ROUTER_SKETCH_CAP``, ``ROUTER_BREAKER_FAILURES`` /
    ``ROUTER_BREAKER_COOLDOWN_S``, ``ROUTER_CONNECT_TIMEOUT_S`` /
    ``ROUTER_FORWARD_TIMEOUT_S``, ``ROUTER_KV_TRANSFER`` /
    ``ROUTER_KV_TRANSFER_MIN_BLOCKS`` (docs/router.md),
    ``ROUTER_DISAGG_MIN_PROMPT_BYTES`` /
    ``ROUTER_DISAGG_PREFILL_TIMEOUT_S`` (docs/disaggregation.md),
    ``ROUTER_RESUME_ATTEMPTS`` / ``ROUTER_TRANSCRIPT_MAX_BYTES`` /
    ``ROUTER_HEARTBEAT_MAX_BACKOFF_S`` (docs/robustness.md), and the
    autoscaler/surge knobs (``ROUTER_AUTOSCALE*`` / ``ROUTER_SURGE_*``,
    docs/autoscaling.md). ``autoscale_factory`` builds a controller
    bound to the finished router (``factory(router) -> controller``);
    ``autoscale`` attaches one already built; ``ROUTER_AUTOSCALE=1``
    builds the env-configured default (dry-run decisions + surge
    admission unless an executor is configured)."""
    if table is None:
        table = ReplicaTable(
            policy=policy or os.environ.get("ROUTER_POLICY", "affinity"),
            block_bytes=int(_env_float("ROUTER_AFFINITY_BLOCK_BYTES", 64)),
            head_bytes=int(_env_float("ROUTER_AFFINITY_HEAD_BYTES", 4096)),
            sketch_cap=int(_env_float("ROUTER_SKETCH_CAP", 2048)),
            breaker_failures=int(_env_float("ROUTER_BREAKER_FAILURES", 3)),
            breaker_cooldown_s=_env_float("ROUTER_BREAKER_COOLDOWN_S", 10))
    elif policy is not None:
        table.policy = policy
    for name, url in replicas:
        table.add(name, url)
    router = FleetRouter(
        table,
        heartbeat_s=(heartbeat_s if heartbeat_s is not None
                     else _env_float("ROUTER_HEARTBEAT_S", 2.0)),
        heartbeat_timeout_s=_env_float("ROUTER_HEARTBEAT_TIMEOUT_S", 2.0),
        retry_attempts=(retry_attempts if retry_attempts is not None
                        else int(_env_float("ROUTER_RETRY_ATTEMPTS", 3))),
        connect_timeout_s=_env_float("ROUTER_CONNECT_TIMEOUT_S", 5.0),
        forward_timeout_s=_env_float("ROUTER_FORWARD_TIMEOUT_S", 300.0),
        kv_transfer=(kv_transfer if kv_transfer is not None
                     else os.environ.get("ROUTER_KV_TRANSFER", "")
                     not in ("", "0", "false", "off")),
        kv_transfer_min_blocks=int(
            _env_float("ROUTER_KV_TRANSFER_MIN_BLOCKS", 2)),
        disagg_min_prompt_bytes=int(
            _env_float("ROUTER_DISAGG_MIN_PROMPT_BYTES", 4096)),
        disagg_prefill_timeout_s=_env_float(
            "ROUTER_DISAGG_PREFILL_TIMEOUT_S", 30.0),
        heartbeat_jitter=_env_float("ROUTER_HEARTBEAT_JITTER", 0.2),
        resume_attempts=(resume_attempts if resume_attempts is not None
                         else int(_env_float("ROUTER_RESUME_ATTEMPTS",
                                             1))),
        heartbeat_max_backoff_s=_env_float(
            "ROUTER_HEARTBEAT_MAX_BACKOFF_S", 30.0))

    if autoscale is None and autoscale_factory is not None:
        autoscale = autoscale_factory(router)
    if autoscale is None and os.environ.get(
            "ROUTER_AUTOSCALE", "") not in ("", "0", "false", "off"):
        autoscale = router_autoscale.AutoscaleController(
            router,
            policy=router_autoscale.AutoscalePolicy.from_env(
                max_replicas=max(1, len(table.replicas()))
                if not os.environ.get("ROUTER_AUTOSCALE_MAX") else None),
            executor=None, surge=router.surge)
    if autoscale is not None:
        router.autoscale = autoscale
        router.surge = autoscale.surge

    app = web.Application(client_max_size=100 * 1024 ** 2)
    app[ROUTER] = router

    async def health(request: web.Request) -> web.Response:
        reps = table.snapshot()
        healthy = sum(1 for r in reps if r["placeable"])
        return web.json_response(
            {"status": "ok" if healthy else "no_replicas",
             "replicas_healthy": healthy, "replicas_total": len(reps)},
            status=200 if healthy else 503)

    async def metrics_endpoint(request: web.Request) -> web.Response:
        # Scrape-time refresh: heartbeat ages recompute from the live
        # table, so a STALLED poller reads as a growing age — a frozen
        # gauge would hide exactly the failure it exists to show.
        table.publish_heartbeat_ages()
        obs_metrics.record_process_stats()
        return web.Response(text=obs_metrics.REGISTRY.render_prometheus(),
                            content_type="text/plain")

    async def debug_requests(request: web.Request) -> web.Response:
        # Router flight recorder: in-flight + last-N routed-request
        # timelines (router/flight.py; same endpoint contract as the
        # chain/model servers via the shared handler body).
        return obs_flight.debug_requests_response(
            request, recorder=router.flight)

    async def debug_fleet(request: web.Request) -> web.Response:
        # The fleet snapshot (router/fleet.py): per-replica rows + fleet
        # totals + capacity headroom. Rebuilt from local state on every
        # GET — never staler than the last heartbeat.
        return web.json_response(router.refresh_fleet())

    async def list_replicas(request: web.Request) -> web.Response:
        return web.json_response({"replicas": table.snapshot(),
                                  "policy": table.policy})

    async def control_replicas(request: web.Request) -> web.Response:
        """Runtime table edits — dynamic membership, the rollout AND
        autoscale story's API:
        ``{"op": "add", "name": "r2", "url": "http://..."}`` /
        ``{"op": "remove", "name": "r2", "drain": true,
        "wait_s": 30}``. Adds probe immediately (traffic flows without
        waiting a heartbeat); removes default to DRAIN-ON-REMOVE —
        placement stops at once, the replica's admission closes, and
        the call returns after its in-flight streams finish (or the
        wait budget expires). ``"drain": false`` is the hard-remove
        escape hatch for an already-dead replica."""
        body = await request.json()
        op, name = body.get("op"), body.get("name", "")
        if op == "add":
            if not name or not body.get("url"):
                raise web.HTTPUnprocessableEntity(
                    text="add needs 'name' and 'url'")
            rep = table.add(name, body["url"])
            # A re-add under a known name is a NEW pod: its window rows
            # (like its sketch and breaker, reset by table.add) must not
            # carry the old pod's history — nor its heartbeat backoff.
            router.flight.slo.forget(name)
            router._hb_fail_streak.pop(name, None)
            router._hb_next_t.pop(name, None)
            # Probe now: an added replica that is already up starts
            # taking traffic without waiting a full heartbeat period.
            await router._probe(rep)
            return web.json_response({"status": "added",
                                      "replica": rep.snapshot()})
        if op == "remove":
            drain = bool(body.get("drain", True))
            wait_s = float(body.get("wait_s", 30.0))
            found = await router.remove_replica(name, drain=drain,
                                                wait_s=wait_s)
            return web.json_response(
                {"status": ("removed" if found else "absent"),
                 "drained": bool(found and drain)},
                status=200 if found else 404)
        raise web.HTTPUnprocessableEntity(text="op must be add|remove")

    async def debug_autoscale(request: web.Request) -> web.Response:
        """The autoscaler's decision ring + surge state
        (docs/autoscaling.md; schema-pinned by
        ``router.autoscale.validate_autoscale_snapshot``)."""
        if router.autoscale is None:
            return web.json_response(
                {"enabled": False, "surge": router.surge.snapshot()})
        limit = obs_history.query_int(request, "limit", 50, minimum=0)
        return web.json_response(router.autoscale.snapshot(limit=limit))

    async def control_autoscale(request: web.Request) -> web.Response:
        """Ops/test surface: ``{"op": "tick"}`` runs one control cycle
        NOW and returns its decision record; ``{"op": "surge",
        "active": bool}`` overrides the surge gate by hand (incident
        control when the autoscaler is not attached)."""
        body = await request.json()
        op = body.get("op")
        if op == "tick":
            if router.autoscale is None:
                raise web.HTTPConflict(text="no autoscaler attached")
            return web.json_response(await router.autoscale.tick())
        if op == "surge":
            router.surge.set_active(bool(body.get("active", False)))
            return web.json_response(router.surge.snapshot())
        raise web.HTTPUnprocessableEntity(text="op must be tick|surge")

    async def control_heartbeat(request: web.Request) -> web.Response:
        """Force one heartbeat cycle now (ops/tests) — probes every
        replica, crash-loop backoff notwithstanding."""
        await router.heartbeat_once(force=True)
        router.refresh_fleet()
        return web.json_response({"replicas": table.snapshot()})

    async def forward(request: web.Request) -> web.StreamResponse:
        return await router.forward(request)

    # Retained telemetry (docs/observability.md): the router's history
    # ring samples the fleet gauges the heartbeat publishes (ages
    # refreshed per sample, same as per scrape), the alert engine runs
    # the FLEET rule set (SLO burn rate, heartbeat staleness), and
    # incident capture is ASYNC — the sampler thread fires, a loop
    # coroutine gathers each replica's /debug/requests + /debug/rounds
    # slice alongside the local evidence, then the bundle write runs
    # off-loop. Inert as a unit when HISTORY_INTERVAL_S=0.
    _obs_loop: dict = {}

    async def _capture_with_fleet(trigger: dict) -> None:
        limit = obs_incidents.INCIDENT_SLICE_LIMIT
        extras: dict = {"fleet": None, "autoscale": None, "replicas": {}}
        try:
            extras["fleet"] = router.refresh_fleet()
        except Exception:  # noqa: BLE001 — evidence is best-effort
            logger.debug("incident fleet snapshot failed", exc_info=True)
        if router.autoscale is not None:
            try:
                extras["autoscale"] = router.autoscale.snapshot(
                    limit=limit)
            except Exception:  # noqa: BLE001
                logger.debug("incident autoscale snapshot failed",
                             exc_info=True)
        session = router._session
        if session is not None:
            for rep in table.replicas():
                row: dict = {}
                for ep in ("requests", "rounds"):
                    try:
                        async with session.get(
                                f"{rep.url}/debug/{ep}?limit={limit}",
                                timeout=aiohttp.ClientTimeout(
                                    total=router.heartbeat_timeout_s)
                                ) as resp:
                            row[ep] = await resp.json()
                    except Exception:  # noqa: BLE001 — replica may be
                        row[ep] = None  # the incident; keep the rest
                extras["replicas"][rep.name] = row
        await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(obs_stack.capture, trigger, extras))

    def _capture_async(rule, trigger: dict) -> None:
        loop = _obs_loop.get("loop")
        if loop is None or loop.is_closed():
            # No running app loop (tests driving tick() by hand):
            # capture the local evidence, skip the replica pulls.
            obs_stack.capture(trigger)
            return
        asyncio.run_coroutine_threadsafe(_capture_with_fleet(trigger),
                                         loop)

    obs_stack = obs_incidents.ObservabilityStack(
        "router",
        pre_sample=[table.publish_heartbeat_ages,
                    obs_metrics.record_process_stats],
        flight=router.flight,
        capture_async=_capture_async)

    async def debug_history(request: web.Request) -> web.Response:
        return obs_history.debug_history_response(request,
                                                  obs_stack.history)

    async def debug_alerts(request: web.Request) -> web.Response:
        return obs_alerts.debug_alerts_response(request, obs_stack.alerts)

    async def debug_incidents(request: web.Request) -> web.Response:
        return obs_incidents.debug_incidents_response(request, obs_stack)

    async def control_incident(request: web.Request) -> web.Response:
        return await obs_incidents.control_incident_response(request,
                                                             obs_stack)

    app.router.add_get("/health", health)
    app.router.add_get("/metrics", metrics_endpoint)
    app.router.add_get("/debug/requests", debug_requests)
    app.router.add_get("/debug/fleet", debug_fleet)
    app.router.add_get("/debug/autoscale", debug_autoscale)
    app.router.add_get("/debug/history", debug_history)
    app.router.add_get("/debug/alerts", debug_alerts)
    app.router.add_get("/debug/incidents", debug_incidents)
    app.router.add_get("/router/replicas", list_replicas)
    app.router.add_post("/control/replicas", control_replicas)
    app.router.add_post("/control/heartbeat", control_heartbeat)
    app.router.add_post("/control/autoscale", control_autoscale)
    app.router.add_post("/control/incident", control_incident)
    for path in FORWARD_PATHS:
        app.router.add_post(path, forward)

    async def on_startup(app_: web.Application) -> None:
        _obs_loop["loop"] = asyncio.get_running_loop()
        await router.start(run_heartbeat=run_heartbeat,
                           run_autoscale=run_autoscale)
        obs_stack.start()

    async def on_cleanup(app_: web.Application) -> None:
        obs_stack.stop()
        _obs_loop.pop("loop", None)
        await router.stop()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app
