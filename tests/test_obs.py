"""Observability tests: metrics registry, request timing, tracing no-ops."""

import time

from generativeaiexamples_tpu.obs.metrics import (Registry, RequestTimer)
from generativeaiexamples_tpu.obs import tracing


def test_counter_and_gauge():
    reg = Registry()
    reg.counter("reqs").inc()
    reg.counter("reqs").inc(2)
    reg.gauge("temp").set(3.5)
    snap = reg.snapshot()
    assert snap["reqs"] == 3
    assert snap["temp"] == 3.5


def test_histogram_percentile_and_render():
    reg = Registry()
    h = reg.histogram("lat")
    for v in [0.01, 0.02, 0.05, 0.1, 0.5]:
        h.observe(v)
    assert h.count == 5
    assert 0.0 < h.percentile(0.5) <= 0.1
    text = reg.render_prometheus()
    assert "# TYPE lat histogram" in text
    assert 'lat_bucket{le="+Inf"} 5' in text
    assert "lat_count 5" in text


def test_request_timer_ttft_and_tps():
    reg = Registry()
    t = RequestTimer("gen", registry=reg)
    time.sleep(0.01)
    t.token(5)
    t.token(5)
    t.finish()
    snap = reg.snapshot()
    assert snap["gen_requests_total"] == 1
    assert snap["gen_ttft_seconds_count"] == 1
    assert snap["gen_tokens_total"] == 10
    assert snap["gen_last_tokens_per_second"] > 0


def test_tracing_disabled_noops():
    assert not tracing.enabled()
    with tracing.server_span("x", headers={"traceparent": "00-abc"}) as span:
        assert span is None
    with tracing.event_span("retrieve", top_k=4) as span:
        assert span is None
    headers = tracing.inject_context({"a": "b"})
    assert headers == {"a": "b"}


def test_instrumented_passthrough():
    import asyncio

    @tracing.instrumented("handler")
    async def handler(request):
        return "ok"

    class FakeReq:
        headers = {}
        rel_url = "/x"

    assert asyncio.new_event_loop().run_until_complete(handler(FakeReq())) == "ok"
