"""Multimodal-assistant tests (reference behavior:
experimental/multimodal_assistant/ — pptx/docx parsing with slide
provenance, conversation memory, fact-check guardrail, feedback)."""

import zipfile

import pytest

from generativeaiexamples_tpu.assistant import (ConversationMemory,
                                                FeedbackStore,
                                                MultimodalAssistant,
                                                fact_check, read_docx,
                                                read_pptx)
from generativeaiexamples_tpu.assistant.parsers import (extract_images,
                                                        parse_pptx)
from generativeaiexamples_tpu.chains.llm import LLM
from generativeaiexamples_tpu.chains.readers import read_document

_A = 'xmlns:a="http://schemas.openxmlformats.org/drawingml/2006/main"'
_P = 'xmlns:p="http://schemas.openxmlformats.org/presentationml/2006/main"'
_R = ('xmlns:r="http://schemas.openxmlformats.org/officeDocument/2006/'
      'relationships"')


def _slide_xml(*texts):
    runs = "".join(f"<a:t>{t}</a:t>" for t in texts)
    return f'<p:sld {_P} {_A}>{runs}</p:sld>'


_REL_NS = ('xmlns="http://schemas.openxmlformats.org/package/2006/'
           'relationships"')
_T_IMAGE = ("http://schemas.openxmlformats.org/officeDocument/2006/"
            "relationships/image")
_T_NOTES = ("http://schemas.openxmlformats.org/officeDocument/2006/"
            "relationships/notesSlide")
_T_VIDEO = ("http://schemas.openxmlformats.org/officeDocument/2006/"
            "relationships/video")


def make_pptx(path):
    """Slide 1: image + a video (must not count as an image). Slide 2:
    the deck's only speaker notes — stored as notesSlide1.xml (notes are
    numbered by creation order, not slide order)."""
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("ppt/slides/slide1.xml",
                   _slide_xml("TPU Architecture", "The MXU does matmuls."))
        z.writestr("ppt/slides/slide2.xml",
                   _slide_xml("Paged KV", "Pages are 128 tokens."))
        z.writestr("ppt/notesSlides/notesSlide1.xml",
                   _slide_xml("Mention the systolic array."))
        z.writestr(
            "ppt/slides/_rels/slide1.xml.rels",
            f'<Relationships {_REL_NS}>'
            f'<Relationship Id="rId2" Type="{_T_IMAGE}" '
            'Target="../media/image1.png"/>'
            f'<Relationship Id="rId3" Type="{_T_VIDEO}" '
            'Target="../media/movie1.mp4"/></Relationships>')
        z.writestr(
            "ppt/slides/_rels/slide2.xml.rels",
            f'<Relationships {_REL_NS}>'
            f'<Relationship Id="rId2" Type="{_T_NOTES}" '
            'Target="../notesSlides/notesSlide1.xml"/></Relationships>')
        z.writestr("ppt/media/image1.png", b"\x89PNGfake")
        z.writestr("ppt/media/movie1.mp4", b"fakemp4")
    return path


def make_docx(path):
    w = ('xmlns:w="http://schemas.openxmlformats.org/wordprocessingml/'
         '2006/main"')
    body = (f'<w:document {w}><w:body>'
            '<w:p><w:r><w:t>First paragraph about ICI.</w:t></w:r></w:p>'
            '<w:p><w:r><w:t>Second about </w:t></w:r>'
            '<w:r><w:t>collectives.</w:t></w:r></w:p>'
            '</w:body></w:document>')
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("word/document.xml", body)
    return path


class ScriptedLLM(LLM):
    def __init__(self, responses):
        self.responses = list(responses)
        self.prompts = []

    def stream(self, prompt, max_tokens=256, stop=None, temperature=1.0,
               top_k=1, top_p=0.0):
        self.prompts.append(prompt)
        idx = min(len(self.prompts) - 1, len(self.responses) - 1)
        yield self.responses[idx]


# ---------------------------------------------------------------- parsers

def test_parse_pptx_slides_notes_images(tmp_path):
    path = make_pptx(str(tmp_path / "deck.pptx"))
    slides = parse_pptx(path)
    assert [s.index for s in slides] == [1, 2]
    assert "MXU" in slides[0].text
    # notes pair through the slide's rels, not the notesSlide number:
    # notesSlide1.xml belongs to SLIDE 2 here
    assert slides[0].notes == ""
    assert "systolic array" in slides[1].notes
    # the embedded video is not an image
    assert slides[0].images == ["image1.png"]
    assert slides[1].images == []
    flat = read_pptx(path)
    assert "[slide 1]" in flat and "Paged KV" in flat
    assert "image1.png" in flat and "movie1.mp4" not in flat


def test_extract_images(tmp_path):
    path = make_pptx(str(tmp_path / "deck.pptx"))
    out = extract_images(path, str(tmp_path / "media"))
    assert any(p.endswith("image1.png") for p in out)


def test_read_docx_and_registry(tmp_path):
    path = make_docx(str(tmp_path / "doc.docx"))
    text = read_docx(path)
    assert "First paragraph about ICI." in text
    assert "Second about collectives." in text
    # the generic reader registry resolves the new extensions too
    assert read_document(path) == text
    assert "MXU" in read_document(make_pptx(str(tmp_path / "d2.pptx")))


# ----------------------------------------------------------------- memory

def test_memory_bounds_and_renders():
    mem = ConversationMemory(max_turns=2, max_chars=10_000)
    mem.add("q1", "a1")
    mem.add("q2", "a2")
    mem.add("q3", "a3")
    text = mem.render()
    assert "q1" not in text and "q2" in text and "q3" in text
    mem2 = ConversationMemory(max_turns=10, max_chars=40)
    mem2.add("a" * 30, "b" * 30)
    mem2.add("new question", "short")
    assert "new question" in mem2.render()
    assert "a" * 30 not in mem2.render()


# -------------------------------------------------------------- guardrail

def test_fact_check_verdicts():
    yes = fact_check(ScriptedLLM(["VERDICT: TRUE All claims match."]),
                     "ctx", "q", "resp")
    assert yes.supported is True and "match" in yes.explanation
    no = fact_check(ScriptedLLM(["VERDICT: FALSE Claim 2 is invented."]),
                    "ctx", "q", "resp")
    assert no.supported is False
    shrug = fact_check(ScriptedLLM(["cannot say"]), "ctx", "q", "resp")
    assert shrug.supported is None


# --------------------------------------------------------------- feedback

def test_feedback_roundtrip(tmp_path):
    store = FeedbackStore(str(tmp_path / "fb.jsonl"))
    store.record("q", "a", 4, comment="good", sources=["deck.pptx"])
    store.record("q2", "a2", 1)
    entries = store.load()
    assert len(entries) == 2
    assert entries[0]["rating"] == 4
    assert entries[0]["sources"] == ["deck.pptx"]


# -------------------------------------------------------------- assistant

def _assistant(llm, tmp_path, check_facts=True):
    from generativeaiexamples_tpu.utils.app_config import AppConfig
    from generativeaiexamples_tpu.utils.configuration import from_dict
    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "echo"},
        "embeddings": {"model_engine": "hash", "dimensions": 64},
        "vector_store": {"name": "exact"},
        "text_splitter": {"chunk_size": 60, "chunk_overlap": 10}})
    return MultimodalAssistant(
        llm=llm, config=cfg, check_facts=check_facts,
        feedback_path=str(tmp_path / "fb.jsonl"))


def test_assistant_pptx_rag_with_guardrail(tmp_path):
    llm = ScriptedLLM(["The MXU does matmuls.",
                       "VERDICT: TRUE Supported by slide 1."])
    bot = _assistant(llm, tmp_path)
    bot.ingest_docs(make_pptx(str(tmp_path / "deck.pptx")), "deck.pptx")
    out = "".join(bot.rag_chain("What does the MXU do?", 64))
    assert "The MXU does matmuls." in out
    assert "[fact check: supported" in out
    hits = bot.document_search("MXU", 4)
    assert any("slide 1" in h["source"] for h in hits)
    # memory carries the turn
    assert len(bot.memory) == 1
    llm.responses.append("follow-up answer")
    "".join(bot.rag_chain("and the pages?", 32))
    assert "Conversation so far:" in llm.prompts[-2]  # history in prompt


def test_assistant_flags_unsupported_answers(tmp_path):
    llm = ScriptedLLM(["Invented claim.",
                       "VERDICT: FALSE Not in the documents."])
    bot = _assistant(llm, tmp_path)
    bot.ingest_docs(make_pptx(str(tmp_path / "deck.pptx")), "deck.pptx")
    out = "".join(bot.rag_chain("question?", 64))
    assert "[fact check: NOT fully supported" in out


def test_assistant_feedback(tmp_path):
    bot = _assistant(ScriptedLLM(["a"]), tmp_path, check_facts=False)
    bot.record_feedback("q", "a", 5, "nice")
    assert bot.feedback.load()[0]["rating"] == 5


def test_assistant_served_by_chain_server(tmp_path):
    """The assistant is a BaseExample: the standard chain server serves
    it (the reference needs a whole separate Streamlit app)."""
    from generativeaiexamples_tpu.chains.server import discover_example
    cls = discover_example("generativeaiexamples_tpu.assistant.assistant")
    assert cls is MultimodalAssistant
