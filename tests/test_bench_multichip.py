"""Tier-1 CPU smoke of the multi-chip serving sweep (``BENCH_MESH``):
tp=1 and tp=2 rungs end-to-end through real engines on the virtual
8-device host platform, the section/rung key contract against
tools/bench_schema.json, and the ACCEPTANCE-criterion scheduling fact:
each rung's round budget is derived from the topology-MATCHED cost row,
so tp=1 and tp=2 budgets differ when the profile rows differ."""

import json
import os

import jax
import jax.numpy as jnp
import pytest

import bench
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from tools.check_bench_schema import load_schema, validate_result
from tools.preflight import validate_multichip_block

# vocab 320 = 2 x 160 (whole 32-token mask words per tp=2 shard); heads
# divide tp=2 so the geometry serves the SHARDED fused tail, not a
# downgrade.
CFG = LlamaConfig(vocab_size=320, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=256)

# Topology-keyed cost artifact: the tp=2 row models prefill 2x cheaper,
# so its derived round budget (decode-round ms / prefill ms-per-token)
# is exactly 2x the single-chip row's — the budgets MUST differ.
PROFILE = {
    "full_ms_per_step": 2.0, "prefill_ms_per_token": 0.125, "slots": 8,
    "topologies": {"tp=2": {"prefill_ms_per_token": 0.0625}},
}


@pytest.fixture(scope="module")
def multichip(tmp_path_factory):
    path = tmp_path_factory.mktemp("prof") / "PROFILE_topo.json"
    path.write_text(json.dumps(PROFILE))
    old = os.environ.get("SCHED_PROFILE_JSON")
    os.environ["SCHED_PROFILE_JSON"] = str(path)
    try:
        params = llama.init_params(CFG, jax.random.key(11),
                                   dtype=jnp.float32)
        return bench.run_multichip_sweep(
            params, CFG, ByteTokenizer(), ["tp=1", "tp=2"],
            prompt_len=16, out_len=4, n_requests=2, slots=2,
            steps_per_round=4,
            # tiny-geometry overrides (production defaults target the
            # chip)
            max_input_length=64, max_output_length=16,
            prefill_buckets=(16, 32, 64), dtype="float32", page_size=16,
            max_queue=64)
    finally:
        if old is None:
            os.environ.pop("SCHED_PROFILE_JSON", None)
        else:
            os.environ["SCHED_PROFILE_JSON"] = old


def test_mesh_rung_parsing_contracts():
    """BENCH_MESH parsing: ';' always separates rungs; without one a
    comma starts a new rung only on a repeated axis (a mesh never
    repeats an axis), and unknown axes fail LOUDLY before any engine
    is built."""
    assert bench.split_mesh_rungs("tp=1,tp=2,tp=4") == \
        ["tp=1", "tp=2", "tp=4"]
    assert bench.split_mesh_rungs("tp=2,sp=2") == ["tp=2,sp=2"]
    assert bench.split_mesh_rungs("tp=2,sp=2;tp=4") == \
        ["tp=2,sp=2", "tp=4"]
    assert bench.split_mesh_rungs("tp=1,tp=2,sp=2") == \
        ["tp=1", "tp=2,sp=2"]
    label, axes, devices = bench.parse_mesh_rung("sp=2,tp=2")
    assert (label, devices) == ("sp=2,tp=2", 4)
    assert axes == {"sp": 2, "tp": 2}
    with pytest.raises(ValueError, match="axis=N"):
        bench.parse_mesh_rung("tpx=4")
    with pytest.raises(ValueError, match="twice"):
        bench.parse_mesh_rung("tp=2,tp=4")
    with pytest.raises(ValueError):
        bench.run_multichip_sweep(
            None, CFG, None, ["tp=2", "bogus=2"], prompt_len=8,
            out_len=4, n_requests=1)


def test_multichip_sweep_runs_every_rung(multichip):
    assert multichip["mesh_sweep"] == ["tp=1", "tp=2"]
    assert [r["mesh"] for r in multichip["rungs"]] == ["tp=1", "tp=2"]
    assert [r["devices"] for r in multichip["rungs"]] == [1, 2]
    for rung in multichip["rungs"]:
        assert rung["decode_tokens_per_sec"] > 0
        assert rung["engine_p50_ttft_ms"] > 0
        assert rung["tokens_per_sec_per_device"] == pytest.approx(
            rung["decode_tokens_per_sec"] / rung["devices"], rel=0.02)
        assert rung["engine_downgrades"] == 0


def test_multichip_mesh_rung_serves_sharded_fused_tail(multichip):
    """The tentpole's point: a mesh rung reads ``fused_tp``, never the
    PR-8 "mesh keeps the materialized tail" fallback."""
    by_mesh = {r["mesh"]: r for r in multichip["rungs"]}
    assert by_mesh["tp=1"]["tail"] == "fused"
    assert by_mesh["tp=2"]["tail"] == "fused_tp"


def test_multichip_budget_from_topology_matched_row(multichip):
    """Acceptance criterion: the round budget each rung's scheduler
    started from is derived from the topology-MATCHED cost row —
    budgets differ between tp=1 and tp=2 because the profile rows do,
    and each rung names the row it used."""
    by_mesh = {r["mesh"]: r for r in multichip["rungs"]}
    b1 = by_mesh["tp=1"]["sched_round_budget_tokens"]
    b2 = by_mesh["tp=2"]["sched_round_budget_tokens"]
    assert b1 > 0 and b2 > 0
    # tp=2 prefill modeled 2x cheaper -> 2x the budget (page-quantized;
    # budget = decode_round_ms / prefill_ms_per_token)
    assert b2 == 2 * b1, (b1, b2)
    assert by_mesh["tp=1"]["cost_topology"] == "tp=1"
    assert by_mesh["tp=2"]["cost_topology"] == "tp=2"
    assert by_mesh["tp=2"]["cost_source"].endswith("@tp=2")


def test_multichip_section_keys_pinned_by_schema(multichip):
    """The emitted section IS the schema's multichip/multichip_rung
    contract — renaming either side alone fails (same enforcement as
    capacity_rung / fleet_policy)."""
    schema = load_schema()
    assert set(multichip) == set(schema["multichip"])
    for rung in multichip["rungs"]:
        assert set(rung) == set(schema["multichip_rung"])
    # the full result path accepts it too
    result = bench.assemble_result(
        kind="engine", model="t", headline=1.0, engine_p50=1.0,
        engine_p99=1.0, tput=1.0, achieved_bw=1.0, bw_util=0.1,
        bw_steady=True, chat=None, e2e_p50=None, e2e_dist=None,
        e2e_breakdown=None, pipeline=bench.pipeline_snapshot({}),
        quant="none", kv_quant=None, weights="random-init",
        prompt_len=16, out_len=4, slots=2, steps_per_round=4,
        kv_pool_pages=8, device="cpu", rtt_ms=None, n_devices=8,
        bench_seconds=1.0, multichip=multichip)
    validate_result(result)


def test_multichip_preflight_validator_accepts_real_sweep(multichip):
    assert validate_multichip_block(multichip) == []


def test_multichip_preflight_validator_can_fail(multichip):
    """The preflight ``multichip`` check is proven able to fail: a mesh
    rung that silently regressed to the materialized tail, a
    devices/mesh mismatch, and a zero budget are each caught."""
    import copy

    broken = copy.deepcopy(multichip)
    broken["rungs"][1]["tail"] = "materialized"
    assert any("regressed" in e for e in validate_multichip_block(broken))
    broken = copy.deepcopy(multichip)
    broken["rungs"][1]["devices"] = 3
    assert any("axis product" in e
               for e in validate_multichip_block(broken))
    broken = copy.deepcopy(multichip)
    broken["rungs"][0]["sched_round_budget_tokens"] = 0
    assert any("budget" in e for e in validate_multichip_block(broken))
    broken = copy.deepcopy(multichip)
    del broken["rungs"][0]["decode_tokens_per_sec"]
    assert validate_multichip_block(broken)
