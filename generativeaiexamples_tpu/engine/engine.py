"""Continuous-batching inference engine with a paged KV cache.

The TPU-native replacement for the reference's Triton + TRT-LLM C++ serving
core with "inflight fused batching" and paged KV
(reference: ensemble_models/llama/tensorrt_llm/config.pbtxt.j2:28-34,
model_server/server.py:67-71). Architecture:

- **Decode slots.** A fixed-size batch of decode requests (static shapes for
  XLA). Every decode step runs the whole slot batch through one jitted
  program; inactive slots are masked. Requests join and leave the batch
  between rounds, the compiled program never changes.
- **Paged KV pool.** KV lives in a shared pool of fixed-size pages; each
  slot holds a block table mapping logical to physical pages. Admission
  allocates a request's full extent (prompt + max_tokens) and backpressures
  when the pool is exhausted — so cache capacity is sized to HBM, not to
  ``slots × max_len``. Decode attention gathers only the smallest page
  window covering the longest active sequence (bucketed per compile), so
  HBM reads scale with live context.
- **Multi-step decode rounds.** Each dispatch is a ``lax.scan`` of
  ``steps_per_round`` decode steps with *device-side* eos/length
  termination — one host<->device round trip per K tokens instead of per
  token, which is what makes decode fast over a remote device link.
- **Dispatch-ahead.** Up to ``dispatch_depth`` rounds are enqueued on the
  device before dispatch pauses, overlapping host processing and device
  compute.
- **Overlapped harvest.** Device→host readbacks never run on the
  scheduling path. The scheduler thread only admits and dispatches; a
  dedicated harvest worker consumes the dispatched programs' output
  arrays IN ORDER (first tokens, then each decode round), blocking on
  each host copy off-thread and waking streams as results land. On a
  tunneled device (~100 ms RTT) the readback wait therefore runs
  concurrently with the next admissions/dispatches instead of
  serializing the loop — the round-6 TTFT lever. Finish decisions feed
  back to the scheduler through a completion queue, so slot/page/cache
  bookkeeping and every device dispatch stay single-threaded.
- **Bucketed prefill.** Prompts are padded to the nearest static bucket
  (a page multiple) and prefilled as a separate jitted call, then their KV
  is scattered into the slot's pages.
- **Streaming.** Each request gets a thread-safe ``TokenStream`` — the
  decoupled-response equivalent of the reference's gRPC streaming callbacks
  (reference: model_server_client/trt_llm.py:417-442).
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama
from ..models.configs import LlamaConfig
from ..models.tokenizer import Tokenizer
from ..obs import flight as obs_flight
from ..obs import rounds as obs_rounds
from ..obs.tracing import record_stage
from ..ops.fused_sampler import (choose_tile, fused_unembed_sample,
                                 fused_unembed_sample_tp,
                                 fused_verify_sample,
                                 fused_verify_sample_tp, tp_shardable,
                                 verify_reference_tiled)
from ..ops.sampling import (apply_repetition_penalty, mask_words,
                            pack_mask, pack_mask_np, sample, seen_mask,
                            set_token_bits, unpack_mask)
from ..parallel.sharding import (llama_param_specs, paged_kv_cache_spec,
                                 shard_params)
from ..utils import faults
from ..utils.errors import (ConfigError, EngineError, RoleMismatchError,
                            SchedulerFullError)
from ..utils.hbm import peak_bw
from ..utils.logging import get_logger, log_event
from . import kv_tier as kv_tier_mod
from . import resume as engine_resume
from .detokenizer import IncrementalDetokenizer, StopWordTrap
from .kv_tier import BlockRecord, KVTier
from .prefix_cache import PrefixCache, hash_blocks, usable_prefix_tokens
from .sampling_params import SamplingParams
from .scheduler import (OnlineCalibrator, PrefillJob, StepCostModel,
                        TokenBudgetScheduler, online_calib_enabled,
                        topology_key)
from .spec_decode import (AdaptiveDraftController, PromptLookupDrafter,
                          SpecConfig, spec_enabled)


logger = get_logger(__name__)

# Short per-engine tag stamped on round-telemetry records: multi-engine
# processes (the fleet bench, tests) share the process-global round ring,
# and the tag is what tells their rounds apart in /debug/rounds.
_ENGINE_TAGS = itertools.count()


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _pow2_ladder(top: int) -> tuple:
    """(1, 2, 4, ..., top): the compiled-shape rungs for decode page
    windows and fused-tail active-row counts — a request pays for the
    smallest rung covering it, not the maximum."""
    ladder = []
    w = 1
    while w < top:
        ladder.append(w)
        w *= 2
    return tuple(ladder + [top])


# Engine-owned cumulative counters, the keys ``stats()`` always carries.
# A TEMPLATE (each Engine copies it) so tools/check_metrics_docs.py can
# enumerate the stats surface without building an engine — the tier-1
# guard that keeps docs/observability.md's gauge table and stats() from
# drifting apart.
_STATS_TEMPLATE = {
    "requests": 0, "tokens_generated": 0, "decode_steps": 0, "prefills": 0,
    # Pipeline stage counters (cumulative ms + event counts): how long
    # the harvest worker blocked on round/first readbacks — time that
    # overlaps dispatch instead of serializing the loop.
    "harvest_wait_ms": 0.0, "harvest_rounds": 0,
    "first_readback_ms": 0.0, "first_readbacks": 0,
    # Monotonic high-water mark of the device queue (rounds dispatched
    # ahead of harvest): the live gauge reads 0 whenever the engine is
    # idle, so artifacts sampled after a run need the peak to show the
    # overlap actually happened.
    "dispatch_depth_peak": 0,
    # Robustness counters: submissions rejected at the queue (shed as
    # 429 at the HTTP edge), queued requests dropped because their
    # deadline expired before admission (they never reach prefill), and
    # decodes stopped mid-generation by a passing deadline.
    "rejected_full": 0,
    "deadline_queue_drops": 0,
    "deadline_stops": 0,
    # Token-budget scheduler (engine/scheduler.py): the resolved
    # per-round budget, cumulative prefill tokens it granted as chunks,
    # cumulative decode token-equivalents charged against it, and how
    # many rounds actually mixed a decode dispatch with prefill chunks
    # (the interleaving the budget exists to enable).
    "sched_round_budget_tokens": 0,
    "sched_prefill_tokens": 0,
    "sched_decode_tokens": 0,
    "sched_interleaved_rounds": 0,
    # Fused unembed/sampling tail (ops/fused_sampler.py): slot-rows that
    # actually ran through the vocab projection + sampler per decode
    # step, vs rows the former all-slots tail would have computed but the
    # active-slot compaction skipped (partial occupancy — the proof the
    # tail no longer pays for empty slots).
    "sampler_rows_sampled": 0,
    "sampler_rows_skipped": 0,
    # Speculative decoding (engine/spec_decode.py): draft tokens
    # proposed by the prompt-lookup drafter, how many of them the
    # batched verify step accepted, verify rounds dispatched, tokens
    # those rounds emitted (accepted drafts + the per-slot correction/
    # bonus token), and slot participations in verify rounds (the
    # denominator of the tokens-per-model-step multiplier).
    "spec_draft_tokens": 0,
    "spec_accepted_tokens": 0,
    "spec_verify_rounds": 0,
    "spec_verify_tokens": 0,
    "spec_verify_slot_steps": 0,
    # Tiered KV store (engine/kv_tier.py): refcount-0 prefix pages
    # offloaded to the host-RAM tier instead of dropped at eviction,
    # pages restored H2D at admission (and admissions that restored
    # >= 1 page), admissions whose host-tier hit was deliberately
    # re-prefilled because the step-cost model priced restore more
    # expensive than recompute, pages imported from a sibling replica
    # over /control/kv_pages, and blocks moved through session
    # suspend/resume. All 0 forever with KV_HOST_POOL_TOKENS=0.
    "kv_tier_offload_pages": 0,
    "kv_tier_restore_pages": 0,
    "kv_tier_restore_hits": 0,
    "kv_restore_skipped_cost": 0,
    "kv_tier_transfer_pages": 0,
    "kv_tier_suspended_blocks": 0,
    "kv_tier_resumed_blocks": 0,
    # Disaggregated prefill/decode handoff (docs/disaggregation.md):
    # finished prefix pages exported for push-on-completion handoff to a
    # decode replica, and donor-side /control/kv_pages exports refused
    # because the concurrent-export bound was already held (the chain
    # server's semaphore sheds with 429 + Retry-After so N simultaneous
    # handoffs can't stall this engine's decode rounds).
    "kv_tier_export_pages": 0,
    "kv_export_shed": 0,
    # KV blob integrity (engine/kv_tier.py v2 wire format): transfer /
    # handoff / session blobs whose per-array CRC32 (or framing) failed
    # verification — each one fell back cleanly to recompute instead of
    # admitting garbage pages. 0 on a healthy network.
    "kv_restore_corrupt": 0,
    # Liveness watchdog (ENGINE_WATCHDOG_STALL_S): times the watchdog
    # declared the engine stalled — work queued or in flight while the
    # round/harvest progress counters stayed frozen past the threshold.
    # Each detection dumps thread stacks + the last round record via a
    # structured ``engine_watchdog_stall`` log event and flips /health
    # to 503 until progress resumes.
    "watchdog_stalls": 0,
    # Round telemetry (obs/rounds.py): engine rounds whose plan AND
    # every harvested device output have been recorded — the flight-
    # recorder-style per-round records behind GET /debug/rounds.
    "rounds_completed": 0,
    # Online cost calibration (engine/scheduler.py OnlineCalibrator):
    # times recalibrate() actually moved the derived round budget —
    # 0 forever when SCHED_ONLINE_CALIB=0 or the budget is pinned.
    "sched_budget_recalibrations": 0,
    # Construction-time feature downgrades (fused tail -> materialized,
    # Pallas kernel -> jnp gather, ...): each one also logs a structured
    # ``engine_feature_downgrade`` event. 0 on a fully-armed engine —
    # > 0 means this engine serves correctly but below its hardware's
    # potential, which used to be a silent comment-only fallback.
    # (Mirrored as the ``engine_downgrades`` gauge.)
    "downgrades": 0,
}


def engine_stat_keys() -> tuple[str, ...]:
    """Every key an ``Engine.stats`` snapshot can contain: the cumulative
    template above, the read-time pipeline gauge, and the prefix-cache
    counters (prefix caching is on by default). The single source of
    truth tools/check_metrics_docs.py checks the docs against."""
    from .prefix_cache import CacheStats
    return (tuple(_STATS_TEMPLATE)
            + ("dispatch_queue_depth", "queue_waiting",
               "sched_prefill_share",
               "spec_acceptance_rate", "spec_tokens_per_step",
               "sched_cost_drift_ratio",
               "kv_tier_host_pages", "kv_restore_hit_rate", "uptime_s")
            + tuple(CacheStats().snapshot()) + ("prefix_cache_pages",))


def _layout_api():
    """Version portability for the explicit-layout API. jax >= 0.5 spells
    a concrete layout ``Format(Layout(major_to_minor), sharding)``; 0.4.x
    spells it ``Layout(DeviceLocalLayout(major_to_minor), sharding)`` and
    has no ``with_layout_constraint`` at all. Returns
    ``(format_for, constrain_or_none)`` where ``format_for(ndim,
    sharding)`` builds a row-major device_put target and
    ``constrain_or_none(x)`` pins an in-program value row-major (None =>
    pinning unavailable; callers degrade to no constraint, which only
    costs the relayout copy the pin exists to avoid)."""
    try:
        from jax.experimental.layout import Format, Layout

        def format_for(ndim, sharding):
            return Format(Layout(major_to_minor=tuple(range(ndim))),
                          sharding)

        def inner(ndim):
            return Layout(major_to_minor=tuple(range(ndim)))
    except ImportError:
        from jax.experimental.layout import DeviceLocalLayout, Layout

        def format_for(ndim, sharding):
            return Layout(DeviceLocalLayout(
                major_to_minor=tuple(range(ndim))), sharding)

        inner = None
    try:
        from jax.experimental.layout import with_layout_constraint
    except ImportError:
        with_layout_constraint = None
    if with_layout_constraint is None or inner is None:
        constrain = None
    else:
        def constrain(x):
            return with_layout_constraint(x, inner(x.ndim))
    return format_for, constrain


class _StaleLoop(Exception):
    """Raised inside a loop thread that reset() has disowned — unwinds the
    whole _run() without touching the new generation's state."""


@dataclass(frozen=True)
class EngineConfig:
    """Engine sizing. Limits mirror the reference's engine defaults
    (reference: model_server/__main__.py:81-92, config.pbtxt.j2:29)."""
    max_slots: int = 8                # concurrent decode requests
    max_input_length: int = 3000
    max_output_length: int = 512
    prefill_buckets: tuple[int, ...] = (128, 512, 1024, 2048, 3072)
    dtype: str = "bfloat16"
    seed: int = 0
    max_queue: int = 256
    # Paged KV pool. "auto" sizes the pool to the device's free HBM (so the
    # default geometry actually runs on one chip); None = full capacity
    # (max_slots x max cache extent); an int = pool size in tokens.
    page_size: int = 128
    kv_pool_tokens: Union[int, str, None] = "auto"
    # Decode pipelining: tokens generated per device dispatch, and how many
    # dispatches ride the device queue before the host blocks on results.
    steps_per_round: int = 8
    dispatch_depth: int = 2
    # Long-prompt serving: cap the largest compiled prefill bucket; prompts
    # beyond it stream through the paged pool in bucket-size chunks
    # (bounded prefill activations/cache for e.g. 32k-token prompts).
    # None = one-shot prefill up to max_input_length (the default; the
    # chunked path never runs).
    max_prefill_bucket: Optional[int] = None
    # KV-cache quantization: "" (pool in `dtype`) or "int8" (per-row
    # symmetric int8 pools + bf16 scale pools, ops/kv_quant.py) — halves
    # KV bytes per token, so the auto-sized pool holds ~2x the pages at
    # fixed HBM (the reference's batch-128 capacity rides the same
    # TRT-LLM lever; reference: config.pbtxt.j2:29).
    kv_quant: str = ""
    # Shared-prefix KV reuse (engine/prefix_cache.py): prompts are hashed
    # in page-sized blocks and admission maps the longest cached prefix
    # into the slot's page table read-only, so prefill starts at the
    # first uncached token — the repeat-turn/chat TTFT lever (vLLM
    # prefix caching / SGLang RadixAttention, adapted to this pool).
    # Retired requests' prompt pages stay resident at refcount 0 and are
    # reclaimed LRU under pool pressure; the pool remains the only
    # capacity budget. NOTE under kv_quant the reused prefix is read
    # back dequantized, so a warm request tracks (not bit-matches) the
    # cold trajectory — same caveat as chunked long-prompt admission.
    prefix_cache: bool = True
    # Token-budget continuous scheduler (engine/scheduler.py): per-round
    # prefill-token budget and per-request chunk cap. None = derive the
    # budget from the PROFILE_rNN step-cost model (prefill tokens whose
    # modeled cost equals one decode round) and let the chunk cap follow
    # the budget. SCHED_ROUND_BUDGET_TOKENS / SCHED_PREFILL_CHUNK_TOKENS
    # env vars override either (docs/configuration.md).
    sched_round_budget_tokens: Optional[int] = None
    sched_prefill_chunk_tokens: Optional[int] = None
    # Speculative decoding (engine/spec_decode.py): host-side prompt-
    # lookup drafting + one batched K+1-position verify forward per
    # round, emitting up to K+1 tokens per slot per model step. Exact:
    # greedy output is token-identical to the non-speculative engine,
    # temperature>0 preserves the output distribution via rejection
    # sampling. ENGINE_SPEC_DECODE env beats this field (0 restores the
    # plain decode path); SPEC_MAX_DRAFT_TOKENS env beats the field
    # below beats the default (docs/configuration.md). Works on
    # single-chip AND tp-sharded engines — the verify tail rides the
    # same (sharded) fused or materialized sampler path as decode.
    spec_decode: bool = False
    spec_max_draft_tokens: Optional[int] = None
    # Tiered KV store (engine/kv_tier.py): host-RAM budget, in tokens,
    # for refcount-0 prefix pages offloaded at eviction instead of
    # dropped (restored via priced H2D at admission; also the landing
    # zone for session resume and cross-replica page transfer). The
    # KV_HOST_POOL_TOKENS env var beats this field; None defers to it.
    # 0 (the default) disables the tier entirely — the engine then
    # byte-for-byte preserves the untiered eviction behavior.
    kv_host_pool_tokens: Optional[int] = None
    # Disaggregation role (docs/disaggregation.md): "unified" serves
    # everything (the default — a role-less fleet byte-for-byte
    # preserves today's behavior); "prefill" runs long prompts at full
    # mesh utilization with decode-bound admission DISABLED (submit
    # rejects requests wanting more than ROLE_PREFILL_MAX_TOKENS output
    # tokens with RoleMismatchError) and exports finished prefix pages
    # to decode siblings; "decode" advertises itself for short-prompt /
    # decode-bound placement (the router keeps long prompts off it when
    # a prefill sibling is placeable — advisory at the engine, enforced
    # at placement). The ENGINE_ROLE env var beats this field.
    role: str = "unified"

    def __post_init__(self) -> None:
        # Geometry validation lives on the config, not the engine — a bad
        # flag must fail in milliseconds at parse/build time, never after
        # minutes of checkpoint conversion (the reference rejects
        # impossible engine shapes up front, model_server/__init__.py:
        # 103-110). Prefill buckets scatter KV into whole pages, so the
        # cap must be a page multiple >= one page.
        if self.page_size <= 0:
            raise ConfigError(f"page_size={self.page_size} must be > 0")
        if self.kv_quant not in ("", "int8"):
            raise ConfigError(
                f"kv_quant={self.kv_quant!r} not supported; use '' or "
                f"'int8'")
        if self.max_prefill_bucket is not None and (
                self.max_prefill_bucket < self.page_size
                or self.max_prefill_bucket % self.page_size):
            raise ConfigError(
                f"max_prefill_bucket={self.max_prefill_bucket} must be a "
                f"multiple of page_size={self.page_size} (>= one page); "
                f"pass a smaller page_size to serve finer prefill caps")
        if self.kv_host_pool_tokens is not None \
                and self.kv_host_pool_tokens < 0:
            raise ConfigError(
                f"kv_host_pool_tokens={self.kv_host_pool_tokens} must "
                f"be >= 0 (0 disables the host KV tier)")
        if self.spec_max_draft_tokens is not None \
                and self.spec_max_draft_tokens < 1:
            raise ConfigError(
                f"spec_max_draft_tokens={self.spec_max_draft_tokens} "
                f"must be >= 1 (it sizes the verify round's K+1 "
                f"scoring positions)")
        if self.role not in ("unified", "prefill", "decode"):
            raise ConfigError(
                f"role={self.role!r} not supported; use 'unified', "
                f"'prefill', or 'decode' (docs/disaggregation.md)")

    @property
    def max_cache_len(self) -> int:
        return self.max_input_length + self.max_output_length


class TokenStream:
    """Thread-safe stream of text chunks for one request.

    ``request_id`` is the END-TO-END identity: the string minted (or
    adopted from ``X-Request-ID``/W3C traceparent) at the serving edge
    and stamped here by ``Engine.submit`` — the same ID names this
    request's flight-recorder timeline (``/debug/requests``), its
    slow-request log dump, and its replayed engine-stage spans.
    """

    def __init__(self, request_id: str):
        self.request_id = request_id
        # Flight-recorder hookup (set by Engine.submit): the timeline
        # this request's events land on, and the recorder that retires
        # it at the terminal transition below. owns_timeline is False
        # when the timeline was ADOPTED from a serving edge (the edge
        # completes it; this stream only contributes sub-call stats —
        # agent chains run several engine calls per request).
        self.timeline: Optional[obs_flight.Timeline] = None
        self.owns_timeline = True
        self._flight: Optional[obs_flight.FlightRecorder] = None
        self._q: "queue.Queue[tuple[str, object]]" = queue.Queue()
        self._error: Optional[BaseException] = None
        self.finish_reason: Optional[str] = None
        self.token_ids: list[int] = []
        self.submit_time = time.monotonic()
        self.first_token_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.cancelled = False
        # Fused-RAG requests: corpus row ids the on-device retrieval
        # picked (populated at first-token harvest).
        self.source_ids: list[int] = []

    def _put_chunk(self, text: str) -> None:
        if text:
            self._q.put(("chunk", text))

    def _record_done(self) -> None:
        """Retire the timeline on the FIRST terminal transition — every
        finish path (harvest finish, drain, fatal fan-out, reset) funnels
        through _finish/_fail, so no request can leak in /debug/requests'
        in-flight view. Idempotent via the recorder."""
        if self._flight is not None:
            self._flight.complete_stream(self)

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self.finish_time = time.monotonic()
        # Record into the timeline BEFORE the terminal sentinel goes
        # out: once the sentinel is consumed, the chain server's finally
        # races to complete() the timeline, and losing that race would
        # drop this stream's generated/ttft/finish annotations
        # (complete() is first-wins).
        self._record_done()
        self._q.put(("done", reason))

    def _fail(self, exc: BaseException) -> None:
        self._error = exc   # sticky: re-iteration re-raises, never hangs
        self.finish_reason = "error"
        self._record_done()  # before the sentinel — see _finish
        self._q.put(("error", exc))

    def cancel(self) -> None:
        """Abort generation (e.g. the HTTP client disconnected). The
        scheduler retires the request at the next harvested token."""
        self.cancelled = True

    def __iter__(self) -> Iterator[str]:
        """Yield chunks until the terminal event. The terminal state is
        STICKY: iterating a stream whose sentinel was already consumed
        (a second ``text()`` call, a retrying client) returns — or
        re-raises — immediately instead of blocking forever on the
        drained queue (found by the submit/cancel/reset stress test)."""
        while True:
            try:
                if self.finish_reason is not None and self._q.empty():
                    raise queue.Empty  # already finished: sticky path now
                # The timeout only bounds the idle wait for the sticky
                # re-check; a queued item is returned immediately, so the
                # streaming hot path pays nothing.
                kind, payload = self._q.get(timeout=0.25)
            except queue.Empty:
                if self.finish_reason is None:
                    continue
                # finish_reason is set BEFORE the terminal sentinel is
                # queued, and the retire path flushes tail chunks just
                # before that — drain them rather than truncating the
                # response of a slow-token stream that raced the finish.
                while True:
                    try:
                        kind, payload = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if kind == "chunk":
                        yield payload  # type: ignore[misc]
                    elif kind == "error":
                        raise EngineError(
                            "engine failure") from payload  # type: ignore[arg-type]
                    else:
                        return
                if self._error is not None:
                    raise EngineError("engine failure") from self._error
                return
            if kind == "chunk":
                yield payload  # type: ignore[misc]
            elif kind == "error":
                raise EngineError("engine failure") from payload  # type: ignore[arg-type]
            else:
                return

    def text(self) -> str:
        """Block until completion, return the full generation."""
        return "".join(self)

    @property
    def ttft_ms(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return (self.first_token_time - self.submit_time) * 1e3


@dataclass
class _Request:
    stream: TokenStream
    prompt_ids: list[int]
    params: SamplingParams
    detok: IncrementalDetokenizer
    stop: StopWordTrap
    eff_max: int = 0          # max_tokens clamped to the cache extent
    extent: int = 0           # prompt + eff_max (cache positions reserved)
    slot: int = -1
    pages: list[int] = field(default_factory=list)
    # Prefix-cache bookkeeping: block hashes this request holds a ref on
    # (matched prefix + blocks it registered), and which of req.pages are
    # cache property (retire must NOT return those to the free list —
    # they stay resident, warm for the next shared-prefix request).
    cache_refs: list = field(default_factory=list)
    cache_pages: set = field(default_factory=set)
    block_hashes: Optional[list] = None  # memoized across admission retries
    proj_pos: int = 0         # host upper bound on the device-side pos
    generated: int = 0
    greedy: bool = False      # top_k==1 / temp<=0: argmax fast path
    banned_ids: list[int] = field(default_factory=list)
    # Multi-token bad-words sequences (each a list of >=2 token ids):
    # banned on-device by matching the tail of generated tokens against the
    # sequence prefix and masking the completing token (the reference's
    # to_word_list_format sequences, preprocessing/1/model.py:211).
    bad_seqs: list[list[int]] = field(default_factory=list)
    # Device-ready renderings of the above, built ONCE at submit() on the
    # caller's thread so the serve loop's admission dispatch stays lean.
    banned_np: Optional[np.ndarray] = None
    bad_seq_np: Optional[np.ndarray] = None
    bad_len_np: Optional[np.ndarray] = None
    # Fused-RAG payload (q_llm (Sq,) int32, q_llm_len, q_enc (2, Se)):
    # admission runs the on-device retrieve+assemble+prefill program.
    rag: Optional[tuple] = None
    # Absolute (monotonic) deadline: queued past it → dropped before
    # prefill (finish deadline_queue); passed mid-decode → stopped at
    # the next harvested token (finish deadline).
    deadline_t: Optional[float] = None
    # Token-budget scheduler bookkeeping: arrival order (slack-sort
    # tiebreak), whether the slot is armed for decode (False while
    # prefill chunks are still in flight across rounds), the next
    # prompt token to prefill, and the admission-time dispatch context
    # (page row, window, masks, RNG key, prefix-cache seed) the chunk
    # dispatches share — built once at _begin_prefill.
    seq: int = 0
    prefill_done: bool = False
    pf_pos: int = 0
    pf: Optional[dict] = None
    # Speculative decoding (spec on only): the request's prompt-lookup
    # drafter (host token index over prompt + generated), its adaptive
    # draft-length controller, and the prompt's device length (the rag
    # bucket for fused-RAG requests) — ``base_len + generated - 1`` is
    # the slot's exact device ``pos``, used to re-anchor ``proj_pos``
    # after each verify round's variable-length burst.
    drafter: Optional[PromptLookupDrafter] = None
    spec_ctrl: Optional[AdaptiveDraftController] = None
    base_len: int = 0
    # Failover resume (engine/resume.py): how many trailing prompt_ids
    # are REPLAYED generated tokens from a dead sibling's transcript.
    # None for ordinary requests. Pins the admission RNG key to
    # (seed, offset) instead of the global step counter, so a resumed
    # request with the same seed draws the same continuation stream.
    resume_offset: Optional[int] = None

    @property
    def done(self) -> bool:
        return self.stream.finish_reason is not None


class Engine:
    """Continuous-batching engine over one model + mesh."""

    # Device-side multi-token bad-words table shape: up to MAX_BAD_SEQS
    # sequences per request, each up to MAX_BAD_LEN tokens. Static caps so
    # the decode round's match is a fixed (B, W, L) compare — growing them
    # recompiles, it does not reallocate per request.
    MAX_BAD_SEQS = 8
    MAX_BAD_LEN = 8

    def __init__(self, params: llama.Params, model_cfg: LlamaConfig,
                 tokenizer: Tokenizer, cfg: EngineConfig = EngineConfig(),
                 mesh: Optional[Mesh] = None):
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.mesh = mesh
        # Construction-time feature downgrades (observable, never
        # silent): populated by _note_downgrade as topology/geometry
        # gates resolve below, mirrored into the doc-fenced
        # ``engine_downgrades`` stat once the stats dict exists.
        self._downgrades: list[dict] = []
        self._dtype = jnp.dtype(cfg.dtype)
        self._kv_quant = bool(cfg.kv_quant)
        B, page = cfg.max_slots, cfg.page_size
        self._pmax = _ceil_div(cfg.max_cache_len, page)

        if mesh is not None:
            params = shard_params(params, mesh, llama_param_specs(model_cfg, mesh))
        self.params = params

        # Effective prefill buckets: page multiples, clipped to the prompt
        # limit, so bucket KV scatters cleanly into whole pages. Computed
        # before pool sizing — the auto sizer reserves headroom for the
        # largest bucket's prefill cache.
        page_up = lambda n: _ceil_div(n, page) * page  # noqa: E731
        # max_prefill_bucket caps the one-shot prefill size; prompts past
        # the cap take the chunked paged-prefill admission instead of
        # compiling (and allocating) an arbitrarily large bucket. Cap
        # geometry (page multiple >= one page) is validated loudly in
        # EngineConfig.__post_init__.
        cap = min(cfg.max_prefill_bucket or cfg.max_input_length,
                  cfg.max_input_length)
        self._buckets = tuple(sorted(
            {page_up(min(b, cap)) for b in cfg.prefill_buckets}
            | {page_up(cap)}))

        # pp>1 serving is a validated REJECTION, not a silent fallback
        # (VERDICT r5 "Next round" #6): every decode round runs all
        # layers in ONE program, so pipeline stages would idle at a 1/pp
        # duty cycle while adding a cross-stage hop to the TTFT-critical
        # dispatch — the wrong trade for a latency path that already
        # pays a ~100 ms tunnel RTT. Serving shards over tp/sp; pp stays
        # a training-time axis (parallel/pipeline.py GPipe). Rationale:
        # docs/api-reference.md "Pipeline-parallel serving: a validated
        # rejection".
        if mesh is not None and int(dict(mesh.shape).get("pp", 1)) > 1:
            raise ConfigError(
                f"serving requires pp == 1 "
                f"(mesh has pp={int(dict(mesh.shape)['pp'])}): the decode "
                f"engine dispatches all layers as one program per round, "
                f"so pipeline stages would idle 1/pp of every round; "
                f"shard serving over tp/sp instead (pp is training-only "
                f"— see docs/api-reference.md, 'Pipeline-parallel "
                f"serving')")

        # sp serving mesh: the ring-attention prefill shards each bucket
        # over sp, so invalid geometry must fail HERE, loudly, not as an
        # opaque trace-time fatal inside the serve loop on first submit.
        if mesh is not None and int(dict(mesh.shape).get("sp", 1)) > 1:
            for b in self._buckets:
                try:
                    llama.validate_sp_mesh(mesh, b, "sp serving prefill")
                except ValueError as exc:
                    raise ConfigError(str(exc)) from exc

        # The Pallas decode kernel has no SPMD partitioning rule, so mesh
        # serving shard_maps it over tp when the head counts divide
        # (models/llama.py:kernel_tp_compatible) and otherwise falls back
        # to the jnp gather path. When the kernel is in play the pool
        # layout is pinned row-major — without pinning, XLA keeps the
        # pre-transpose physical layout and inserts a full-pool relayout
        # copy (2x pool HBM) inside every decode round. Decided BEFORE
        # pool sizing: the auto sizer's headroom reserve depends on
        # whether the gather window ever materializes.
        kernel_wanted = llama.use_paged_kernel(model_cfg, page)
        self._use_kernel = (kernel_wanted
                            and llama.kernel_tp_compatible(model_cfg, mesh))
        if kernel_wanted and not self._use_kernel:
            self._note_downgrade(
                "paged_kernel", "jnp_gather",
                f"mesh {dict(mesh.shape)} cannot shard_map the Pallas "
                f"decode kernel (heads {model_cfg.num_heads}/"
                f"{model_cfg.num_kv_heads} must divide tp, pp must be 1)")
        self._pin_layouts = self._use_kernel

        # Page pool: physical page 0 is the trash page (never allocated);
        # the allocator hands out 1..n_pages-1.
        self._n_pages = 1 + self._resolve_pool_pages()
        self._free_pages = list(range(1, self._n_pages))
        # Shared-prefix page reuse over the pool above. Mutated only on
        # the serve-loop thread; reset() swaps in a fresh instance.
        self._prefix_cache = (PrefixCache(page) if cfg.prefix_cache
                              else None)
        # Tiered KV store (engine/kv_tier.py): env beats config beats
        # the disabled default — with 0 the tier object never exists
        # and every tier code path below is skipped, preserving the
        # untiered engine byte-for-byte (pinned by the parity test).
        env_host = os.environ.get("KV_HOST_POOL_TOKENS", "")
        host_tokens = (int(env_host) if env_host
                       else (cfg.kv_host_pool_tokens or 0))
        self._kv_tier: Optional[KVTier] = None
        if self._prefix_cache is not None and host_tokens > 0:
            mcfg = self.model_cfg
            self._kv_tier = KVTier(
                page_size=page, host_pool_tokens=host_tokens,
                bytes_per_token=self._kv_bytes_per_token(),
                meta={"kv_quant": cfg.kv_quant,
                      "num_layers": mcfg.num_layers,
                      "num_kv_heads": mcfg.num_kv_heads,
                      "head_dim": mcfg.head_dim,
                      "dtype": cfg.dtype},
                transfer_max_pages=int(os.environ.get(
                    "KV_TRANSFER_MAX_PAGES", "32") or 32),
                transfer_timeout_s=float(os.environ.get(
                    "KV_TRANSFER_TIMEOUT_S", "5") or 5))
        # Disaggregation role: env beats config (the bench builds mixed
        # fleets via per-engine configs; deployments roll roles via
        # ENGINE_ROLE). "unified" changes nothing anywhere — the role
        # paths below are all gated on it. A prefill-role engine rejects
        # decode-bound requests at submit (more output tokens than the
        # ROLE_PREFILL_MAX_TOKENS cap): its whole mesh belongs to the
        # prefill wall; decode rounds stream from the decode pool.
        env_role = (os.environ.get("ENGINE_ROLE", "") or "").strip().lower()
        if env_role and env_role not in ("unified", "prefill", "decode"):
            raise ConfigError(
                f"ENGINE_ROLE={env_role!r} not supported; use 'unified', "
                f"'prefill', or 'decode' (docs/disaggregation.md)")
        self.role: str = env_role or cfg.role
        self._role_prefill_max_tokens = max(1, int(os.environ.get(
            "ROLE_PREFILL_MAX_TOKENS", "4") or 4))
        # Page gather/scatter programs for the tier (built lazily; jit
        # re-specializes per padded page-count rung automatically).
        # _io_rungs tracks scatter rungs already compiled: a rung's
        # FIRST dispatch pays jit compile inside the measured wall, and
        # feeding that into the h2d EWMA would price every later
        # restore as if it compiled too (observed: one cold 32-page
        # restore taught the calibrator 23 ms/page and the pricing
        # refused all restores thereafter).
        self._gather_fn = None
        self._scatter_fn = None
        self._io_rungs: set = set()
        # Control-op queue: suspend/resume/export mutate serve-loop-
        # owned structures (prefix cache, free pages, device state), so
        # callers funnel closures here; the loop executes them between
        # rounds. On a stopped engine they run inline (single-threaded).
        self._control: "queue.Queue[tuple]" = queue.Queue()
        self._state = self._init_device_state()
        self._base_key = jax.random.key(cfg.seed)
        self._step_counter = itertools.count()
        # Flight recorder override for per-request timelines (None = the
        # process-global obs_flight.RECORDER, resolved at USE time so a
        # swapped global never splits one request across two recorders);
        # tests install a private instance via the `flight` setter.
        self._flight_override: Optional[obs_flight.FlightRecorder] = None

        self._fused_rag = None           # set by enable_fused_rag()
        self._rag_jit = None
        self._slots: dict[int, _Request] = {}
        self._free_slots = list(range(B))
        self._pending: "queue.Queue[tuple[_Request, SamplingParams]]" = (
            queue.Queue(maxsize=cfg.max_queue))
        # Scheduler-owned admission backlog: _pull_pending drains the
        # thread-safe intake queue here (bounded by max_queue, so the
        # intake still sheds 429s under pressure) and the token-budget
        # scheduler orders it by deadline slack each round.
        self._backlog: list[tuple[_Request, SamplingParams]] = []
        self._arrival_seq = itertools.count()
        # Token-budget continuous scheduler (engine/scheduler.py): env
        # overrides beat the config fields beat the PROFILE-derived
        # default, mirroring the BENCH_* knob convention.
        env_budget = os.environ.get("SCHED_ROUND_BUDGET_TOKENS", "")
        env_chunk = os.environ.get("SCHED_PREFILL_CHUNK_TOKENS", "")
        # Online cost calibration (SCHED_ONLINE_CALIB, default on): the
        # artifact prior seeds the model; measured per-round costs from
        # the round recorder blend it toward this deployment's reality
        # and recalibrate() re-derives the budget between rounds.
        # =0 pins the static model — the pre-calibration behavior.
        # The prior is TOPOLOGY-KEYED: a tp-sharded engine loads the
        # artifact row measured at its own mesh shape
        # (tools/profile_decode.py --mesh), so the budget the first
        # rounds run under — before the calibrator has evidence — is
        # derived from the right hardware, not the single-chip row.
        cost_prior = StepCostModel.load(topology=topology_key(
            dict(mesh.shape) if mesh is not None else None))
        self._calib = (OnlineCalibrator(cost_prior)
                       if online_calib_enabled() else None)
        self._sched = TokenBudgetScheduler(
            cost_prior, page_size=page,
            steps_per_round=cfg.steps_per_round,
            round_budget_tokens=(int(env_budget) if env_budget
                                 else cfg.sched_round_budget_tokens),
            chunk_tokens=(int(env_chunk) if env_chunk
                          else cfg.sched_prefill_chunk_tokens),
            max_one_shot_tokens=self._buckets[-1],
            calibrator=self._calib)
        # Round telemetry (obs/rounds.py): per-round plan+execution
        # records behind GET /debug/rounds, the engine_round_* metric
        # surface, and the calibrator's evidence. Override-able like the
        # flight recorder (tests install private instances).
        self._rounds_override: Optional[obs_rounds.RoundRecorder] = None
        self._engine_tag = f"e{next(_ENGINE_TAGS)}"
        # Inputs of the per-round HBM-traffic estimate: weight bytes
        # streamed once per decode step, KV page bytes per touched page,
        # and the chip's peak bandwidth (0 on CPU — no roofline there).
        self._param_bytes = sum(
            int(x.nbytes) for x in jax.tree.leaves(self.params))
        try:
            dev0 = (self.mesh.devices.flat[0] if self.mesh is not None
                    else jax.local_devices()[0])
            self._hbm_peak = (0.0 if dev0.platform == "cpu"
                              else peak_bw(dev0))
        except Exception:  # noqa: BLE001 — telemetry must not block build
            self._hbm_peak = 0.0
        # Model-vs-measured drift: EWMA of (round wall / modeled round
        # cost), updated per completed round on the harvest thread.
        # Tracked even with calibration pinned off — drift against a
        # deliberately static model is exactly the regression signal.
        self._drift_ratio: Optional[float] = None
        self._drift_dump_ratio = float(
            os.environ.get("ROUND_DRIFT_DUMP_RATIO", "8") or 0)
        self._slow_round_ms = float(
            os.environ.get("ROUND_SLOW_MS", "0") or 0)
        # Harvest pipeline: the scheduler enqueues each dispatched
        # program's output (first-token scalars, decode-round token
        # blocks) onto ``_harvest_q`` in dispatch order; the harvest
        # worker blocks on the host copies there, OFF the scheduling
        # path, and posts finish decisions back on ``_completed`` for
        # the scheduler to retire (slot/page/device bookkeeping stays
        # single-threaded). FIFO order across both item kinds preserves
        # per-request token order. reset() swaps in fresh queues so a
        # disowned worker's stale mutations land on garbage.
        self._harvest_q: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._completed: "queue.Queue[tuple[_Request, str]]" = queue.Queue()
        self._inflight_rounds = 0   # decode rounds dispatched, unharvested
        self._pipe_lock = threading.Lock()
        self._wake = threading.Event()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._harvest_thread: Optional[threading.Thread] = None
        # Liveness watchdog (docs/robustness.md): work queued/in-flight
        # while the progress counters stay frozen past the threshold
        # flips ``stalled`` (chains/server.py /health answers 503 on it)
        # and dumps thread stacks. 0 disables — the default, because a
        # legitimate first-time compile on a slow host looks exactly
        # like a stall to any timer.
        self._watchdog_stall_s = float(os.environ.get(
            "ENGINE_WATCHDOG_STALL_S", "0") or 0)
        self._watchdog_thread: Optional[threading.Thread] = None
        self._stalled = False
        self._fatal: Optional[BaseException] = None
        # Loop generation: reset() bumps it to disown wedged threads —
        # a stale loop drops its writes and exits when it unsticks.
        self._gen = 0

        self._stats_lock = threading.Lock()
        self._stats = dict(_STATS_TEMPLATE)  # keys doc-checked, see above
        # Construction instant for the uptime_s stat — mirrored as the
        # engine_uptime_s gauge so restarts are visible in /debug/history
        # (a counter reset joins an uptime drop in the same sample).
        self._created_monotonic = time.monotonic()
        self._stats["sched_round_budget_tokens"] = \
            self._sched.round_budget_tokens
        # Decode-attention page windows: power-of-two ladder up to the max.
        self._windows = _pow2_ladder(self._pmax)

        # Fused vocab-tiled unembed+sampling tail (ops/fused_sampler.py).
        # Under a tp mesh the lm_head shards over the vocab axis, so the
        # tail runs SHARDED (fused_unembed_sample_tp): each chip streams
        # its own vocab shard's 32-aligned tiles, folds penalties/masks
        # locally, and the running argmax / Gumbel-top-k candidate carry
        # + logsumexp merge with one small (B, cand_k) cross-chip
        # collective at the end — (B, V) still never materializes on ANY
        # chip (re-pinned by the sharded jaxpr memory proof). Geometries
        # whose vocab cannot split into whole 32-token mask words per
        # shard downgrade to the materialized tail — observably, via
        # _note_downgrade, never as a silent comment-only fallback.
        # ENGINE_FUSED_SAMPLER=0 forces the materialized tail anywhere
        # (it doubles as the parity oracle in tests).
        want_fused = os.environ.get("ENGINE_FUSED_SAMPLER", "1") != "0"
        tp_size = (int(dict(mesh.shape).get("tp", 1))
                   if mesh is not None else 1)
        self._tail_sharded = False
        self._head_specs = None
        if want_fused and tp_size > 1:
            if tp_shardable(model_cfg.vocab_size, tp_size):
                self._tail_sharded = True
                self._head_specs = llama.lm_head_specs(self.params, mesh)
            else:
                want_fused = False
                self._note_downgrade(
                    "fused_sampler", "materialized_tail",
                    f"vocab_size={model_cfg.vocab_size} does not split "
                    f"over tp={tp_size} into whole 32-token mask words")
        self._fused_tail = want_fused
        # Speculative decoding (engine/spec_decode.py): host-side
        # prompt-lookup drafting + a batched verify round scoring
        # S = max_draft + 1 positions per slot in ONE model step. Runs
        # on single-chip AND tp-sharded engines: the verify tail rides
        # the same fused (sharded) or materialized sampler path as the
        # decode tail, with identical greedy-token / rejection-sampling
        # distribution guarantees (parity re-pinned on a sharded
        # engine). ENGINE_SPEC_DECODE=0 restores the exact plain path.
        self._spec: Optional[SpecConfig] = None
        if spec_enabled(cfg.spec_decode):
            self._spec = SpecConfig.resolve(cfg.spec_max_draft_tokens)
        self._spec_S = (self._spec.max_draft_tokens + 1) if self._spec \
            else 0
        # Draft plan staged between _plan_round and _execute_plan
        # (serve-loop thread only): {slot: [draft token ids]}.
        self._draft_plan: Optional[dict] = None
        # Active-row ladder for the fused tail: decode rounds gather the
        # armed slots into the smallest rung >= the live count, so the
        # unembed/sampling tail is sized to OCCUPANCY, not max_slots.
        # Two rungs only — {1, B} — on purpose: every rung multiplies
        # the decode-round compile ladder (each (window, steps, greedy)
        # variant recompiles per rung, seconds of serve-loop stall per
        # crossing on a real model), while the tail's cost is dominated
        # by the row-count-INDEPENDENT lm_head tile stream, so the
        # single-stream rung captures nearly all the win. prewarm()
        # compiles both rungs through the real serving path.
        self._ba_ladder = (1, B) if B > 1 else (1,)

        self._build_jitted()

    def _ba_for(self, n: int) -> int:
        """Smallest active-row rung covering ``n`` armed slots."""
        n = max(1, n)
        return next(b for b in self._ba_ladder if b >= n)

    def _note_downgrade(self, feature: str, fallback: str,
                        reason: str) -> None:
        """Record a construction-time feature downgrade OBSERVABLY: one
        structured ``engine_feature_downgrade`` log event plus the
        doc-fenced ``engine_downgrades`` stat (derived from this list at
        read time). A downgraded engine still serves correctly, just
        below its hardware's potential — which used to hide in code
        comments (the PR-8/9 "mesh keeps the materialized tail" gates)
        instead of in telemetry."""
        self._downgrades.append(
            {"feature": feature, "fallback": fallback, "reason": reason})
        log_event(logger, "engine_feature_downgrade", feature=feature,
                  fallback=fallback, reason=reason)

    @property
    def downgrades(self) -> list[dict]:
        """Construction-time feature downgrades (copies)."""
        return [dict(d) for d in self._downgrades]

    # -------------------------------------------------- fused tail dispatch

    def _tail_sample(self, params, ha, key, *, temp, top_k, top_p,
                     rep_pen, seen_words, banned_words, ban_tok, ban_hit,
                     greedy: bool):
        """One fused unembed+sample call over already-normed hidden rows
        ``ha`` (rows, D), routed to the single-chip tile stream or — on
        a tp mesh — the sharded stream whose per-chip carries merge with
        one small collective (ops/fused_sampler.py). Traced inside the
        decode/verify round programs."""
        mcfg = self.model_cfg
        V = mcfg.vocab_size
        if self._tail_sharded:
            return fused_unembed_sample_tp(
                self.mesh, "tp", llama.lm_head_subtree(params),
                self._head_specs,
                lambda head, rows, t0, tile: llama.lm_head_tile(
                    head, mcfg, rows, t0, tile),
                V, hn=ha, key=key, temp=temp, top_k=top_k, top_p=top_p,
                rep_pen=rep_pen, seen_words=seen_words,
                banned_words=banned_words, ban_tok=ban_tok,
                ban_hit=ban_hit, greedy=greedy)
        return fused_unembed_sample(
            lambda t0, tile: llama.lm_head_tile(params, mcfg, ha, t0,
                                                tile),
            V, key=key, temp=temp, top_k=top_k, top_p=top_p,
            rep_pen=rep_pen, seen_words=seen_words,
            banned_words=banned_words, ban_tok=ban_tok, ban_hit=ban_hit,
            greedy=greedy)

    def _tail_verify(self, params, ha, key, u, *, temp, top_k, top_p,
                     rep_pen, seen_words, banned_words, draft_ids,
                     ban_tok, ban_hit):
        """One fused verification call (rejection-sampling verdicts per
        scored row) — same single-chip/sharded routing as
        :meth:`_tail_sample`."""
        mcfg = self.model_cfg
        V = mcfg.vocab_size
        if self._tail_sharded:
            return fused_verify_sample_tp(
                self.mesh, "tp", llama.lm_head_subtree(params),
                self._head_specs,
                lambda head, rows, t0, tile: llama.lm_head_tile(
                    head, mcfg, rows, t0, tile),
                V, hn=ha, key=key, u=u, temp=temp, top_k=top_k,
                top_p=top_p, rep_pen=rep_pen, seen_words=seen_words,
                banned_words=banned_words, draft_ids=draft_ids,
                ban_tok=ban_tok, ban_hit=ban_hit)
        return fused_verify_sample(
            lambda t0, tile: llama.lm_head_tile(params, mcfg, ha, t0,
                                                tile),
            V, key=key, u=u, temp=temp, top_k=top_k, top_p=top_p,
            rep_pen=rep_pen, seen_words=seen_words,
            banned_words=banned_words, draft_ids=draft_ids,
            ban_tok=ban_tok, ban_hit=ban_hit)

    def _init_device_state(self) -> dict:
        """Fresh device-side scheduler state (cache pool + slot arrays).
        Used at construction and by ``reset()`` after an abandoned loop —
        donated buffers from a wedged thread are unusable, so recovery
        means rebuilding, not reusing."""
        B = self.cfg.max_slots
        mcfg, mesh = self.model_cfg, self.mesh
        cache = llama.init_paged_kv_cache(mcfg, self._n_pages,
                                          self.cfg.page_size, self._dtype,
                                          quantized=self._kv_quant)
        # Distinct arrays per field: donated jit args must not alias.
        state = {
            "cache": cache,
            "table": jnp.zeros((B, self._pmax), jnp.int32),
            "pos": jnp.zeros((B,), jnp.int32),
            "last_token": jnp.zeros((B,), jnp.int32),
            "active": jnp.zeros((B,), bool),
            "remaining": jnp.zeros((B,), jnp.int32),
            "eos_ok": jnp.zeros((B,), bool),
            "temp": jnp.zeros((B,), jnp.float32),
            "top_k": jnp.zeros((B,), jnp.int32),
            "top_p": jnp.zeros((B,), jnp.float32),
            "rep_pen": jnp.ones((B,), jnp.float32),
            # Seen/banned vocab masks as uint32 BITFIELDS (32 tokens per
            # word, ops/sampling.py pack_mask): 1 bit per token instead
            # of a byte-bool — 8x less mask state and per-step mask
            # traffic, and the fused sampler slices whole words per
            # vocab tile.
            "seen": jnp.zeros((B, mask_words(mcfg.vocab_size)),
                              jnp.uint32),
            "banned": jnp.zeros((B, mask_words(mcfg.vocab_size)),
                                jnp.uint32),
            # Multi-token bad-words: per-slot sequence table (padded with
            # -1), per-sequence lengths, and a ring of the last L-1
            # generated tokens the match runs against. -1 padding can never
            # equal a real token id, so "not enough history yet" needs no
            # separate mask.
            "bad_seq": jnp.full((B, self.MAX_BAD_SEQS, self.MAX_BAD_LEN),
                                -1, jnp.int32),
            "bad_len": jnp.zeros((B, self.MAX_BAD_SEQS), jnp.int32),
            "recent": jnp.full((B, self.MAX_BAD_LEN - 1), -1, jnp.int32),
        }
        if mesh is not None:
            cache_specs = paged_kv_cache_spec(
                mcfg, mesh, quantized=self._kv_quant)
            state = {
                k: (jax.tree.map(
                        lambda x, s: jax.device_put(
                            x, self._cache_placement(
                                NamedSharding(mesh, s), x.ndim)),
                        v, cache_specs) if k == "cache"
                    else jax.device_put(v, NamedSharding(mesh, P())))
                for k, v in state.items()}
        elif self._pin_layouts:
            from jax.sharding import SingleDeviceSharding
            dev_sharding = SingleDeviceSharding(jax.local_devices()[0])
            state["cache"] = jax.tree.map(
                lambda x: jax.device_put(
                    x, self._cache_placement(dev_sharding, x.ndim)),
                state["cache"])
        return state

    # ------------------------------------------------------------- layouts

    def _cache_placement(self, sharding, ndim: int = 5):
        """device_put target for pool leaves: row-major-pinned when the
        Pallas kernel is in play, plain sharding otherwise. Scale pools
        (int8-KV mode) are 4D; their layout pins row-major too."""
        if not self._pin_layouts:
            return sharding
        format_for, _ = _layout_api()
        return format_for(ndim, sharding)

    def _pin_cache(self, cache):
        """Constrain pool leaves to row-major inside a jitted program so
        every producer hands the next program (and Pallas) the same
        physical layout — no inter-program relayout copies. On jax
        versions without with_layout_constraint this is a no-op (the
        device_put pin in _cache_placement still applies)."""
        if not self._pin_layouts:
            return cache
        _, constrain = _layout_api()
        if constrain is None:
            return cache
        return {k: constrain(v) for k, v in cache.items()}

    # -------------------------------------------------------------- sizing

    # Per-chip HBM by device kind (public specs), used when the platform
    # doesn't report memory_stats (e.g. tunneled devices return None and
    # allocate lazily, so OOM only surfaces at first execution).
    _HBM_BY_KIND = (
        ("v5 lite", 16 << 30), ("v5e", 16 << 30),
        ("v5p", 95 << 30),
        ("v6 lite", 32 << 30), ("v6e", 32 << 30),
        ("v4", 32 << 30), ("v3", 32 << 30), ("v2", 16 << 30),
    )

    def _kv_bytes_per_token(self, pooled: bool = True) -> int:
        """KV bytes per cached token. ``pooled``: bytes in the page pool
        (int8 + bf16 scales under kv_quant); False: the DENSE bytes of
        prefill-bucket KV, which stays at the compute dtype — the
        quantization happens at insert, so sizing the prefill headroom
        with pooled bytes would under-reserve by ~2x in quant mode (the
        r5 32-slot OOM)."""
        mcfg = self.model_cfg
        if pooled and self._kv_quant:
            # int8 K+V rows + one bf16 scale each (ops/kv_quant.py)
            return (mcfg.num_layers * mcfg.num_kv_heads
                    * 2 * (mcfg.head_dim + 2))
        return (mcfg.num_layers * mcfg.num_kv_heads * mcfg.head_dim
                * 2 * self._dtype.itemsize)

    def _pool_shard_factor(self) -> int:
        """How many ways the page pool is actually split across devices —
        NOT the device count: pages replicate across dp, and KV heads only
        shard over tp when divisible (parallel/sharding.py:
        paged_kv_cache_spec P(pp, None, kv_tp, None, None))."""
        if self.mesh is None:
            return 1
        mcfg = self.model_cfg
        factor = 1
        if "tp" in self.mesh.shape:
            tp = self.mesh.shape["tp"]
            if tp > 1 and mcfg.num_kv_heads % tp == 0:
                factor *= tp
        if "pp" in self.mesh.shape:
            pp = self.mesh.shape["pp"]
            if pp > 1 and mcfg.num_layers % pp == 0:
                factor *= pp
        return factor

    def _free_hbm_bytes(self):
        """Best-effort estimate of HBM available to the GLOBAL pool, or
        None.

        Free bytes are measured per device (memory_stats when available;
        else a device-kind HBM table minus that device's resident share of
        live arrays) and scaled by the pool's shard factor — a pool
        replicated across dp must fit per device, so multiplying by the
        device count would oversubscribe every replica. The 0.92 factor
        models the runtime's reserved slice of HBM."""
        try:
            dev0 = (self.mesh.devices.flat[0] if self.mesh is not None
                    else jax.local_devices()[0])
            factor = self._pool_shard_factor()
            stats = dev0.memory_stats()
            if stats and "bytes_limit" in stats:
                per_dev = int(stats["bytes_limit"]
                              - stats.get("bytes_in_use", 0))
                return per_dev * factor
            kind = getattr(dev0, "device_kind", "").lower()
            total = next((b for key, b in self._HBM_BY_KIND if key in kind),
                         None)
            if total is None:
                return None
            # No memory_stats => tunneled runtime: its reserves measure
            # ~2.5-3 GB beyond the usual runtime slice (r5 ceiling probes:
            # ~11.5 GB of 16 GB actually serveable), and a serving OOM is
            # unrecoverable in-process (see _probe_pool_pages) — so the
            # blind-estimate path takes the deep haircut. Deployments
            # needing every page pin kv_pool_tokens explicitly, the way
            # the reference hand-tunes kv_cache_free_gpu_mem_fraction.
            total = int(total * 0.87)
            live = 0
            for a in jax.live_arrays():
                try:
                    # Metadata only: touching shard.data on a tunneled
                    # device can fail silently and undercount (round-4
                    # pool overshoot OOM), so estimate each array's share
                    # of this device from its sharding instead.
                    devs = getattr(a.sharding, "device_set", None)
                    if devs and dev0 in devs:
                        live += a.nbytes // max(1, len(devs))
                except Exception:
                    continue
            return (int(total * 0.92) - live) * factor
        except Exception:
            return None

    def _headroom_bytes(self) -> int:
        """Peak transient bytes the engine needs beyond params + pool: the
        largest prefill bucket's contiguous KV — live THREE ways at the
        prefill->insert overlap (prefill output, insert's page-shaped
        relayout copies, the scatter in flight) — plus prefill
        logits/activations and the decode round's gathered page window.
        Prefill attention is chunked (ops/attention.py), so no S^2 score
        tensor appears here. Without this reserve the "auto" pool claims
        HBM the first dispatch then fights over (round-2 bench OOM)."""
        cfg, mcfg = self.cfg, self.model_cfg
        S = max(self._buckets)
        bucket_cache = S * self._kv_bytes_per_token(pooled=False)
        logits = S * mcfg.vocab_size * 4
        acts = S * mcfg.hidden_size * 64
        # The gathered page window only exists on the jnp fallback path;
        # the Pallas kernel streams pages through VMEM and never
        # materializes it — reserving for it there starves the pool
        # (the 16-slot throughput collapse, VERDICT r3 weak #2).
        gather = 0 if self._use_kernel else (
            cfg.max_slots * self._pmax * cfg.page_size
            * mcfg.num_kv_heads * mcfg.head_dim * 2 * self._dtype.itemsize)
        # int8-KV insert quantizes the bucket per-row; XLA sequences the
        # K and V transforms, so ~one bucket's f32 copy is live at once
        quant = bucket_cache if self._kv_quant else 0
        # 1.5x the bucket cache: the cache itself plus in-flight copy
        # slack at the prefill->insert overlap. (The former 3x model,
        # cross-checked against r5's measured serving ceilings, over-
        # reserved by ~2 GB at a 2048 bucket and floor-collapsed the
        # auto pool when an embedder shared the chip.)
        return int(1.5 * bucket_cache) + logits + acts + gather + quant \
            + (256 << 20)

    def _resolve_pool_pages(self) -> int:
        # The resolved pool is the ONLY capacity budget: the prefix
        # cache's warm (refcount-0) pages live inside it and are evicted
        # back to the free list under admission pressure, so no extra
        # headroom is reserved for caching (engine/prefix_cache.py).
        cfg = self.cfg
        full = cfg.max_slots * self._pmax
        spec = cfg.kv_pool_tokens
        if spec is None:
            return full
        if isinstance(spec, int):
            return min(full, max(self._pmax, _ceil_div(spec, cfg.page_size)))
        # "auto": fit the pool to free device memory after an explicit
        # headroom reserve (the reference sizes its paged pool via
        # kv_cache_free_gpu_mem_fraction; same idea, with the reserve made
        # explicit instead of a blanket fraction).
        free = self._free_hbm_bytes()
        if free is None:
            return full
        # Safety multiplier on the post-headroom budget. Quant mode runs
        # 0.8: its serving peak was measured ~1.5 GB past the modeled
        # headroom on v5e (r5: estimate said 141+ pages, the true ceiling
        # sat between 130 and 150), and on tunneled backends one serving
        # OOM is unrecoverable in-process — see _probe_pool_pages.
        margin = 0.8 if self._kv_quant else 0.9
        budget = int((free - self._headroom_bytes()) * margin)
        pages = budget // (cfg.page_size * self._kv_bytes_per_token())
        return self._probe_pool_pages(min(full, max(self._pmax, pages)))

    def _probe_pool_pages(self, pages: int) -> int:
        """Validate an estimated pool size by ACTUALLY allocating (and
        freeing) pool-plus-headroom bytes before the pool exists.

        The estimate can overshoot (tunneled devices report no
        memory_stats), and on this backend a mid-serving OOM is
        unrecoverable in-process: buffers freed afterward never return to
        the allocator, so prewarm's shrink-retry can only rescue healthy
        backends (measured r5: after one serving OOM, even a 3.4 GB
        allocation fails forever while live arrays total 6.9/16 GB). A
        FAILED plain allocation leaks nothing — no program ran — so
        probing first converges to a safe size without ever poisoning the
        device. The probe is one contiguous array, slightly conservative
        vs the fragmented real peak."""
        cfg = self.cfg
        page_bytes = cfg.page_size * self._kv_bytes_per_token()
        shard = self._pool_shard_factor()
        head = self._headroom_bytes()
        floor = self._pmax
        while pages > floor:
            want = pages * page_bytes // shard + head
            try:
                probe = jnp.zeros((want,), jnp.int8)
                jax.block_until_ready(probe)
                del probe
                return pages
            except Exception as exc:  # noqa: BLE001 — filtered below
                if "RESOURCE_EXHAUSTED" not in str(exc):
                    return pages
                import sys as _sys
                shrunk = max(floor, int(pages * 0.85))
                _sys.stderr.write(
                    f"engine pool probe: {pages} pages + headroom does "
                    f"not allocate; trying {shrunk}\n")
                pages = shrunk
        return pages

    def prewarm(self, max_retries: int = 4) -> None:
        """Verify the pool sizing by actually SERVING a worst-case dummy
        request through the real loop (max-length prompt, full decode
        rounds, dispatch-ahead overlap), shrinking the pool ~20% and
        rebuilding on RESOURCE_EXHAUSTED.

        Allocation on tunneled TPU devices is lazy and ``memory_stats``
        is unavailable, so any free-HBM *estimate* can overshoot and the
        OOM only surfaces mid-serving (round-3/4 bench failures). No
        synthetic pass reproduces the pipeline's true high-water mark
        (measured ~2 GB above a sequential replay of the same programs) —
        so the verification IS the serving path. Call before serving;
        idempotent. Must not be called while the engine loop is running."""
        if self._thread is not None and self._thread.is_alive():
            raise EngineError("prewarm() requires a stopped engine")
        for attempt in range(max_retries + 1):
            try:
                if attempt:
                    # Rebuild at the shrunken size INSIDE the try: the
                    # rebuild's own allocations can OOM too (old donated
                    # buffers may still be resident on a lazy-allocating
                    # tunneled device), and that must consume a retry and
                    # shrink again, not abort the whole prewarm (the r5
                    # 32-slot bench died exactly here).
                    self.reset()
                    self._stopped.clear()
                self._verify_alloc()
                return
            except Exception as exc:  # noqa: BLE001 — filtered below
                if "RESOURCE_EXHAUSTED" not in str(exc) or \
                        attempt == max_retries:
                    raise
                new_pages = max(self._pmax + 1,
                                int((self._n_pages - 1) * 0.8) + 1)
                if new_pages >= self._n_pages:
                    raise
                import sys as _sys
                _sys.stderr.write(
                    f"engine prewarm: pool of {self._n_pages - 1} pages "
                    f"OOMs in serving; retrying with {new_pages - 1}\n")
                # The caught exception's traceback frames pin device
                # arrays (prefill outputs, old state) — drop them before
                # the rebuild allocates the replacement pool.
                exc = None  # noqa: F841
                self._n_pages = new_pages

    def _verify_alloc(self) -> None:
        """Serve one worst-case request for real — max-length prompt,
        enough tokens for full decode rounds — while holding a slack
        allocation, so the accepted sizing has genuine headroom beyond
        the pipeline's measured peak."""
        slack = jnp.zeros(((256 << 20),), jnp.int8)
        jax.block_until_ready(slack)
        self.start()
        try:
            ids = [min(3, self.model_cfg.vocab_size - 1)
                   ] * self.cfg.max_input_length
            from .sampling_params import SamplingParams as _SP
            stream = self.submit(ids, _SP(
                max_tokens=min(self.cfg.max_output_length,
                               2 * self.cfg.steps_per_round + 1),
                top_k=1, ignore_eos=True),
                request_id="engine-prewarm")  # recognizable in /debug
            try:
                for _ in stream:
                    pass
            except EngineError as exc:
                # Unwrap: prewarm's caller matches on RESOURCE_EXHAUSTED,
                # which lives in the loop's fatal, not the stream wrapper.
                raise (self._fatal or exc) from exc
            if stream.finish_reason == "error":
                raise self._fatal or EngineError("prewarm serve failed")
            # Warm the FULL-WIDTH active-row rung through the real path:
            # the request above compiled the single-stream decode round
            # (ba rung 1); two short concurrent streams force a
            # multi-slot round so the first real occupancy crossing
            # doesn't pay that compile on the serve loop mid-traffic.
            dummies = 1
            if self.cfg.max_slots > 1 and self._fused_tail:
                pair = [self.submit(
                    ids[:min(16, len(ids))], _SP(
                        max_tokens=self.cfg.steps_per_round + 1,
                        top_k=1, ignore_eos=True),
                    request_id=f"engine-prewarm-b{i}") for i in range(2)]
                dummies += 2
                for s in pair:
                    for _ in s:
                        pass
                    if s.finish_reason == "error":
                        raise self._fatal or EngineError(
                            "prewarm rung warm failed")
        finally:
            try:
                self.stop()
            except Exception:  # noqa: BLE001 — post-fatal cleanup only
                pass
            del slack
        # Scrub the dummies from served stats.
        with self._stats_lock:
            self._stats["requests"] -= dummies

    @property
    def flight(self) -> obs_flight.FlightRecorder:
        """Flight recorder in use: the process-global one unless a
        private instance was installed (tests). Resolved per access so
        the engine and the HTTP servers always agree on the recorder."""
        return self._flight_override or obs_flight.RECORDER

    @flight.setter
    def flight(self, recorder: obs_flight.FlightRecorder) -> None:
        self._flight_override = recorder

    @property
    def rounds(self) -> obs_rounds.RoundRecorder:
        """Round recorder in use: the process-global one unless a
        private instance was installed (tests) — same resolution rule
        as the flight recorder."""
        return self._rounds_override or obs_rounds.RECORDER

    @rounds.setter
    def rounds(self, recorder: obs_rounds.RoundRecorder) -> None:
        self._rounds_override = recorder

    @property
    def engine_tag(self) -> str:
        """This engine's tag on its round-telemetry records — the
        ``?engine=`` filter value for ``/debug/rounds`` in multi-engine
        processes, and what bench's per-engine aggregation scopes by."""
        return self._engine_tag

    @property
    def stats(self) -> dict[str, float]:
        with self._stats_lock:
            out = dict(self._stats)
        with self._pipe_lock:
            # Instantaneous device-queue depth: decode rounds dispatched
            # but not yet harvested. >0 during steady decode means the
            # device never goes idle waiting for the host.
            out["dispatch_queue_depth"] = self._inflight_rounds
        # Queued WORK awaiting admission: intake + scheduler backlog —
        # the leading congestion signal the router's load score and the
        # autoscaler's queue trigger read (dispatch_queue_depth alone
        # saturates at dispatch_depth and reads "2" on a replica
        # drowning in queued prefills). len()/qsize() are GIL-atomic;
        # this is a snapshot, not an admission decision.
        out["queue_waiting"] = len(self._backlog) + self._pending.qsize()
        # Scheduler mix: what share of the budgeted work was prefill.
        sched_total = out["sched_prefill_tokens"] + out["sched_decode_tokens"]
        out["sched_prefill_share"] = (
            round(out["sched_prefill_tokens"] / sched_total, 4)
            if sched_total else 0.0)
        # Speculative decoding: acceptance rate over all drafted tokens,
        # and tokens emitted per verify slot-step (>1 = the speculative
        # multiplier is real; 0.0 until the first verify round runs).
        out["spec_acceptance_rate"] = (
            round(out["spec_accepted_tokens"]
                  / out["spec_draft_tokens"], 4)
            if out["spec_draft_tokens"] else 0.0)
        out["spec_tokens_per_step"] = (
            round(out["spec_verify_tokens"]
                  / out["spec_verify_slot_steps"], 4)
            if out["spec_verify_slot_steps"] else 0.0)
        # Construction-time feature downgrades — derived from the list
        # (written once at build, before any reader exists).
        out["downgrades"] = len(self._downgrades)
        # Model-vs-measured drift over completed rounds: 1.0 = the
        # step-cost model predicts round time; >1 = rounds run slower
        # than planned (regression, or a stale artifact prior); 0.0
        # until the first round completes.
        drift = self._drift_ratio
        out["sched_cost_drift_ratio"] = (round(drift, 4)
                                         if drift is not None else 0.0)
        cache = self._prefix_cache
        if cache is not None:
            # Cache counters are written only on the serve-loop thread;
            # reading them here without its lock can tear between fields
            # by at most one in-flight admission — fine for metrics.
            out.update(cache.stats.snapshot())
            out["prefix_cache_pages"] = cache.cached_pages
        # KV tier (engine/kv_tier.py): live host-store occupancy and the
        # restore-hit rate — what fraction of prefix lookups the host
        # tier turned into restored pages instead of recompute.
        tier = self._kv_tier
        out["kv_tier_host_pages"] = tier.store.pages if tier else 0
        lookups = out.get("prefix_cache_lookups", 0)
        out["kv_restore_hit_rate"] = (
            round(out["kv_tier_restore_hits"] / lookups, 4)
            if lookups else 0.0)
        # Engine age: mirrored as engine_uptime_s — the restart marker
        # history/alert consumers join cumulative-counter resets against.
        out["uptime_s"] = round(
            time.monotonic() - self._created_monotonic, 3)
        return out

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self._stats[key] += n

    # ------------------------------------------------------------------ jit

    def _build_jitted(self) -> None:
        cfg, mcfg = self.cfg, self.model_cfg
        page = cfg.page_size
        eos = int(self.tokenizer.eos_id)
        B = cfg.max_slots
        L = mcfg.num_layers

        sp_mesh = (self.mesh is not None
                   and int(dict(self.mesh.shape).get("sp", 1)) > 1)

        def prefill(params, tokens, length, temp, top_k, top_p, rep_pen,
                    banned, key, greedy: bool):
            """tokens: (1, S_bucket); returns (k,v) for the bucket, the
            sampled first token, and the prompt's seen-token mask as a
            (Wn,) uint32 bitfield. ``banned``: (Wn,) uint32 bad-words
            bitfield (unpacked transiently here — admission runs once
            per request; the per-STEP decode path never unpacks).
            ``greedy`` is a trace-time flag: the greedy variant is a
            pure argmax — no vocab sort on the TTFT-critical path.

            Under a dp×sp mesh the forward is the RING-ATTENTION prefill
            (llama.apply_prefill_sp): bucket activations shard over sp,
            so prompts beyond one device's activation budget admit as a
            single exact prefill — sp serving, not just sp scoring
            (VERDICT r4 weak #9)."""
            S = tokens.shape[1]
            positions = jnp.arange(S, dtype=jnp.int32)[None, :]
            if sp_mesh:
                k_new, v_new, last = llama.apply_prefill_sp(
                    params, mcfg, tokens, positions, self.mesh, length)
                # (L, 1, S, KV, hd) matches the dense cache layout below
                cache = {"k": k_new, "v": v_new}
                last = last[0]  # (V,)
            else:
                cache = llama.init_kv_cache(mcfg, 1, S, self._dtype)
                logits, cache = llama.apply(params, mcfg, tokens,
                                            positions, cache,
                                            kv_valid_len=length[None])
                last = jnp.take_along_axis(
                    logits,
                    (length - 1)[None, None, None].astype(jnp.int32),
                    axis=1)[0, 0]  # (V,)
            seen = seen_mask(tokens, length[None], mcfg.vocab_size)  # (1, V)
            last = apply_repetition_penalty(last[None, :], seen,
                                            rep_pen[None])
            last = jnp.where(unpack_mask(banned, mcfg.vocab_size)[None, :],
                             -1e30, last)
            if greedy:
                first_tok = jnp.argmax(last[0].astype(jnp.float32)
                                       ).astype(jnp.int32)
            else:
                first_tok = sample(last, key, temp[None], top_k[None],
                                   top_p[None])[0]
            seen = pack_mask(seen[0].at[first_tok].set(True))  # (Wn,) u32
            return cache["k"], cache["v"], first_tok, seen

        def insert(state, k_new, v_new, slot, length, first_tok,
                   temp, top_k, top_p, rep_pen, seen, banned,
                   bad_seq, bad_len, row, remaining, eos_ok):
            """Scatter a prefilled bucket into the slot's pages and arm the
            slot. ``row``: (Pmax,) physical page per logical page, padded
            with 0 (trash) — bucket overhang beyond the allocated extent
            lands in the trash page."""
            S = k_new.shape[2]
            nb = S // page
            dest = row[:nb]
            cache = state["cache"]
            # (L,1,S,KV,hd) -> (L, nb, KV, page, hd): pool layout keeps KV
            # ahead of page (see llama.init_paged_kv_cache).
            kp = k_new.reshape(L, nb, page, mcfg.num_kv_heads,
                               mcfg.head_dim).swapaxes(2, 3)
            vp = v_new.reshape(L, nb, page, mcfg.num_kv_heads,
                               mcfg.head_dim).swapaxes(2, 3)
            if self._kv_quant:
                from ..ops.kv_quant import quantize_rows
                kq, ks = quantize_rows(kp)   # scales: (L, nb, KV, page)
                vq, vs = quantize_rows(vp)
                cache = {
                    "k": cache["k"].at[:, dest].set(kq),
                    "v": cache["v"].at[:, dest].set(vq),
                    "ks": cache["ks"].at[:, dest].set(
                        ks.astype(cache["ks"].dtype)),
                    "vs": cache["vs"].at[:, dest].set(
                        vs.astype(cache["vs"].dtype)),
                }
            else:
                cache = {
                    "k": cache["k"].at[:, dest].set(
                        kp.astype(cache["k"].dtype)),
                    "v": cache["v"].at[:, dest].set(
                        vp.astype(cache["v"].dtype)),
                }
            # Device-side finish state: a slot whose first token already
            # ends it (eos, or max_tokens == 1) never activates.
            active = (remaining > 0) & ~((first_tok == eos) & eos_ok)
            return {
                "cache": self._pin_cache(cache),
                "table": state["table"].at[slot].set(row),
                "pos": state["pos"].at[slot].set(length),
                "last_token": state["last_token"].at[slot].set(first_tok),
                "active": state["active"].at[slot].set(active),
                "remaining": state["remaining"].at[slot].set(remaining),
                "eos_ok": state["eos_ok"].at[slot].set(eos_ok),
                "temp": state["temp"].at[slot].set(temp),
                "top_k": state["top_k"].at[slot].set(top_k),
                "top_p": state["top_p"].at[slot].set(top_p),
                "rep_pen": state["rep_pen"].at[slot].set(rep_pen),
                "seen": state["seen"].at[slot].set(seen),
                "banned": state["banned"].at[slot].set(banned),
                "bad_seq": state["bad_seq"].at[slot].set(bad_seq),
                "bad_len": state["bad_len"].at[slot].set(bad_len),
                # Sequence matching runs over *generated* tokens only (the
                # reference bans output occurrences): fresh ring, seeded
                # with the first sampled token.
                "recent": state["recent"].at[slot].set(
                    jnp.full((self.MAX_BAD_LEN - 1,), -1, jnp.int32)
                    .at[-1].set(first_tok)),
            }

        def bad_seq_hits(seq, blen, recent):
            """Multi-token bad-words: a sequence of length l is banned by
            masking its LAST token whenever the l-1 most recent generated
            tokens equal its prefix. Returns (hit (R, W) bool,
            tail (R, W) int32) — the compare is (R, W, L) int32, noise
            next to the vocab work around it."""
            R, W_, Lb = seq.shape
            slen = recent.shape[1]
            j = jnp.arange(Lb, dtype=jnp.int32)
            # seq position j aligns with ring index Lb - l + j
            gi = jnp.clip(Lb - blen[..., None] + j, 0, slen - 1)
            hist = jnp.take_along_axis(
                jnp.broadcast_to(recent[:, None, :], (R, W_, slen)),
                gi, axis=2)
            need = j[None, None, :] < (blen[..., None] - 1)
            hit = ((hist == seq) | ~need).all(-1) & (blen >= 2)
            tail = jnp.take_along_axis(
                seq, jnp.maximum(blen - 1, 0)[..., None], axis=2)[..., 0]
            return hit, tail

        def make_round(window: int, steps: int, greedy: bool, ba: int):
            fused = self._fused_tail
            V = mcfg.vocab_size

            def decode_round(params, state, key, act_idx):
                """K decode steps fused in one dispatch; returns (K, B)
                tokens with -1 for slots inactive at step entry. eos and
                length termination happen on-device (``active`` drops), so
                the host only needs one transfer per round.

                ``act_idx``: (ba,) armed-slot indices, padded with B
                (out of bounds: gathers clamp to a throwaway row, token
                scatters drop). The FUSED tail gathers those rows and
                runs the vocab-tiled unembed+sampler on (ba, …) shapes
                only — a half-empty engine no longer unembeds max_slots
                rows — and never materializes (B, V) penalized logits or
                bool masks (ops/fused_sampler.py; under a tp mesh the
                tile stream is SHARDED per chip with one small carry
                merge — see _tail_sample). The materialized tail remains
                for ENGINE_FUSED_SAMPLER=0 / downgraded geometries and
                as the parity oracle; the greedy variant of either tail
                is a pure argmax (no vocab sort / no sampling noise)."""
                def body(st, key_k):
                    pos, active = st["pos"], st["active"]
                    page_of = jnp.take_along_axis(
                        st["table"], (pos // page)[:, None], axis=1)[:, 0]
                    wp = jnp.where(active, page_of, 0)  # inactive -> trash
                    # Masked positions: the kernel's per-slot dynamic page
                    # loop trips ceil(pos/page) times — an inactive slot
                    # (pos -> 0) streams nothing, so dead slots cost no HBM.
                    eff_pos = jnp.where(active, pos, 0)
                    net, cache = llama.apply_decode_paged(
                        params, mcfg, st["last_token"][:, None],
                        eff_pos[:, None], st["cache"], st["table"][:, :window],
                        pos + 1, wp, eff_pos % page,
                        use_kernel=self._use_kernel, mesh=self.mesh,
                        return_hidden=fused)
                    if fused:
                        hn = llama.unembed_norm(params, mcfg,
                                                net[:, 0])       # (B, D)
                        ha = hn[act_idx]                         # (ba, D)
                        hit, tail = bad_seq_hits(st["bad_seq"][act_idx],
                                                 st["bad_len"][act_idx],
                                                 st["recent"][act_idx])
                        tok_a = self._tail_sample(
                            params, ha, key_k,
                            temp=st["temp"][act_idx],
                            top_k=st["top_k"][act_idx],
                            top_p=st["top_p"][act_idx],
                            rep_pen=st["rep_pen"][act_idx],
                            seen_words=st["seen"][act_idx],
                            banned_words=st["banned"][act_idx],
                            ban_tok=tail, ban_hit=hit, greedy=greedy)
                        # padding indices (== B) drop on scatter; rows not
                        # in act_idx are inactive, so their (unused) token
                        # defaults to 0 and every update below masks on
                        # ``active``.
                        tok = jnp.zeros((B,), jnp.int32).at[
                            act_idx].set(tok_a)
                    else:
                        penalized = apply_repetition_penalty(
                            net[:, 0], unpack_mask(st["seen"], V),
                            st["rep_pen"])
                        penalized = jnp.where(unpack_mask(st["banned"], V),
                                              -1e30, penalized)
                        hit, tail = bad_seq_hits(st["bad_seq"],
                                                 st["bad_len"],
                                                 st["recent"])
                        penalized = penalized.at[
                            jnp.arange(B)[:, None],
                            jnp.where(hit, tail, 0)].min(
                            jnp.where(hit, -1e30, jnp.inf).astype(
                                penalized.dtype))
                        if greedy:
                            tok = jnp.argmax(penalized.astype(jnp.float32),
                                             axis=-1).astype(jnp.int32)
                        else:
                            tok = sample(penalized, key_k, st["temp"],
                                         st["top_k"], st["top_p"])
                    emitted = jnp.where(active, tok, -1)
                    remaining = jnp.where(active, st["remaining"] - 1,
                                          st["remaining"])
                    finished = active & (((tok == eos) & st["eos_ok"])
                                         | (remaining <= 0))
                    new_st = dict(
                        st, cache=cache,
                        pos=jnp.where(active, pos + 1, pos),
                        last_token=jnp.where(active, tok, st["last_token"]),
                        active=active & ~finished,
                        remaining=remaining,
                        seen=set_token_bits(st["seen"], tok, active),
                        recent=jnp.where(
                            active[:, None],
                            jnp.concatenate([st["recent"][:, 1:],
                                             tok[:, None]], axis=1),
                            st["recent"]))
                    return new_st, emitted

                state, toks = jax.lax.scan(body, state,
                                           jax.random.split(key, steps))
                state = dict(state, cache=self._pin_cache(state["cache"]))
                return state, toks
            return decode_round

        def make_verify(window: int, greedy: bool, ba: int):
            """One speculative VERIFY round: score S = max_draft + 1
            positions per slot (the last accepted token + up to S-1
            prompt-lookup drafts) through one multi-token paged forward
            (llama.apply_verify_paged), run the vocab-tiled sampler on
            every scored row, and accept on-device — emitting, per
            active slot, the longest agreed draft prefix plus one
            correction/bonus token. Exactness: greedy keeps a draft iff
            it equals the row's argmax (token-identical to sequential
            decode); temperature>0 rows use exact rejection sampling
            (fused_verify_sample), so the output DISTRIBUTION matches
            the non-speculative sampler. Rollback is free: ``pos``
            advances only past consumed inputs, so rejected drafts'
            K/V rows are dead weight the next step overwrites — pages
            never advance past the last accepted token.

            Returns (state, ((S, B) emitted tokens with -1 padding —
            the classic round grid shape, so the harvest loop is
            shared — and (B,) accepted-draft counts for stats and the
            adaptive-K controllers))."""
            fused = self._fused_tail
            V = mcfg.vocab_size
            S = self._spec_S
            slen = self.MAX_BAD_LEN - 1

            def verify_round(params, state, key, act_idx, drafts, n_draft):
                pos, active = state["pos"], state["active"]
                offs = jnp.arange(S, dtype=jnp.int32)
                eff_pos = jnp.where(active, pos, 0)
                positions = eff_pos[:, None] + offs[None, :]      # (B, S)
                tokens = jnp.concatenate(
                    [state["last_token"][:, None], drafts], axis=1)
                # Writes: inactive slots and rows past the slot's draft
                # count land in the trash page.
                write_ok = active[:, None] \
                    & (offs[None, :] <= n_draft[:, None])
                page_idx = jnp.clip(positions // page, 0, self._pmax - 1)
                page_of = jnp.take_along_axis(state["table"], page_idx,
                                              axis=1)
                wp = jnp.where(write_ok, page_of, 0)
                net, cache = llama.apply_verify_paged(
                    params, mcfg, tokens, positions, state["cache"],
                    state["table"][:, :window], eff_pos + S, wp,
                    positions % page, return_hidden=fused)
                # Per-position sampler state: the seen mask / recent
                # ring row j would carry after accepting drafts 0..j-1 —
                # exactly the sequential path's (rows are only consumed
                # when every preceding draft was accepted).
                seen_list = [state["seen"]]
                recent_list = [state["recent"]]
                for j in range(1, S):
                    d = drafts[:, j - 1]
                    on = active & (j <= n_draft)
                    seen_list.append(set_token_bits(seen_list[-1], d, on))
                    recent_list.append(jnp.where(
                        on[:, None],
                        jnp.concatenate([recent_list[-1][:, 1:],
                                         d[:, None]], axis=1),
                        recent_list[-1]))
                seen_pos = jnp.stack(seen_list, axis=1)      # (B, S, Wn)
                recent_pos = jnp.stack(recent_list, axis=1)  # (B, S, sl)
                # Row j verifies draft j (the token at input j+1); -1 on
                # the bonus row (j == n_draft) and padding rows.
                drafts_ext = jnp.concatenate(
                    [drafts, jnp.full((B, 1), -1, jnp.int32)], axis=1)
                draft_grid = jnp.where(offs[None, :] < n_draft[:, None],
                                       drafts_ext, -1)
                key_g = jax.random.fold_in(key, 0)
                key_u = jax.random.fold_in(key, 1)
                if fused:
                    hn = llama.unembed_norm(params, mcfg, net)  # (B,S,D)
                    ha = hn[act_idx].reshape(ba * S, -1)
                    hit, tail = bad_seq_hits(
                        jnp.repeat(state["bad_seq"][act_idx], S, axis=0),
                        jnp.repeat(state["bad_len"][act_idx], S, axis=0),
                        recent_pos[act_idx].reshape(ba * S, slen))
                    temp_r = jnp.repeat(state["temp"][act_idx], S)
                    tk_r = jnp.repeat(state["top_k"][act_idx], S)
                    tp_r = jnp.repeat(state["top_p"][act_idx], S)
                    rp_r = jnp.repeat(state["rep_pen"][act_idx], S)
                    seen_r = seen_pos[act_idx].reshape(ba * S, -1)
                    ban_r = jnp.repeat(state["banned"][act_idx], S,
                                       axis=0)
                    draft_r = draft_grid[act_idx].reshape(ba * S)

                    if greedy:
                        tgt = self._tail_sample(
                            params, ha, key_g, temp=temp_r,
                            top_k=tk_r, top_p=tp_r, rep_pen=rp_r,
                            seen_words=seen_r, banned_words=ban_r,
                            ban_tok=tail, ban_hit=hit, greedy=True)
                        acc_r, out_r = draft_r == tgt, tgt
                    else:
                        u = jax.random.uniform(key_u, (ba * S,))
                        acc_r, out_r = self._tail_verify(
                            params, ha, key_g, u, temp=temp_r,
                            top_k=tk_r, top_p=tp_r, rep_pen=rp_r,
                            seen_words=seen_r, banned_words=ban_r,
                            draft_ids=draft_r, ban_tok=tail, ban_hit=hit)
                    # padding indices (== B) drop on scatter
                    acc_g = jnp.zeros((B, S), bool).at[act_idx].set(
                        acc_r.reshape(ba, S))
                    out_g = jnp.zeros((B, S), jnp.int32).at[act_idx].set(
                        out_r.reshape(ba, S))
                else:
                    # Materialized tail (ENGINE_FUSED_SAMPLER=0): same
                    # verdict rule from full (B*S, V) penalized logits.
                    # Greedy verdicts are identical to the fused tail
                    # at any occupancy; sampled verdicts share the
                    # per-tile noise layout but index rows B*S-wide
                    # where the fused tail indexes its act_idx-gathered
                    # ba*S rows — identical draws only at FULL
                    # occupancy (act_idx == arange(B)); elsewhere the
                    # tails are distribution-identical, not
                    # sample-identical.
                    lf = net.reshape(B * S, V)
                    pen = apply_repetition_penalty(
                        lf, unpack_mask(seen_pos.reshape(B * S, -1), V),
                        jnp.repeat(state["rep_pen"], S))
                    pen = jnp.where(
                        unpack_mask(jnp.repeat(state["banned"], S,
                                               axis=0), V),
                        -1e30, pen)
                    hit, tail = bad_seq_hits(
                        jnp.repeat(state["bad_seq"], S, axis=0),
                        jnp.repeat(state["bad_len"], S, axis=0),
                        recent_pos.reshape(B * S, slen))
                    pen = pen.at[jnp.arange(B * S)[:, None],
                                 jnp.where(hit, tail, 0)].min(
                        jnp.where(hit, -1e30, jnp.inf).astype(pen.dtype))
                    draft_r = draft_grid.reshape(B * S)
                    if greedy:
                        tgt = jnp.argmax(pen.astype(jnp.float32),
                                         axis=-1).astype(jnp.int32)
                        acc_r, out_r = draft_r == tgt, tgt
                    else:
                        u = jax.random.uniform(key_u, (B * S,))
                        acc_r, out_r = verify_reference_tiled(
                            pen, key_g, u,
                            jnp.repeat(state["temp"], S),
                            jnp.repeat(state["top_k"], S),
                            jnp.repeat(state["top_p"], S),
                            draft_r, tile=choose_tile(V))
                    acc_g = acc_r.reshape(B, S)
                    out_g = out_r.reshape(B, S)
                # Longest agreed prefix, then the correction/bonus token
                # from its first disagreeing (or bonus) row.
                valid_draft = offs[None, :] < n_draft[:, None]
                chain = jnp.cumprod(
                    (acc_g & valid_draft).astype(jnp.int32), axis=1)
                a = chain.sum(axis=1)        # (B,) accepted draft count
                corr = jnp.take_along_axis(out_g, a[:, None], axis=1)
                e = jnp.where(offs[None, :] < a[:, None], drafts_ext,
                              corr)
                # eos / length termination INSIDE the burst, mirroring
                # the sequential device rule: the terminal token itself
                # is emitted, nothing after it is.
                rem0 = state["remaining"]
                is_eos = (e == eos) & state["eos_ok"][:, None]
                stop_j = is_eos \
                    | ((rem0[:, None] - (offs[None, :] + 1)) <= 0)
                no_stop_before = jnp.cumprod(jnp.concatenate(
                    [jnp.ones((B, 1), jnp.int32),
                     (~stop_j[:, :-1]).astype(jnp.int32)], axis=1),
                    axis=1)
                emit = ((offs[None, :] <= a[:, None])
                        & (no_stop_before > 0) & active[:, None])
                m = emit.sum(axis=1)
                last_tok = jnp.take_along_axis(
                    e, jnp.maximum(m - 1, 0)[:, None], axis=1)[:, 0]
                finished = active & jnp.any(emit & stop_j, axis=1)
                seen = state["seen"]
                recent = state["recent"]
                for j in range(S):
                    on = emit[:, j]
                    seen = set_token_bits(seen, e[:, j], on)
                    recent = jnp.where(
                        on[:, None],
                        jnp.concatenate([recent[:, 1:], e[:, j:j + 1]],
                                        axis=1),
                        recent)
                new_state = dict(
                    state,
                    cache=self._pin_cache(cache),
                    # pos advances past CONSUMED inputs only — the
                    # rewind invariant: never past the last accepted
                    # token (+1 for the input that produced it).
                    pos=jnp.where(active, pos + m, pos),
                    last_token=jnp.where(active, last_tok,
                                         state["last_token"]),
                    active=active & ~finished,
                    remaining=jnp.where(active, rem0 - m, rem0),
                    seen=seen, recent=recent)
                return new_state, (jnp.where(emit, e, -1).T,
                                   jnp.where(active, a, 0)
                                   .astype(jnp.int32))
            return verify_round

        def release(state, slot):
            return dict(state, active=state["active"].at[slot].set(False))

        def prefill_insert(state, params, tokens, length, slot, row,
                           temp, top_k, top_p, rep_pen, banned, bad_seq,
                           bad_len, key, remaining, eos_ok, greedy: bool):
            """Admission as ONE dispatch: prefill + sample + scatter into
            the slot's pages. Separate prefill/insert programs put two
            program boundaries (and a bucket-KV hand-off) on the
            TTFT-critical path — on tunneled devices each boundary adds
            real latency."""
            k_new, v_new, first_tok, seen = prefill(
                params, tokens, length, temp, top_k, top_p, rep_pen,
                banned, key, greedy)
            new_state = insert(state, k_new, v_new, slot, length, first_tok,
                               temp, top_k, top_p, rep_pen, seen, banned,
                               bad_seq, bad_len, row, remaining, eos_ok)
            return new_state, first_tok

        self._prefill_insert = jax.jit(prefill_insert, static_argnums=(16,),
                                       donate_argnums=(0,))
        self._prefill_insert_raw = prefill_insert  # for fused-RAG composition
        self._release = jax.jit(release, donate_argnums=(0,))
        self._make_round = make_round
        self._make_verify = make_verify
        self._round_fns: dict[tuple[int, int, bool], object] = {}
        self._verify_fns: dict[tuple, object] = {}
        self._chunk_fns: dict[tuple, object] = {}

    def _round_fn(self, window: int, steps: int, greedy: bool, ba: int):
        key = (window, steps, greedy, ba)
        fn = self._round_fns.get(key)
        if fn is None:
            fn = jax.jit(self._make_round(window, steps, greedy, ba),
                         donate_argnums=(1,))
            self._round_fns[key] = fn
        return fn

    def _verify_fn(self, window: int, greedy: bool, ba: int):
        key = (window, greedy, ba)
        fn = self._verify_fns.get(key)
        if fn is None:
            fn = jax.jit(self._make_verify(window, greedy, ba),
                         donate_argnums=(1,))
            self._verify_fns[key] = fn
        return fn

    # --------------------------------------------- long-prompt admission

    def _chunk_seen(self, state, tokens, start, valid, slot, mode: str,
                    seen0=None):
        """Accumulate the slot's seen-token mask chunk by chunk (the
        repetition-penalty state the one-shot prefill computes in one
        go). ``mode``: "replace" (chunk 0 of a cold chunked admission —
        drop the previous occupant's stale mask), "accum" (OR into the
        slot's mask), or "seed" (chunk 0 of a prefix-cache hit: OR into
        ``seen0``, the host-built PACKED mask over the cached prefix
        tokens the chunks never revisit). All forms are uint32 bitfields
        (ops/sampling.py pack_mask); OR on packed words == OR on the
        bool masks they encode."""
        C = tokens.shape[1]
        in_chunk = jnp.clip(valid - start, 0, C)
        chunk_seen = pack_mask(seen_mask(tokens, in_chunk[None],
                                         self.model_cfg.vocab_size)[0])
        if mode == "accum":
            chunk_seen = state["seen"][slot] | chunk_seen
        elif mode == "seed":
            chunk_seen = seen0 | chunk_seen
        return state["seen"].at[slot].set(chunk_seen)

    def _chunk_extend_fn(self, window: int, mode: str):
        """Jitted ONE-CHUNK paged prefill: the chunk's KV lands in the
        slot's pool pages and its attention reads the whole prefix back
        from the pool (models/llama.py apply_prefill_paged) — used both
        for longer-than-any-bucket prompts and for prefix-cache hits,
        whose first chunk starts at the first uncached token. Non-final
        chunks skip the vocab projection entirely. ``mode`` is the seen
        handling (_chunk_seen); "seed" variants take the prefix mask as
        an extra arg so the TTFT path stays a single dispatch per chunk."""
        key = ("extend", window, mode)
        fn = self._chunk_fns.get(key)
        if fn is None:
            mcfg = self.model_cfg

            def extend(state, params, tokens, start, valid, slot, row_win,
                       *seed):
                C = tokens.shape[1]
                positions = (start + jnp.arange(C, dtype=jnp.int32))[None, :]
                _, cache = llama.apply_prefill_paged(
                    params, mcfg, tokens, positions, state["cache"],
                    row_win, valid[None], start // self.cfg.page_size,
                    with_logits=False)
                # Round-telemetry completion marker: a scalar OUTPUT
                # that data-depends on the chunk's paged prefill, so a
                # host readback of it blocks until this program has
                # executed. Its buffer is NOT part of the donated state
                # dict — it survives the next dispatch, unlike any ref
                # into the returned state (which donation invalidates).
                marker = cache["k"][0, 0, 0, 0, 0]
                return dict(state,
                            cache=self._pin_cache(cache),
                            seen=self._chunk_seen(state, tokens, start,
                                                  valid, slot, mode,
                                                  *seed)), marker

            fn = jax.jit(extend, donate_argnums=(0,))
            self._chunk_fns[key] = fn
        return fn

    def _chunk_final_fn(self, window: int, greedy: bool, seed: bool):
        """The LAST chunk: paged prefill + first-token sample + slot
        arming in one dispatch — insert()'s non-cache half (the chunk
        loop already scattered all prompt KV). Only the sampling
        position is unembedded, not the whole chunk. ``seed``: this is
        ALSO the first chunk (single-chunk prefix-cache hit), so the
        seen mask seeds from the host-built prefix mask instead of the
        slot's accumulated one."""
        key = ("final", window, greedy, seed)
        fn = self._chunk_fns.get(key)
        if fn is None:
            mcfg = self.model_cfg
            eos = int(self.tokenizer.eos_id)

            def final(state, params, tokens, start, valid, slot, row,
                      row_win, temp, top_k, top_p, rep_pen, banned,
                      bad_seq, bad_len, key_, remaining, eos_ok, *seed0):
                C = tokens.shape[1]
                positions = (start + jnp.arange(C, dtype=jnp.int32))[None, :]
                h, cache = llama.apply_prefill_paged(
                    params, mcfg, tokens, positions, state["cache"],
                    row_win, valid[None], start // self.cfg.page_size,
                    with_logits=False)
                seen = self._chunk_seen(state, tokens, start, valid, slot,
                                        "seed" if seed else "accum",
                                        *seed0)
                idx = jnp.clip(valid - start - 1, 0, C - 1)
                h_last = jnp.take_along_axis(
                    h, idx[None, None, None].astype(jnp.int32), axis=1)
                # Admission runs once per request — unpacking the packed
                # masks transiently here is fine; the per-STEP decode
                # path never unpacks.
                V = mcfg.vocab_size
                last = llama.unembed(params, mcfg, h_last)[0, 0]  # (V,)
                last = apply_repetition_penalty(
                    last[None, :], unpack_mask(seen[slot], V)[None, :],
                    rep_pen[None])
                last = jnp.where(unpack_mask(banned, V)[None, :],
                                 -1e30, last)
                if greedy:
                    first_tok = jnp.argmax(
                        last[0].astype(jnp.float32)).astype(jnp.int32)
                else:
                    first_tok = sample(last, key_, temp[None], top_k[None],
                                       top_p[None])[0]
                active = (remaining > 0) & ~((first_tok == eos) & eos_ok)
                length = valid
                return dict(
                    state,
                    cache=self._pin_cache(cache),
                    table=state["table"].at[slot].set(row),
                    pos=state["pos"].at[slot].set(length),
                    last_token=state["last_token"].at[slot].set(first_tok),
                    active=state["active"].at[slot].set(active),
                    remaining=state["remaining"].at[slot].set(remaining),
                    eos_ok=state["eos_ok"].at[slot].set(eos_ok),
                    temp=state["temp"].at[slot].set(temp),
                    top_k=state["top_k"].at[slot].set(top_k),
                    top_p=state["top_p"].at[slot].set(top_p),
                    rep_pen=state["rep_pen"].at[slot].set(rep_pen),
                    seen=seen.at[jnp.asarray(slot)].set(
                        set_token_bits(seen[slot][None], first_tok[None],
                                       jnp.ones((1,), bool))[0]),
                    banned=state["banned"].at[slot].set(banned),
                    bad_seq=state["bad_seq"].at[slot].set(bad_seq),
                    bad_len=state["bad_len"].at[slot].set(bad_len),
                    recent=state["recent"].at[slot].set(
                        jnp.full((self.MAX_BAD_LEN - 1,), -1, jnp.int32)
                        .at[-1].set(first_tok))), first_tok

            fn = jax.jit(final, donate_argnums=(0,))
            self._chunk_fns[key] = fn
        return fn

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is None:
            self._stopped.clear()  # allow restart after a stop()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="engine-loop")
            self._thread._engine_gen = self._gen  # type: ignore[attr-defined]
            self._thread.start()
        if self._harvest_thread is None:
            self._harvest_thread = threading.Thread(
                target=self._harvest_worker, daemon=True,
                name="engine-harvest")
            self._harvest_thread._engine_gen = self._gen  # type: ignore[attr-defined]
            self._harvest_thread.start()
        if self._watchdog_thread is None and self._watchdog_stall_s > 0:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True,
                name="engine-watchdog")
            self._watchdog_thread._engine_gen = self._gen  # type: ignore[attr-defined]
            self._watchdog_thread.start()

    def stop(self) -> None:
        self._stopped.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            if self._thread.is_alive():
                # Loop is wedged (e.g. a huge first-time compile). Keep the
                # handle so a later start() can't spawn a second loop racing
                # this one over the donated device state; reset() disowns
                # the thread and rebuilds.
                raise EngineError(
                    "engine loop did not stop within 30s; call reset() to "
                    "abandon it and rebuild the device state")
            self._thread = None
        if self._harvest_thread is not None:
            # The worker's longest block is one round's device execution
            # + host copy — bounded, unlike a first-time compile.
            self._harvest_thread.join(timeout=30)
            if self._harvest_thread.is_alive():
                raise EngineError(
                    "harvest worker did not stop within 30s; call reset() "
                    "to abandon it and rebuild the device state")
            self._harvest_thread = None
        if self._watchdog_thread is not None:
            self._watchdog_thread.join(timeout=5)
            self._watchdog_thread = None
            self._stalled = False
        self._drain_on_stop()

    # ------------------------------------------------------------- watchdog

    @property
    def stalled(self) -> bool:
        """Liveness-watchdog verdict: True while work is queued or in
        flight but no progress counter has moved for
        ``ENGINE_WATCHDOG_STALL_S`` (docs/robustness.md). The chain
        server's /health answers 503 on it — truthful readiness, so the
        fleet router places elsewhere — and it clears by itself the
        moment a round completes again."""
        return self._stalled

    def _progress_marks(self) -> tuple:
        """The counters any live engine moves: one frozen sweep of these
        with work pending is the stall signature."""
        with self._stats_lock:
            s = self._stats
            return (s["rounds_completed"], s["harvest_rounds"],
                    s["first_readbacks"], s["prefills"],
                    s["tokens_generated"])

    def _work_pending(self) -> bool:
        with self._pipe_lock:
            inflight = self._inflight_rounds
        return (inflight > 0 or len(self._backlog) > 0
                or self._pending.qsize() > 0)

    def _watchdog_loop(self) -> None:
        import sys
        import traceback

        gen = self._gen
        poll = max(0.05, min(1.0, self._watchdog_stall_s / 4.0))
        marks = self._progress_marks()
        last_move = time.monotonic()
        while not self._stopped.wait(poll):
            if gen != self._gen:
                return  # disowned by reset()
            now = time.monotonic()
            cur = self._progress_marks()
            if cur != marks or not self._work_pending():
                if self._stalled:
                    self._stalled = False
                    log_event(logger, "engine_watchdog_recovered",
                              stalled_s=round(now - last_move, 2))
                marks = cur
                last_move = now
                continue
            if self._stalled or now - last_move < self._watchdog_stall_s:
                continue
            # Stall declared: work is pending and nothing has moved for
            # the whole threshold. Dump every thread's stack + the last
            # round record — the post-mortem an operator needs when the
            # process is about to be killed — and flip readiness.
            self._stalled = True
            self._bump("watchdog_stalls")
            names = {t.ident: t.name for t in threading.enumerate()}
            stacks = {
                f"{names.get(tid, '?')}:{tid}":
                    "".join(traceback.format_stack(frame))[-2000:]
                for tid, frame in sys._current_frames().items()}
            try:
                last_round = self.rounds.snapshot(limit=1).get("records")
            except Exception:  # noqa: BLE001 — diagnostics must not throw
                last_round = None
            log_event(logger, "engine_watchdog_stall",
                      stall_s=round(now - last_move, 2),
                      threshold_s=self._watchdog_stall_s,
                      queue_waiting=(len(self._backlog)
                                     + self._pending.qsize()),
                      inflight_rounds=self._inflight_rounds,
                      last_round=last_round, stacks=stacks)

    def reset(self) -> None:
        """Recover from a wedged loop: disown the stuck threads (their
        writes are dropped via the generation check when they unstick),
        fail every live request, and rebuild the device state — serving
        restarts without process death (VERDICT r2 weak #10).

        Responsive threads are joined first, so reset() on a healthy
        engine degrades to stop-and-rebuild with no thread racing the
        rebuild; the disown path only covers threads actually stuck in a
        device call (the scheduler in a compile/dispatch, the harvest
        worker in a readback)."""
        self._stopped.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._harvest_thread is not None:
            self._harvest_thread.join(timeout=5)
        self._gen += 1
        self._thread = None
        self._harvest_thread = None
        self._watchdog_thread = None
        self._stalled = False
        exc = EngineError("engine was reset")
        for req in self._live_requests():
            if not req.done:
                req.stream._fail(exc)
        # Fresh queues, not .clear(): a disowned harvest worker may still
        # hold the old objects — its stale puts/gets must land on garbage,
        # never on the rebuilt pipeline. The depth counter is zeroed AFTER
        # the generation bump above, so a stale worker's guarded decrement
        # (see _harvest_worker) can never corrupt the new count.
        self._harvest_q = queue.Queue()
        self._completed = queue.Queue()
        # Control ops queued against the dead generation must fail NOW
        # (not hang out the 30 s wait) and must never execute against
        # the rebuilt state — a stale suspend would demote a fresh
        # cache. Fresh queue for the same disowned-thread reason as the
        # pipeline queues above.
        self._fail_control_ops("engine was reset")
        self._control = queue.Queue()
        with self._pipe_lock:
            self._inflight_rounds = 0
        self._slots.clear()
        self._free_slots = list(range(self.cfg.max_slots))
        self._free_pages = list(range(1, self._n_pages))
        if self._prefix_cache is not None:
            # Fresh instance, not .clear(): a disowned loop thread may
            # still hold the old object — its stale mutations must land
            # on garbage, never on the rebuilt pool's index.
            self._prefix_cache = PrefixCache(self.cfg.page_size)
        self._fatal = None
        # Drop the old pool BEFORE allocating the new one — holding both
        # across the rebuild doubles pool HBM exactly when recovering
        # from an OOM (prewarm's shrink-retry died re-allocating).
        self._state = None
        import gc
        gc.collect()
        self._state = self._init_device_state()

    def _loop_stale(self) -> bool:
        """True on a thread that reset() has disowned."""
        g = getattr(threading.current_thread(), "_engine_gen", None)
        return g is not None and g != self._gen

    def _guard_live(self) -> None:
        """Unwind a disowned loop thread entirely — a stale thread must
        not proceed to any later phase, where it would donate the rebuilt
        generation's device state into a jit call."""
        if self._loop_stale():
            raise _StaleLoop()

    def _live_requests(self) -> list[_Request]:
        """Every request the scheduler still knows about, across all of its
        staging structures (pending queue, head buffer, prefill-in-flight,
        slots). The single source of truth for both the fatal-error
        fan-out and the stop() drain — a request missed here would leave
        its consumer blocked forever. Requests whose first-token or round
        output still sits in the harvest queue are covered via ``_slots``:
        admission registers the slot BEFORE enqueueing the first-token
        item, and retirement (which removes the slot) only happens after
        the harvest worker finished their stream."""
        live: list[_Request] = []
        live += self._slots.values()
        live += [req for req, _ in self._backlog]
        self._backlog = []
        while not self._pending.empty():
            try:
                live.append(self._pending.get_nowait()[0])
            except queue.Empty:
                break
        return live

    def _drain_on_stop(self) -> None:
        """Retire everything still live so (a) consumers blocked on streams
        never hang forever and (b) no device slot stays active holding pages
        that a post-restart insert would reuse. Both worker threads are
        joined (or disowned) before this runs, so touching the pipeline
        structures and dispatching releases here is single-threaded."""
        # Unharvested device work is dropped; its requests stay visible
        # via _slots and are cancelled below.
        self._harvest_q = queue.Queue()
        with self._pipe_lock:
            self._inflight_rounds = 0
        # Queued control ops (suspend/export) will never run — fail
        # their waiters instead of leaving them to the wait timeout.
        self._fail_control_ops("engine stopped")
        # Deactivate every occupied device slot FIRST: a host-detected
        # finish pending in _completed never had its device release
        # dispatched, and retiring it below removes the slot from _slots
        # — a still-active device slot would keep writing KV into pages
        # the free list is about to hand to the next occupant.
        for slot in list(self._slots):
            # device-side deactivate: safe here, the loop thread is joined
            self._state = self._release(self._state, jnp.int32(slot))
        # Slot/page bookkeeping for streams the harvest worker already
        # finished but the scheduler never got to retire.
        while True:
            try:
                req, finish = self._completed.get_nowait()
            except queue.Empty:
                break
            if self._slots.get(req.slot) is req:
                self._retire(req, finish)
        leftovers = self._live_requests()
        for req in leftovers:
            if self._slots.get(req.slot) is req:
                self._retire(req, "cancelled")
            elif not req.done:
                req.stream._finish("cancelled")

    def _fail_control_ops(self, reason: str) -> None:
        """Fail every queued control op's waiter (stop/reset paths —
        the ops will never run, and must neither hang their callers out
        the wait timeout nor execute later against rebuilt state)."""
        while True:
            try:
                _fn, box, ev = self._control.get_nowait()
            except queue.Empty:
                return
            box["error"] = EngineError(reason)
            ev.set()

    def __enter__(self) -> "Engine":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------ API

    def _compile_bad_words(
            self, params: SamplingParams
    ) -> tuple[list[int], list[list[int]]]:
        """bad_words -> (single-token ids, multi-token sequences).

        Single-token spellings go on the static (V,) vocab mask; words
        that only exist as multi-token spellings become device-side
        sequence bans (the reference's word-list tensors,
        preprocessing/1/model.py:211 ``to_word_list_format``).
        """
        banned_ids: list[int] = []
        bad_seqs: list[list[int]] = []
        for word in params.bad_words:
            # Subword tokenizers give a word several single-token
            # spellings — word-initial (metaspace-prefixed, what encode
            # produces after its dummy prefix) and bare continuation —
            # ban every variant the vocab holds so none slips the mask.
            variants = set()
            seqs: list[list[int]] = []
            for text in (word, " " + word):
                ids = [int(i) for i in
                       self.tokenizer.encode(text, add_bos=False)]
                if len(ids) == 1:
                    variants.add(ids[0])
                elif ids and ids not in seqs:
                    seqs.append(ids)
            lookup = getattr(self.tokenizer, "piece_id", None)
            if lookup is not None:
                for piece in (word, "▁" + word):
                    pid = lookup(piece)
                    if pid is not None:
                        variants.add(int(pid))
            banned_ids.extend(sorted(variants))
            # A word is banned in EVERY spelling (the reference's word
            # list carries all of them): single-token variants go on the
            # vocab mask AND multi-token spellings become sequence bans —
            # a word with a one-piece " word" form can still surface via
            # its split bare form after a quote or newline.
            for seq in seqs:
                if any(t in variants for t in seq):
                    # spellings whose pieces include an already-banned
                    # variant can never complete anyway — and must not
                    # trip the length cap below (a word whose ' word'
                    # form is one banned piece stays servable however
                    # long its split spelling is)
                    continue
                if len(seq) > self.MAX_BAD_LEN:
                    raise EngineError(
                        f"bad_words entry {word!r} tokenizes to "
                        f"{len(seq)} tokens; the device-side sequence "
                        f"ban supports up to {self.MAX_BAD_LEN}")
                # dedupe across ALL entries, not just this word's
                # spellings — duplicate sequences would burn device table
                # slots and spuriously trip the MAX_BAD_SEQS cap
                if seq not in bad_seqs:
                    bad_seqs.append(seq)
            if not variants and not seqs:
                raise EngineError(
                    f"bad_words entry {word!r} produced no tokens")
        if len(bad_seqs) > self.MAX_BAD_SEQS:
            raise EngineError(
                f"{len(bad_seqs)} multi-token bad-word sequences; the "
                f"device table holds {self.MAX_BAD_SEQS}")
        return banned_ids, bad_seqs

    def _render_bad_words(self, banned_ids: list[int],
                          bad_seqs: list[list[int]]):
        """Device-ready numpy renderings, built on the SUBMITTING thread
        so the serve loop's admission dispatch does no mask assembly.
        The banned mask ships PACKED (uint32 bitfield, 32 tokens/word —
        ops/sampling.py): 1/8 the upload bytes and the exact layout the
        device state stores per slot."""
        banned_row = np.zeros((self.model_cfg.vocab_size,), bool)
        if banned_ids:
            banned_row[banned_ids] = True
        seq_tbl = np.full((self.MAX_BAD_SEQS, self.MAX_BAD_LEN), -1,
                          np.int32)
        seq_len = np.zeros((self.MAX_BAD_SEQS,), np.int32)
        for i, seq in enumerate(bad_seqs):
            seq_tbl[i, :len(seq)] = seq
            seq_len[i] = len(seq)
        return pack_mask_np(banned_row), seq_tbl, seq_len

    # -------------------------------------------------------- fused RAG

    def enable_fused_rag(self, enc_params, enc_cfg, spec) -> None:
        """Compile-in the on-device retrieve->assemble->prefill admission
        (engine/rag_fusion.py). ``spec``: FusedRagSpec. The corpus is
        uploaded separately via set_rag_corpus()."""
        from .rag_fusion import FusedRag
        if spec.bucket % self.cfg.page_size:
            raise EngineError("fused-RAG bucket must be a page multiple")
        if spec.bucket + 1 > self.cfg.max_cache_len:
            raise EngineError("fused-RAG bucket exceeds the cache extent")
        fused = FusedRag(enc_params, enc_cfg, spec)

        def rag_admit(state, params, enc_params, corpus, q_enc, q_llm,
                      q_llm_len, slot, row, temp, top_k, top_p, rep_pen,
                      banned, bad_seq, bad_len, key, remaining, eos_ok,
                      greedy: bool):
            tokens, length, top_ids = fused.assemble(
                enc_params, corpus, q_enc, q_llm, q_llm_len)
            new_state, first = self._prefill_insert_raw(
                state, params, tokens[None, :], length, slot, row, temp,
                top_k, top_p, rep_pen, banned, bad_seq, bad_len, key,
                remaining, eos_ok, greedy)
            # One readback for everything the host needs: token, real
            # prompt length, retrieved corpus rows.
            aux = jnp.concatenate([
                first[None].astype(jnp.int32), length[None], top_ids])
            return new_state, aux

        self._fused_rag = fused
        self._rag_jit = jax.jit(rag_admit, static_argnums=(19,),
                                donate_argnums=(0,))

    @property
    def fused_rag_spec(self):
        """Spec of the compiled fused-RAG admission program, or None when
        fused RAG is not enabled (e.g. after an engine rebuild) — callers
        cache specs and must compare against the ENGINE's truth."""
        return self._fused_rag.spec if self._fused_rag is not None else None

    def set_rag_corpus(self, emb, toks, lens) -> None:
        """Upload/replace the device-resident retrieval corpus
        (rag_fusion.corpus_rows builds toks/lens from chunk texts)."""
        if self._fused_rag is None:
            raise EngineError("enable_fused_rag() first")
        self._fused_rag.set_corpus(emb, toks, lens)

    def _new_stream(self, request_id: Optional[str],
                    prompt_tokens: int, eff_max: int) -> TokenStream:
        """TokenStream + flight timeline for one submission. The request
        ID resolves in priority order: explicit argument, the ID bound on
        the calling context (the chain server's adopted X-Request-ID,
        visible here because the chain generator runs under a copied
        context), else a freshly minted one."""
        tl_ctx = obs_flight.current()
        if request_id is None and tl_ctx is not None:
            # The serving edge already opened this request's timeline —
            # pair by OBJECT identity (not by re-looking-up the rid,
            # which could collide with an unrelated in-flight request
            # reusing the same client-supplied ID). The edge owns its
            # completion; this stream only contributes sub-call stats.
            tl = tl_ctx
            owns = False
        else:
            # Direct submission (OpenAI surface, tests, prewarm): every
            # call is a new request — fresh disambiguates duplicate IDs.
            tl = self.flight.begin(
                request_id or obs_flight.mint_request_id(), fresh=True)
            owns = True
        stream = TokenStream(tl.request_id)
        stream.owns_timeline = owns
        tl.annotate(prompt_tokens=prompt_tokens, max_tokens=eff_max)
        tl.event("engine_submit")
        stream.timeline = tl
        stream._flight = self.flight
        return stream

    def _resolve_deadline(self, stream: TokenStream,
                          deadline_t: Optional[float]) -> Optional[float]:
        """The request's effective deadline: the explicit argument (the
        OpenAI surface passes it — run_in_executor drops context), else
        whatever the serving edge armed on the adopted timeline. An
        explicit deadline is stamped back onto an unarmed timeline so
        /debug/requests shows the budget the request ran against."""
        tl = stream.timeline
        if deadline_t is None:
            return tl.deadline_t if tl is not None else None
        if tl is not None and tl.deadline_t is None:
            tl.set_deadline((deadline_t - tl.t_start) * 1e3)
            # set_deadline recomputes off t_start; pin the exact value
            tl.deadline_t = deadline_t
        return deadline_t

    def submit_rag(self, question_ids: Sequence[int],
                   question_enc_ids: Sequence[int],
                   params: Optional[SamplingParams] = None,
                   request_id: Optional[str] = None,
                   deadline_t: Optional[float] = None) -> TokenStream:
        """Enqueue a fused-RAG request: retrieval and prompt assembly
        happen on-device during admission; ``question_ids`` are the
        question's tokens in the LLM vocab (no BOS), ``question_enc_ids``
        in the encoder vocab (with any query prefix applied)."""
        if self._fatal is not None:
            raise EngineError("engine is dead") from self._fatal
        if self._fused_rag is None:
            raise EngineError("fused RAG is not enabled on this engine")
        params = params or SamplingParams()
        spec = self._fused_rag.spec
        ids = list(question_ids)
        if len(ids) > spec.q_bucket:
            # mirror submit()'s loud rejection — silently cutting the
            # question mid-sentence would answer a different question
            raise EngineError(
                f"question is {len(ids)} tokens but the fused-RAG "
                f"question bucket is {spec.q_bucket}; use the host "
                "retrieval path for long questions")
        q_llm = np.zeros((spec.q_bucket,), np.int32)
        q_llm[:len(ids)] = ids
        q_enc = np.zeros((2, spec.enc_bucket), np.int32)
        eids = list(question_enc_ids)[:spec.enc_bucket]
        q_enc[0, :len(eids)] = eids
        q_enc[1, :len(eids)] = 1
        eff_max = min(params.max_tokens,
                      self.cfg.max_cache_len - spec.bucket)
        if eff_max < 1:
            raise EngineError("fused-RAG bucket leaves no room to decode")
        need = _ceil_div(spec.bucket + eff_max, self.cfg.page_size)
        if need > self._n_pages - 1:
            # mirror submit(): an extent the pool can never hold must fail
            # here — enqueued, _admit would skip it forever (silent hang)
            raise EngineError(
                f"fused-RAG request needs {need} KV pages but the pool "
                f"only has {self._n_pages - 1} (kv_pool_tokens too small)")
        banned_ids, bad_seqs = self._compile_bad_words(params)
        banned_np, bad_seq_np, bad_len_np = self._render_bad_words(
            banned_ids, bad_seqs)
        stream = self._new_stream(request_id, len(ids), eff_max)
        req = _Request(stream=stream, prompt_ids=[], params=params,
                       eff_max=eff_max, extent=spec.bucket + eff_max,
                       detok=IncrementalDetokenizer(self.tokenizer),
                       stop=StopWordTrap(params.stop_words),
                       greedy=(params.top_k == 1 or params.temperature <= 0),
                       banned_ids=banned_ids, bad_seqs=bad_seqs,
                       banned_np=banned_np, bad_seq_np=bad_seq_np,
                       bad_len_np=bad_len_np,
                       rag=(q_llm, len(ids), q_enc),
                       deadline_t=self._resolve_deadline(stream, deadline_t),
                       seq=next(self._arrival_seq),
                       base_len=spec.bucket)
        if self._spec is not None:
            # The fused-RAG prompt is assembled on-device — the host
            # never sees its tokens, so the drafter indexes generated
            # tokens only (prompt-lookup still fires once the answer
            # starts repeating spans it generated).
            req.drafter = PromptLookupDrafter(
                ngram_max=self._spec.ngram_max,
                ngram_min=self._spec.ngram_min)
            req.spec_ctrl = AdaptiveDraftController(self._spec)
        self._enqueue(req, params, stream)
        if self._fatal is not None:
            stream._fail(self._fatal)
        self._bump("requests")
        self._wake.set()
        return stream

    def _enqueue(self, req: "_Request", params: SamplingParams,
                 stream: TokenStream) -> None:
        """Admission gate: ``max_queue`` bounds TOTAL queued work —
        intake queue plus the scheduler's backlog — so the PR-5 meaning
        of the knob (queued capacity before 429) survives the backlog
        refactor; without this check the backlog would silently double
        it. The combined read is approximate under concurrent
        submitters (``qsize``/``len`` race by design, like every
        queue-depth check), but the intake queue's own ``maxsize`` still
        hard-bounds any overshoot."""
        if len(self._backlog) + self._pending.qsize() >= self.cfg.max_queue:
            self._reject_full(stream)
        try:
            self._pending.put_nowait((req, params))
        except queue.Full:
            self._reject_full(stream)

    def _reject_full(self, stream: TokenStream) -> None:
        """Queue-full rejection: count the shed, retire the timeline
        (reason recorded, so rejected admissions show up in
        /debug/requests instead of leaking as forever-in-flight
        entries) — but only when this stream OWNS it; an edge-adopted
        timeline is completed by the edge, which turns this exception
        into a structured 429."""
        self._bump("rejected_full")
        tl = stream.timeline
        if tl is not None:
            tl.annotate(finish="rejected")
            if stream.owns_timeline:
                self.flight.complete(tl)
        raise SchedulerFullError(
            f"request queue full ({self.cfg.max_queue})") from None

    def submit(self, prompt_ids: Sequence[int],
               params: Optional[SamplingParams] = None,
               request_id: Optional[str] = None,
               deadline_t: Optional[float] = None) -> TokenStream:
        """Enqueue a request; returns its stream immediately.

        ``request_id``: the end-to-end request identity (see
        TokenStream). Omitted, it is adopted from the calling context
        (obs/flight.py contextvar — how the chain server's
        ``X-Request-ID`` reaches the engine without threading a parameter
        through every BaseExample chain) or minted fresh.

        ``deadline_t``: absolute ``time.monotonic`` deadline. Omitted,
        it is adopted from the same contextvar timeline (the chain
        server arms it from ``X-Deadline-Ms``). Expired in queue → the
        request is dropped before prefill (finish ``deadline_queue``);
        passed mid-decode → generation stops at the next harvested
        token (finish ``deadline``)."""
        if self._fatal is not None:
            raise EngineError("engine is dead") from self._fatal
        params = params or SamplingParams()
        prewarm_probe = bool(request_id) \
            and request_id.startswith("engine-prewarm")
        if self.role == "prefill" and not prewarm_probe \
                and params.max_tokens > self._role_prefill_max_tokens:
            # Role enforcement at admission: a prefill-role engine's
            # mesh belongs to the prefill wall — a decode-bound request
            # here would starve handoff exports behind its decode
            # rounds. Routing error, not capacity: edges map this to a
            # retryable 429 without tripping the breaker. Prewarm's own
            # worst-case calibration probes are exempt — they run
            # before the replica takes traffic and must exercise full
            # decode rounds regardless of role.
            raise RoleMismatchError(
                f"prefill-role engine refuses decode-bound request "
                f"(max_tokens={params.max_tokens} > role cap "
                f"{self._role_prefill_max_tokens}); route it to a "
                f"decode/unified replica")
        if len(prompt_ids) > self.cfg.max_input_length:
            raise EngineError(
                f"prompt length {len(prompt_ids)} exceeds max_input_length "
                f"{self.cfg.max_input_length}")
        if len(prompt_ids) == 0:
            raise EngineError("empty prompt")
        # Failover resume (engine/resume.py, docs/robustness.md): a
        # router-replayed continuation admits as prompt + generated-so-
        # far tokens. The replayed tokens are PROMPT from here on — the
        # prefix cache / host-tier restore / donor transfer make them
        # cheap, the rep-penalty seen mask covers them exactly like any
        # prefix-cache hit, and the stream emits only NEW tokens. The
        # max_input_length bound above applies to the ORIGINAL prompt:
        # the replayed tail was legitimately generated output.
        rz = engine_resume.current_resume()
        replay_ids = [int(t) for t in (rz or {}).get("ids", ())]
        full_ids = list(prompt_ids) + replay_ids
        eff_max = min(params.max_tokens - len(replay_ids),
                      self.cfg.max_cache_len - len(full_ids))
        if replay_ids and eff_max < 1:
            raise EngineError(
                f"resume replays {len(replay_ids)} tokens but the "
                f"request has no token budget left "
                f"(max_tokens={params.max_tokens})")
        need = _ceil_div(len(full_ids) + eff_max, self.cfg.page_size)
        if need > self._n_pages - 1:
            raise EngineError(
                f"request needs {need} KV pages but the pool only has "
                f"{self._n_pages - 1} (kv_pool_tokens too small)")
        banned_ids, bad_seqs = self._compile_bad_words(params)
        banned_np, bad_seq_np, bad_len_np = self._render_bad_words(
            banned_ids, bad_seqs)
        stream = self._new_stream(request_id, len(full_ids), eff_max)
        req = _Request(stream=stream, prompt_ids=full_ids,
                       params=params, eff_max=eff_max,
                       extent=len(full_ids) + eff_max,
                       detok=IncrementalDetokenizer(self.tokenizer),
                       stop=StopWordTrap(params.stop_words),
                       greedy=(params.top_k == 1 or params.temperature <= 0),
                       banned_ids=banned_ids, bad_seqs=bad_seqs,
                       banned_np=banned_np, bad_seq_np=bad_seq_np,
                       bad_len_np=bad_len_np,
                       deadline_t=self._resolve_deadline(stream, deadline_t),
                       seq=next(self._arrival_seq),
                       base_len=len(full_ids),
                       resume_offset=(len(replay_ids) if replay_ids
                                      else None))
        if replay_ids:
            # Fresh stop-word trap is CORRECT here: any held-back
            # stop-word prefix on the dead replica never reached the
            # router's transcript, so the replayed text ends before it
            # and the trap re-accumulates the straddle from the new
            # tokens. The detokenizer seeds the replayed tail as
            # already-emitted context so only new text streams.
            req.detok.prime(replay_ids)
            tl = stream.timeline
            if tl is not None:
                tl.annotate(resume_replayed=len(replay_ids),
                            resume_attempt=int((rz or {}).get("attempt",
                                                              1)))
                tl.event("resume_admit", {"replayed": len(replay_ids)})
        if self._spec is not None:
            # Prompt-lookup index built on the SUBMITTING thread (like
            # the bad-words masks): the serve loop only proposes. On a
            # resume, the replayed tokens index too — the uninterrupted
            # run would have indexed them as generated output.
            req.drafter = PromptLookupDrafter(
                full_ids, ngram_max=self._spec.ngram_max,
                ngram_min=self._spec.ngram_min)
            req.spec_ctrl = AdaptiveDraftController(self._spec)
        if self._kv_tier is not None:
            # Cross-replica prefix-page import (router placement-miss
            # hint): bounded network fetch on the CALLER's thread, so
            # the serve loop never does I/O; failures place cold.
            self._transfer_prefetch(req)
        self._enqueue(req, params, stream)
        if self._fatal is not None:
            # The loop may have died between the check above and the put;
            # fail the stream here so callers never block forever.
            stream._fail(self._fatal)
        self._bump("requests")
        self._wake.set()
        return stream

    def generate_text(self, prompt: str,
                      params: Optional[SamplingParams] = None,
                      request_id: Optional[str] = None) -> str:
        """Sync convenience: tokenize, generate, detokenize."""
        self.start()
        ids = self.tokenizer.encode(prompt)
        return self.submit(ids, params, request_id=request_id).text()

    def stream_text(self, prompt: str,
                    params: Optional[SamplingParams] = None,
                    request_id: Optional[str] = None,
                    deadline_t: Optional[float] = None) -> TokenStream:
        self.start()
        return self.submit(self.tokenizer.encode(prompt), params,
                           request_id=request_id, deadline_t=deadline_t)

    # ------------------------------------------------------------ scheduler

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _window_for(self, pages: int) -> int:
        for w in self._windows:
            if pages <= w:
                return w
        return self._pmax

    def _prefix_lookup(self, req: _Request):
        """Match the prompt's full page-sized blocks against the prefix
        cache and take refs on the usable prefix. Returns
        ``(hashes, k_use, pages)``: the prompt's block-chain hashes, how
        many leading blocks to map read-only, and their physical pages.

        ``usable_prefix_tokens`` caps a full-cover match one block short
        (COW demotion): the tail block the request must recompute — at
        least one token has to run through prefill for first-token
        logits — gets a PRIVATE page instead of the shared one, so the
        write never lands on cache property. Fused-RAG requests skip the
        cache: their prompt is assembled on-device and the host never
        sees its tokens."""
        if self._prefix_cache is None or req.rag is not None \
                or not req.prompt_ids:
            return [], 0, []
        page = self.cfg.page_size
        if req.block_hashes is None:  # backpressure retries re-enter here
            req.block_hashes = hash_blocks(req.prompt_ids, page)
        hashes = req.block_hashes
        matched = self._prefix_cache.match(hashes)
        k_use = usable_prefix_tokens(matched, len(req.prompt_ids),
                                     page) // page
        if k_use == 0:
            return hashes, 0, []
        return hashes, k_use, self._prefix_cache.acquire(hashes[:k_use])

    def _register_prefix(self, req: _Request, hashes: list,
                         k_use: int) -> None:
        """Hand the freshly prefilled full prompt blocks to the cache
        (they hold pure prompt KV: decode writes always land past the
        last full block, see prefix_cache.py). Blocks whose chain hash
        is already cached — e.g. the COW-demoted tail recomputed into a
        private page — keep their page private; it frees normally at
        retire."""
        if self._prefix_cache is None or req.rag is not None:
            return
        for i in range(k_use, len(hashes)):
            parent = hashes[i - 1] if i else None
            if self._prefix_cache.insert(hashes[i], parent, req.pages[i]):
                req.cache_refs.append(hashes[i])
                req.cache_pages.add(req.pages[i])

    # ---------------------------------------------------- tiered KV store

    def _page_io_fns(self):
        """Lazily-built page gather/scatter programs over the paged
        pool. Gather reads selected pages out of the live cache (the
        D2H offload source; non-donating — the pool stays valid);
        scatter writes page-shaped host data into selected pages (the
        H2D restore sink; donates the state like every other state
        transition). Both take a padded page-index vector (power-of-two
        rungs, padded with the trash page 0) so jit specializes per
        rung, not per count."""
        if self._gather_fn is None:
            def gather(cache, idx):
                return {k: v[:, idx] for k, v in cache.items()}

            def scatter(state, arrays, idx):
                cache = {k: v.at[:, idx].set(arrays[k].astype(v.dtype))
                         for k, v in state["cache"].items()}
                return dict(state, cache=self._pin_cache(cache))

            self._gather_fn = jax.jit(gather)
            self._scatter_fn = jax.jit(scatter, donate_argnums=(0,))
        return self._gather_fn, self._scatter_fn

    @staticmethod
    def _pad_pages(pages) -> np.ndarray:
        """Pad a page-id list to the next power-of-two rung with the
        trash page (0): gathers of page 0 are discarded host-side,
        scatters into it land on the designated garbage page."""
        n = max(1, len(pages))
        m = 1
        while m < n:
            m *= 2
        return np.asarray(list(pages) + [0] * (m - len(pages)), np.int32)

    def _offload_victims(self, victims: list, rec=None) -> None:
        """Offload evicted refcount-0 prefix pages to the host tier:
        one gather dispatch over the victim pages (device FIFO order
        guarantees it reads the pages BEFORE any later dispatch of this
        or another admission overwrites them), async D2H started here,
        materialized into the host store by the harvest worker — the
        blocking copy never runs on the scheduling path. Any failure
        (including an injected ``kv.offload`` fault) degrades to the
        untiered behavior: the pages are simply dropped."""
        tier = self._kv_tier
        if tier is None or not victims:
            return
        try:
            faults.inject("kv.offload")
            fresh = [(h, par, pg) for h, par, pg in victims
                     if not tier.store.has(h)]
            if not fresh:
                return
            gather, _ = self._page_io_fns()
            idx = self._pad_pages([pg for _, _, pg in fresh])
            rung_warm = ("gather", len(idx)) in self._io_rungs
            self._guard_live()
            arrays = gather(self._state["cache"], jnp.asarray(idx))
            self._io_rungs.add(("gather", len(idx)))
            for a in arrays.values():
                try:
                    a.copy_to_host_async()
                except Exception:  # noqa: BLE001 — optional fast path
                    pass
            self._harvest_q.put((
                "offload", [(h, par) for h, par, _ in fresh], arrays,
                rung_warm))
            if rec is not None:
                # D2H traffic term: the offloaded pages cross HBM once.
                rec.hbm_bytes += len(fresh) * self.cfg.page_size \
                    * self._kv_bytes_per_token()
        except _StaleLoop:
            raise
        except Exception:  # noqa: BLE001 — offload is best-effort
            logger.debug("kv offload failed; pages dropped", exc_info=True)

    def _plan_restore(self, req: _Request, hashes: list,
                      k_use: int) -> list:
        """Host-tier half of admission lookup: the contiguous chain
        continuation ``hashes[k_use:]`` present in the host store,
        COW-capped like any other prefix match, and PRICED — the
        restore only happens when the step-cost model says uploading
        the pages beats recomputing their tokens (refusals are counted
        in ``kv_restore_skipped_cost``). Returns the block records to
        restore (possibly shorter than planned if the store's LRU raced
        us)."""
        tier = self._kv_tier
        page = self.cfg.page_size
        avail = tier.store.match_chain(hashes[k_use:])
        if not avail:
            return []
        usable = usable_prefix_tokens(k_use + avail, len(req.prompt_ids),
                                      page) // page
        r = usable - k_use
        if r <= 0:
            return []
        if not self._sched.cost.restore_cheaper(r, page):
            self._bump("kv_restore_skipped_cost")
            return []
        recs = []
        for h in hashes[k_use:k_use + r]:
            rec = tier.store.get(h)
            if rec is None:
                break  # LRU raced: restore the contiguous prefix we hold
            recs.append(rec)
        return recs

    def _restore_blocks(self, req: _Request, hashes: list, k_use: int,
                        recs: list, rec=None) -> int:
        """Upload host-tier blocks into this request's freshly
        allocated pages — ONE scatter dispatch, enqueued ahead of the
        scheduler's prefill-chunk grants (device FIFO), so by the time
        the first chunk's attention reads the prefix back it is
        resident. The restored blocks enter the prefix cache exactly
        like freshly prefilled ones (one ref held by this request)."""
        tier = self._kv_tier
        page = self.cfg.page_size
        r = len(recs)
        t0 = time.monotonic()
        faults.inject("kv.restore")
        arrays = tier.stack_blocks(recs)          # name -> (L, r, ...)
        pages = req.pages[k_use:k_use + r]
        idx = self._pad_pages(pages)
        pad = len(idx) - r
        if pad:
            arrays = {k: np.concatenate(
                [v, np.zeros(v.shape[:1] + (pad,) + v.shape[2:],
                             v.dtype)], axis=1)
                for k, v in arrays.items()}
        _, scatter = self._page_io_fns()
        rung_warm = ("scatter", len(idx)) in self._io_rungs
        self._guard_live()
        new_state = scatter(
            self._state, {k: jnp.asarray(v) for k, v in arrays.items()},
            jnp.asarray(idx))
        self._guard_live()
        self._state = new_state
        self._io_rungs.add(("scatter", len(idx)))
        dt = time.monotonic() - t0
        if self._calib is not None and rung_warm:
            # Host wall of build+upload dispatch per page: on async
            # backends this under-counts on-device copy time, but it IS
            # the serve-loop cost the admission decision trades against
            # recompute dispatch cost (docs/kv-tiering.md, pricing).
            # First-use rungs are excluded — their wall is dominated by
            # the one-time jit compile, not the transfer.
            self._calib.observe_h2d(r, dt * 1e3)
        record_stage("engine_kv_restore", dt)
        tl = req.stream.timeline
        if tl is not None:
            tl.stage("engine_kv_restore", dt)
        for i, pg in enumerate(pages):
            h = hashes[k_use + i]
            parent = hashes[k_use + i - 1] if (k_use + i) else None
            if self._prefix_cache.insert(h, parent, pg):
                req.cache_refs.append(h)
                req.cache_pages.add(pg)
        with self._stats_lock:
            self._stats["kv_tier_restore_pages"] += r
            self._stats["kv_tier_restore_hits"] += 1
        if rec is not None:
            rec.kv_restore_pages += r
            rec.hbm_bytes += r * page * self._kv_bytes_per_token()
        return r

    def _transfer_prefetch(self, req: _Request) -> None:
        """Cross-replica prefix-page import, on the SUBMITTING thread
        (like bad-words compilation — the serve loop never does network
        I/O): when the router hinted a donor via ``X-KV-Transfer-From``
        (bound to the request context by the chain server), fetch the
        prompt-head blocks missing from the host tier from the donor's
        ``/control/kv_pages``. Bounded + best-effort: any failure or
        timeout places cold."""
        tier = self._kv_tier
        src = kv_tier_mod.current_transfer_source()
        if tier is None or src is None or not req.prompt_ids:
            return
        if not kv_tier_mod.donor_allowed(src):
            # The hint header is client-suppliable on a directly-hit
            # replica: when KV_TRANSFER_ALLOW scopes donors, anything
            # outside it is ignored — no fetch, no SSRF surface.
            logger.warning("kv transfer: donor %s not in "
                           "KV_TRANSFER_ALLOW; ignoring hint", src)
            return
        if req.block_hashes is None:
            req.block_hashes = hash_blocks(req.prompt_ids,
                                           self.cfg.page_size)
        missing = [h for h in req.block_hashes[:tier.transfer_max_pages]
                   if not tier.store.has(h)]
        if not missing:
            return
        got = kv_tier_mod.fetch_blocks(
            src, missing, timeout_s=tier.transfer_timeout_s,
            max_pages=tier.transfer_max_pages,
            on_corrupt=lambda: self._bump("kv_restore_corrupt"))
        if not got:
            return
        meta, records = got
        if not tier.compatible(meta):
            logger.warning("kv transfer: donor %s pool geometry does not "
                           "match; ignoring payload", src)
            return
        # Only blocks we ASKED for may land: the content address is this
        # prompt's own hash chain, so an answer naming any other hash is
        # either a donor bug or an attempt to poison unrelated cached
        # prefixes through the shared host store — dropped either way.
        wanted = set(missing)
        n = sum(1 for record in records
                if record.hash in wanted and tier.store.put(record))
        if n:
            self._bump("kv_tier_transfer_pages", n)
            tl = req.stream.timeline
            if tl is not None:
                tl.annotate(kv_transfer_pages=n)

    # ------------------------------------------------ control operations

    def _drain_control(self) -> bool:
        """Execute queued control closures (suspend/export) on the serve
        loop, between rounds — they touch scheduler-owned structures
        (prefix cache, free pages, device state) that must never see a
        second thread."""
        did = False
        while True:
            try:
                fn, box, ev = self._control.get_nowait()
            except queue.Empty:
                return did
            did = True
            try:
                box["result"] = fn()
            except BaseException as exc:  # noqa: BLE001 — relayed to caller
                box["error"] = exc
            finally:
                ev.set()

    def _run_control(self, fn, timeout: float = 30.0):
        """Run ``fn`` on the serve loop (queued; bounded wait) — or
        inline when the loop is not running (construction-time and
        stopped engines are single-threaded by contract)."""
        thread = self._thread
        if thread is None or not thread.is_alive():
            return fn()
        box: dict = {}
        ev = threading.Event()
        self._control.put((fn, box, ev))
        self._wake.set()
        if not ev.wait(timeout):
            raise EngineError("engine control op timed out")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _collect_blocks(self, hashes: list, start: int, stop: int,
                        into_store: bool = True) -> list:
        """Serve-loop body of export/suspend: walk the chain slice
        ``[start, stop)``, pulling each block from the host tier or
        gathering it out of HBM (one batched gather + blocking readback
        — a control op, off the token path). Callers BATCH long chains
        across control ops so decode rounds interleave between slices
        (one uncapped readback would stall every live stream). Stops at
        the first block resident in neither tier; chained hashes make a
        gapped chain useless anyway."""
        tier = self._kv_tier
        out: list = []
        gather_meta: list = []   # (out index, hash, parent, page)
        for i in range(start, min(stop, len(hashes))):
            h = hashes[i]
            rec = tier.store.peek(h)
            if rec is not None:
                out.append(rec)
                continue
            pg = (self._prefix_cache.page_of(h)
                  if self._prefix_cache is not None else None)
            if pg is None:
                break
            parent = hashes[i - 1] if i else None
            gather_meta.append((len(out), h, parent, pg))
            out.append(None)
        if gather_meta:
            gather, _ = self._page_io_fns()
            idx = self._pad_pages([pg for _, _, _, pg in gather_meta])
            arrays = gather(self._state["cache"], jnp.asarray(idx))
            host = {k: np.asarray(v) for k, v in arrays.items()}
            records = KVTier.split_pages(
                host, [(h, par) for _, h, par, _ in gather_meta])
            for (slot_i, _, _, _), record in zip(gather_meta, records):
                out[slot_i] = record
                if into_store:
                    # Exporting is free warming: the gathered block now
                    # also lives in the host tier.
                    tier.store.put(record)
        return [r for r in out if r is not None]

    def export_blob(self, hashes: Sequence[bytes],
                    max_blocks: Optional[int] = None
                    ) -> tuple[bytes, int]:
        """Serialize the leading cached blocks of a hash chain for a
        peer replica (the ``GET /control/kv_pages`` payload). Returns
        ``(blob, n_blocks)`` — n may be 0 (empty blob) when nothing of
        the chain is resident in either tier. Size-capped at the
        transfer page cap."""
        if self._kv_tier is None:
            raise EngineError(
                "KV tiering is disabled (KV_HOST_POOL_TOKENS=0)")
        tier = self._kv_tier
        cap = int(max_blocks or tier.transfer_max_pages)
        chain = list(hashes)
        recs = self._run_control(
            lambda: self._collect_blocks(chain, 0, cap))
        # Serialization happens HERE, on the caller's thread — the
        # serve loop only gathers.
        return kv_tier_mod.to_blob(recs, tier.meta), len(recs)

    def suspend_session(self, token_ids: Sequence[int]
                        ) -> Optional[bytes]:
        """Demote an idle conversation's full prefix chain out of BOTH
        tiers into a compact blob (engine/kv_tier.py wire format).
        HBM pages return to the free list; host copies are dropped.
        Blocks still referenced by live requests — or shared as
        interior blocks of another resident chain — stay put (they are
        exported into the blob regardless, so resume is complete).
        Returns None when nothing of the chain is cached."""
        if self._kv_tier is None:
            raise EngineError(
                "KV tiering is disabled (KV_HOST_POOL_TOKENS=0)")
        ids = list(token_ids)
        tier = self._kv_tier
        page = self.cfg.page_size
        hashes = hash_blocks(ids, page)
        # Collect in transfer-cap slices, one control op each: decode
        # rounds interleave between slices, so a long conversation's
        # suspend never stalls live streams for its whole readback.
        records: list = []
        step = max(1, tier.transfer_max_pages)
        for lo in range(0, len(hashes), step):
            batch = self._run_control(
                lambda lo=lo: self._collect_blocks(
                    hashes, lo, lo + step, into_store=False))
            records.extend(batch)
            if len(batch) < min(step, len(hashes) - lo):
                break   # chain ended mid-slice
        if not records:
            return None
        n = len(records)

        def demote():
            for h in reversed(hashes[:n]):   # leaf-first
                tier.store.pop(h)
                pg = self._prefix_cache.remove(h)
                if pg is not None:
                    self._free_pages.append(pg)
            with self._stats_lock:
                self._stats["kv_tier_suspended_blocks"] += n
        self._run_control(demote)
        # Blob assembly off the serve loop, on the caller's thread.
        return kv_tier_mod.to_blob(records, tier.meta)

    def resume_session(self, blob: bytes) -> int:
        """Re-seed a suspended session's blocks into the HOST tier (no
        device work — the next admission of the conversation restores
        them through the normal priced H2D path). Returns the number of
        blocks accepted. Raises EngineError on a geometry mismatch —
        silently loading another model's KV would serve garbage."""
        if self._kv_tier is None:
            raise EngineError(
                "KV tiering is disabled (KV_HOST_POOL_TOKENS=0)")
        try:
            meta, records = kv_tier_mod.from_blob(blob)
        except (ValueError, KeyError, TypeError) as exc:
            # Corrupt or malformed import (session resume, handoff
            # push): counted, then refused loudly — the sender's
            # fallback is recompute, never garbage pages in our pool.
            self._bump("kv_restore_corrupt")
            raise EngineError(f"malformed KV blob: {exc}") from exc
        if not self._kv_tier.compatible(meta):
            raise EngineError(
                f"KV blob geometry does not match this engine (blob "
                f"{meta!r} vs engine {self._kv_tier.meta!r})")
        n = sum(1 for rec in records if self._kv_tier.store.put(rec))
        self._bump("kv_tier_resumed_blocks", n)
        return n

    def export_handoff(self, token_ids: Sequence[int]
                       ) -> Optional[tuple[bytes, int]]:
        """Serialize a finished prompt's full prefix chain for
        push-on-completion handoff to a decode replica
        (docs/disaggregation.md). Unlike :meth:`suspend_session` the
        pages STAY resident here (the donor keeps serving pull-side
        ``/control/kv_pages`` fallbacks for the same prefix), and unlike
        :meth:`export_blob` the chain is NOT capped at the transfer page
        cap — it is collected in transfer-cap slices, one control op
        each, so decode rounds interleave between slices and the export
        overlaps them instead of stalling them. Returns ``(blob,
        n_blocks)`` or None when nothing of the chain is cached."""
        if self._kv_tier is None:
            raise EngineError(
                "KV tiering is disabled (KV_HOST_POOL_TOKENS=0)")
        tier = self._kv_tier
        hashes = hash_blocks(list(token_ids), self.cfg.page_size)
        records: list = []
        step = max(1, tier.transfer_max_pages)
        for lo in range(0, len(hashes), step):
            batch = self._run_control(
                lambda lo=lo: self._collect_blocks(
                    hashes, lo, lo + step))
            records.extend(batch)
            if len(batch) < min(step, len(hashes) - lo):
                break   # chain ended mid-slice
        if not records:
            return None
        self._bump("kv_tier_export_pages", len(records))
        # Blob assembly off the serve loop, on the caller's thread.
        return kv_tier_mod.to_blob(records, tier.meta), len(records)

    def _run(self) -> None:
        """Scheduler thread: retire completions, then execute ROUND PLANS
        from the token-budget scheduler — each iteration dispatches at
        most one decode round plus the prefill chunks that fit under the
        per-round budget (engine/scheduler.py), so a long prompt streams
        through in page-quantized chunks between decode rounds instead
        of monopolizing the loop until its prefill completes. NO device
        readback ever runs here — the harvest worker owns those — so the
        device queue stays >=1 round deep whenever there is work instead
        of draining behind a blocking np.asarray (the r5 ``loop_hround``
        ~285 ms serialization). Idle iterations park on ``_wake``, which
        submit(), cancel-capable emission, and every harvested item set —
        a completion-signalled pipeline, not a poll."""
        gen = self._gen
        try:
            while (not self._stopped.is_set() and self._gen == gen
                   and self._fatal is None):
                t0 = time.monotonic()
                did_drain = self._drain_completed()
                did_work = did_drain
                t1 = time.monotonic()
                # Only phases that did work get recorded: idle iterations
                # would race a first-wins stage collector with
                # meaningless ~0 values.
                if did_drain:
                    record_stage("loop_drain", t1 - t0)
                self._pull_pending()
                did_work |= self._drain_control()
                did_work |= self._cull_backlog()
                # Online calibration: fold any new measured-round
                # evidence into the planning model BEFORE this round is
                # planned (cheap version check; no-op when pinned).
                if self._calib is not None and self._sched.recalibrate():
                    with self._stats_lock:
                        self._stats["sched_round_budget_tokens"] = \
                            self._sched.round_budget_tokens
                        self._stats["sched_budget_recalibrations"] += 1
                plan = self._plan_round()
                did_work |= self._execute_plan(plan)
                self._guard_live()
                if not did_work:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
            if self._fatal is not None and self._gen == gen:
                # The harvest worker died: it set _fatal and woke us; fan
                # the failure out from HERE so _live_requests (which
                # mutates scheduler-owned structures) stays on this thread.
                for req in self._live_requests():
                    if not req.done:
                        req.stream._fail(self._fatal)
        except _StaleLoop:
            return  # disowned by reset(): its requests already failed
        except BaseException as exc:  # noqa: BLE001 - report to all streams
            if self._gen != gen:
                return  # disowned by reset(): its requests already failed
            self._fatal = exc
            for req in self._live_requests():
                if not req.done:
                    req.stream._fail(exc)

    def _queued_rounds(self) -> int:
        with self._pipe_lock:
            return self._inflight_rounds

    def _assert_harvestable(self, *arrays) -> None:
        """Sharded-serving harvest contract: every array headed for the
        harvest queue must materialize with ONE ``np.asarray`` and no
        implicit cross-host gather — per-round outputs are small
        REPLICATED arrays by construction (the sharded tail's out_specs
        replicate tokens/verdicts; scatters of replicated operands stay
        replicated). A violation means a dispatch returned
        device-SHARDED output the harvest thread would silently gather
        per round (cross-device always, cross-host on a multi-host
        slice): fail loudly at dispatch instead. Metadata check only —
        never a device sync."""
        if self.mesh is None:
            return
        for a in arrays:
            if not getattr(a, "is_fully_replicated", True):
                raise EngineError(
                    "round output is not replicated (sharding "
                    f"{getattr(a, 'sharding', None)!r}); harvest would "
                    "implicitly gather it every round — sharded round "
                    "outputs must be small replicated arrays")

    def _drain_completed(self) -> bool:
        """Scheduler-side half of request completion: the harvest worker
        finished these streams (terminal chunk + sentinel already
        delivered); dispatch the device release where the device still
        thinks the slot is live, then free slot/pages/cache refs."""
        did = False
        while True:
            try:
                req, finish = self._completed.get_nowait()
            except queue.Empty:
                return did
            did = True
            if self._slots.get(req.slot) is not req:
                continue  # already torn down by a reset/stop drain
            if finish not in ("eos", "length"):
                # Host-detected finish (stop word / cancel): the device
                # still thinks the slot is live — deactivate it before the
                # slot and its pages are reused. Commit the new state only
                # after a liveness re-check so a thread disowned mid-call
                # can't clobber the rebuilt generation.
                self._guard_live()
                new_state = self._release(self._state, jnp.int32(req.slot))
                self._guard_live()
                self._state = new_state
            self._retire(req, finish)

    def _harvest_worker(self) -> None:
        """Harvest thread: consume dispatched programs' outputs in FIFO
        order, blocking on each host copy HERE so the scheduler never
        does. The async copy was started at dispatch, so by the time an
        item is popped its bytes are usually already in flight; the wait
        measured into ``harvest_wait_ms``/``first_readback_ms`` overlaps
        admission and dispatch on the scheduler thread.

        This thread touches NO device state and none of the scheduler's
        structures: it reads its items' own snapshots, feeds streams
        (detokenize/stop-check are host-only), and posts finish decisions
        to ``_completed``. Execution errors surface at the readback on
        tunneled backends — they are caught here, recorded as _fatal, and
        fanned out by the scheduler."""
        gen = self._gen
        try:
            while (not self._stopped.is_set() and self._gen == gen
                   and self._fatal is None):
                try:
                    item = self._harvest_q.get(timeout=0.05)
                except queue.Empty:
                    continue
                faults.inject("engine.harvest")  # chaos: readback failure
                kind = item[0]
                t0 = time.monotonic()
                if kind == "mark":
                    # A prefill-only round's completion marker: the
                    # scalar's readback lands when the round's last
                    # chunk has executed on the device — the execution
                    # half of its RoundRecord completes here.
                    _, rec, marker = item
                    np.asarray(marker)  # blocks off-thread
                    wait = time.monotonic() - t0
                    if self._gen != gen:
                        return
                    self.rounds.complete_part(rec,
                                              harvest_wait_ms=wait * 1e3)
                    self._wake.set()
                    continue
                if kind == "offload":
                    # Evicted prefix pages on their way to the host
                    # tier: materialize the gather's async D2H copies
                    # here, OFF the scheduling path, and park them in
                    # the content-addressed host store.
                    _, metas, dev_arrays, rung_warm = item
                    host = {k: np.asarray(v)
                            for k, v in dev_arrays.items()}
                    wait = time.monotonic() - t0
                    if self._gen != gen:
                        return
                    tier = self._kv_tier
                    if tier is not None:
                        for block in KVTier.split_pages(host, metas):
                            tier.store.put(block)
                        if self._calib is not None and rung_warm:
                            # First-use gather rungs are excluded like
                            # the scatter side: their wait is dominated
                            # by the one-time jit compile. (Steady
                            # state, the async copy often lands before
                            # the pop — the wait is a floor estimate.)
                            self._calib.observe_d2h(len(metas),
                                                    wait * 1e3)
                        self._bump("kv_tier_offload_pages", len(metas))
                    self._wake.set()
                    continue
                if kind == "first":
                    _, req, first_tok, rec = item
                    arr = np.asarray(first_tok)  # blocks off-thread
                    wait = time.monotonic() - t0
                    record_stage("engine_first_readback", wait)
                    self._bump("first_readback_ms", wait * 1e3)
                    self._bump("first_readbacks")
                    tl = req.stream.timeline
                    if tl is not None:   # lock-free ring append
                        tl.stage("engine_first_readback", wait)
                    if self._gen != gen:
                        return
                    emitted_first = not req.done
                    if not req.done:
                        if arr.ndim == 0:
                            self._emit_token(req, int(arr))
                        else:
                            # Fused-RAG aux row:
                            # [first_token, prompt_len, top_ids...]
                            req.stream.source_ids = [int(x)
                                                     for x in arr[2:]]
                            self._emit_token(req, int(arr[0]))
                    self.rounds.first_token(rec, wait_ms=wait * 1e3,
                                            counted=emitted_first)
                else:
                    if kind == "verify":
                        _, members, toks_dev, acc_dev, drafted, rec = item
                        accs = np.asarray(acc_dev)   # blocks off-thread
                    else:
                        _, members, toks_dev, rec = item
                        accs = drafted = None
                    toks = np.asarray(toks_dev)  # (K, B); blocks off-thread
                    wait = time.monotonic() - t0
                    record_stage("engine_harvest_wait", wait)
                    self._bump("harvest_wait_ms", wait * 1e3)
                    self._bump("harvest_rounds")
                    if self._gen != gen:
                        return
                    emitted: dict[int, int] = {}
                    for k in range(toks.shape[0]):
                        row = toks[k]
                        for slot, req in members.items():
                            if req.done:
                                # A host-detected finish (stop word /
                                # cancel / deadline) mid-burst: trailing
                                # device-accepted tokens are DISCARDED —
                                # never streamed, never counted, never
                                # fed to the drafter (the slot retires,
                                # so the device's advanced pos is moot).
                                continue
                            tok = int(row[slot])
                            if tok < 0:
                                continue  # inactive on-device at this step
                            emitted[slot] = emitted.get(slot, 0) + 1
                            self._emit_token(req, tok)
                    # ONE timeline event per request per round (token
                    # count), never per token — the flight recorder's
                    # token-path budget. Ring appends are lock-free.
                    for slot, n in emitted.items():
                        tl = members[slot].stream.timeline
                        if tl is not None:
                            tl.event("decode_round", n)
                    accepted = 0
                    if kind == "verify":
                        accepted = self._finish_verify(members, accs,
                                                       drafted, emitted)
                    self.rounds.complete_part(
                        rec, tokens=sum(emitted.values()),
                        spec_accepted=accepted,
                        harvest_wait_ms=wait * 1e3)
                    with self._pipe_lock:
                        # Guarded by the generation check just above: a
                        # worker disowned during the readback must not
                        # decrement the rebuilt pipeline's fresh counter.
                        if self._gen == gen:
                            self._inflight_rounds -= 1
                self._wake.set()  # dispatch capacity / slots may be free
        except BaseException as exc:  # noqa: BLE001 — fan out via scheduler
            if self._gen != gen:
                return  # disowned by reset(): its requests already failed
            self._fatal = exc
            # Wake the scheduler: it notices _fatal, exits its loop, and
            # fails every live request (all of them reachable via _slots /
            # _pending, including this item's members).
            self._wake.set()

    def _finish_verify(self, members: dict, accs, drafted: dict,
                       emitted: dict) -> int:
        """Harvest-side bookkeeping of one verify round: speculative
        stats, per-request flight-recorder draft/accept counts, the
        adaptive-K controllers, and the ``proj_pos`` re-anchor (the
        dispatch bumped it by the full S upper bound; the burst may
        have consumed less — ``base_len + generated - 1`` is the exact
        device pos for any armed slot). Runs on the harvest thread;
        the scheduler only reads these fields after ``_queued_rounds``
        drops to 0, which happens strictly after this returns."""
        draft_total = sum(drafted.values())
        accept_total = 0
        for slot, req in members.items():
            k = drafted.get(slot, 0)
            a = min(int(accs[slot]), k)
            accept_total += a
            if k > 0 and req.spec_ctrl is not None:
                req.spec_ctrl.update(k, a)
            tl = req.stream.timeline
            if tl is not None and (k or emitted.get(slot)):
                tl.event("spec_drafted", k)
                tl.event("spec_accepted", a)
            if req.prefill_done and not req.done:
                req.proj_pos = min(req.extent,
                                   req.base_len + req.generated - 1)
        with self._stats_lock:
            self._stats["spec_draft_tokens"] += draft_total
            self._stats["spec_accepted_tokens"] += accept_total
            self._stats["spec_verify_tokens"] += sum(emitted.values())
            self._stats["spec_verify_slot_steps"] += len(emitted)
        return accept_total

    def _pull_pending(self) -> bool:
        """Drain the thread-safe intake queue into the scheduler's
        backlog. Pulls stop at ``max_queue`` backlog entries so the
        intake queue still fills — and still sheds 429s — under
        sustained overload; the backlog itself is scheduler-private and
        re-ordered by deadline slack every round."""
        moved = False
        while len(self._backlog) < self.cfg.max_queue:
            try:
                self._backlog.append(self._pending.get_nowait())
            except queue.Empty:
                break
            moved = True
        return moved

    def _cull_backlog(self) -> bool:
        """Shed cancelled and queue-expired backlog entries BEFORE any
        slot/page is touched — the PR-5 ``deadline_queue`` path, now run
        over the whole backlog every round instead of only at FIFO head
        pickup (a deep expired request no longer waits for the queue to
        drain past it before it is dropped)."""
        kept: list[tuple[_Request, SamplingParams]] = []
        did = False
        now = time.monotonic()
        for req, sp in self._backlog:
            if req.stream.cancelled:
                req.stream._finish("cancelled")
                did = True
                continue
            if req.deadline_t is not None and now > req.deadline_t:
                self._bump("deadline_queue_drops")
                tl = req.stream.timeline
                if tl is not None:
                    tl.stage("engine_admit_pickup",
                             now - req.stream.submit_time)
                req.stream._finish("deadline_queue")
                did = True
                continue
            kept.append((req, sp))
        self._backlog = kept
        return did

    def _plan_round(self):
        """Build this round's token-budget plan: the right-sized decode
        dispatch (power-of-two step ladder, unchanged from the pre-
        scheduler loop — a decode-only workload plans exactly the rounds
        it always got) plus the prefill jobs the scheduler may grant
        chunks to. In-flight prefills (slots mid-chunking) are offered
        first; backlog admissions are offered only when a slot is free
        and are slack-ordered inside plan_round."""
        armed = [r for r in self._slots.values() if r.prefill_done]
        need_steps = max((r.extent - r.proj_pos for r in armed), default=0)

        def ladder_steps() -> int:
            # Right-size the classic round against the power-of-two
            # step ladder — ONE definition, so spec-on and spec-off
            # engines can never drift apart in round shape.
            s = self.cfg.steps_per_round
            while s // 2 >= need_steps:
                s //= 2
            return s

        steps = 0
        verify_cost = None
        self._draft_plan = None
        if self._spec is not None:
            # Verify rounds require a DRAINED pipeline: the drafter
            # needs the previous round's tokens on the host, so
            # dispatch-ahead would draft blind — the up-to-S-tokens
            # multiplier pays for that lost overlap. Rounds that will
            # NOT draft gain nothing from the drain, so a workload with
            # no repetition in sight keeps the PR-8 dispatch-ahead
            # classic rounds instead of serializing for free.
            if need_steps > 0 and self._queued_rounds() == 0:
                self._draft_plan = self._plan_drafts(armed)
                if self._draft_plan is not None:
                    # One model step; priced as the S positions each
                    # armed slot actually computes, converted through
                    # the measured verify cost (StepCostModel).
                    steps = 1
                    verify_cost = self._sched.cost.verify_cost_tokens(
                        self._spec_S * len(armed))
                else:
                    # Nothing draftable at the drain point: classic
                    # multi-step round, the exact plain-decode program.
                    steps = ladder_steps()
            elif (need_steps > 0
                    and self._queued_rounds() < self.cfg.dispatch_depth
                    and not self._any_draftable(armed)):
                # Pipeline is non-empty and no armed slot shows a
                # draftable n-gram even on its (possibly stale) host
                # context — dispatch ahead as plain decode always did.
                # If a slot DOES look draftable, hold this round so the
                # pipeline drains and the next plan can verify.
                steps = ladder_steps()
        elif need_steps > 0 \
                and self._queued_rounds() < self.cfg.dispatch_depth:
            steps = ladder_steps()
        inflight = [
            PrefillJob(key=r, remaining=len(r.prompt_ids) - r.pf_pos,
                       deadline_t=r.deadline_t, seq=r.seq, started=True)
            for r in self._slots.values() if not r.prefill_done]
        backlog_jobs = []
        if self._free_slots:
            for req, _sp in self._backlog:
                # Pre-admission estimate: the full prompt (a prefix-cache
                # hit is only discovered at admission and can only SHRINK
                # the real chunk plan). Fused-RAG prompts are assembled
                # on-device at the spec's bucket size.
                remaining = (self._fused_rag.spec.bucket
                             if req.rag is not None
                             else len(req.prompt_ids))
                backlog_jobs.append(PrefillJob(
                    key=req, remaining=remaining,
                    deadline_t=req.deadline_t, seq=req.seq))
        return self._sched.plan_round(
            decode_steps=steps, active_decodes=len(armed),
            inflight=inflight, backlog=backlog_jobs,
            now=time.monotonic(), max_new=len(self._free_slots),
            decode_cost_tokens=verify_cost)

    def _any_draftable(self, armed) -> bool:
        """Cheap hint: could any armed slot propose >= 1 draft token
        right now? Used while rounds are still in flight — the host
        context may lag the device by the unharvested rounds, so this
        is a HINT for the pipeline-vs-drain decision, never the source
        of actual drafts (those are proposed only at a drained
        pipeline, where the context is exact). A stale positive just
        drains the pipeline one round earlier than necessary; a stale
        negative keeps one more round pipelined."""
        for req in armed:
            if req.drafter is None or req.spec_ctrl is None \
                    or not req.stream.token_ids:
                continue
            if min(req.spec_ctrl.k,
                   req.eff_max - req.generated - 1) <= 0:
                continue
            if req.drafter.propose(1):
                return True
        return False

    def _plan_drafts(self, armed) -> Optional[dict]:
        """Prompt-lookup proposals for this round: {slot: draft ids}.
        None when no armed slot can draft — the caller then dispatches a
        classic round instead (a verify round with zero drafts would
        emit one token per slot at multi-token-forward prices).

        Slots whose first token is still unharvested draft nothing (the
        host index would be behind the device's last token — proposals
        would verify against the wrong position's context); their rows
        still ride the verify round and emit exactly one token, so
        correctness never depends on the drafter's view."""
        plan: dict[int, list[int]] = {}
        total = 0
        for req in armed:
            if req.drafter is None or req.spec_ctrl is None \
                    or not req.stream.token_ids:
                continue
            # Never draft past the request's remaining output budget:
            # positions past it could write K/V beyond the allocated
            # extent (the device would truncate the emission anyway,
            # but the pages must stay in bounds).
            k = min(req.spec_ctrl.k, self._spec.max_draft_tokens,
                    req.eff_max - req.generated - 1)
            if k <= 0:
                continue
            proposal = req.drafter.propose(k)
            if proposal:
                plan[req.slot] = proposal
                total += len(proposal)
        return plan if total else None

    def _execute_plan(self, plan) -> bool:
        """Dispatch one round plan: the decode round first (the latency-
        critical work for every armed stream), then the granted prefill
        chunks. Stops admitting on pool backpressure; counts the round
        as interleaved when both kinds of work actually dispatched.

        Round telemetry: the plan opens a RoundRecord (scheduler-side
        half), each dispatch fills its execution fields, and the harvest
        worker completes it — a prefill-only round gets a completion
        MARKER in the harvest queue (a scalar output of the last chunk's
        program, so its readback lands exactly when the chunk's device
        work finishes)."""
        rec = None
        if plan.decode_steps or plan.chunks:
            rec = self.rounds.begin(
                engine_tag=self._engine_tag,
                budget_tokens=plan.budget_tokens,
                decode_steps=plan.decode_steps,
                decode_cost_tokens=plan.decode_cost_tokens,
                active_decodes=plan.active_decodes,
                kind=("verify" if (plan.decode_steps
                                   and self._draft_plan is not None)
                      else "decode" if plan.decode_steps else "prefill"),
                on_complete=self._on_round_complete)
        try:
            return self._execute_plan_inner(plan, rec)
        except BaseException:
            # The round died mid-dispatch (fault injection, _StaleLoop
            # from a reset, a device error): an unsealed record would
            # sit in the ring as not-done debris forever — drop it. A
            # SEALED record's fate rides the harvest pipeline as usual.
            if rec is not None and not rec._sealed:
                self.rounds.discard(rec)
            raise

    def _execute_plan_inner(self, plan, rec) -> bool:
        did = False
        decoded = False
        t0 = time.monotonic()
        if plan.decode_steps:
            if self._draft_plan is not None:
                decoded = self._dispatch_verify(self._draft_plan, rec)
                self._draft_plan = None
            else:
                decoded = self._dispatch_round(plan.decode_steps, rec)
            if decoded:
                did = True
                self._bump("sched_decode_tokens", plan.decode_cost_tokens)
                record_stage("loop_dispatch", time.monotonic() - t0)
        t1 = time.monotonic()
        prefilled = 0
        grants: list[tuple[str, int]] = []
        marker = None
        for key, grant in plan.chunks:
            req: _Request = key
            if req.slot < 0:
                if not self._free_slots:
                    break
                ok = self._begin_prefill(req, rec)
                if ok is None:     # dropped (cancel raced the grant)
                    continue
                if not ok:         # pool backpressure: stop admitting
                    break
            n, m = self._advance_prefill(req, grant, rec)
            self._guard_live()
            if n:
                did = True
                prefilled += n
                grants.append((req.stream.request_id, n))
                if m is not None:
                    marker = m
                if rec is not None:
                    # Prefill traffic estimate: each chunk streams the
                    # weights once and writes its tokens' KV.
                    rec.hbm_bytes += self._param_bytes \
                        + n * self._kv_bytes_per_token()
        if prefilled:
            self._bump("sched_prefill_tokens", prefilled)
            record_stage("loop_admit", time.monotonic() - t1)
            if decoded:
                self._bump("sched_interleaved_rounds")
        if rec is not None:
            parts = int(decoded)
            if prefilled and marker is not None:
                # Completion marker: a scalar OUTPUT of the last chunk's
                # program (never part of the donated state). The harvest
                # worker's np.asarray on it blocks until that program —
                # and, the device stream being FIFO, every earlier chunk
                # of this round — has executed: the honest end-of-round
                # signal for prefill work that otherwise produces no
                # readback until a slot arms.
                parts += 1
                self._assert_harvestable(marker)
                self._harvest_q.put(("mark", rec, marker))
            if parts == 0:
                self.rounds.discard(rec)
            else:
                if not decoded:
                    rec.kind = "prefill"
                elif prefilled:
                    rec.kind = "mixed" if rec.kind == "decode" \
                        else rec.kind
                self.rounds.seal(
                    rec, parts=parts, prefill_tokens=prefilled,
                    grants=grants,
                    modeled_ms=self._modeled_round_ms(
                        rec, plan.decode_steps if decoded else 0,
                        prefilled))
        return did

    def _modeled_round_ms(self, rec, decode_steps: int,
                          prefill_tokens: int) -> float:
        """What the live step-cost model predicts this round should
        take — the denominator of the drift ratio. Captured at seal
        time so a later recalibration cannot rewrite history."""
        cost = self._sched.cost
        modeled = 0.0
        if decode_steps:
            if rec.verify_positions:
                per = cost.verify_ms_per_token or cost.prefill_ms_per_token
                modeled += rec.verify_positions * per
            else:
                modeled += cost.decode_round_ms(decode_steps)
        modeled += prefill_tokens * cost.prefill_ms_per_token
        # In-flight H2D: restored pages ride the round's device queue
        # ahead of the chunk grants — priced so the drift gauge stays
        # truthful on restore-heavy rounds (0 until h2d is measured).
        if rec.kv_restore_pages:
            modeled += cost.restore_ms(rec.kv_restore_pages)
        return modeled

    def _on_round_complete(self, rec) -> None:
        """Harvest-thread completion callback for one round record:
        bandwidth estimate, drift accounting, calibrator feed, metric
        mirror, slow-round dump, and the retrospective OTel span.
        Observability — never raises into the harvest worker."""
        try:
            if self._hbm_peak > 0 and rec.device_ms > 0:
                rec.bw_util = rec.hbm_bytes / (rec.device_ms / 1e3) \
                    / self._hbm_peak
            ratio = (rec.round_ms / rec.modeled_ms
                     if rec.modeled_ms > 0 else 0.0)
            rec.drift_ratio = ratio
            if ratio > 0:
                prev = self._drift_ratio
                self._drift_ratio = (ratio if prev is None
                                     else prev + 0.2 * (ratio - prev))
            # Calibration: only PURE rounds are attributable (a mixed
            # round's device time cannot be split honestly).
            if self._calib is not None:
                if rec.kind == "decode" and not rec.prefill_tokens:
                    self._calib.observe_decode(rec.decode_steps,
                                               rec.device_ms)
                elif rec.kind == "verify" and not rec.prefill_tokens:
                    self._calib.observe_verify(rec.verify_positions,
                                               rec.device_ms)
                elif rec.kind == "prefill":
                    self._calib.observe_prefill(rec.prefill_tokens,
                                                rec.device_ms)
            self._bump("rounds_completed")
            obs_rounds.record_round_metrics(rec, self._drift_ratio)
            slow = (self._slow_round_ms
                    and rec.round_ms > self._slow_round_ms)
            drifted = (self._drift_dump_ratio and ratio
                       and ratio > self._drift_dump_ratio
                       # micro-rounds drift wildly on noise alone; only
                       # dump when the model predicted measurable work
                       and rec.modeled_ms >= 0.25)
            if slow or drifted:
                obs_rounds.count_slow_dump()
                log_event(logger, "slow_round",
                          reason=("slow" if slow else "drift"),
                          drift_ratio=round(ratio, 3),
                          drift_threshold=self._drift_dump_ratio,
                          slow_ms_threshold=self._slow_round_ms,
                          round=rec.to_dict())
            obs_rounds.emit_round_span(rec)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            logger.debug("round completion accounting failed",
                         exc_info=True)

    def _begin_prefill(self, req: _Request, rec=None):
        """Admission half 1: allocate the slot and pages, take prefix-
        cache refs, and build the dispatch context the chunk programs
        share. Returns True on success, False on pool backpressure (the
        request stays in the backlog; the caller stops admitting this
        round — pool pressure is global), None when the request was
        dropped instead of admitted. ``rec``: this round's telemetry
        record — KV-tier offload/restore traffic is attributed to it."""
        if req.stream.cancelled:
            self._backlog = [e for e in self._backlog if e[0] is not req]
            req.stream._finish("cancelled")
            return None
        sp = req.params
        n_alloc = _ceil_div(req.extent, self.cfg.page_size)
        # Shared-prefix match: map the longest cached block chain of
        # this prompt read-only (refs taken NOW so pool-pressure
        # eviction below can't reclaim it out from under us).
        hashes, k_use, hit_pages = self._prefix_lookup(req)
        # Host tier: plan the priced restore of the chain's continuation
        # BEFORE eviction (the records are materialized host-side now,
        # so this admission's own offloads can't LRU them away).
        restore_recs: list = []
        if self._kv_tier is not None and req.rag is None and hashes:
            restore_recs = self._plan_restore(req, hashes, k_use)
        need_new = n_alloc - k_use
        if need_new > len(self._free_pages):
            # Pool pressure: reclaim retired requests' warm prefix
            # pages (refcount 0, LRU leaf-first) before declaring
            # backpressure — the cache borrows pool pages, it never
            # shrinks serving capacity. With the host tier enabled the
            # victims are OFFLOADED (async D2H) instead of dropped.
            if self._prefix_cache is not None:
                victims: list = []
                sink = None
                if self._kv_tier is not None:
                    sink = (lambda h, e:
                            victims.append((h, e.parent, e.page)))
                self._free_pages.extend(self._prefix_cache.evict(
                    need_new - len(self._free_pages), sink=sink))
                self._offload_victims(victims, rec)
            if need_new > len(self._free_pages):
                if k_use:
                    self._prefix_cache.release(hashes[:k_use])
                return False  # pool backpressure: wait for pages
        self._backlog = [e for e in self._backlog if e[0] is not req]
        slot = self._free_slots.pop()
        req.slot = slot
        req.pages = hit_pages + [self._free_pages.pop()
                                 for _ in range(need_new)]
        req.cache_refs = list(hashes[:k_use])
        req.cache_pages = set(hit_pages)
        restored = 0
        if restore_recs:
            try:
                restored = self._restore_blocks(req, hashes, k_use,
                                                restore_recs, rec)
            except _StaleLoop:
                raise
            except Exception:  # noqa: BLE001 — fall back to recompute
                # The allocated pages hold garbage at worst; prefill
                # recomputes straight over them from the HBM-hit
                # boundary — token-identical, just slower (pinned by
                # the kv.restore chaos test).
                logger.warning("kv restore failed; recomputing prefix",
                               exc_info=True)
                restored = 0
        start_tok = (k_use + restored) * self.cfg.page_size
        req.proj_pos = len(req.prompt_ids)
        req.pf_pos = start_tok
        row = np.zeros((self._pmax,), np.int32)
        row[:n_alloc] = req.pages
        if self._prefix_cache is not None and req.rag is None:
            st = self._prefix_cache.stats
            st.lookups += 1
            st.lookup_tokens += len(req.prompt_ids)
            if start_tok:
                st.hits += 1
                st.hit_tokens += start_tok

        now = time.monotonic()
        qwait = now - req.stream.submit_time
        record_stage("engine_admit_pickup", qwait)
        if req.deadline_t is not None:
            # Slack at admission: the headroom left after the modeled
            # prefill of the UNCACHED suffix. Clamped at 0 — the
            # histogram answers "how much margin do admitted requests
            # carry"; negative-slack admissions all land in the first
            # bucket (they are also the ones deadline_stops later
            # counts if the model was right).
            slack = (req.deadline_t - now) - self._sched.cost.prefill_s(
                len(req.prompt_ids) - start_tok)
            record_stage("sched_slack", max(slack, 0.0))
        tl = req.stream.timeline
        if tl is not None:
            # Scheduler-side timeline events: queue wait, the slot
            # and pages this request occupies, and how much of the
            # prompt the prefix cache already held.
            tl.stage("engine_admit_pickup", qwait)
            tl.annotate(slot=slot, pages_held=len(req.pages),
                        prefix_hit_tokens=start_tok)
        # Masks/tables were built at submit() on the caller's thread
        # (overlapped with the queue wait) — the serve loop only
        # uploads them, keeping admission dispatch lean.
        banned = jnp.asarray(req.banned_np)
        bad_seq = jnp.asarray(req.bad_seq_np)
        bad_len = jnp.asarray(req.bad_len_np)
        # uploaded; don't pin ~vocab-size bytes per request for the
        # rest of its lifetime (queue depth x 128k-vocab rows adds up)
        req.banned_np = req.bad_seq_np = req.bad_len_np = None
        if req.resume_offset is not None:
            # Failover resume (docs/robustness.md): the admission key
            # must be a pure function of (seed, replay offset) — the
            # global step counter would make the continuation's first
            # draw depend on unrelated admissions, breaking the "same
            # seed ⇒ same continuation" resume contract. The offset
            # salt keeps a resume at offset N distinct from both a
            # fresh request and a resume at a different boundary.
            key = jax.random.fold_in(
                self._base_key,
                ((req.resume_offset + 1) << 20) ^ sp.random_seed)
        else:
            key = jax.random.fold_in(
                self._base_key,
                next(self._step_counter) ^ sp.random_seed)
        # Chunk-window geometry (only the chunked path reads it): the
        # gather window must cover the PADDED chunk span, not just the
        # request extent — a chunk whose padding runs past the window
        # would make dynamic_update_slice/dynamic_slice CLAMP their
        # starts and silently relocate KV over the prompt's own pages.
        # Chunk pads come from the prefill-bucket ladder, so one extra
        # max-bucket of pages covers any final-chunk overhang; pages
        # past the extent map to the trash page 0.
        page = self.cfg.page_size
        span_pages = (start_tok // page
                      + _ceil_div(len(req.prompt_ids) - start_tok, page)
                      + self._buckets[-1] // page)
        window = max(self._window_for(_ceil_div(req.extent, page)),
                     span_pages)
        row_ext = np.zeros((window,), np.int32)
        row_ext[:min(len(row), window)] = row[:min(len(row), window)]
        seen0 = None
        if start_tok > 0:
            # Prefix-cache hit: the seen (repetition-penalty) mask over
            # the skipped prefix is rebuilt host-side from the prompt
            # itself and seeded into the first chunk's dispatch (packed,
            # same uint32 bitfield layout as the device state).
            V = self.model_cfg.vocab_size
            seen0 = np.zeros((V,), bool)
            ids = np.asarray(req.prompt_ids[:start_tok], np.int64)
            seen0[ids[(ids >= 0) & (ids < V)]] = True
            seen0 = pack_mask_np(seen0)
        req.pf = {
            "row": row, "row_win": jnp.asarray(row_ext[None, :]),
            "window": window, "start_tok": start_tok,
            "hashes": hashes, "k_use": k_use,
            "seed": None if seen0 is None else jnp.asarray(seen0),
            "banned": banned, "bad_seq": bad_seq, "bad_len": bad_len,
            "key": key, "dispatch_s": 0.0,
        }
        self._slots[slot] = req
        self._bump("prefills")
        return True

    def _abort_prefill(self, req: _Request, finish: str) -> None:
        """Retire a mid-prefill request (cancel / passed deadline). The
        slot was never armed on the device (``active`` stays False until
        the final chunk), so no device release is needed — just the
        slot/page/cache-ref bookkeeping."""
        req.pf = None
        self._retire(req, finish)

    def _chunk_pad(self, n: int) -> int:
        """Compiled shape for an ``n``-token chunk: the smallest prefill
        bucket that covers it — chunk programs reuse the bucket ladder's
        shapes, so interleaving adds no new compile geometries."""
        return self._bucket_for(n)

    def _advance_prefill(self, req: _Request, grant: int,
                         rec=None) -> tuple[int, Optional[object]]:
        """Admission half 2, run once per round plan: dispatch ONE
        prefill chunk of up to ``grant`` tokens (bucket-shape padded).
        The final chunk arms the slot and hands the first token to the
        harvest worker. Returns ``(tokens computed, completion
        marker)`` — the marker is a device scalar that data-depends on
        the dispatched program (round telemetry reads it to time the
        round's end); ``(0, None)`` when nothing dispatched. Short cold
        prompts whose whole extent fits the grant keep the ONE-dispatch
        fused prefill+insert path — the TTFT-critical case is still a
        single program."""
        sp = req.params
        if req.rag is not None:
            return self._dispatch_rag(req, rec)
        pf = req.pf
        if req.pf_pos > pf["start_tok"]:
            # Between-chunk aborts only: an admission that began keeps
            # the PR-5 contract (its first dispatch runs and the harvest
            # path notices cancellation/deadline at the first token) —
            # but a MULTI-chunk prefill whose caller is gone stops
            # sinking further rounds into an unwanted answer.
            if req.stream.cancelled:
                self._abort_prefill(req, "cancelled")
                return 0, None
            if req.deadline_t is not None \
                    and time.monotonic() > req.deadline_t:
                # Counted as a mid-flight deadline stop (the request DID
                # consume compute, unlike a deadline_queue drop).
                self._bump("deadline_stops")
                self._abort_prefill(req, "deadline")
                return 0, None
        total = len(req.prompt_ids)
        page = self.cfg.page_size
        n = min(grant, total - req.pf_pos, self._buckets[-1])
        final = req.pf_pos + n >= total
        if not final:
            n = (n // page) * page
            if n <= 0:
                return 0, None
        faults.inject("engine.dispatch")  # chaos: slow/failed prefill
        t_chunk = time.monotonic()
        key = pf["key"]
        if final and req.pf_pos == 0 and total <= self._buckets[-1]:
            # Whole cold prompt in one grant: the classic fused
            # prefill+sample+insert dispatch (one program boundary on
            # the TTFT path — see _build_jitted).
            bucket = self._bucket_for(total)
            ids = req.prompt_ids + [0] * (bucket - total)
            tokens = jnp.asarray(np.asarray(ids, np.int32)[None, :])
            self._guard_live()
            new_state, first_tok = self._prefill_insert(
                self._state, self.params, tokens, jnp.int32(total),
                jnp.int32(req.slot), jnp.asarray(pf["row"]),
                jnp.float32(sp.temperature), jnp.int32(sp.top_k),
                jnp.float32(sp.top_p), jnp.float32(sp.repetition_penalty),
                pf["banned"], pf["bad_seq"], pf["bad_len"], key,
                jnp.int32(req.eff_max - 1), jnp.bool_(not sp.ignore_eos),
                req.greedy)
            self._guard_live()
            self._state = new_state
            marker = first_tok
        else:
            C = self._chunk_pad(n)
            chunk = req.prompt_ids[req.pf_pos:req.pf_pos + n] \
                + [0] * (C - n)
            toks = jnp.asarray(np.asarray(chunk, np.int32)[None, :])
            start = jnp.int32(req.pf_pos)
            valid = jnp.int32(req.pf_pos + n)
            seeding = (req.pf_pos == pf["start_tok"]
                       and pf["seed"] is not None)
            self._guard_live()
            if not final:
                if seeding:
                    new_state, marker = self._chunk_extend_fn(
                        pf["window"], "seed")(
                        self._state, self.params, toks, start, valid,
                        jnp.int32(req.slot), pf["row_win"], pf["seed"])
                else:
                    mode = ("replace"
                            if req.pf_pos == 0 and pf["start_tok"] == 0
                            else "accum")
                    new_state, marker = self._chunk_extend_fn(
                        pf["window"], mode)(
                        self._state, self.params, toks, start, valid,
                        jnp.int32(req.slot), pf["row_win"])
                first_tok = None
            else:
                args = (self._state, self.params, toks, start, valid,
                        jnp.int32(req.slot), jnp.asarray(pf["row"]),
                        pf["row_win"], jnp.float32(sp.temperature),
                        jnp.int32(sp.top_k), jnp.float32(sp.top_p),
                        jnp.float32(sp.repetition_penalty), pf["banned"],
                        pf["bad_seq"], pf["bad_len"], key,
                        jnp.int32(req.eff_max - 1),
                        jnp.bool_(not sp.ignore_eos))
                if seeding:
                    args = args + (pf["seed"],)
                new_state, first_tok = self._chunk_final_fn(
                    pf["window"], req.greedy, seeding)(*args)
                marker = first_tok
            self._guard_live()
            self._state = new_state
        dt = time.monotonic() - t_chunk
        pf["dispatch_s"] += dt
        tl = req.stream.timeline
        if tl is not None:
            # Host-side dispatch time of this chunk (the device work
            # is async); one event per chunk.
            tl.stage("engine_prefill_chunk", dt)
        req.pf_pos += n
        if final:
            self._arm_slot(req, first_tok, rec)
        return n, marker

    def _arm_slot(self, req: _Request, first_tok, rec=None) -> None:
        """Prefill complete: publish cache blocks, mark the slot armed
        for decode rounds, and hand the first-token readback to the
        harvest worker (its wait overlaps the decode rounds dispatched
        right after — FIFO order in the queue keeps it ahead of them).
        ``rec``: the round record of the ARMING round — the harvest
        worker attributes the first-token readback wait (and the first
        token itself) to it."""
        pf = req.pf
        self._register_prefix(req, pf["hashes"], pf["k_use"])
        record_stage("engine_admit_dispatch", pf["dispatch_s"])
        tl = req.stream.timeline
        if tl is not None:
            # Cumulative host dispatch time across every chunk of this
            # admission — the same meaning the one-dispatch path always
            # had, now summed over the interleaved pieces.
            tl.stage("engine_admit_dispatch", pf["dispatch_s"])
        try:
            # Start the device->host transfer of the first token now —
            # the harvest worker's np.asarray then finds the value
            # host-side (or at least in flight) instead of paying the
            # full readback RTT after the fact.
            first_tok.copy_to_host_async()
        except Exception:  # noqa: BLE001 — optional fast path
            pass
        req.pf = None
        req.prefill_done = True
        self._assert_harvestable(first_tok)
        self._harvest_q.put(("first", req, first_tok, rec))

    def _dispatch_rag(self, req: _Request, rec=None
                      ) -> tuple[int, Optional[object]]:
        """Fused-RAG admission: retrieval + assembly + prefill happen in
        ONE device program, so the dispatch is atomic — the scheduler
        charges the whole assembled bucket against the round budget (a
        grant can't split an on-device assembly). Returns ``(tokens,
        completion marker)`` like ``_advance_prefill``."""
        sp = req.params
        pf = req.pf
        faults.inject("engine.dispatch")  # chaos: slow/failed prefill
        t0 = time.monotonic()
        q_llm, q_len, q_enc = req.rag
        fused = self._fused_rag
        req.proj_pos = fused.spec.bucket  # device pos upper bound
        self._guard_live()
        new_state, first_tok = self._rag_jit(
            self._state, self.params, fused.enc_params,
            fused.corpus, jnp.asarray(q_enc), jnp.asarray(q_llm),
            jnp.int32(q_len), jnp.int32(req.slot),
            jnp.asarray(pf["row"]),
            jnp.float32(sp.temperature), jnp.int32(sp.top_k),
            jnp.float32(sp.top_p),
            jnp.float32(sp.repetition_penalty), pf["banned"],
            pf["bad_seq"], pf["bad_len"], pf["key"],
            jnp.int32(req.eff_max - 1), jnp.bool_(not sp.ignore_eos),
            req.greedy)
        self._guard_live()
        self._state = new_state
        pf["dispatch_s"] += time.monotonic() - t0
        self._arm_slot(req, first_tok, rec)
        return fused.spec.bucket, first_tok

    def _dispatch_round(self, steps: int, rec=None) -> bool:
        """Dispatch one decode round of ``steps`` fused steps (the plan
        right-sized them against the power-of-two ladder), or decline
        (False) when no ARMED slot still needs tokens — slots mid-
        chunked-prefill are excluded: they are inactive on the device
        until their final chunk arms them, so a round over them would be
        pure masked work. ``rec``: this round's telemetry record; the
        dispatched program's harvest item carries it so the harvest
        worker can complete the execution half."""
        members = {s: r for s, r in self._slots.items() if r.prefill_done}
        need_steps = max((r.extent - r.proj_pos for r in
                          members.values()), default=0)
        if need_steps <= 0 or steps <= 0:
            return False
        faults.inject("engine.dispatch")  # chaos: slow/failed decode round
        need = max(min(r.proj_pos + steps, r.extent) + 1
                   for r in members.values())
        # Kernel path: pass the full table — the kernel's per-slot dynamic
        # loop bound already scales HBM reads with live context, so there
        # is exactly ONE compiled round per (steps, greedy) instead of a
        # whole window ladder. The jnp gather path still needs the window
        # sliced (its gather materializes window x page rows per slot).
        if self._use_kernel:
            window = self._pmax
        else:
            window = self._window_for(_ceil_div(need, self.cfg.page_size))
        greedy = all(r.greedy for r in members.values())
        key = jax.random.fold_in(self._base_key, next(self._step_counter))
        # Active-slot compaction: the fused tail unembeds/samples only
        # the armed slots, padded to the smallest compiled rung (padding
        # indices == max_slots: gathers clamp, scatters drop). The
        # materialized tail (ENGINE_FUSED_SAMPLER=0 / downgraded
        # geometry) always runs full-width.
        B = self.cfg.max_slots
        ba = self._ba_for(len(members)) if self._fused_tail else B
        act = np.full((ba,), B, np.int32)
        act[:len(members)] = sorted(members)
        new_state, toks = self._round_fn(window, steps, greedy, ba)(
            self.params, self._state, key, jnp.asarray(act))
        self._guard_live()  # reset() may have run while the round compiled
        self._state = new_state
        if self._fused_tail:
            # Documented as fused-tail occupancy (observability.md):
            # materialized-tail runs leave both at 0 rather than
            # masquerading as a full-occupancy fused engine.
            self._bump("sampler_rows_sampled", ba * steps)
            self._bump("sampler_rows_skipped", (B - ba) * steps)
        try:
            # Async host copy: the harvest worker's np.asarray then finds
            # the round's tokens already on the host instead of paying a
            # blocking readback RTT per round (dominant on tunneled TPUs).
            toks.copy_to_host_async()
        except Exception:  # noqa: BLE001 — optional fast path
            pass
        if rec is not None:
            # Execution estimate for the round record: live pages each
            # step must read (per-slot ceil(pos/page), pre-advance) and
            # the HBM traffic they plus the weight stream imply.
            page = self.cfg.page_size
            pages_per_step = sum(
                _ceil_div(max(1, r.proj_pos + 1), page)
                for r in members.values())
            rec.decode_slots = len(members)
            rec.pages_touched += pages_per_step * steps
            rec.hbm_bytes += steps * (
                self._param_bytes
                + pages_per_step * page * self._kv_bytes_per_token())
        for req in members.values():
            req.proj_pos = min(req.proj_pos + steps, req.extent)
        with self._pipe_lock:
            self._inflight_rounds += 1
            depth = self._inflight_rounds
        with self._stats_lock:
            if depth > self._stats["dispatch_depth_peak"]:
                self._stats["dispatch_depth_peak"] = depth
        self._assert_harvestable(toks)
        self._harvest_q.put(("round", members, toks, rec))
        self._bump("decode_steps", steps)
        return True

    def _dispatch_verify(self, drafts: dict, rec=None) -> bool:
        """Dispatch one speculative VERIFY round: every armed slot rides
        it (slots without proposals as plain 1-token rows), slots in
        ``drafts`` carry their prompt-lookup proposals. One model step,
        up to S tokens emitted per slot. Only called with the pipeline
        drained (``_queued_rounds() == 0``), so the host's per-request
        token lists — and therefore ``proj_pos`` — are exact."""
        members = {s: r for s, r in self._slots.items() if r.prefill_done}
        need_steps = max((r.extent - r.proj_pos
                          for r in members.values()), default=0)
        if need_steps <= 0 or not drafts:
            return False
        faults.inject("engine.dispatch")  # chaos: slow/failed decode round
        S = self._spec_S
        B = self.cfg.max_slots
        page = self.cfg.page_size
        # The gather window must cover every scored position (pos..
        # pos+S-1 in-register rows included); proj_pos is exact here.
        need = max(min(r.proj_pos + S, r.extent) + 1
                   for r in members.values())
        window = self._window_for(_ceil_div(need, page))
        greedy = all(r.greedy for r in members.values())
        ba = self._ba_for(len(members)) if self._fused_tail else B
        act = np.full((ba,), B, np.int32)
        act[:len(members)] = sorted(members)
        draft_np = np.zeros((B, S - 1), np.int32)
        n_np = np.zeros((B,), np.int32)
        drafted: dict[int, int] = {}
        for slot, toks in drafts.items():
            k = min(len(toks), S - 1)
            draft_np[slot, :k] = toks[:k]
            n_np[slot] = k
            drafted[slot] = k
        key = jax.random.fold_in(self._base_key, next(self._step_counter))
        t0 = time.monotonic()
        new_state, (toks, acc) = self._verify_fn(window, greedy, ba)(
            self.params, self._state, key, jnp.asarray(act),
            jnp.asarray(draft_np), jnp.asarray(n_np))
        self._guard_live()  # reset() may have run while the round compiled
        self._state = new_state
        dt = time.monotonic() - t0
        # Speculative overhead attribution: host-side dispatch time of
        # the verify round, globally and on each member's timeline (one
        # stage event per round per slot — the decode_round budget).
        record_stage("engine_verify", dt)
        for req in members.values():
            tl = req.stream.timeline
            if tl is not None:
                tl.stage("engine_verify", dt)
        if self._fused_tail:
            self._bump("sampler_rows_sampled", ba * S)
            self._bump("sampler_rows_skipped", (B - ba) * S)
        try:
            toks.copy_to_host_async()
            acc.copy_to_host_async()
        except Exception:  # noqa: BLE001 — optional fast path
            pass
        if rec is not None:
            pages_per_step = sum(
                _ceil_div(max(1, r.proj_pos + 1), page)
                for r in members.values())
            rec.decode_slots = len(members)
            rec.spec_drafted = sum(drafted.values())
            rec.verify_positions = S * len(members)
            rec.pages_touched += pages_per_step
            rec.hbm_bytes += (
                self._param_bytes
                + pages_per_step * page * self._kv_bytes_per_token())
        for req in members.values():
            req.proj_pos = min(req.proj_pos + S, req.extent)
        with self._pipe_lock:
            self._inflight_rounds += 1
            depth = self._inflight_rounds
        with self._stats_lock:
            if depth > self._stats["dispatch_depth_peak"]:
                self._stats["dispatch_depth_peak"] = depth
        self._assert_harvestable(toks, acc)
        self._harvest_q.put(("verify", members, toks, acc, drafted, rec))
        self._bump("decode_steps")
        self._bump("spec_verify_rounds")
        return True

    def _emit_token(self, req: _Request, token: int) -> None:
        """Deliver one generated token (HARVEST-worker thread); finish the
        stream and post the completion for the scheduler to retire when
        the request ends. Finish logic mirrors the device-side termination
        exactly, so the host and device agree on each slot's last token.
        No device state is touched here — a host-detected finish's slot
        release is the scheduler's job (_drain_completed)."""
        req.generated += 1
        req.stream.token_ids.append(token)
        if req.drafter is not None:
            # Keep the prompt-lookup index in step with the stream (the
            # drafter only proposes between fully-harvested rounds, so
            # this index is never behind the device at proposal time).
            req.drafter.extend((token,))
        self._bump("tokens_generated")
        if req.stream.first_token_time is None:
            req.stream.first_token_time = time.monotonic()
            ttft = req.stream.first_token_time - req.stream.submit_time
            # Once per request, not per token. The single authoritative
            # engine_ttft record: timeline + stage histogram/collector
            # (EngineLLM deliberately does not re-report it).
            record_stage("engine_ttft", ttft)
            tl = req.stream.timeline
            if tl is not None:
                tl.stage("engine_ttft", ttft)

        finish: Optional[str] = None
        if token == self.tokenizer.eos_id and not req.params.ignore_eos:
            finish = "eos"
        elif req.generated >= req.eff_max:
            finish = "length"

        if req.stream.cancelled and finish is None:
            finish = "cancelled"
        elif (finish is None and req.deadline_t is not None
                and time.monotonic() > req.deadline_t):
            # Deadline passed mid-generation: stop decoding now — the
            # tokens already emitted stand, but nobody is waiting for
            # more. Retired like a host-detected finish (the scheduler
            # releases the slot on the device).
            finish = "deadline"
            self._bump("deadline_stops")
        elif finish != "eos":  # eos token itself is not emitted as text
            chunk = req.stop.feed(req.detok.push(token))
            req.stream._put_chunk(chunk)
            if req.stop.stopped:
                finish = "stop"

        if finish is not None:
            if finish in ("eos", "length"):
                # Emit text still held back — both the detokenizer's
                # incomplete-fragment window and any potential stop-word
                # prefix in the stop checker.
                req.stream._put_chunk(req.stop.feed(req.detok.flush()))
                req.stream._put_chunk(req.stop.flush())
                if req.stop.stopped and finish == "length":
                    finish = "stop"  # stop word surfaced in the final flush
            # Terminal sentinel goes out NOW (consumer latency), before
            # the scheduler gets around to the slot/page bookkeeping.
            if not req.done:  # a failed stream keeps its "error" reason
                req.stream._finish(finish)
            self._completed.put((req, finish))
            self._wake.set()  # the freed slot may unblock an admission

    def _retire(self, req: _Request, finish: str) -> None:
        """Scheduler-side completion: return the slot and its non-cache
        pages, release prefix-cache refs. The stream is usually already
        finished by the harvest worker; the drain paths pass a terminal
        reason for requests that never got one."""
        del self._slots[req.slot]
        self._free_slots.append(req.slot)
        # Pages under cache control stay resident (warm for the next
        # shared-prefix request) instead of returning to the free list;
        # releasing the refs afterwards makes them reclaimable at LRU
        # order once no live request maps them.
        self._free_pages.extend(p for p in req.pages
                                if p not in req.cache_pages)
        if req.cache_refs:
            self._prefix_cache.release(req.cache_refs)
        req.pages = []
        req.cache_refs = []
        req.cache_pages = set()
        if not req.done:  # a failed stream keeps its "error" reason
            req.stream._finish(finish)
