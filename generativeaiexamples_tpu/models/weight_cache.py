"""Converted-weight cache: orbax-backed, content-hash keyed.

The reference avoids rebuilding its TRT engines by caching them per
world-size/compute-capability directory, gated by a content hash of the
model dir (reference: model_server/model.py:140-145, 230-246). The TPU
stack's conversion is cheaper than an engine build but still real work —
torch-format parsing, key mapping, transpose/stack, quantization — and
it runs on every server start. This module is the SURVEY §5 "orbax-style
sharded weight cache": the CONVERTED (and, when requested, quantized)
parameter tree saved once in orbax's on-disk format, keyed by the same
identity string the XLA compile cache uses (model name + dtype + quant +
checkpoint content hash), so a restart loads arrays straight from disk
and skips conversion entirely.

Layout: ``$GAIE_WEIGHT_CACHE_DIR (default ~/.cache/generativeaiexamples_tpu/
weights)/<identity>/tree``. Disable with ``GAIE_WEIGHT_CACHE=0``.
Writes are atomic (orbax finalizes into place), so a crashed save never
leaves a half-written tree that a later boot would trust.
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Any, Callable, Optional

logger = logging.getLogger("tpu-rag.weight_cache")


def enabled() -> bool:
    return os.environ.get("GAIE_WEIGHT_CACHE", "1") != "0"


def cache_root() -> str:
    return os.environ.get(
        "GAIE_WEIGHT_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "generativeaiexamples_tpu", "weights"))


def _tree_dir(identity: str) -> str:
    safe = identity.replace("/", "_")
    return os.path.join(cache_root(), safe, "tree")


def load(identity: str) -> Optional[Any]:
    """The cached param tree for this identity, or None (absent, disabled,
    or unreadable — an unreadable entry is dropped so the next save can
    replace it)."""
    if not enabled():
        return None
    path = _tree_dir(identity)
    if not os.path.isdir(path):
        return None
    try:
        import orbax.checkpoint as ocp
        with ocp.StandardCheckpointer() as ckptr:
            params = ckptr.restore(path)
        logger.info("weights loaded from cache %s", path)
        return params
    except Exception:  # noqa: BLE001 — cache must never block serving
        logger.exception("weight cache at %s unreadable; dropping it", path)
        shutil.rmtree(os.path.dirname(path), ignore_errors=True)
        return None


def save(identity: str, params: Any,
         prune_prefix: Optional[str] = None) -> bool:
    """Best-effort write; True when the tree landed.

    ``prune_prefix``: after a successful save, sibling cache entries
    whose identity starts with this prefix (same model/dtype/quant,
    OLD content hash) are deleted — a converted 7B tree is multi-GB, and
    without eviction every checkpoint update would leave a full copy in
    the cache forever."""
    if not enabled():
        return False
    path = _tree_dir(identity)
    try:
        import orbax.checkpoint as ocp
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, params, force=True)  # atomic finalize
        logger.info("weights cached at %s", path)
    except Exception:  # noqa: BLE001 — cache must never block serving
        logger.exception("weight cache save failed for %s", identity)
        shutil.rmtree(os.path.dirname(path), ignore_errors=True)
        return False
    if prune_prefix:
        keep = os.path.basename(os.path.dirname(path))
        prefix = prune_prefix.replace("/", "_")
        try:
            for entry in os.listdir(cache_root()):
                if entry.startswith(prefix) and entry != keep:
                    shutil.rmtree(os.path.join(cache_root(), entry),
                                  ignore_errors=True)
                    logger.info("pruned stale weight cache %s", entry)
        except OSError:
            pass
    return True


def cached_or_convert(identity: str, convert: Callable[[], Any],
                      prune_prefix: Optional[str] = None
                      ) -> tuple[Any, bool]:
    """(params, from_cache): load the cached tree, or run ``convert()``
    and cache its result. The convert callable must return the FINAL
    served tree (post-quantization) — the identity string encodes the
    quantization mode, so a cached int8 tree is never served as raw."""
    params = load(identity)
    if params is not None:
        return params, True
    params = convert()
    save(identity, params, prune_prefix=prune_prefix)
    return params, False
