"""Weight-only quantization: per-channel int8/int4 + group-wise int4.

Parity point: the reference offers int8 / int4 / int4-AWQ / GPTQ
weight-only engines (reference: conversion/llama.py:81-97
``--quantization int4_awq``, conversion_scripts/llama/build.py:543-580
QuantMode wiring, weight.py:979 GPTQ / :1194 AWQ loaders).
TPU-idiomatic version: weights live in HBM as int8 (int4 packed
two-per-byte), and the matmul consumes them via mixed-dtype dots (per
channel) or per-group partial dots — the MXU still sees bf16 operands,
but HBM traffic and footprint drop 2-4x, which is what matters for
weight-bound decode.

A quantized tensor is a dict leaf:
  int8:        ``{"q":  int8[..., K, N],   "scale": f32[..., N]}``
  int4:        ``{"q4": int8[..., K/2, N], "scale": f32[..., N]}``
  group int4:  ``{"q4": int8[..., K/2, N], "gscale": f32[..., G, N]}``
               + optional ``"gbias"`` f32[..., G, N] (asymmetric zeros,
               GPTQ) and ``"pre_scale"`` f32[..., K] (AWQ activation
               smoothing scale), with G = K / group_size.
(int4 packs two nibbles per byte along the reduction axis, low nibble =
even k.) Every leaf is an array and weight rank is preserved, so one
PartitionSpec tree serves raw and quantized params alike.
"""

from __future__ import annotations

from typing import Any, Union

import os

import jax
import jax.numpy as jnp

QTensor = dict[str, jax.Array]

# Weights quantized by quantize_params; norms/embeddings stay high precision
# (embed doubles as the tied lm_head input and is gather-bound, not
# matmul-bound).
_QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and (
        ("scale" in w or "gscale" in w) and ("q" in w or "q4" in w))


def is_grouped(w: Any) -> bool:
    return isinstance(w, dict) and "gscale" in w


def quantize_tensor(w: jax.Array, bits: int = 8) -> QTensor:
    """Symmetric per-output-channel quantization over the reduction axis.

    w: (..., K, N) float → q in [-127,127] (int8) or [-7,7] (int4) with
    ``q * scale ≈ w``.
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    wf = w.astype(jnp.float32)
    qmax = 127.0 if bits == 8 else 7.0
    absmax = jnp.max(jnp.abs(wf), axis=-2)              # (..., N)
    scale = jnp.maximum(absmax / qmax, 1e-12)
    q = jnp.clip(jnp.round(wf / scale[..., None, :]), -qmax, qmax
                 ).astype(jnp.int8)
    if bits == 4:
        K = q.shape[-2]
        if K % 2:
            raise ValueError(f"int4 needs even reduction dim, got {K}")
        packed = ((q[..., 0::2, :] & 0x0F) | (q[..., 1::2, :] << 4)
                  ).astype(jnp.int8)
        return {"q4": packed, "scale": scale.astype(jnp.float32)}
    return {"q": q, "scale": scale.astype(jnp.float32)}


def _unpack4(q4: jax.Array) -> jax.Array:
    """(..., K/2, N) packed nibbles → (..., K, N) int8."""
    lo = (q4 << 4).astype(jnp.int8) >> 4     # sign-extend low nibble
    hi = q4 >> 4                              # arithmetic shift: high nibble
    out = jnp.stack([lo, hi], axis=-2)        # (..., K/2, 2, N)
    return out.reshape(*q4.shape[:-2], q4.shape[-2] * 2, q4.shape[-1])


def _int_weights(w: QTensor) -> jax.Array:
    return _unpack4(w["q4"]) if "q4" in w else w["q"]


def quantize_tensor_grouped(w: jax.Array, group_size: int = 128) -> QTensor:
    """Group-wise symmetric int4 (the AWQ storage format: per-(group, out)
    scales = absmax/8 over each ``group_size`` slice of the reduction
    axis — reference weight.py:1290 ``get_scale``; the activation-aware
    scale *search* needs calibration data and lives in the importer)."""
    K, N = w.shape[-2], w.shape[-1]
    if K % group_size:
        raise ValueError(f"reduction dim {K} not divisible by group "
                         f"{group_size}")
    G = K // group_size
    wf = w.astype(jnp.float32).reshape(*w.shape[:-2], G, group_size, N)
    absmax = jnp.max(jnp.abs(wf), axis=-2)                     # (..., G, N)
    gscale = jnp.maximum(absmax / 7.0, 1e-12)
    q = jnp.clip(jnp.round(wf / gscale[..., None, :]), -7, 7
                 ).astype(jnp.int8)
    q = q.reshape(*w.shape[:-2], K, N)
    packed = ((q[..., 0::2, :] & 0x0F) | (q[..., 1::2, :] << 4)
              ).astype(jnp.int8)
    return {"q4": packed, "gscale": gscale.astype(jnp.float32)}


def dequantize(w: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    q = _int_weights(w).astype(jnp.float32)
    if is_grouped(w):
        K, N = q.shape[-2], q.shape[-1]
        G = w["gscale"].shape[-2]
        qg = q.reshape(*q.shape[:-2], G, K // G, N)
        out = qg * w["gscale"][..., None, :]
        if "gbias" in w:
            out = out + w["gbias"][..., None, :]
        out = out.reshape(q.shape)
        if "pre_scale" in w:
            # fold the activation smoothing scale back for an effective
            # full-precision view: y = (x*s) @ W  ==  x @ (s[:,None]*W)
            out = out * w["pre_scale"][..., :, None]
        return out.astype(dtype)
    return (q * w["scale"][..., None, :]).astype(dtype)


def _use_int4_kernel(w: QTensor) -> bool:
    """Packed-int4 Pallas matmul gate: TPU backend, 2D weight, kernel-
    supported geometry (ops/int4_matmul.py). The XLA path must unpack
    the nibbles to a full int8 tensor inside the decode scan — 5x the
    int4 HBM bytes per step (measured r5: 72 vs 504 tok/s on 7B) — so
    the kernel is the difference between int4 being a capacity+speed win
    and a capacity-only trade."""
    if os.environ.get("GENAI_TPU_INT4_KERNEL", "1") == "0":
        return False
    q4 = w["q4"]
    if q4.ndim != 2 or "gbias" in w:
        return False
    from .int4_matmul import supported
    gs = 0
    if is_grouped(w):
        gs = (2 * q4.shape[0]) // w["gscale"].shape[-2]
    try:
        return (jax.default_backend() == "tpu"
                and supported(2 * q4.shape[0], q4.shape[1], group_size=gs))
    except Exception:  # noqa: BLE001 — no backend yet
        return False


def matmul(x: jax.Array, w: Union[jax.Array, QTensor]) -> jax.Array:
    """``x @ w`` where w may be raw or quantized.

    int8 uses a mixed-dtype dot (bf16 activations x s8 weights,
    accumulated f32): the MXU feed widens s8 tiles on the fly, so HBM
    traffic is the int8 bytes and no full-precision copy of w is ever
    materialized — measured ~2x faster than dequant-then-dot on v5e,
    where XLA hoists the dequant out of the decode step loop and writes
    a bf16 copy of the whole weight. The per-channel scale is applied
    after the matmul (mathematically identical, one multiply per output
    element instead of per weight).

    int4 on TPU routes through the packed-nibble Pallas kernel
    (ops/int4_matmul.py) so HBM sees only the int4 bytes.
    """
    if not is_quantized(w):
        return x @ w
    if "q4" in w and _use_int4_kernel(w):
        from .int4_matmul import int4_matmul
        scale = w["gscale"] if is_grouped(w) else w["scale"]
        # AWQ activation smoothing folds into the inputs; GPTQ's rank-1
        # gbias term is not in the kernel (gated in _use_int4_kernel)
        xin = x * w["pre_scale"] if "pre_scale" in w else x
        return int4_matmul(xin.astype(x.dtype), w["q4"], scale)
    q = _int_weights(w)
    if is_grouped(w):
        return _grouped_matmul(x, q, w)
    dims = (((x.ndim - 1,), (q.ndim - 2,)), ((), ()))
    try:
        y = jax.lax.dot_general(x, q, dims,
                                preferred_element_type=jnp.float32)
    except TypeError:  # backend/version without mixed-dtype dots
        y = jax.lax.dot_general(x, q.astype(x.dtype), dims)
    return (y * w["scale"]).astype(x.dtype)


def matmul_f32(x: jax.Array, w: Union[jax.Array, QTensor]) -> jax.Array:
    """``x @ w`` with float32 output — the logits path.

    Unlike ``matmul`` the result is NOT downcast to the activation dtype,
    and unlike casting operands to f32 up front (which makes XLA
    materialize a full f32 copy of the weight — measured 6.9 ms/step on
    the 7B lm_head, ~25% of decode step time) the operands stay in their
    compact dtypes with f32 MXU accumulation, which is numerically the
    same: bf16/int8 operand values carry no extra mantissa to lose.
    """
    if is_quantized(w) and "q4" in w and _use_int4_kernel(w):
        from .int4_matmul import int4_matmul
        scale = w["gscale"] if is_grouped(w) else w["scale"]
        xin = x * w["pre_scale"] if "pre_scale" in w else x
        return int4_matmul(xin.astype(x.dtype), w["q4"], scale,
                           out_dtype=jnp.float32)
    if is_grouped(w):
        return _grouped_matmul(x, _int_weights(w), w,
                               out_dtype=jnp.float32)
    q = _int_weights(w) if is_quantized(w) else w
    dims = (((x.ndim - 1,), (q.ndim - 2,)), ((), ()))
    try:
        y = jax.lax.dot_general(x, q, dims,
                                preferred_element_type=jnp.float32)
    except TypeError:  # backend/version without mixed-dtype dots
        y = jax.lax.dot_general(x.astype(jnp.float32),
                                q.astype(jnp.float32), dims)
    return y * w["scale"] if is_quantized(w) else y


def _grouped_matmul(x: jax.Array, q: jax.Array, w: QTensor,
                    out_dtype=None) -> jax.Array:
    """Group-wise dequant matmul without materializing the weight:
    per-group partial dots scaled by (G, N) scales, plus a rank-1 bias
    term for asymmetric (GPTQ) zeros:
      y[n] = sum_g dot(x_g, q_g)[n] * s[g,n]  +  sum_g (sum x_g) b[g,n]
    ``out_dtype``: result dtype (default: activation dtype). The logits
    path passes f32 so accumulated values are not rounded through bf16.
    """
    if q.ndim != 2:
        raise ValueError("grouped quantization supports 2D weights only")
    K, N = q.shape
    G = w["gscale"].shape[-2]
    group = K // G
    lead = x.shape[:-1]
    xf = x.astype(jnp.float32)
    if "pre_scale" in w:
        xf = xf * w["pre_scale"]
    xg_f = xf.reshape(-1, G, group)
    xg = xg_f.astype(x.dtype)
    qg = q.reshape(G, group, N)
    try:
        # Mixed-dtype dot (activations x int weights, f32 accumulate), as
        # the int8 path: HBM traffic stays at the int bytes — no f32 copy
        # of the weight (8x the packed size) is ever materialized.
        p = jnp.einsum("bgk,gkn->bgn", xg, qg,
                       preferred_element_type=jnp.float32)
    except TypeError:  # backend/version without mixed-dtype dots
        p = jnp.einsum("bgk,gkn->bgn", xg.astype(jnp.float32),
                       qg.astype(jnp.float32))
    y = jnp.einsum("bgn,gn->bn", p, w["gscale"])
    if "gbias" in w:
        y = y + jnp.einsum("bg,gn->bn", jnp.sum(xg_f, axis=-1), w["gbias"])
    return y.reshape(*lead, N).astype(out_dtype or x.dtype)


def quantize_params(params: Any, mode: str = "int8",
                    group_size: int = 128) -> Any:
    """Quantize a llama param tree's matmul weights in place of the raw
    arrays. ``mode``: int8 | int4 (per-channel) | int4_awq (group-wise
    AWQ storage format; pre-quantized AWQ/GPTQ checkpoints instead load
    their own scales via models/import_quantized.py)."""
    if mode not in ("int8", "int4", "int4_awq"):
        raise ValueError(f"unknown quantization mode {mode!r}")

    def quant(w):
        if mode == "int4_awq":
            # stacked (L, K, N) per-layer weights: group along K per layer
            if w.ndim == 3:
                import jax as _jax
                return _jax.vmap(
                    lambda m: quantize_tensor_grouped(m, group_size))(w)
            return quantize_tensor_grouped(w, group_size)
        return quantize_tensor(w, 8 if mode == "int8" else 4)

    out = dict(params)
    layers = dict(params["layers"])
    for key in _QUANT_LAYER_KEYS:
        # MoE expert tensors (L,E,K,N) keep full precision for now — the
        # expert einsums contract differently than plain matmul.
        if (key in layers and not is_quantized(layers[key])
                and layers[key].ndim <= 3):
            layers[key] = quant(layers[key])
    out["layers"] = layers
    if "lm_head" in out and not is_quantized(out["lm_head"]):
        out["lm_head"] = quant(out["lm_head"])
    return out
