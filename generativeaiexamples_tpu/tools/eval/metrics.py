"""RAGAS-style LLM-graded metrics + deterministic retrieval metrics.

LLM-graded (reference: tools/evaluation/03_eval_ragas.ipynb wires RAGAS
``faithfulness`` and ``context_precision`` to a Llama-70B judge):

- **faithfulness**: decompose the answer into atomic statements, ask the
  verdict LLM whether each can be inferred from the retrieved contexts;
  score = supported / total.
- **context precision**: ask, per retrieved context, whether it was useful
  for arriving at the ground-truth answer; score = rank-weighted mean of
  precision@k at each relevant position (the RAGAS formulation).

Deterministic (BASELINE.md north star "retrieval nDCG parity"): binary-
relevance nDCG@k, hit-rate@k, and MRR of the gold chunk's rank — these
need no judge, so they are meaningful even on the dev (echo/hash) stack.
"""

from __future__ import annotations

import math
import re
from typing import Optional, Sequence

STATEMENT_PROMPT = (
    "Question: {question}\n"
    "Answer: {answer}\n\n"
    "Break the answer above into simple, self-contained factual "
    "statements, one per line. Output only the statements."
)

FAITHFULNESS_VERDICT_PROMPT = (
    "Context:\n{context}\n\n"
    "Statement: {statement}\n\n"
    "Can the statement be directly inferred from the context above? "
    "Answer with a single word: Yes or No."
)

CONTEXT_PRECISION_PROMPT = (
    "Question: {question}\n"
    "Reference answer: {answer}\n\n"
    "Candidate context:\n{context}\n\n"
    "Was the candidate context useful in arriving at the reference "
    "answer? Answer with a single word: Yes or No."
)

_YES = re.compile(r"\b(yes|true)\b", re.IGNORECASE)
_NO = re.compile(r"\b(no|false)\b", re.IGNORECASE)


def parse_verdict(text: str) -> Optional[bool]:
    """First clear yes/no wins; None when the output has neither (the
    caller counts it as unparsed rather than guessing)."""
    yes = _YES.search(text)
    no = _NO.search(text)
    if yes and (not no or yes.start() < no.start()):
        return True
    if no:
        return False
    return None


def extract_statements(llm, question: str, answer: str,
                       max_statements: int = 8) -> list[str]:
    text = llm.complete(STATEMENT_PROMPT.format(question=question,
                                                answer=answer),
                        max_tokens=300, temperature=0.2, top_k=4)
    lines = [re.sub(r"^[\s\-\*\d\.\)]+", "", ln).strip()
             for ln in text.splitlines()]
    stmts = [ln for ln in lines if len(ln.split()) >= 3]
    return stmts[:max_statements] or [answer]


def faithfulness(llm, question: str, answer: str,
                 contexts: Sequence[str]) -> Optional[float]:
    """Fraction of answer statements supported by the contexts; None when
    no verdict parsed (dev-stack LLM doubles answer neither yes nor no)."""
    if not answer.strip() or not contexts:
        return None
    context = "\n\n".join(contexts)
    verdicts = []
    for stmt in extract_statements(llm, question, answer):
        v = parse_verdict(llm.complete(
            FAITHFULNESS_VERDICT_PROMPT.format(context=context,
                                               statement=stmt),
            max_tokens=10, temperature=0.0, top_k=1))
        if v is not None:
            verdicts.append(v)
    if not verdicts:
        return None
    return sum(verdicts) / len(verdicts)


def context_precision(llm, question: str, gt_answer: str,
                      contexts: Sequence[str]) -> Optional[float]:
    """RAGAS context precision: mean over relevant positions k of
    precision@k — rewards putting the useful contexts first."""
    if not contexts:
        return None
    # Unparsed verdicts are dropped (their rank positions excluded), same
    # policy as faithfulness — counting them as "irrelevant" would let
    # parser flakiness systematically deflate the score.
    rels: list[bool] = []
    for ctx in contexts:
        v = parse_verdict(llm.complete(
            CONTEXT_PRECISION_PROMPT.format(question=question,
                                            answer=gt_answer, context=ctx),
            max_tokens=10, temperature=0.0, top_k=1))
        if v is not None:
            rels.append(v)
    if not rels:
        return None
    if not any(rels):
        return 0.0
    score = 0.0
    hits = 0
    for k, rel in enumerate(rels, start=1):
        if rel:
            hits += 1
            score += hits / k
    return score / hits


# ------------------------------------------------------------- retrieval

def ndcg_at_k(ranked_ids: Sequence[int], gold_id: int, k: int) -> float:
    """Binary-relevance nDCG@k: one relevant item (the gold chunk), so
    ideal DCG is 1 and nDCG = 1/log2(rank+1) if found in the top k."""
    for rank, rid in enumerate(list(ranked_ids)[:k], start=1):
        if rid == gold_id:
            return 1.0 / math.log2(rank + 1)
    return 0.0


def retrieval_metrics(ranked_ids: Sequence[int], gold_id: Optional[int],
                      k: int) -> Optional[dict[str, float]]:
    """Per-question retrieval scores vs the chunk the question was
    synthesized from. None when the gold id is unknown."""
    if gold_id is None:
        return None
    ranked = list(ranked_ids)
    hit = gold_id in ranked[:k]
    rr = 0.0
    for rank, rid in enumerate(ranked, start=1):
        if rid == gold_id:
            rr = 1.0 / rank
            break
    return {"ndcg": ndcg_at_k(ranked, gold_id, k),
            "hit": 1.0 if hit else 0.0,
            "mrr": rr}


def mean_of(values: Sequence[Optional[float]]) -> Optional[float]:
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    return sum(vals) / len(vals)
