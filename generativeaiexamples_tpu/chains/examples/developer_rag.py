"""The canonical QA chatbot: ingest → retrieve → prompt → stream.

Parity with the reference's developer RAG example
(reference: examples/developer_rag/chains.py — ``QAChatbot``:
``ingest_docs`` 51 loads PDFs/files and chunks them into the vector store,
``llm_chain`` 86 answers without retrieval, ``rag_chain`` 101 retrieves
top-4 / caps context at 1500 tokens / streams through the LLM,
``document_search`` 136 exposes raw retrieval). Built on this framework's
own retrieval + LLM layers instead of LlamaIndex.
"""

from __future__ import annotations

import base64
import os
from typing import Generator, Optional

from ...embed.encoder import get_embedder
from ...obs import flight as obs_flight
from ...obs import metrics as obs_metrics
from ...obs.tracing import event_span
from ...retrieval.docstore import Document, DocumentIndex
from ...utils.app_config import get_config
from ...utils.errors import BreakerOpenError, RetrievalError
from ...utils.logging import get_logger
from ..base import BaseExample
from ..llm import get_llm
from ..readers import read_document
from ..splitter import TokenTextSplitter, cap_context

logger = get_logger(__name__)

#: User-visible preamble when retrieval is down and the answer comes from
#: the model alone (docs/robustness.md "Graceful degradation").
DEGRADED_NOTICE = ("[notice] the knowledge base is temporarily "
                   "unavailable; answering from the model alone.\n\n")


def record_degraded(reason: str) -> None:
    """Count a degraded answer and stamp the request's flight timeline —
    the signal that separates 'quality dip' from 'outage' on /metrics."""
    obs_metrics.REGISTRY.counter(
        "degraded_total", "requests served degraded, by failed dependency",
        labelnames=("reason",)).labels(reason).inc()
    tl = obs_flight.current()
    if tl is not None:
        tl.annotate(degraded=reason)


def degrade_to_llm(chatbot, exc, prompt: str, num_tokens: int,
                   ) -> Generator[str, None, None]:
    """Retrieval-down fallback shared by the example chains: notice +
    LLM-only answer. The fallback's FIRST chunk is pulled before
    anything is yielded — if the LLM is down too, its typed error
    propagates with nothing emitted, so the chain server still maps it
    to a real pre-stream HTTP status (and the engine breaker still sees
    the failure) instead of a 200 carrying notice-then-error text. The
    degraded counter likewise only increments once the fallback is
    actually serving."""
    reason = (getattr(exc, "reason", "") or
              getattr(exc, "breaker", "") or "retrieval")
    logger.warning("rag chain degrading to llm_chain (%s): %s", reason, exc)
    fallback = chatbot.llm_chain("", prompt, num_tokens)
    try:
        first = next(fallback)
    except StopIteration:
        first = None
    record_degraded(reason)
    yield DEGRADED_NOTICE
    if first is not None:
        yield first
    yield from fallback


class QAChatbot(BaseExample):
    """Canonical developer RAG chatbot."""

    def __init__(self, llm=None, embedder=None, index: Optional[DocumentIndex] = None,
                 config=None, engine=None, fused_rag: Optional[bool] = None):
        self.config = config or get_config()
        self.llm = llm or get_llm(self.config, engine=engine)
        embedder = embedder or (index.embedder if index else None) or \
            get_embedder(self.config.embeddings.model_engine,
                         self.config.embeddings.model_name,
                         dim=self.config.embeddings.dimensions)
        if index is None:
            from ...retrieval.store import store_from_config
            index = DocumentIndex(embedder, store=store_from_config(
                self.config.vector_store, embedder.dim))
        self.index = index
        self.splitter = TokenTextSplitter(
            chunk_size=self.config.text_splitter.chunk_size,
            chunk_overlap=self.config.text_splitter.chunk_overlap)
        # Fused on-device RAG (engine/rag_fusion.py): None = auto-enable
        # when the LLM is an in-process engine, the embedder runs
        # on-device (has params), and the store can export raw vectors.
        if fused_rag is None:
            fused_rag = os.environ.get("GENAI_TPU_FUSED_RAG", "1") != "0"
        self._fused_requested = fused_rag
        self._fused_ready = False
        self._fused_spec = None
        self._fused_sources: list[int] = []

    # ----------------------------------------------------------- ingestion

    def ingest_docs(self, data_dir: str, filename: str) -> None:
        """Read, chunk, and index one document file.

        The reference base64-encodes the filename into node metadata to
        survive odd characters (reference: chains.py:68-75); kept here.
        """
        text = read_document(data_dir)
        chunks = self.splitter.split_text(text)
        encoded = base64.b64encode(filename.encode()).decode()
        docs = [Document(text=c, metadata={"source": filename,
                                           "source_b64": encoded,
                                           "chunk": i})
                for i, c in enumerate(chunks)]
        self.index.add_documents(docs)
        logger.info("ingested %s: %d chunks", filename, len(chunks))
        self._sync_fused_corpus()

    def _sync_fused_corpus(self) -> None:
        """Mirror the corpus onto the device for fused-RAG admission.
        Best-effort: any miss (remote store, host-only embedder, remote
        LLM) just leaves the classic host path in charge."""
        self._fused_ready = False
        if not self._fused_requested:
            return
        from ..llm import EngineLLM
        if not isinstance(self.llm, EngineLLM):
            return
        emb = self.index.embedder
        if not (hasattr(emb, "params") and hasattr(emb, "cfg")):
            return
        data = self.index.export_corpus()
        if data is None or not data[0]:
            return
        try:
            from ...engine.rag_fusion import (FusedRagSpec,
                                              build_prompt_parts,
                                              corpus_rows)
            engine = self.llm.engine
            parts = build_prompt_parts(
                self.config.prompts.rag_template, engine.tokenizer)
            C = self.config.text_splitter.chunk_size + 32
            K = self.config.retriever.top_k
            ids, vecs, texts = data
            toks, lens = corpus_rows(texts, engine.tokenizer, C)
            # Bucket sized from the CONFIG worst case (k chunks at the
            # splitter cap + separators), not from this corpus's actual
            # chunk lengths: a corpus-derived bucket would shift as files
            # arrive and recompile the fused admission program on every
            # ingest. The config bound is stable, so the compile happens
            # once; ingest only re-uploads the corpus arrays.
            q_bucket = 64
            budget = min(self.config.retriever.max_context_tokens,
                         K * (C + len(parts["sep_ids"])))
            overhead = (len(parts["prefix_ids"]) + len(parts["mid_ids"])
                        + len(parts["suffix_ids"]) + q_bucket)
            page = engine.cfg.page_size
            bucket = -(-(overhead + budget) // page) * page
            bucket = min(bucket, (engine.cfg.max_cache_len // page - 1)
                         * page)
            # A clamped bucket must clamp the context budget with it, or
            # assemble() would scatter the question past the bucket edge
            # (mode='drop') and answer a question the model never saw.
            budget = min(budget, bucket - overhead)
            if budget <= 0:
                logger.warning("fused RAG disabled: prompt bucket %d "
                               "cannot hold template+question", bucket)
                return
            spec = FusedRagSpec(**parts, top_k=K, ctx_budget=budget,
                                bucket=bucket, chunk_tokens=C,
                                q_bucket=q_bucket, enc_bucket=128)
            # Compare against the ENGINE's compiled spec, not a local
            # cache alone — a rebuilt engine has no fused program even if
            # this chatbot saw the same spec before.
            if engine.fused_rag_spec != spec:
                engine.enable_fused_rag(emb.params, emb.cfg, spec)
            self._fused_spec = spec
            engine.set_rag_corpus(vecs, toks, lens)
            self._fused_doc_ids = ids
            self._fused_ready = True
        except Exception:  # noqa: BLE001 — fused is an optimization
            logger.exception("fused-RAG corpus sync failed; "
                             "using the host retrieval path")

    # -------------------------------------------------------------- chains

    def llm_chain(self, context: str, question: str, num_tokens: int,
                  ) -> Generator[str, None, None]:
        prompt = self.config.prompts.chat_template.format(
            context_str=context or "", query_str=question)
        with event_span("llm", num_tokens=num_tokens):
            yield from self.llm.stream(prompt, max_tokens=num_tokens,
                                       stop=["</s>", "[INST]"])

    def rag_chain(self, prompt: str, num_tokens: int,
                  ) -> Generator[str, None, None]:
        # Attribution is per-request: clear before either path runs so
        # last_sources never reports a previous answer's documents.
        self._fused_sources = []
        spec = self._fused_spec if self._fused_ready else None
        q_ids = (self.llm.engine.tokenizer.encode(prompt, add_bos=False)
                 if spec is not None else [])
        if spec is not None and len(q_ids) <= spec.q_bucket:
            # Retrieval + prompt assembly + prefill fused into the
            # engine's admission program: one device dispatch, one
            # readback — the whole RAG hot path without host hops.
            # (Over-long questions fall through to the host path, which
            # has no question-length bucket.)
            emb = self.index.embedder
            enc_ids = emb.tokenizer.encode(f"query: {prompt}")

            def keep_sources(rows: list[int]) -> None:
                # map on-device corpus rows back to document metadata —
                # the fused analogue of document_search attribution
                ids = getattr(self, "_fused_doc_ids", [])
                self._fused_sources = [ids[r] for r in rows
                                       if 0 <= r < len(ids)]

            with event_span("llm", fused_rag=True, num_tokens=num_tokens):
                yield from self.llm.stream_rag(
                    prompt, enc_ids, max_tokens=num_tokens,
                    stop=["</s>", "[INST]"], on_sources=keep_sources,
                    q_ids=q_ids)
            return
        # Child spans per pipeline stage — the retrieve/synthesize/llm
        # events the reference bridges out of LlamaIndex callbacks
        # (reference: tools/observability/llamaindex/
        # opentelemetry_callback.py:84-197).
        try:
            with event_span("retrieve",
                            top_k=self.config.retriever.top_k) as sp:
                docs = self.index.similarity_search(
                    prompt, k=self.config.retriever.top_k)
                if sp is not None:
                    for i, d in enumerate(docs):
                        sp.set_attribute(f"retrieval.score.{i}",
                                         float(d.score or 0.0))
        except (RetrievalError, BreakerOpenError) as exc:
            # Graceful degradation: a dead vector store or embedder
            # costs retrieval QUALITY, not the whole chatbot. Answer
            # from the model alone, tell the user, count it.
            yield from degrade_to_llm(self, exc, prompt, num_tokens)
            return
        with event_span("templating", n_docs=len(docs)):
            context_texts = cap_context(
                [d.text for d in docs],
                max_tokens=self.config.retriever.max_context_tokens,
                tokenizer=self.splitter.tok)
            context = "\n\n".join(context_texts)
            full_prompt = self.config.prompts.rag_template.format(
                context_str=context, query_str=prompt)
        with event_span("llm", num_tokens=num_tokens,
                        prompt_chars=len(full_prompt)):
            yield from self.llm.stream(full_prompt, max_tokens=num_tokens,
                                       stop=["</s>", "[INST]"])

    @property
    def last_sources(self) -> list[dict]:
        """Source attribution of the most recent fused-RAG answer
        (document metadata of the chunks the on-device retrieval picked).
        Empty when the host path served the last request."""
        out = []
        for i in self._fused_sources:
            doc = self.index.get(i)
            if doc is not None:
                out.append({"source": doc.metadata.get("source", ""),
                            "chunk": doc.metadata.get("chunk")})
        return out

    # ------------------------------------------------------------- search

    def document_search(self, content: str, num_docs: int) -> list[dict]:
        """Raw retrieval results (reference: chains.py:136-153 returns
        [{score, source, content}])."""
        docs = self.index.similarity_search(content, k=num_docs)
        return [{"score": d.score,
                 "source": d.metadata.get("source", ""),
                 "content": d.text} for d in docs]


Example = QAChatbot
