"""Tokenizers: HF `tokenizers` wrapper + self-contained byte fallback.

The reference tokenizes inside Triton Python models with AutoTokenizer
(reference: ensemble_models/llama/preprocessing/1/model.py:56-92, pad id
END_ID=2 at _create_request 167-181) and detokenizes per-token handling
sentencepiece space/newline sentinels
(reference: ensemble_models/llama/postprocessing/1/model.py:131-154).

Here tokenization is a host-side service used by the engine and the text
splitter. ``ByteTokenizer`` needs no vocab files (important for hermetic
tests and air-gapped TPU pods); ``HFTokenizer`` loads a ``tokenizer.json``.
"""

from __future__ import annotations

import os
from typing import Protocol, Sequence


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int

    @property
    def vocab_size(self) -> int: ...
    def encode(self, text: str, *, add_bos: bool = True) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def piece_id(self, piece: str) -> "int | None": ...


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..2 = pad/bos/eos, 3..258 = bytes.

    Id conventions follow the Llama sentencepiece family (pad=0, bos=1,
    eos=2 — the reference pads with END_ID=2,
    ensemble_models/llama/preprocessing/1/model.py:167-181).
    """

    pad_id, bos_id, eos_id = 0, 1, 2
    _OFFSET = 3

    @property
    def vocab_size(self) -> int:
        return 256 + self._OFFSET

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        ids = [b + self._OFFSET for b in text.encode("utf-8")]
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i - self._OFFSET for i in ids
                     if i >= self._OFFSET and i < self._OFFSET + 256)
        return data.decode("utf-8", errors="replace")

    def piece_id(self, piece: str) -> "int | None":
        data = piece.encode("utf-8")
        return data[0] + self._OFFSET if len(data) == 1 else None


class HFTokenizer:
    """Wraps a ``tokenizers.Tokenizer`` loaded from tokenizer.json."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer as _Tok
        if os.path.isdir(path):
            path = os.path.join(path, "tokenizer.json")
        self._tok = _Tok.from_file(path)
        self.pad_id = self._special_id(("<pad>", "[PAD]", "<unk>"), 0)
        self.bos_id = self._special_id(("<s>", "[CLS]", "<|begin_of_text|>"), 1)
        self.eos_id = self._special_id(("</s>", "[SEP]", "<|end_of_text|>"), 2)

    def _special_id(self, candidates: tuple[str, ...], default: int) -> int:
        for tok in candidates:
            tid = self._tok.token_to_id(tok)
            if tid is not None:
                return tid
        return default

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def piece_id(self, piece: str) -> "int | None":
        return self._tok.token_to_id(piece)


def get_tokenizer(spec: str = "byte") -> Tokenizer:
    """Factory: 'byte', or a path to a checkpoint dir / tokenizer file.

    Checkpoint dirs resolve in the order real Llama-2 releases ship them:
    ``tokenizer.model`` (sentencepiece — loaded by the self-contained
    reader in models/sentencepiece.py since no sentencepiece wheel is
    assumed), then ``tokenizer.json`` (HF tokenizers).
    """
    if spec == "byte":
        return ByteTokenizer()
    from .sentencepiece import SentencePieceTokenizer
    if os.path.isdir(spec):
        sp = os.path.join(spec, "tokenizer.model")
        if os.path.isfile(sp):
            return SentencePieceTokenizer(sp)
        return HFTokenizer(spec)
    if spec.endswith(".model"):
        return SentencePieceTokenizer(spec)
    return HFTokenizer(spec)
