"""``python -m generativeaiexamples_tpu.frontend`` — frontend CLI
(reference: frontend/frontend/__main__.py)."""

from .server import main

main()
