"""LoRA fine-tuning: low-rank adapters over the stacked param tree.

The reference covers model customization with NeMo LoRA/SFT notebook
recipes (reference: models/Gemma/lora.ipynb, sft.ipynb — NeMo handles the
adapter math). Here LoRA is first-class and functional: adapters are a
separate small pytree, the forward merges ``W + (alpha/r) * A @ B`` on
the fly inside the loss, and the optimizer steps only the adapters — the
base params stay frozen (and can stay quantized int8/int4, QLoRA-style,
since ``dequantize`` runs inside the merge). Works over any mesh: the
merged weights inherit the base weights' shardings.

Adapter tree shape (stacked like the base): for each target key
``{"a": (L, K, r), "b": (L, r, N)}`` — b zero-init so step 0 is exactly
the base model.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import optax

from .models import llama
from .models.configs import LlamaConfig
from .ops.quant import dequantize, is_quantized
from .training import cross_entropy_loss

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")

LoraParams = dict[str, dict[str, jax.Array]]


def _weight_shape(w: Any) -> tuple[int, ...]:
    if is_quantized(w):
        K2, N = w["q4"].shape[-2:] if "q4" in w else w["q"].shape[-2:]
        K = K2 * 2 if "q4" in w else K2
        lead = (w["q4"] if "q4" in w else w["q"]).shape[:-2]
        return (*lead, K, N)
    return tuple(w.shape)


def init_lora(cfg: LlamaConfig, base_params: llama.Params, key: jax.Array,
              rank: int = 8, targets: Sequence[str] = DEFAULT_TARGETS,
              dtype: jnp.dtype = jnp.float32) -> LoraParams:
    """Zero-delta init: a ~ N(0, 1/K), b = 0 (the standard LoRA init)."""
    lora: LoraParams = {}
    keys = jax.random.split(key, len(targets))
    for k_rng, name in zip(keys, targets):
        if name not in base_params["layers"]:
            raise KeyError(f"unknown LoRA target {name!r}")
        shape = _weight_shape(base_params["layers"][name])
        if len(shape) != 3:
            raise ValueError(f"LoRA target {name!r} must be stacked "
                             f"(L, K, N); got shape {shape}")
        L, K, N = shape
        lora[name] = {
            "a": (jax.random.normal(k_rng, (L, K, rank), jnp.float32)
                  * (K ** -0.5)).astype(dtype),
            "b": jnp.zeros((L, rank, N), dtype),
        }
    return lora


def merge_lora(base_params: llama.Params, lora: LoraParams,
               alpha: float = 16.0) -> llama.Params:
    """Effective params: W + (alpha/r) * a @ b per target. Quantized base
    leaves dequantize for the merge (QLoRA-style serving of a tuned
    adapter over a quantized base)."""
    layers = dict(base_params["layers"])
    for name, ab in lora.items():
        w = layers[name]
        rank = ab["a"].shape[-1]
        scale = alpha / rank
        if is_quantized(w):
            w = dequantize(w, ab["a"].dtype)
        delta = jnp.einsum("lkr,lrn->lkn", ab["a"].astype(jnp.float32),
                           ab["b"].astype(jnp.float32)) * scale
        layers[name] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    return {**base_params, "layers": layers}


def make_lora_train_step(cfg: LlamaConfig,
                         optimizer: optax.GradientTransformation,
                         alpha: float = 16.0):
    """(lora, opt_state, base_params, batch) -> (lora, opt_state, loss).

    Only the adapters receive gradients/updates; jit with donate_argnums
    (0, 1) and the base params as a captured or donated-free argument.
    """

    def loss_fn(lora: LoraParams, base_params: llama.Params,
                batch: dict[str, jax.Array]) -> jax.Array:
        params = merge_lora(base_params, lora, alpha)
        B, S = batch["tokens"].shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        logits, _ = llama.apply(params, cfg, batch["tokens"], positions,
                                kv_valid_len=jnp.sum(batch["mask"],
                                                     axis=-1))
        return cross_entropy_loss(logits, batch["targets"], batch["mask"])

    def train_step(lora: LoraParams, opt_state: Any,
                   base_params: llama.Params, batch: dict[str, jax.Array]):
        loss, grads = jax.value_and_grad(loss_fn)(lora, base_params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        lora = optax.apply_updates(lora, updates)
        return lora, opt_state, loss

    return train_step
