"""Tier-1 smoke for the bench's multi-turn chat scenario.

Runs bench.run_chat_bench against a tiny CPU engine so the whole
prefix-cache serving path (hash -> match -> mapped pages -> suffix-chunk
prefill -> refcounted release) executes inside the fast test suite, not
only on TPU bench runs. Wall-clock TTFT ordering is NOT asserted here —
CPU timing is noise — the contract is that warm turns hit the cache
(``prefix_cache_hit_tokens`` > 0) and the scenario reports the fields
the BENCH_r06 artifact publishes.
"""

import jax
import jax.numpy as jnp

import bench
from generativeaiexamples_tpu.engine import Engine, EngineConfig
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=512)


def test_chat_scenario_hits_prefix_cache_on_cpu():
    params = llama.init_params(CFG, jax.random.key(5), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), EngineConfig(
        max_slots=2, max_input_length=256, max_output_length=16,
        prefill_buckets=(32, 64), page_size=16, dtype="float32",
        kv_pool_tokens=None, steps_per_round=4))
    with eng:
        res = bench.run_chat_bench(eng, n_turns=3, system_len=48,
                                   user_len=10, reply_len=4)
    assert res["turns"] == 3
    assert res["cold_ttft_ms"] is not None
    assert res["warm_p50_ttft_ms"] is not None
    assert len(res["warm_ttfts_ms"]) == 2
    # warm turns reused the cached conversation prefix: prefill started
    # at the first uncached token, not at token 0
    assert res["prefix_cache_hit_tokens"] > 0
    assert 0 < res["prefix_cache_hit_rate"] <= 1
    # every page is either free or warm in the cache afterwards
    cached = eng._prefix_cache.cached_pages
    assert len(eng._free_pages) + cached == eng._n_pages - 1


def test_chat_scenario_survives_cache_disabled():
    """BENCH comparability rung: the scenario itself must run (and report
    zero hits) when the engine's prefix cache is off."""
    params = llama.init_params(CFG, jax.random.key(5), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), EngineConfig(
        max_slots=2, max_input_length=256, max_output_length=16,
        prefill_buckets=(32, 64), page_size=16, dtype="float32",
        kv_pool_tokens=None, steps_per_round=4, prefix_cache=False))
    with eng:
        res = bench.run_chat_bench(eng, n_turns=2, system_len=48,
                                   user_len=10, reply_len=4, warmup=False)
    assert res["prefix_cache_hit_tokens"] == 0
    assert res["prefix_cache_hit_rate"] == 0.0


def test_e2e_scenario_breakdown_from_flight_recorder():
    """Tier-1 smoke of bench.run_e2e_bench: the full HTTP chatbot path on
    a tiny CPU engine, with the per-stage breakdown sourced from each
    request's FLIGHT-RECORDER timeline (keyed by the X-Request-ID the
    bench sends) — chain stages and engine stages on one record. CPU
    timings are noise; the contract is that the breakdown exists, is
    schema-legal, and the bench's request IDs landed in the recorder."""
    from generativeaiexamples_tpu.embed.encoder import get_embedder
    from generativeaiexamples_tpu.obs import flight
    from tools.check_bench_schema import load_schema

    params = llama.init_params(CFG, jax.random.key(5), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), EngineConfig(
        max_slots=2, max_input_length=1024, max_output_length=32,
        prefill_buckets=(128, 1024), dtype="float32",
        kv_pool_tokens=None, steps_per_round=4))
    with eng:
        p50, dist, breakdown, tps_p50 = bench.run_e2e_bench(
            eng, get_embedder("hash", dim=64), n_requests=3)
    assert p50 > 0 and dist["samples"] == 3
    # per-request tokens/sec median computed from timeline
    # generated/duration — exact, not histogram-bucket-quantized
    assert tps_p50 is not None and tps_p50 > 0
    # engine-side stages only exist because the adopted request ID
    # reached Engine.submit through the chain server's bound context
    for stage in ("engine_ttft", "engine_admit_dispatch", "llm"):
        assert stage in breakdown, breakdown
    # every reported stage is schema-legal (the TPU bench would refuse
    # to emit otherwise)
    allowed = set(load_schema()["breakdown_stages"])
    assert set(breakdown) <= allowed, set(breakdown) - allowed
    # the bench's request IDs are findable afterwards — the same lookup
    # an operator does via /debug/requests
    completed = [t["request_id"]
                 for t in flight.RECORDER.snapshot(limit=100)["completed"]]
    assert any(r.startswith("bench-") for r in completed)
