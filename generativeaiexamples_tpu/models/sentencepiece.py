"""Self-contained SentencePiece ``tokenizer.model`` loader + BPE encoder.

Real Llama-2 checkpoints ship their vocabulary as a serialized
``sentencepiece.ModelProto`` (``tokenizer.model``) — the format the
reference's preprocessing model consumes via AutoTokenizer (reference:
ensemble_models/llama/preprocessing/1/model.py:56-92). This image has no
``sentencepiece`` wheel, so both halves are implemented here:

- a minimal protobuf wire-format reader for the fields the tokenizer
  needs: ``ModelProto.pieces`` (field 1: piece/score/type) and the
  special-token ids from ``TrainerSpec`` (field 2: unk/bos/eos/pad ids,
  fields 40-43);
- the SentencePiece BPE encoding algorithm: normalize spaces to the
  U+2581 metaspace (with the dummy-prefix rule), seed with per-character
  symbols, then repeatedly merge the adjacent pair whose concatenation is
  the highest-scoring vocab piece (scores encode merge rank in BPE
  models), with UTF-8 byte-fallback pieces (``<0xNN>``) for anything
  outside the vocab.

Decoding handles the metaspace and reassembles byte-fallback runs.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Optional, Sequence

_METASPACE = "▁"

# ModelProto.SentencePiece.type values (sentencepiece_model.proto)
_TYPE_NORMAL = 1
_TYPE_UNKNOWN = 2
_TYPE_CONTROL = 3
_TYPE_USER_DEFINED = 4
_TYPE_BYTE = 6


def _varint(buf: bytes, i: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: bytes) -> Iterator[tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a protobuf message."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:        # varint
            value, i = _varint(buf, i)
        elif wire == 1:      # fixed64
            value = buf[i:i + 8]
            i += 8
        elif wire == 2:      # length-delimited
            ln, i = _varint(buf, i)
            value = buf[i:i + ln]
            i += ln
        elif wire == 5:      # fixed32
            value = buf[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        yield field, wire, value


def _parse_piece(buf: bytes) -> tuple[str, float, int]:
    piece, score, ptype = "", 0.0, _TYPE_NORMAL
    for field, wire, value in _fields(buf):
        if field == 1 and wire == 2:
            piece = value.decode("utf-8")
        elif field == 2 and wire == 5:
            score = struct.unpack("<f", value)[0]
        elif field == 3 and wire == 0:
            ptype = int(value)
    return piece, score, ptype


def _parse_trainer_ids(buf: bytes) -> dict[str, int]:
    # TrainerSpec: unk_id=40, bos_id=41, eos_id=42, pad_id=43
    names = {40: "unk", 41: "bos", 42: "eos", 43: "pad"}
    out: dict[str, int] = {}
    for field, wire, value in _fields(buf):
        if field in names and wire == 0:
            # ids are int32, but protobuf serializes negatives (pad_id=-1)
            # as 64-bit varints: mask to 32 bits before sign-adjusting
            v = int(value) & 0xFFFFFFFF
            if v >= 1 << 31:
                v -= 1 << 32
            out[names[field]] = v
    return out


class SentencePieceTokenizer:
    """Llama-family ``tokenizer.model`` (BPE + byte fallback)."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            path = os.path.join(path, "tokenizer.model")
        with open(path, "rb") as f:
            blob = f.read()
        self.pieces: list[tuple[str, float, int]] = []
        ids = {"unk": 0, "bos": 1, "eos": 2, "pad": -1}
        for field, wire, value in _fields(blob):
            if field == 1 and wire == 2:
                self.pieces.append(_parse_piece(value))
            elif field == 2 and wire == 2:
                ids.update(_parse_trainer_ids(value))
        if not self.pieces:
            raise ValueError(f"{path}: no sentencepiece vocabulary found")
        self._vocab: dict[str, int] = {}
        self._bytes: dict[int, int] = {}    # byte value -> piece id
        for idx, (piece, _, ptype) in enumerate(self.pieces):
            if ptype == _TYPE_BYTE:
                self._bytes[int(piece[1:-1], 16)] = idx   # "<0xNN>"
            if piece not in self._vocab:
                self._vocab[piece] = idx
        self.unk_id = ids["unk"]
        self.bos_id = ids["bos"]
        self.eos_id = ids["eos"]
        self.pad_id = ids["pad"] if ids["pad"] >= 0 else ids["eos"]

    @property
    def vocab_size(self) -> int:
        return len(self.pieces)

    def id_to_piece(self, idx: int) -> str:
        return self.pieces[idx][0]

    def piece_id(self, piece: str) -> Optional[int]:
        return self._vocab.get(piece)

    # ------------------------------------------------------------- encode

    def _byte_fallback(self, text: str) -> list[int]:
        out = []
        for b in text.encode("utf-8"):
            out.append(self._bytes.get(b, self.unk_id))
        return out if self._bytes else [self.unk_id]

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        import heapq

        norm = _METASPACE + text.replace(" ", _METASPACE)  # dummy prefix
        # Seed with one symbol per character, then best-score-first merges
        # (the BPE half of sentencepiece: scores are -merge_rank, so max
        # score == earliest learned merge). Heap + doubly-linked symbol
        # list keeps long prompts O(n log n) — a rescan-all loop would put
        # seconds of Python on the TTFT-critical prefill path.
        n = len(norm)
        sym: list[Optional[str]] = list(norm)
        nxt = list(range(1, n)) + [-1]
        prv = [-1] + list(range(n - 1))
        heap: list[tuple[float, int, int, str]] = []
        tie = 0

        def push(i: int) -> None:
            nonlocal tie
            if i < 0:
                return
            j = nxt[i]
            if j < 0 or sym[i] is None or sym[j] is None:
                return
            merged = sym[i] + sym[j]           # type: ignore[operator]
            idx = self._vocab.get(merged)
            if idx is not None:
                tie += 1
                heapq.heappush(heap,
                               (-self.pieces[idx][1], i, tie, merged))

        for i in range(n - 1):
            push(i)
        while heap:
            _, i, _, merged = heapq.heappop(heap)
            j = nxt[i]
            if (sym[i] is None or j < 0 or sym[j] is None
                    or sym[i] + sym[j] != merged):
                continue                        # stale entry
            sym[i] = merged
            sym[j] = None
            nxt[i] = nxt[j]
            if nxt[j] >= 0:
                prv[nxt[j]] = i
            push(prv[i])
            push(i)

        out: list[int] = [self.bos_id] if add_bos else []
        i = 0
        while i >= 0:
            s = sym[i]
            if s is not None:
                idx = self._vocab.get(s)
                if idx is not None and self.pieces[idx][2] != _TYPE_UNKNOWN:
                    out.append(idx)
                else:
                    out.extend(self._byte_fallback(s))
            i = nxt[i]
        return out

    # ------------------------------------------------------------- decode

    def decode(self, ids: Sequence[int]) -> str:
        parts: list[str] = []
        byte_run: list[int] = []

        def flush_bytes() -> None:
            if byte_run:
                parts.append(bytes(byte_run).decode("utf-8",
                                                    errors="replace"))
                byte_run.clear()

        for idx in ids:
            if idx < 0 or idx >= len(self.pieces):
                continue
            piece, _, ptype = self.pieces[idx]
            if ptype == _TYPE_BYTE:
                byte_run.append(int(piece[1:-1], 16))
                continue
            flush_bytes()
            if ptype in (_TYPE_CONTROL, _TYPE_UNKNOWN):
                continue
            parts.append(piece.replace(_METASPACE, " "))
        flush_bytes()
        text = "".join(parts)
        return text[1:] if text.startswith(" ") else text
