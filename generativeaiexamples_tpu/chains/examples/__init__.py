"""Built-in chain-server examples (reference: RetrievalAugmentedGeneration/examples/)."""
