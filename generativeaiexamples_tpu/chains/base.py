"""The pluggable-example contract.

Exact parity with the reference's ABC (reference: common/base.py:21-33):
``llm_chain`` / ``rag_chain`` stream answer text, ``ingest_docs`` loads a
file into the knowledge base; ``document_search`` is optional and duck-typed
by the server (reference: common/server.py:152).
"""

from __future__ import annotations

import abc
from typing import Any, Generator


class BaseExample(abc.ABC):
    """Base class for all chain-server examples."""

    @abc.abstractmethod
    def llm_chain(self, context: str, question: str, num_tokens: int,
                  ) -> Generator[str, None, None]:
        """Answer ``question`` with the LLM alone (no knowledge base);
        ``context`` is caller-supplied free text."""

    @abc.abstractmethod
    def rag_chain(self, prompt: str, num_tokens: int,
                  ) -> Generator[str, None, None]:
        """Answer using retrieval over the ingested knowledge base."""

    @abc.abstractmethod
    def ingest_docs(self, data_dir: str, filename: str) -> None:
        """Load a document file into the knowledge base."""

    # Optional (duck-typed by the server, like the reference):
    # def document_search(self, content: str, num_docs: int) -> list[dict]
