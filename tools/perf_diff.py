"""Perf regression gate: diff two bench JSON artifacts on headline
metrics.

The repo's perf trajectory is a series of committed ``BENCH_rNN.json``
artifacts, but until now nothing TOOLED the comparison — a regression
between rounds was only visible to a human reading two JSON blobs. This
tool compares a candidate run against a baseline on the headline
metrics and exits non-zero when any regresses past its threshold, so it
can gate CI (or the driver's round loop):

    python tools/perf_diff.py BENCH_r05.json fresh_run.json
    python tools/perf_diff.py base.json new.json --threshold-pct 3 \
        --threshold decode_tokens_per_sec=10

Metrics compared (each skipped with a note when absent from either
artifact — older rounds predate some sections):

======================================  =========  =====================
metric                                  direction  source
======================================  =========  =====================
``decode_tokens_per_sec``               higher     top level
``engine_p50_ttft_ms``                  lower      top level
``engine_p99_ttft_ms``                  lower      top level
``e2e_chat_ttft_ms``                    lower      top level
``chat.warm_p50_ttft_ms``               lower      chat scenario
``hbm_bw_util``                         higher     top level
``slo_attainment@<rps>``                higher     openloop, per common
                                                   swept rate
``goodput_tokens_per_sec@<rps>``        higher     openloop, per rate
``spec.tokens_per_step``                higher     chat/openloop spec
                                                   block (first present)
``fleet.prefix_hit_rate@<policy>``      higher     fleet scenario, per
                                                   placement-policy arm
``fleet.slo_attainment@<policy>``       higher     fleet, per policy arm
``fleet.ttft_p50_ms@<policy>``          lower      fleet, per policy arm
``fleet.kv_transfer_pages@<policy>``    higher     fleet, per policy arm
                                                   (transfer arms only —
                                                   a 0 baseline skips)
``autoscale.slo_attainment@<policy>``   higher     autoscale scenario,
                                                   per arm (autoscaled /
                                                   static)
``autoscale.replica_minutes@<policy>``  lower      autoscale scenario,
                                                   per arm — the bill:
                                                   attainment gains must
                                                   not hide behind a
                                                   quietly fatter fleet
``multichip.tokens_per_sec@<mesh>``     higher     multichip sweep, per
                                                   mesh rung (tp=1,
                                                   tp=2, ...)
``multichip.ttft_p50_ms@<mesh>``        lower      multichip sweep, per
                                                   mesh rung — TTFT must
                                                   DROP as chips grow,
                                                   not merely hold
``disagg.ttft_p50_ms@<arm>``            lower      disagg scenario, per
                                                   arm (unified /
                                                   disagg at equal
                                                   chips)
``disagg.decode_goodput@<arm>``         higher     disagg scenario, per
                                                   arm — the handoff
                                                   must protect decode
                                                   rounds, not just
                                                   TTFT
``obs_overhead.overhead_pct``           lower      obs-overhead scenario
                                                   — armed vs disarmed
                                                   decode tok/s cost of
                                                   the telemetry layer
``obs_overhead.armed_tokens_per_sec``   higher     obs-overhead scenario
======================================  =========  =====================

Accepts raw bench results or the driver's artifact wrapper (an object
with a ``parsed`` sub-object). Exit codes: 0 = no regression, 1 =
regression(s), 2 = usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

#: metric -> direction ("higher" = bigger is better).
HEADLINE_METRICS: dict[str, str] = {
    "decode_tokens_per_sec": "higher",
    "engine_p50_ttft_ms": "lower",
    "engine_p99_ttft_ms": "lower",
    "e2e_chat_ttft_ms": "lower",
    "chat.warm_p50_ttft_ms": "lower",
    "hbm_bw_util": "higher",
    # openloop per-rate and spec metrics are added dynamically by
    # extract_metrics with the directions below
}
_OPENLOOP_DIRECTIONS = {"slo_attainment": "higher",
                        "goodput_tokens_per_sec": "higher"}
_SPEC_DIRECTION = ("spec.tokens_per_step", "higher")
#: Fleet-scenario headline metrics, per placement-policy arm — the
#: cross-replica numbers the router exists to move, gated with the same
#: direction-aware thresholds as the single-replica headlines.
_FLEET_DIRECTIONS = {"prefix_hit_rate": "higher",
                     "slo_attainment": "higher",
                     "ttft_p50_ms": "lower",
                     "kv_transfer_pages": "higher"}
#: Autoscale-scenario headlines, per policy arm (autoscaled / static):
#: attainment up, replica-minutes DOWN — the control loop is only a win
#: if it attains more without quietly spending a fatter fleet.
_AUTOSCALE_DIRECTIONS = {"slo_attainment": "higher",
                         "replica_minutes": "lower",
                         "ttft_p50_ms": "lower"}

#: multichip rung field -> (published gate name, direction); keyed per
#: mesh rung, e.g. ``multichip.tokens_per_sec@tp=2``.
_MULTICHIP_FIELDS = {"decode_tokens_per_sec": ("tokens_per_sec",
                                               "higher"),
                     "engine_p50_ttft_ms": ("ttft_p50_ms", "lower")}
#: Disaggregation-scenario headlines, per arm (unified / disagg at
#: equal chips): the PR's claim is the disagg arm wins BOTH — p50 TTFT
#: down AND decode goodput up — so both are gated round-over-round.
_DISAGG_DIRECTIONS = {"ttft_p50_ms": "lower",
                      "decode_goodput": "higher"}
#: Failover-scenario headlines, per arm (resume_on / resume_off around
#: the same scripted mid-stream kill): the claim is the resume arm
#: keeps the error-free completion rate at 1.0 without paying much
#: added latency on the resumed streams — both gated round-over-round.
#: (resumed_added_p50_ms is null on the resume_off arm and simply
#: contributes nothing there.)
_FAILOVER_DIRECTIONS = {"completed_no_error_rate": "higher",
                        "resumed_added_p50_ms": "lower"}
#: Observability-overhead scenario: the armed arm (history sampler +
#: alert engine ticking at a tight interval) must stay within budget of
#: the disarmed arm — overhead percent DOWN, armed decode tok/s UP. The
#: disarmed arm is the reference and is not gated on its own.
_OBS_OVERHEAD_DIRECTIONS = {"overhead_pct": "lower",
                            "armed_tokens_per_sec": "higher"}

DEFAULT_THRESHOLD_PCT = 5.0


def _num(value) -> Optional[float]:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value)


def extract_metrics(result: dict) -> dict[str, tuple[float, str]]:
    """Flatten one bench result into ``{metric: (value, direction)}``.
    Missing sections simply contribute nothing — the comparison later
    skips metrics absent on either side."""
    result = result.get("parsed", result)   # driver artifact wrapper
    out: dict[str, tuple[float, str]] = {}
    for name, direction in HEADLINE_METRICS.items():
        obj = result
        ok = True
        for part in name.split("."):
            if not isinstance(obj, dict) or part not in obj:
                ok = False
                break
            obj = obj[part]
        v = _num(obj) if ok else None
        if v is not None:
            out[name] = (v, direction)
    openloop = result.get("openloop")
    if isinstance(openloop, dict):
        for entry in openloop.get("rates") or []:
            if not isinstance(entry, dict):
                continue
            rps = entry.get("arrival_rps")
            if rps is None:
                continue
            for key, direction in _OPENLOOP_DIRECTIONS.items():
                v = _num(entry.get(key))
                if v is not None:
                    out[f"{key}@{rps:g}"] = (v, direction)
    for section in ("chat", "openloop"):
        spec = (result.get(section) or {}) if \
            isinstance(result.get(section), dict) else {}
        block = spec.get("spec")
        if isinstance(block, dict):
            v = _num(block.get("tokens_per_step"))
            if v is not None and _SPEC_DIRECTION[0] not in out:
                out[_SPEC_DIRECTION[0]] = (v, _SPEC_DIRECTION[1])
    fleet = result.get("fleet")
    if isinstance(fleet, dict):
        for entry in fleet.get("policies") or []:
            if not isinstance(entry, dict):
                continue
            policy = entry.get("policy")
            if not policy:
                continue
            for key, direction in _FLEET_DIRECTIONS.items():
                v = _num(entry.get(key))
                if v is not None:
                    out[f"fleet.{key}@{policy}"] = (v, direction)
    autoscale = result.get("autoscale")
    if isinstance(autoscale, dict):
        for entry in autoscale.get("policies") or []:
            if not isinstance(entry, dict):
                continue
            policy = entry.get("policy")
            if not policy:
                continue
            for key, direction in _AUTOSCALE_DIRECTIONS.items():
                v = _num(entry.get(key))
                if v is not None:
                    out[f"autoscale.{key}@{policy}"] = (v, direction)
    multichip = result.get("multichip")
    if isinstance(multichip, dict):
        for entry in multichip.get("rungs") or []:
            if not isinstance(entry, dict):
                continue
            mesh = entry.get("mesh")
            if not mesh:
                continue
            for field, (name, direction) in _MULTICHIP_FIELDS.items():
                v = _num(entry.get(field))
                if v is not None:
                    out[f"multichip.{name}@{mesh}"] = (v, direction)
    disagg = result.get("disagg")
    if isinstance(disagg, dict):
        for entry in disagg.get("arms") or []:
            if not isinstance(entry, dict):
                continue
            arm = entry.get("arm")
            if not arm:
                continue
            for key, direction in _DISAGG_DIRECTIONS.items():
                v = _num(entry.get(key))
                if v is not None:
                    out[f"disagg.{key}@{arm}"] = (v, direction)
    obs = result.get("obs_overhead")
    if isinstance(obs, dict):
        for key, direction in _OBS_OVERHEAD_DIRECTIONS.items():
            v = _num(obs.get(key))
            if v is not None:
                out[f"obs_overhead.{key}"] = (v, direction)
    failover = result.get("failover")
    if isinstance(failover, dict):
        for entry in failover.get("arms") or []:
            if not isinstance(entry, dict):
                continue
            arm = entry.get("arm")
            if not arm:
                continue
            for key, direction in _FAILOVER_DIRECTIONS.items():
                v = _num(entry.get(key))
                if v is not None:
                    out[f"failover.{key}@{arm}"] = (v, direction)
    return out


def compare(base: dict, new: dict,
            threshold_pct: float = DEFAULT_THRESHOLD_PCT,
            per_metric_pct: Optional[dict[str, float]] = None
            ) -> tuple[list[str], list[str]]:
    """Compare two extracted metric maps. Returns ``(regressions,
    notes)`` — regressions are metrics that moved in the WRONG direction
    by more than their threshold percent; notes cover skips and
    improvements."""
    per_metric_pct = per_metric_pct or {}
    regressions: list[str] = []
    notes: list[str] = []
    for name in sorted(set(base) | set(new)):
        if name not in base or name not in new:
            side = "baseline" if name not in base else "candidate"
            notes.append(f"skip {name}: absent from {side}")
            continue
        b, direction = base[name]
        n, _ = new[name]
        if b == 0:
            notes.append(f"skip {name}: baseline is 0")
            continue
        # Signed change in the GOOD direction, percent of baseline.
        delta_pct = (n - b) / abs(b) * 100.0
        if direction == "lower":
            delta_pct = -delta_pct
        limit = per_metric_pct.get(name, threshold_pct)
        arrow = f"{b:g} -> {n:g}"
        if delta_pct < -limit:
            regressions.append(
                f"{name}: {arrow} ({-delta_pct:.1f}% worse, "
                f"threshold {limit:g}%)")
        elif delta_pct > limit:
            notes.append(f"improved {name}: {arrow} "
                         f"(+{delta_pct:.1f}%)")
        else:
            notes.append(f"ok {name}: {arrow} ({delta_pct:+.1f}%)")
    return regressions, notes


def diff_files(base_path: str, new_path: str,
               threshold_pct: float = DEFAULT_THRESHOLD_PCT,
               per_metric_pct: Optional[dict[str, float]] = None
               ) -> tuple[list[str], list[str]]:
    with open(base_path) as f:
        base = extract_metrics(json.load(f))
    with open(new_path) as f:
        new = extract_metrics(json.load(f))
    if not base:
        raise ValueError(f"{base_path}: no headline metrics found")
    if not new:
        raise ValueError(f"{new_path}: no headline metrics found")
    return compare(base, new, threshold_pct, per_metric_pct)


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Compare two bench JSON artifacts; non-zero exit on "
                    "a headline-metric regression (CI gate).")
    parser.add_argument("baseline", help="baseline bench JSON "
                                         "(e.g. BENCH_r05.json)")
    parser.add_argument("candidate", help="candidate bench JSON")
    parser.add_argument("--threshold-pct", type=float,
                        default=DEFAULT_THRESHOLD_PCT,
                        help="default allowed regression percent "
                             "(default %(default)s)")
    parser.add_argument("--threshold", action="append", default=[],
                        metavar="METRIC=PCT",
                        help="per-metric threshold override "
                             "(repeatable), e.g. "
                             "--threshold engine_p50_ttft_ms=10")
    args = parser.parse_args(argv)
    per_metric: dict[str, float] = {}
    for spec in args.threshold:
        name, sep, pct = spec.partition("=")
        if not sep:
            parser.error(f"--threshold needs METRIC=PCT, got {spec!r}")
        try:
            per_metric[name.strip()] = float(pct)
        except ValueError:
            parser.error(f"--threshold {spec!r}: PCT must be numeric")
    try:
        regressions, notes = diff_files(
            args.baseline, args.candidate, args.threshold_pct, per_metric)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"perf_diff: {exc}", file=sys.stderr)
        return 2
    for note in notes:
        print(note)
    if regressions:
        print(f"\n{len(regressions)} REGRESSION(S) vs {args.baseline}:")
        for r in regressions:
            print(f"  FAIL {r}")
        return 1
    print(f"\nno regressions vs {args.baseline}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
