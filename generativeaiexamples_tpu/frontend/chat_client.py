"""HTTP client for the chain server.

Method-for-method parity with the reference's client (reference:
frontend/frontend/chat_client.py): ``search`` (43), streaming ``predict``
(72 — requests.post(stream=True), yields chunks then a ``None`` sentinel),
``upload_documents`` (101). Outgoing requests carry W3C trace context
(reference: frontend/tracing.py:47-63) plus an ``X-Request-ID`` minted
per call (or supplied by the caller) — the server adopts it as the
request's flight-recorder identity, so a slow answer can be looked up in
the chain server's ``/debug/requests`` by the ID this client holds in
``last_request_id``.
"""

from __future__ import annotations

from typing import Generator, Optional

import requests

from ..obs.flight import mint_request_id
from ..obs.tracing import inject_context
from ..utils.logging import get_logger

logger = get_logger(__name__)


class ChatClient:
    def __init__(self, server_url: str, model_name: str = "",
                 timeout: float = 120.0):
        self.server_url = server_url.rstrip("/")
        self.model_name = model_name
        self.timeout = timeout
        # Request ID of the most recent call — what to quote when asking
        # the chain server's /debug/requests why it was slow.
        self.last_request_id: Optional[str] = None

    def _headers(self, request_id: Optional[str] = None) -> dict:
        rid = request_id or mint_request_id()
        self.last_request_id = rid
        return inject_context({"X-Request-ID": rid})

    def search(self, prompt: str, num_docs: int = 4,
               request_id: Optional[str] = None) -> list[dict]:
        """Document retrieval (reference: chat_client.py:43)."""
        resp = requests.post(
            f"{self.server_url}/documentSearch",
            json={"content": prompt, "num_docs": num_docs},
            headers=self._headers(request_id), timeout=self.timeout)
        resp.raise_for_status()
        return resp.json()

    def predict(self, query: str, use_knowledge_base: bool = True,
                num_tokens: int = 256, context: str = "",
                request_id: Optional[str] = None,
                ) -> Generator[Optional[str], None, None]:
        """Stream answer chunks; yields ``None`` when the stream ends
        (reference: chat_client.py:72-99 — 16-byte chunk reads with a
        final None sentinel)."""
        import codecs
        decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
        with requests.post(
                f"{self.server_url}/generate",
                json={"question": query, "context": context,
                      "use_knowledge_base": use_knowledge_base,
                      "num_tokens": num_tokens},
                headers=self._headers(request_id), stream=True,
                timeout=self.timeout) as resp:
            resp.raise_for_status()
            for chunk in resp.iter_content(chunk_size=16,
                                           decode_unicode=False):
                # incremental decode: multi-byte UTF-8 sequences may
                # straddle the 16-byte chunk boundary
                text = decoder.decode(chunk)
                if text:
                    yield text
        tail = decoder.decode(b"", final=True)
        if tail:
            yield tail
        yield None

    def upload_documents(self, file_paths: list[str]) -> None:
        """Upload files into the knowledge base
        (reference: chat_client.py:101-127)."""
        for path in file_paths:
            with open(path, "rb") as f:
                resp = requests.post(
                    f"{self.server_url}/uploadDocument",
                    files={"file": (path.split("/")[-1], f)},
                    headers=self._headers(), timeout=self.timeout)
            resp.raise_for_status()
            logger.info("uploaded %s (request %s)", path,
                        self.last_request_id)
