"""Sparse mixture-of-experts: top-k routing with capacity-bounded dispatch.

The reference never runs Mixtral locally — it reaches it through cloud
endpoints (reference: examples/5_mins_rag_no_gpu/main.py:50). Here expert
parallelism is first-class: this module is the sparse-compute path promised
by ``models/llama.py`` — O(tokens x k) expert FLOPs instead of the dense
formulation's O(tokens x E).

Design (TPU-first):
- **Static shapes.** Each expert processes a fixed-capacity buffer
  ``C = ceil(T*k/E * capacity_factor)``; overflowing tokens are dropped
  (their combine weight is zero) — the GShard/Switch capacity discipline
  that keeps XLA shapes static.
- **Scatter/gather dispatch.** Tokens are routed with one scatter-add into
  ``(E, C, D)`` and one gather back — O(T*k*D) data movement, not the
  O(T*E*C*D) one-hot-einsum formulation (quadratic in T at prefill).
- **EP sharding.** Under GSPMD the expert axis of the ``(E, C, D)`` buffers
  follows the ``ep``-sharded expert weights, so XLA inserts the token
  all-to-all over ICI on its own. ``ep_expert_ffn`` is the explicit
  ``shard_map`` equivalent (experts over ``ep``, FFN width over ``tp`` with
  a psum), used where manual control is wanted and as the parity oracle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.configs import LlamaConfig


def expert_capacity(n_tokens: int, n_experts: int, k: int,
                    capacity_factor: float) -> int:
    """Per-expert token slots; static given the (padded) token count."""
    return max(1, int(-(-n_tokens * k * capacity_factor // n_experts)))


def route_topk(router_logits: jax.Array, k: int, capacity: int):
    """Top-k routing with in-expert slot assignment.

    router_logits: (T, E). Returns flat (T*k,) arrays, token-major:
      expert  — chosen expert id per claim
      slot    — position inside that expert's capacity buffer
      weight  — softmaxed router weight (float32)
      keep    — False where the expert's capacity was already full
    Earlier tokens claim slots first (deterministic, order-based priority).
    """
    T, E = router_logits.shape
    w, idx = jax.lax.top_k(router_logits, k)                    # (T, k)
    w = jax.nn.softmax(w.astype(jnp.float32), axis=-1)
    expert = idx.reshape(-1)                                    # (T*k,)
    claims = jax.nn.one_hot(expert, E, dtype=jnp.int32)         # (T*k, E)
    pos = jnp.cumsum(claims, axis=0) - 1                        # claim rank
    slot = jnp.take_along_axis(pos, expert[:, None], axis=1)[:, 0]
    keep = slot < capacity
    return expert, jnp.clip(slot, 0, capacity - 1), w.reshape(-1), keep


def _dispatch(x_flat: jax.Array, expert: jax.Array, slot: jax.Array,
              keep: jax.Array, n_experts: int, capacity: int) -> jax.Array:
    """(T, D) tokens -> (E, C, D) expert buffers (scatter; slots unique)."""
    T, D = x_flat.shape
    k = expert.shape[0] // T
    t_idx = jnp.repeat(jnp.arange(T), k)
    contrib = x_flat[t_idx] * keep[:, None].astype(x_flat.dtype)
    return jnp.zeros((n_experts, capacity, D), x_flat.dtype).at[
        expert, slot].add(contrib)


def _combine(expert_out: jax.Array, expert: jax.Array, slot: jax.Array,
             weight: jax.Array, keep: jax.Array, n_tokens: int) -> jax.Array:
    """(E, C, D) expert outputs -> (T, D) weighted token outputs (gather)."""
    k = expert.shape[0] // n_tokens
    t_idx = jnp.repeat(jnp.arange(n_tokens), k)
    y = expert_out[expert, slot]                                # (T*k, D)
    w = (weight * keep).astype(y.dtype)[:, None]
    return jnp.zeros((n_tokens, expert_out.shape[-1]), y.dtype).at[
        t_idx].add(y * w)


def _expert_ffn(expert_in: jax.Array, w_gate: jax.Array, w_up: jax.Array,
                w_down: jax.Array) -> jax.Array:
    """Per-expert SwiGLU on (E, C, D) with stacked (E, D, F) weights."""
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, w_gate))
    up = jnp.einsum("ecd,edf->ecf", expert_in, w_up)
    return jnp.einsum("ecf,efd->ecd", gate * up, w_down)


def sparse_moe_ffn(x: jax.Array, lp: dict[str, jax.Array],
                   cfg: LlamaConfig) -> jax.Array:
    """Sparse MoE layer: (B, S, D) -> (B, S, D), top-k experts per token.

    Pure jnp — under jit with ``ep``-sharded expert weights GSPMD reshards
    the (E, C, D) buffers over ``ep`` and emits the all-to-all itself.
    """
    B, S, D = x.shape
    T = B * S
    x_flat = x.reshape(T, D)
    logits = x_flat.astype(jnp.float32) @ lp["router"].astype(jnp.float32)
    C = expert_capacity(T, cfg.num_experts, cfg.num_experts_per_tok,
                        cfg.moe_capacity_factor)
    expert, slot, weight, keep = route_topk(logits,
                                            cfg.num_experts_per_tok, C)
    expert_in = _dispatch(x_flat, expert, slot, keep, cfg.num_experts, C)
    expert_out = _expert_ffn(expert_in, lp["w_gate"], lp["w_up"],
                             lp["w_down"])
    return _combine(expert_out, expert, slot, weight, keep, T).reshape(B, S, D)


def ep_expert_ffn(mesh: Mesh, expert_in: jax.Array, w_gate: jax.Array,
                  w_up: jax.Array, w_down: jax.Array) -> jax.Array:
    """Explicit shard_map expert FFN: experts over ``ep``, FFN width over
    ``tp`` (row-parallel down-projection closed with a psum over tp)."""
    def local(ei, g, u, d):
        out = _expert_ffn(ei, g, u, d)
        return jax.lax.psum(out, "tp")

    from .compat import shard_map
    return shard_map(
        local, mesh=mesh,
        in_specs=(P("ep", None, None), P("ep", None, "tp"),
                  P("ep", None, "tp"), P("ep", "tp", None)),
        out_specs=P("ep", None, None))(expert_in, w_gate, w_up, w_down)


def ep_sparse_moe_ffn(mesh: Mesh, x: jax.Array, lp: dict[str, jax.Array],
                      cfg: LlamaConfig) -> jax.Array:
    """``sparse_moe_ffn`` with the expert compute under explicit shard_map
    (dispatch/combine stay global: XLA lowers the boundary resharding to
    the ep all-to-all over ICI)."""
    B, S, D = x.shape
    T = B * S
    x_flat = x.reshape(T, D)
    logits = x_flat.astype(jnp.float32) @ lp["router"].astype(jnp.float32)
    C = expert_capacity(T, cfg.num_experts, cfg.num_experts_per_tok,
                        cfg.moe_capacity_factor)
    # capacity must tile over ep shards evenly for the shard_map specs
    expert, slot, weight, keep = route_topk(logits,
                                            cfg.num_experts_per_tok, C)
    expert_in = _dispatch(x_flat, expert, slot, keep, cfg.num_experts, C)
    expert_out = ep_expert_ffn(mesh, expert_in, lp["w_gate"], lp["w_up"],
                               lp["w_down"])
    return _combine(expert_out, expert, slot, weight, keep, T).reshape(B, S, D)
