"""Pallas paged-attention decode kernel (TPU).

The decode hot path reads each slot's KV page window from the shared pool
and appends the step's new K/V row. Doing either through XLA ops was the
bottleneck and the round-2/3 OOMs in one:

- ``pool[block_table]`` lowers to a generic gather that runs an order of
  magnitude below DMA speed (measured ~18 ms/step on v5e for ~2 ms of page
  traffic — >2/3 of decode step time);
- the row scatter makes XLA prefer a permuted pool layout while the kernel
  needs row-major, so every round paid a full-pool relayout copy (2x pool
  HBM — the VERDICT weak-#1 OOM family);
- pool reads inside an opaque kernel plus an external scatter defeat
  XLA's aliasing analysis, double-buffering the loop carry.

This kernel does the whole step natively: the block table and write
location ride scalar prefetch (SMEM), page windows stream HBM->VMEM
through a manual multi-buffered DMA pipeline, attention accumulates
page-by-page with an online softmax (flash style) over PER-SLOT dynamic
page counts (HBM reads follow each sequence's live length, not the batch
max), and the new K/V row lands in the pool via an aligned 8-row-tile
write whose preserved rows come from the already-streamed window page —
no read-modify-write round trip. The pool is aliased in/out
(``input_output_aliases``), so the whole decode step leaves the pool in
place, in one layout, with zero XLA gathers/scatters/copies.

Program layout (round 8): programs are SLOT GROUPS, not single slots.
The former one-program-per-slot grid ran B sequential programs per layer,
and each program boundary drained its private 2-deep DMA pipeline — at 64
slots the drains and fixed per-program overhead were most of the decay
from 0.735 to 0.576 HBM-bandwidth utilization (BENCH_SWEEP_r05). Now one
program owns ``_GROUP`` slots and streams ALL their live pages through a
single flat (slot, page) loop behind one ``_NBUF``-deep buffer ring:

- page fetches batch across slots — the fetch for the next slot's first
  page issues while the current slot's last pages are still computing, so
  a short or finished slot never leaves the stream idle;
- per-slot online-softmax state lives in VMEM scratch, indexed by the
  flat loop's current slot;
- the pipeline depth (``_NBUF - 1`` fetches in flight) rides out
  per-page DMA latency variance that double buffering could not;
- program count (and per-program fixed overhead) drops by the group
  factor.

Same role as the paged-KV device kernels the reference gets from the
TRT-LLM C++ backend (reference: ensemble_models/llama/tensorrt_llm/
config.pbtxt.j2:28-34 paged_kv_cache; model_server/server.py:67-71).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

NEG = -1e30
_TILE = 8   # sublane tile: HBM DMA slices must be 8-row aligned
_NBUF = 4   # page-buffer ring depth: _NBUF - 1 fetches stay in flight
_GROUP = 8  # slots per program (largest divisor of B <= this)


def group_size(batch: int) -> int:
    """Slots per kernel program: the largest divisor of ``batch`` that is
    <= ``PAGED_GROUP_SLOTS`` (default 8). A divisor keeps the grid exact;
    the env knob exists for VMEM-constrained geometries."""
    cap = int(os.environ.get("PAGED_GROUP_SLOTS", str(_GROUP)))
    g = max(1, min(batch, cap))
    while batch % g:
        g -= 1
    return g


def kernel_supported(page: int, num_heads: int, num_kv_heads: int,
                     head_dim: int) -> bool:
    """Kernel preconditions: lane-width page/head_dim (Mosaic tiling) and
    GQA-divisible head counts (the (KV, G, hd) query reshape)."""
    return (head_dim % 128 == 0 and page % 128 == 0
            and num_kv_heads > 0 and num_heads % num_kv_heads == 0)


def paged_attention_decode(q: jax.Array, pool_k: jax.Array,
                           pool_v: jax.Array, block_table: jax.Array,
                           lengths: jax.Array, cur_k: jax.Array,
                           cur_v: jax.Array, write_page: jax.Array,
                           write_offset: jax.Array, layer: jax.Array,
                           *, pool_ks: jax.Array | None = None,
                           pool_vs: jax.Array | None = None,
                           interpret: bool = False):
    """GQA decode attention + KV append over a paged pool, one query token
    per slot.

    q:            (B, H, hd)           current token's queries
    pool_k/v:     (L, N, KV, page, hd) shared page pool, all layers (the
                                       caller scans layers with the pools
                                       in the carry; passing whole pools
                                       through the aliased call keeps the
                                       scan carry in place)
    block_table:  (B, W) int32         physical page of each logical page
    lengths:      (B,) int32           cached tokens per slot (== pos;
                                       current token is NOT in the pool)
    cur_k/cur_v:  (B, KV, hd)          current token's K/V (pool dtype,
                                       or bf16/f32 when the pool is int8 —
                                       the kernel quantizes on append)
    write_page:   (B,) int32           physical page for the new row
                                       (page 0 = trash, inactive slots)
    write_offset: (B,) int32           row within that page
    layer:        (1,) int32           which layer to read/write
    pool_ks/vs:   (L, N, KV, page)     OPTIONAL per-row scales: presence
                                       switches the kernel to the int8-KV
                                       path (ops/kv_quant.py) — int8 pages
                                       stream at half the HBM bytes, are
                                       widened to bf16 once in VMEM, and
                                       the scales fold into scores (K) and
                                       probabilities (V) around the MXU
                                       dots; the append quantizes the new
                                       row in-kernel and writes its scale
                                       back through the already-streamed
                                       scale page.
    Returns (attn (B, H, hd) in q.dtype, new_pool_k, new_pool_v[,
    new_pool_ks, new_pool_vs]) with the pools aliased in place. Scaling
    (1/sqrt(hd)) applied here.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, hd = q.shape
    L, N, KV, page, _ = pool_k.shape
    G = H // KV
    scale = hd ** -0.5
    quant = pool_ks is not None
    if quant:
        return _paged_attention_decode_quant(
            q, pool_k, pool_v, pool_ks, pool_vs, block_table, lengths,
            cur_k, cur_v, write_page, write_offset, layer,
            interpret=interpret)
    Gs = group_size(B)

    def kernel(tbl_ref, len_ref, wp_ref, off_ref, l_ref, q_ref,
               k_hbm, v_hbm, ck_ref, cv_ref, out_ref, opk_ref, opv_ref,
               kbuf, vbuf, accs, ms, ls, stk, stv, krw, vrw, sem, rw_sem):
        gi = pl.program_id(0)
        li = l_ref[0]
        b0 = gi * Gs
        # Per-slot live page counts and their flat prefix starts: the
        # group's pages stream as ONE flat sequence t in [0, total),
        # slot boundaries invisible to the DMA pipeline.
        counts = [jax.lax.div(len_ref[b0 + i] + (page - 1), page)
                  for i in range(Gs)]
        starts = [jnp.int32(0)]
        for c in counts:
            starts.append(starts[-1] + c)
        total = starts[Gs]

        # Scratch persists across grid programs: re-init this group's
        # softmax state (a zero-page slot must fold its current token
        # against a fresh carry, not the previous group's).
        for i in range(Gs):
            accs[i] = jnp.zeros((KV, G, hd), jnp.float32)
            ms[i] = jnp.full((KV, G), NEG, jnp.float32)
            ls[i] = jnp.zeros((KV, G), jnp.float32)

        def locate(t):
            """flat index -> (slot-in-group, page-within-slot, count)."""
            sidx = jnp.int32(0)
            base = jnp.int32(0)
            for i in range(Gs - 1):
                past = t >= starts[i + 1]
                sidx = sidx + past.astype(jnp.int32)
                base = base + jnp.where(past, counts[i], 0)
            cnt = counts[Gs - 1]
            for i in range(Gs - 1):
                cnt = jnp.where(sidx == i, counts[i], cnt)
            return sidx, t - base, cnt

        def dmas(sidx, w, slot):
            pg = tbl_ref[b0 + sidx, w]
            return (pltpu.make_async_copy(k_hbm.at[li, pg], kbuf.at[slot],
                                          sem.at[slot, 0]),
                    pltpu.make_async_copy(v_hbm.at[li, pg], vbuf.at[slot],
                                          sem.at[slot, 1]))

        def start_fetch(t):
            sidx, w, _ = locate(t)
            for d in dmas(sidx, w, jax.lax.rem(t, _NBUF)):
                d.start()

        # Prologue: fill the ring (up to _NBUF - 1 fetches in flight).
        for j in range(_NBUF - 1):
            @pl.when(jnp.int32(j) < total)
            def _(j=j):
                start_fetch(jnp.int32(j))

        def body(t, carry):
            # Top off the pipeline first: buffer (t-1) % _NBUF was freed
            # by the previous step's (program-ordered) compute.
            @pl.when(t + _NBUF - 1 < total)
            def _():
                start_fetch(t + _NBUF - 1)
            slot = jax.lax.rem(t, _NBUF)
            # ONE locate per iteration: the wait descriptors reuse its
            # result (the top-off fetch above locates t + _NBUF - 1, a
            # different flat index).
            sidx, w, cnt = locate(t)
            b = b0 + sidx
            for d in dmas(sidx, w, slot):
                d.wait()
            length = len_ref[b]
            qv = q_ref[sidx].reshape(KV, G, hd)
            # Operands stay in pool dtype into the MXU; accumulation is
            # f32 via preferred_element_type — no widened VMEM copies.
            kp = kbuf[slot]                                  # (KV,page,hd)
            vp = vbuf[slot]
            scores = jax.lax.dot_general(
                qv, kp, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32) * scale  # (KV,G,page)
            valid = (w * page + jax.lax.broadcasted_iota(
                jnp.int32, (1, 1, page), 2)) < length
            scores = jnp.where(valid, scores, NEG)

            m = ms[sidx][..., None]
            l = ls[sidx][..., None]
            m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(scores - m_new)                      # (KV,G,page)
            ls[sidx] = (l * alpha + jnp.sum(p, axis=-1,
                                            keepdims=True))[..., 0]
            ms[sidx] = m_new[..., 0]
            pv = jax.lax.dot_general(
                p.astype(vp.dtype), vp, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)          # (KV,G,hd)
            accs[sidx] = accs[sidx] * alpha + pv

            # Stage the append-source tile at the slot's LAST page: the
            # ring reuses this buffer _NBUF pages later (possibly mid-way
            # through ANOTHER slot), so the 8 preserved rows are copied
            # out now instead of read back from HBM in the epilogue.
            off = off_ref[b]
            tile0 = (off // _TILE) * _TILE

            @pl.when(w + 1 == cnt)
            def _():
                stk[sidx] = kbuf[slot, :, pl.ds(tile0, _TILE), :]
                stv[sidx] = vbuf[slot, :, pl.ds(tile0, _TILE), :]
            return carry

        jax.lax.fori_loop(0, total, body, jnp.int32(0))

        # Per-slot epilogue: fold the current (not yet pooled) token in
        # exactly via partials, then append the new row without a
        # read-modify-write round trip — rows to preserve (rows < off of
        # the write page) were staged from the streamed window; when
        # off == 0 the page is fresh and dead rows are garbage attention
        # masks (rows >= length are never read).
        writes = []
        for i in range(Gs):
            b = b0 + i
            qv = q_ref[i].reshape(KV, G, hd)
            m = ms[i][..., None]
            l = ls[i][..., None]
            acc = accs[i]
            ck = ck_ref[i].astype(jnp.float32)               # (KV,hd)
            cv = cv_ref[i].astype(jnp.float32)
            s_cur = jnp.sum(qv.astype(jnp.float32) * ck[:, None, :],
                            axis=-1, keepdims=True) * scale  # (KV,G,1)
            m2 = jnp.maximum(m, s_cur)
            a = jnp.exp(m - m2)
            bta = jnp.exp(s_cur - m2)
            out = acc * a + cv[:, None, :] * bta
            denom = l * a + bta
            out_ref[i] = (out / denom).reshape(H, hd).astype(out_ref.dtype)

            off = off_ref[b]
            tile0 = (off // _TILE) * _TILE
            row_mask = jax.lax.broadcasted_iota(
                jnp.int32, (1, _TILE, 1), 1) == (off - tile0)
            krw[i] = jnp.where(row_mask, ck_ref[i][:, None, :], stk[i])
            vrw[i] = jnp.where(row_mask, cv_ref[i][:, None, :], stv[i])
            wp = wp_ref[b]
            kwr = pltpu.make_async_copy(
                krw.at[i], opk_ref.at[li, wp, :, pl.ds(tile0, _TILE)],
                rw_sem.at[i, 0])
            vwr = pltpu.make_async_copy(
                vrw.at[i], opv_ref.at[li, wp, :, pl.ds(tile0, _TILE)],
                rw_sem.at[i, 1])
            kwr.start()
            vwr.start()
            writes += [kwr, vwr]
        for wcp in writes:
            wcp.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,   # table, lengths, write page/offset, layer
        grid=(B // Gs,),
        in_specs=[
            pl.BlockSpec((Gs, H, hd), lambda g, *_: (g, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # K pool stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # V pool stays in HBM
            pl.BlockSpec((Gs, KV, hd), lambda g, *_: (g, 0, 0)),
            pl.BlockSpec((Gs, KV, hd), lambda g, *_: (g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((Gs, H, hd), lambda g, *_: (g, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((_NBUF, KV, page, hd), pool_k.dtype),
            pltpu.VMEM((_NBUF, KV, page, hd), pool_v.dtype),
            pltpu.VMEM((Gs, KV, G, hd), jnp.float32),   # accs
            pltpu.VMEM((Gs, KV, G), jnp.float32),       # ms
            pltpu.VMEM((Gs, KV, G), jnp.float32),       # ls
            pltpu.VMEM((Gs, KV, _TILE, hd), pool_k.dtype),  # staged k
            pltpu.VMEM((Gs, KV, _TILE, hd), pool_v.dtype),  # staged v
            pltpu.VMEM((Gs, KV, _TILE, hd), pool_k.dtype),  # k writeback
            pltpu.VMEM((Gs, KV, _TILE, hd), pool_v.dtype),  # v writeback
            pltpu.SemaphoreType.DMA((_NBUF, 2)),
            pltpu.SemaphoreType.DMA((Gs, 2)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, hd), q.dtype),
            jax.ShapeDtypeStruct(pool_k.shape, pool_k.dtype),
            jax.ShapeDtypeStruct(pool_v.shape, pool_v.dtype),
        ],
        # operand numbering includes the scalar-prefetch args (tbl=0,
        # lens=1, wp=2, off=3, layer=4, q=5, pool_k=6, pool_v=7, ck=8,
        # cv=9)
        input_output_aliases={6: 1, 7: 2},
        interpret=interpret,
    )(block_table, lengths, write_page, write_offset, layer,
      q, pool_k, pool_v, cur_k, cur_v)


def _paged_attention_decode_quant(q, pool_k, pool_v, pool_ks, pool_vs,
                                  block_table, lengths, cur_k, cur_v,
                                  write_page, write_offset, layer,
                                  *, interpret=False):
    """int8-KV variant of the decode kernel (see paged_attention_decode).

    Same slot-grouped program structure — flat cross-slot page loop,
    ``_NBUF``-deep buffer ring, per-slot softmax scratch, staged
    appends — with int8 pool pages and a bf16 per-row scale pool
    (``(L, N, KV, page)``) streamed alongside. HBM page traffic: int8
    K+V (half the bf16 bytes) + the scale blocks (~1/128 of the int8
    bytes each). The int8->compute-dtype widen happens once per page in
    VMEM; the MXU dots stay in the query dtype. K scales fold into the
    scores AFTER the QK^T dot (each K row scales its column of scores);
    V scales fold INTO the probabilities before the PV dot (each V row
    scales its contribution).

    The append quantizes the current row in-kernel (symmetric per-row,
    ops/kv_quant.py semantics: scale cast to bf16 before the divide) and
    writes the int8 8-row tile the same way as the bf16 kernel. The
    SCALE write is a full (KV, page) block instead of a tile: the page
    dim sits on lanes there (so score broadcasting needs no transpose),
    and lane-dim slices can't DMA — but the block to preserve was staged
    from the streamed window at the slot's last page (the write page IS
    the last streamed window page when off > 0; fresh-page rows are
    garbage that attention masks), so the write-back costs one small
    extra DMA, not a read-modify-write.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, hd = q.shape
    L, N, KV, page, _ = pool_k.shape
    G = H // KV
    scale = hd ** -0.5
    cd = q.dtype  # compute dtype for the MXU dots
    Gs = group_size(B)

    def kernel(tbl_ref, len_ref, wp_ref, off_ref, l_ref, q_ref,
               k_hbm, v_hbm, ks_hbm, vs_hbm, ck_ref, cv_ref,
               out_ref, opk_ref, opv_ref, opks_ref, opvs_ref,
               kbuf, vbuf, ksbuf, vsbuf, accs, ms, ls,
               stk, stv, stks, stvs, krw, vrw, ksrw, vsrw, sem, rw_sem):
        gi = pl.program_id(0)
        li = l_ref[0]
        b0 = gi * Gs
        counts = [jax.lax.div(len_ref[b0 + i] + (page - 1), page)
                  for i in range(Gs)]
        starts = [jnp.int32(0)]
        for c in counts:
            starts.append(starts[-1] + c)
        total = starts[Gs]

        for i in range(Gs):
            accs[i] = jnp.zeros((KV, G, hd), jnp.float32)
            ms[i] = jnp.full((KV, G), NEG, jnp.float32)
            ls[i] = jnp.zeros((KV, G), jnp.float32)

        def locate(t):
            sidx = jnp.int32(0)
            base = jnp.int32(0)
            for i in range(Gs - 1):
                past = t >= starts[i + 1]
                sidx = sidx + past.astype(jnp.int32)
                base = base + jnp.where(past, counts[i], 0)
            cnt = counts[Gs - 1]
            for i in range(Gs - 1):
                cnt = jnp.where(sidx == i, counts[i], cnt)
            return sidx, t - base, cnt

        def dmas(sidx, w, slot):
            pg = tbl_ref[b0 + sidx, w]
            pairs = ((k_hbm, kbuf), (v_hbm, vbuf),
                     (ks_hbm, ksbuf), (vs_hbm, vsbuf))
            return [pltpu.make_async_copy(hbm.at[li, pg], buf.at[slot],
                                          sem.at[slot, which])
                    for which, (hbm, buf) in enumerate(pairs)]

        def start_fetch(t):
            sidx, w, _ = locate(t)
            for d in dmas(sidx, w, jax.lax.rem(t, _NBUF)):
                d.start()

        for j in range(_NBUF - 1):
            @pl.when(jnp.int32(j) < total)
            def _(j=j):
                start_fetch(jnp.int32(j))

        def body(t, carry):
            @pl.when(t + _NBUF - 1 < total)
            def _():
                start_fetch(t + _NBUF - 1)
            slot = jax.lax.rem(t, _NBUF)
            # ONE locate per iteration (the top-off above locates its
            # own flat index); wait descriptors reuse the result.
            sidx, w, cnt = locate(t)
            b = b0 + sidx
            for d in dmas(sidx, w, slot):
                d.wait()
            length = len_ref[b]
            qv = q_ref[sidx].reshape(KV, G, hd)
            kp = kbuf[slot].astype(cd)                       # (KV,page,hd)
            vp = vbuf[slot].astype(cd)
            ks = ksbuf[slot].astype(jnp.float32)             # (KV,page)
            vs = vsbuf[slot].astype(jnp.float32)
            scores = jax.lax.dot_general(
                qv, kp, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)          # (KV,G,page)
            scores = scores * ks[:, None, :] * scale
            valid = (w * page + jax.lax.broadcasted_iota(
                jnp.int32, (1, 1, page), 2)) < length
            scores = jnp.where(valid, scores, NEG)

            m = ms[sidx][..., None]
            l = ls[sidx][..., None]
            m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(scores - m_new)                      # (KV,G,page)
            # Zero masked probabilities AND scales explicitly before the
            # PV dot: p underflows to ~0 for masked lanes, but the scale
            # lanes beyond `length` hold whatever bytes the page carries
            # (garbage on a fresh page), and 0 * NaN = NaN would poison
            # the accumulator. Prefix-cache page sharing makes page-
            # content invariants load-bearing — same hygiene as the
            # sibling _paged_prefix_attention.
            p = jnp.where(valid, p, 0.0)
            vs = jnp.where(valid[0], vs, 0.0)
            ls[sidx] = (l * alpha + jnp.sum(p, axis=-1,
                                            keepdims=True))[..., 0]
            ms[sidx] = m_new[..., 0]
            pv = jax.lax.dot_general(
                (p * vs[:, None, :]).astype(cd), vp,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)          # (KV,G,hd)
            accs[sidx] = accs[sidx] * alpha + pv

            off = off_ref[b]
            tile0 = (off // _TILE) * _TILE

            @pl.when(w + 1 == cnt)
            def _():
                stk[sidx] = kbuf[slot, :, pl.ds(tile0, _TILE), :]
                stv[sidx] = vbuf[slot, :, pl.ds(tile0, _TILE), :]
                stks[sidx] = ksbuf[slot]
                stvs[sidx] = vsbuf[slot]
            return carry

        jax.lax.fori_loop(0, total, body, jnp.int32(0))

        # Per-slot epilogue: exact current-token fold (unquantized), then
        # the in-kernel quantized append. quantize_rows is the SAME
        # function the engine's insert/gather paths use (ops/kv_quant.py)
        # — plain jnp, and single-sourcing it keeps appended rows
        # bit-identical to bucket-inserted rows.
        from .kv_quant import quantize_rows
        writes = []
        for i in range(Gs):
            b = b0 + i
            qv = q_ref[i].reshape(KV, G, hd)
            m = ms[i][..., None]
            l = ls[i][..., None]
            acc = accs[i]
            ck = ck_ref[i].astype(jnp.float32)               # (KV,hd)
            cv = cv_ref[i].astype(jnp.float32)
            s_cur = jnp.sum(qv.astype(jnp.float32) * ck[:, None, :],
                            axis=-1, keepdims=True) * scale  # (KV,G,1)
            m2 = jnp.maximum(m, s_cur)
            a = jnp.exp(m - m2)
            bta = jnp.exp(s_cur - m2)
            out = acc * a + cv[:, None, :] * bta
            denom = l * a + bta
            out_ref[i] = (out / denom).reshape(H, hd).astype(out_ref.dtype)

            k_int, k_s = quantize_rows(ck)      # (KV, hd) int8, (KV,) bf16
            v_int, v_s = quantize_rows(cv)
            off = off_ref[b]
            tile0 = (off // _TILE) * _TILE
            row_mask = jax.lax.broadcasted_iota(
                jnp.int32, (1, _TILE, 1), 1) == (off - tile0)
            krw[i] = jnp.where(row_mask, k_int[:, None, :], stk[i])
            vrw[i] = jnp.where(row_mask, v_int[:, None, :], stv[i])
            # Scale block: lane `off` takes the new scale, every other
            # lane keeps the streamed page's value (garbage on a fresh
            # page — rows >= length are never attended). When NO page
            # was streamed (a trash-page append for an inactive slot)
            # the staging scratch is uninitialized VMEM — fill the other
            # lanes with zeros instead of copying a possible NaN bit
            # pattern into the pool.
            lane = jax.lax.broadcasted_iota(
                jnp.int32, (1, page), 1) == off
            streamed = counts[i] > 0
            ksrw[i] = jnp.where(lane, k_s[:, None].astype(jnp.bfloat16),
                                jnp.where(streamed, stks[i], 0))
            vsrw[i] = jnp.where(lane, v_s[:, None].astype(jnp.bfloat16),
                                jnp.where(streamed, stvs[i], 0))
            wp = wp_ref[b]
            slot_writes = [
                pltpu.make_async_copy(
                    krw.at[i], opk_ref.at[li, wp, :, pl.ds(tile0, _TILE)],
                    rw_sem.at[i, 0]),
                pltpu.make_async_copy(
                    vrw.at[i], opv_ref.at[li, wp, :, pl.ds(tile0, _TILE)],
                    rw_sem.at[i, 1]),
                pltpu.make_async_copy(ksrw.at[i], opks_ref.at[li, wp],
                                      rw_sem.at[i, 2]),
                pltpu.make_async_copy(vsrw.at[i], opvs_ref.at[li, wp],
                                      rw_sem.at[i, 3]),
            ]
            for wcp in slot_writes:
                wcp.start()
            writes += slot_writes
        for wcp in writes:
            wcp.wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,   # table, lengths, write page/offset, layer
        grid=(B // Gs,),
        in_specs=[
            pl.BlockSpec((Gs, H, hd), lambda g, *_: (g, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # K pool (int8, HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),   # V pool (int8, HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),   # K scales (HBM)
            pl.BlockSpec(memory_space=pltpu.ANY),   # V scales (HBM)
            pl.BlockSpec((Gs, KV, hd), lambda g, *_: (g, 0, 0)),
            pl.BlockSpec((Gs, KV, hd), lambda g, *_: (g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((Gs, H, hd), lambda g, *_: (g, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((_NBUF, KV, page, hd), pool_k.dtype),
            pltpu.VMEM((_NBUF, KV, page, hd), pool_v.dtype),
            pltpu.VMEM((_NBUF, KV, page), pool_ks.dtype),
            pltpu.VMEM((_NBUF, KV, page), pool_vs.dtype),
            pltpu.VMEM((Gs, KV, G, hd), jnp.float32),   # accs
            pltpu.VMEM((Gs, KV, G), jnp.float32),       # ms
            pltpu.VMEM((Gs, KV, G), jnp.float32),       # ls
            pltpu.VMEM((Gs, KV, _TILE, hd), pool_k.dtype),  # staged k
            pltpu.VMEM((Gs, KV, _TILE, hd), pool_v.dtype),  # staged v
            pltpu.VMEM((Gs, KV, page), pool_ks.dtype),      # staged ks
            pltpu.VMEM((Gs, KV, page), pool_vs.dtype),      # staged vs
            pltpu.VMEM((Gs, KV, _TILE, hd), pool_k.dtype),  # k writeback
            pltpu.VMEM((Gs, KV, _TILE, hd), pool_v.dtype),  # v writeback
            pltpu.VMEM((Gs, KV, page), pool_ks.dtype),      # ks writeback
            pltpu.VMEM((Gs, KV, page), pool_vs.dtype),      # vs writeback
            pltpu.SemaphoreType.DMA((_NBUF, 4)),
            pltpu.SemaphoreType.DMA((Gs, 4)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, hd), q.dtype),
            jax.ShapeDtypeStruct(pool_k.shape, pool_k.dtype),
            jax.ShapeDtypeStruct(pool_v.shape, pool_v.dtype),
            jax.ShapeDtypeStruct(pool_ks.shape, pool_ks.dtype),
            jax.ShapeDtypeStruct(pool_vs.shape, pool_vs.dtype),
        ],
        # operands: tbl=0, lens=1, wp=2, off=3, layer=4, q=5, pool_k=6,
        # pool_v=7, pool_ks=8, pool_vs=9, ck=10, cv=11
        input_output_aliases={6: 1, 7: 2, 8: 3, 9: 4},
        interpret=interpret,
    )(block_table, lengths, write_page, write_offset, layer,
      q, pool_k, pool_v, pool_ks, pool_vs, cur_k, cur_v)


def paged_attention_decode_reference(q, pool_k, pool_v, block_table,
                                     lengths, cur_k, cur_v):
    """Pure-jnp attention oracle with identical masking/softmax semantics
    (tests + non-TPU backends); the pool append is left to the caller.
    This is the gather formulation the kernel replaces."""
    B, H, hd = q.shape
    N, KV, page, _ = pool_k.shape
    W = block_table.shape[1]
    G = H // KV
    scale = hd ** -0.5

    kg = pool_k[block_table].swapaxes(2, 3).reshape(B, W * page, KV, hd)
    vg = pool_v[block_table].swapaxes(2, 3).reshape(B, W * page, KV, hd)
    qg = q.reshape(B, KV, G, hd).astype(jnp.float32)
    scores = jnp.einsum("bkgd,btkd->bkgt", qg, kg.astype(jnp.float32),
                        precision=jax.lax.Precision.HIGHEST) * scale
    tpos = jnp.arange(W * page)[None, None, None, :]
    scores = jnp.where(tpos < lengths[:, None, None, None], scores, NEG)
    s_cur = jnp.einsum("bkgd,bkd->bkg", qg, cur_k.astype(jnp.float32),
                       precision=jax.lax.Precision.HIGHEST) * scale
    all_scores = jnp.concatenate([scores, s_cur[..., None]], axis=-1)
    probs = jax.nn.softmax(all_scores, axis=-1)
    vg_all = jnp.concatenate(
        [vg.astype(jnp.float32),
         cur_v.astype(jnp.float32)[:, None, :, :]], axis=1)
    out = jnp.einsum("bkgt,btkd->bkgd", probs, vg_all,
                     precision=jax.lax.Precision.HIGHEST)
    return out.reshape(B, H, hd).astype(q.dtype)
