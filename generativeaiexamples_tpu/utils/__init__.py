"""Shared utilities: configuration, logging, errors."""
