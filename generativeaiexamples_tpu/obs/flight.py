"""Per-request flight recorder: who was slow, and where.

The aggregate registry (``obs/metrics.py``) answers "how is the fleet
doing"; this module answers the question aggregates can't: *why was THIS
request slow?* Every request entering the serving path gets

- a **request ID**: adopted from the caller's ``X-Request-ID`` header (or
  the W3C ``traceparent`` trace-id) at the HTTP edge, minted otherwise,
  and threaded through the chains layer into ``Engine.submit()`` via a
  contextvar — no signature changes through ``BaseExample``;
- a **timeline**: a preallocated per-request event ring recording queue
  wait, admission dispatch, prefix-cache hit length, prefill chunks,
  first token, per-round token counts, and the finish/cancel reason.

Concurrency contract (the token-path budget): timeline appends are O(1)
slot writes into a preallocated ring, indexed by an atomic-under-GIL
``itertools.count`` — no lock is taken on append, so the engine's
scheduler and harvest threads never contend with each other or with a
``/debug/requests`` reader. Per-TOKEN work records nothing; the harvest
worker records one event per decode round. The recorder's own lock
guards only the in-flight/completed maps, touched once at begin and once
at completion — never from ``decode_round`` dispatch.

Exposure:

- ``GET /debug/requests`` on the chain server and the model server
  renders ``RECORDER.snapshot()`` — in-flight plus the last-N completed
  timelines;
- requests breaching the SLO thresholds (``FLIGHT_SLO_TTFT_MS``,
  ``FLIGHT_SLO_TOTAL_MS``) dump their whole timeline as one structured
  log line (``utils/logging.log_event``);
- when tracing is on (``obs/tracing.py``), completion replays the
  timeline's duration events as OTel child spans carrying the request ID
  — the engine's internal stages land in the same trace as the chain's
  retrieve/templating/llm spans.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Optional

from ..utils.logging import get_logger, log_event

logger = get_logger(__name__)

# Current request's timeline, bound at the serving edge. Worker threads
# see it because the chain server runs its sync generators under a copied
# context (serving/streaming.py iterate_in_thread).
_current: contextvars.ContextVar[Optional["Timeline"]] = \
    contextvars.ContextVar("flight_timeline", default=None)

_MAX_RID_CHARS = 128


def mint_request_id() -> str:
    """A fresh request ID (16 hex chars — short enough to grep, unique
    enough for a ring of thousands)."""
    return uuid.uuid4().hex[:16]


def adopt_request_id(headers: Any, mint=mint_request_id) -> str:
    """Request ID from inbound HTTP headers: ``X-Request-ID`` verbatim
    (sanitized), else the W3C ``traceparent`` trace-id — so a traced
    caller's spans and its flight timeline share an identity — else one
    from ``mint`` (callers with their own ID shape, e.g. the OpenAI
    surface's ``cmpl-`` completion ids, pass their minter so malformed
    headers fall back to the documented shape)."""
    rid = ""
    if headers is not None:
        rid = (headers.get("X-Request-ID") or "").strip()
        if not rid:
            # traceparent: 00-<trace-id 32hex>-<span-id 16hex>-<flags>
            parts = (headers.get("traceparent") or "").split("-")
            if len(parts) == 4 and len(parts[1]) == 32:
                rid = parts[1]
    rid = "".join(c for c in rid[:_MAX_RID_CHARS]
                  if c.isprintable() and c not in '{}"\\')
    return rid or mint()


def adopt_deadline_ms(headers: Any,
                      default_ms: Optional[float] = None) -> Optional[float]:
    """Per-request deadline from the ``X-Deadline-Ms`` header: how long
    the caller is willing to wait for this request END TO END. Returns
    milliseconds, or None when neither the header nor ``default_ms``
    sets a positive bound. Malformed values fall back to the default —
    a garbled header must not grant an infinite deadline when the
    deployment configured a finite one."""
    ms: Optional[float] = None
    if headers is not None:
        raw = (headers.get("X-Deadline-Ms") or "").strip()
        if raw:
            try:
                ms = float(raw)
            except ValueError:
                ms = None
    if ms is None:
        ms = default_ms
    if ms is None or ms <= 0:
        return None
    return ms


def bind(timeline: Optional["Timeline"]):
    """Bind ``timeline`` as the current request's; returns the reset
    token for ``unbind``."""
    return _current.set(timeline)


def unbind(token) -> None:
    _current.reset(token)


def current() -> Optional["Timeline"]:
    return _current.get()


def current_request_id() -> Optional[str]:
    tl = _current.get()
    return tl.request_id if tl is not None else None


def record_current_stage(name: str, seconds: float) -> None:
    """Append a stage duration to the bound timeline, if any — the hook
    ``obs.tracing.record_stage`` fans into, which makes every existing
    ``event_span``/``record_stage`` call site (chain retrieve/templating/
    llm, embedder dispatch, EngineLLM first-chunk) feed the per-request
    timeline with zero changes at those sites."""
    tl = _current.get()
    if tl is not None:
        tl.stage(name, seconds)


class Timeline:
    """Event ring for one request.

    Events are ``(seq, t_monotonic, name, value)`` tuples in a
    preallocated ring; value typing is by convention — ``float`` means a
    stage DURATION in seconds, ``int`` a count, ``str`` an annotation,
    ``None`` a bare marker. Appends take no lock (see module docstring);
    readers snapshot best-effort. ``meta`` is a plain dict for
    single-value facts (slot, prompt tokens, finish reason, ...) —
    per-key assignment is atomic under the GIL.
    """

    __slots__ = ("request_id", "t_start", "wall_start", "meta", "done",
                 "otel_ctx", "deadline_t", "_events", "_cap", "_seq", "_n")

    def __init__(self, request_id: str, event_cap: int = 64):
        self.request_id = request_id
        self.t_start = time.monotonic()
        self.wall_start = time.time()
        self.meta: dict[str, Any] = {}
        self.done = False
        # Absolute (monotonic) deadline for this request, set at the
        # serving edge from X-Deadline-Ms / the configured default.
        # The engine adopts it through the same contextvar as the
        # request ID — queue drops and mid-decode stops key off it.
        self.deadline_t: Optional[float] = None
        # OTel context captured at begin() (the request's server span)
        # so the retrospective span replay parents engine stages INTO
        # the request's trace instead of emitting disconnected roots.
        self.otel_ctx: Any = None
        self._cap = max(8, int(event_cap))
        self._events: list = [None] * self._cap
        self._seq = itertools.count()   # next() is atomic under the GIL
        self._n = 0                     # approximate (racy, monotonic-ish)

    # ------------------------------------------------------------ writers

    def event(self, name: str, value: Any = None,
              t: Optional[float] = None) -> None:
        """O(1) ring append from any thread."""
        i = next(self._seq)
        self._events[i % self._cap] = (
            i, time.monotonic() if t is None else t, name, value)
        self._n = i + 1

    def stage(self, name: str, seconds: float) -> None:
        """A completed stage of ``seconds`` duration ending now."""
        self.event(name, float(seconds))

    def annotate(self, **fields: Any) -> None:
        self.meta.update(fields)

    def set_deadline(self, ms: Optional[float]) -> None:
        """Arm this request's deadline, ``ms`` from its start (None/<=0
        clears). Recorded in meta so /debug/requests shows the budget a
        dropped request was admitted against."""
        if ms is None or ms <= 0:
            self.deadline_t = None
            self.meta.pop("deadline_ms", None)
            return
        self.deadline_t = self.t_start + ms / 1e3
        self.meta["deadline_ms"] = round(float(ms), 1)

    # ------------------------------------------------------------ readers

    def events_snapshot(self) -> list[tuple]:
        """Best-effort ordered copy of the ring's live events."""
        items = [e for e in list(self._events) if e is not None]
        items.sort(key=lambda e: e[0])
        return items

    def stage_durations(self) -> dict[str, float]:
        """name -> seconds for every duration event (first occurrence
        wins, matching the old first-wins stage collector)."""
        out: dict[str, float] = {}
        for _, _, name, value in self.events_snapshot():
            if isinstance(value, float) and not isinstance(value, bool) \
                    and name not in out:
                out[name] = value
        return out

    def epoch_ns(self, t_monotonic: float) -> int:
        return int((self.wall_start + (t_monotonic - self.t_start)) * 1e9)

    def to_dict(self) -> dict:
        events = []
        for _, t, name, value in self.events_snapshot():
            ev: dict[str, Any] = {"event": name,
                                  "t_ms": round((t - self.t_start) * 1e3, 3)}
            if isinstance(value, float) and not isinstance(value, bool):
                ev["dur_ms"] = round(value * 1e3, 3)
            elif isinstance(value, bool) or value is not None:
                ev["value"] = value
            events.append(ev)
        n = self._n
        out = {
            "request_id": self.request_id,
            "started_unix_ms": int(self.wall_start * 1e3),
            "age_ms": round((time.monotonic() - self.t_start) * 1e3, 1),
            "done": self.done,
            "meta": dict(self.meta),
            "events": events,
            "events_dropped": max(0, n - self._cap),
        }
        return out


class FlightRecorder:
    """In-flight map + bounded completed ring of request timelines."""

    def __init__(self, completed_cap: Optional[int] = None,
                 event_cap: Optional[int] = None):
        self._lock = threading.Lock()   # maps only; never on the token path
        self._inflight: dict[str, Timeline] = {}
        self._completed: "deque[Timeline]" = deque(
            maxlen=completed_cap if completed_cap is not None
            else int(os.environ.get("FLIGHT_COMPLETED_CAP", "256")))
        self.event_cap = (event_cap if event_cap is not None
                          else int(os.environ.get("FLIGHT_EVENT_CAP", "64")))
        # Slow-request dump thresholds, ms; 0 disables either check.
        self.slo_ttft_ms = float(
            os.environ.get("FLIGHT_SLO_TTFT_MS", "2000") or 0)
        self.slo_total_ms = float(
            os.environ.get("FLIGHT_SLO_TOTAL_MS", "30000") or 0)

    # ---------------------------------------------------------- lifecycle

    def begin(self, request_id: Optional[str] = None,
              fresh: bool = False) -> Timeline:
        """Timeline for ``request_id``, creating one if none is in
        flight under that ID — idempotent by default, so two begin()
        calls for the same logical request share one timeline.

        ``fresh=True`` is for serving EDGES, where each call is a new
        request by definition: a client-supplied ID colliding with a
        different still-in-flight request (a retry racing its original,
        a duplicating proxy) gets a ``#N``-suffixed timeline instead of
        silently interleaving into — and being swallowed by — the first
        request's record."""
        rid = request_id or mint_request_id()
        with self._lock:
            tl = self._inflight.get(rid)
            if tl is not None and fresh:
                n = 2
                while f"{rid}#{n}" in self._inflight:
                    n += 1
                rid = f"{rid}#{n}"
                tl = None
            if tl is None:
                tl = Timeline(rid, self.event_cap)
                self._inflight[rid] = tl
                created = True
            else:
                created = False
        if created:
            from . import tracing
            if tracing.enabled() and tl.otel_ctx is None:
                # Capture the caller's span context (the server span when
                # begin() runs inside an instrumented handler); the
                # completion-time replay runs on an engine thread with an
                # EMPTY context, so without this the stage spans would be
                # parentless roots outside the request's trace.
                try:
                    from opentelemetry import context as otel_context
                    tl.otel_ctx = otel_context.get_current()
                except Exception:  # noqa: BLE001 — tracing is best-effort
                    pass
        return tl

    def complete(self, tl: Optional[Timeline]) -> None:
        """Move a timeline to the completed ring (idempotent; first call
        wins), then run the SLO dump and span replay off the maps lock."""
        if tl is None:
            return
        with self._lock:
            if tl.done:
                return
            tl.done = True
            if self._inflight.get(tl.request_id) is tl:
                del self._inflight[tl.request_id]
            self._completed.append(tl)
        # Requests that never reached an engine (echo chains, pre-submit
        # failures) have no stream-measured duration — fall back to the
        # timeline's own age so the total-duration SLO still fires on
        # chain-side slowness.
        tl.meta.setdefault(
            "duration_ms", round((time.monotonic() - tl.t_start) * 1e3, 2))
        self._check_slo(tl)
        self._emit_spans(tl)

    def complete_stream(self, stream) -> None:
        """Completion driven from a terminal ``TokenStream`` transition
        (finish/fail/cancel): stamp the engine's serving measurements
        into the timeline and — when the ENGINE owns it — complete it.

        A stream that ADOPTED a serving edge's timeline
        (``stream.owns_timeline`` False) must not retire it: agent-style
        chains run several engine calls per HTTP request (e.g.
        query_decomposition's sub-queries + synthesis), and the request
        is only over when the edge's own completion fires. Sub-call
        stats accumulate instead: ``generated`` sums, ``ttft_ms`` keeps
        the first sub-call's (the request's first produced token),
        ``finish`` tracks the latest sub-call, and the request duration
        is left for ``complete()``'s whole-timeline fallback."""
        tl = getattr(stream, "timeline", None)
        if tl is None or tl.done:
            return
        reason = stream.finish_reason or "unknown"
        owns = getattr(stream, "owns_timeline", True)
        tl.meta["generated"] = (tl.meta.get("generated") or 0) \
            + len(stream.token_ids)
        if stream.ttft_ms is not None:
            tl.meta.setdefault("ttft_ms", round(stream.ttft_ms, 2))
        tl.annotate(finish=reason)
        if owns and stream.finish_time is not None:
            # failed streams have no finish_time; complete() falls back
            # to the timeline's age for the duration SLO
            tl.annotate(duration_ms=round(
                (stream.finish_time - stream.submit_time) * 1e3, 2))
        tl.event("finish", reason)
        if owns:
            self.complete(tl)

    # ------------------------------------------------------------ queries

    def find(self, request_id: str) -> Optional[Timeline]:
        with self._lock:
            tl = self._inflight.get(request_id)
            if tl is not None:
                return tl
            for tl in reversed(self._completed):   # most recent first
                if tl.request_id == request_id:
                    return tl
        return None

    def recent_stage_ms(self, name: str, limit: int = 32,
                        window_s: float = 60.0) -> tuple[int, float]:
        """``(samples, avg_ms)`` of stage ``name`` over the most recently
        completed timelines — the data behind edge admission control: the
        chain server estimates a new request's queue wait from the
        ``engine_admit_pickup`` durations of the last N requests and
        sheds arrivals whose deadline the estimate already exceeds.
        ``window_s`` bounds how STALE the evidence may be: without it, a
        past congestion burst would keep shedding requests long after
        the queue drained idle (no completions → the ring never turns
        over). Cheap by construction: reads only the bounded ring."""
        now = time.monotonic()
        with self._lock:
            tls = list(self._completed)[-max(0, int(limit)):]
        vals = []
        for tl in tls:
            if window_s and now - tl.t_start > window_s:
                continue
            d = tl.stage_durations().get(name)
            if d is not None:
                vals.append(d * 1e3)
        if not vals:
            return 0, 0.0
        return len(vals), sum(vals) / len(vals)

    def snapshot(self, limit: int = 50) -> dict:
        """JSON-ready view for ``/debug/requests``: every in-flight
        timeline plus the ``limit`` most recently completed."""
        limit = int(limit)
        with self._lock:
            inflight = list(self._inflight.values())
            # NB [-limit:] with limit=0 would slice EVERYTHING
            completed = list(self._completed)[-limit:] if limit > 0 else []
        inflight.sort(key=lambda t: t.t_start)
        return {
            "in_flight": [t.to_dict() for t in inflight],
            "completed": [t.to_dict() for t in reversed(completed)],
            "completed_retained": len(completed),
            "slo": {"ttft_ms": self.slo_ttft_ms,
                    "total_ms": self.slo_total_ms},
        }

    # ----------------------------------------------------------- exposure

    def _check_slo(self, tl: Timeline) -> None:
        ttft = tl.meta.get("ttft_ms")
        total = tl.meta.get("duration_ms")
        slow = ((self.slo_ttft_ms and ttft is not None
                 and ttft > self.slo_ttft_ms)
                or (self.slo_total_ms and total is not None
                    and total > self.slo_total_ms))
        if slow:
            log_event(logger, "slow_request", request_id=tl.request_id,
                      ttft_ms=ttft, duration_ms=total,
                      slo_ttft_ms=self.slo_ttft_ms,
                      slo_total_ms=self.slo_total_ms,
                      timeline=tl.to_dict())

    def _emit_spans(self, tl: Timeline) -> None:
        """Replay the timeline's duration events as OTel child spans
        (request ID + stage attributes) when tracing is enabled. Spans
        are emitted retrospectively at completion with explicit
        timestamps, so the token path never touches the OTel SDK."""
        from . import tracing
        if not tracing.enabled():
            return
        try:
            tracer = tracing._get_tracer()  # may ImportError w/o the SDK
            if tracer is None:
                return
            for _, t, name, value in tl.events_snapshot():
                if not isinstance(value, float) or isinstance(value, bool):
                    continue
                span = tracer.start_span(
                    name, context=tl.otel_ctx,
                    start_time=tl.epoch_ns(t - value),
                    attributes={"request.id": tl.request_id, "stage": name})
                span.end(end_time=tl.epoch_ns(t))
        except Exception:   # noqa: BLE001 — observability must never raise
            logger.debug("span replay failed", exc_info=True)


# Process-wide default recorder: the engine, both HTTP servers, and the
# bench all read/write this instance unless handed a private one.
RECORDER = FlightRecorder()


def debug_requests_response(request,
                            recorder: Optional[FlightRecorder] = None):
    """The ``GET /debug/requests`` aiohttp handler body, shared by the
    chain server and the model server so the endpoint contract (``limit``
    parsing, error shape, snapshot schema) cannot drift between them."""
    from aiohttp import web

    from .history import query_int
    limit = query_int(request, "limit", 50, minimum=0)
    return web.json_response((recorder or RECORDER).snapshot(limit=limit))
