"""Incremental streaming detokenizer.

Parity with the reference's per-token Python detokenizer model
(reference: ensemble_models/llama/postprocessing/1/model.py:131-154 —
``_id_to_token`` handles sentencepiece SPACE/NEWLINE sentinel chars), done
robustly with bounded work per token: decode a sliding window of recent ids
and emit the stable prefix diff, holding back trailing bytes that are still
an incomplete UTF-8 / sentencepiece fragment. (Decoding the full history
every step would be O(n²) on the engine's single scheduler thread.)
"""

from __future__ import annotations

from ..models.tokenizer import Tokenizer


class IncrementalDetokenizer:
    """Feed token ids one at a time; get back printable text chunks."""

    def __init__(self, tokenizer: Tokenizer):
        self._tok = tokenizer
        self._ids: list[int] = []
        # Window [prefix:] is what gets re-decoded each step; once a chunk is
        # emitted the window start advances to the last emitted boundary, so
        # per-token decode cost stays bounded by the hold-back span.
        self._prefix = 0        # ids before this index are fully emitted
        self._read = 0          # ids in [prefix:read] produced emitted text
        self._text = ""         # everything emitted so far

    def prime(self, ids: list[int]) -> None:
        """Seed already-emitted context (the failover resume path): the
        replayed ids count as fully emitted — ``push`` decodes new
        tokens against this tail window (sentencepiece space handling
        stays correct across the resume boundary) while ``text`` and
        future chunks carry only NEW text, so the sibling never
        re-streams what the caller already received."""
        self._ids = [int(i) for i in ids]
        self._read = len(self._ids)
        self._prefix = max(0, self._read - 8)

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        window = self._ids[self._prefix:]
        emitted = self._tok.decode(self._ids[self._prefix:self._read])
        full = self._tok.decode(window)
        # Hold back a trailing replacement char: it usually means the last
        # token ends mid-UTF-8-sequence and the next token completes it.
        if full.endswith("�") or len(full) <= len(emitted):
            return ""
        chunk = full[len(emitted):]
        self._text += chunk
        self._prefix = self._read
        self._read = len(self._ids)
        return chunk

    def flush(self) -> str:
        emitted = self._tok.decode(self._ids[self._prefix:self._read])
        full = self._tok.decode(self._ids[self._prefix:])
        chunk = full[len(emitted):]
        self._text += chunk
        self._prefix = self._read = len(self._ids)
        return chunk

    @property
    def text(self) -> str:
        emitted = self._tok.decode(self._ids[self._prefix:self._read])
        full = self._tok.decode(self._ids[self._prefix:])
        return self._text + full[len(emitted):]


class StopWordTrap:
    """Stop-word scanning over the accumulated stream.

    Parity with the client-side stop-word drain in the reference
    (reference: model_server_client/trt_llm.py:211-223 — it scans the
    accumulated text for stop strings and truncates). Returns the emittable
    portion of each chunk while withholding text that could be the start of
    a stop word.

    Multi-token bursts: a speculative verify round (or the detokenizer
    releasing held-back UTF-8 fragments) can deliver SEVERAL tokens'
    text in one ``feed``. Truncation is at the EARLIEST stop occurrence
    in the text across all stop words — the former first-in-list match
    could stream text past an earlier stop word when two stops landed
    in the same burst. Once tripped, every later ``feed``/``flush``
    returns "" — trailing burst tokens the device already accepted are
    text-invisible; the engine discards them from the stream's token
    bookkeeping too (harvest skips a finished request's remaining rows)
    and retires the slot, so no device state runs ahead of the stop.
    """

    def __init__(self, stop_words: list[str]):
        self._stops = [s for s in stop_words if s]
        self._buf = ""
        self.stopped = False

    def feed(self, chunk: str) -> str:
        if self.stopped:
            return ""
        self._buf += chunk
        # Earliest occurrence across ALL stop words, not first match in
        # list order — in a multi-token burst both can be present, and
        # list order would leak text past the earlier stop.
        idx = min((i for i in (self._buf.find(s) for s in self._stops)
                   if i >= 0), default=-1)
        if idx >= 0:
            self.stopped = True
            out, self._buf = self._buf[:idx], ""
            return out
        # Withhold the longest suffix that is a prefix of any stop word.
        hold = 0
        for stop in self._stops:
            for n in range(min(len(stop) - 1, len(self._buf)), 0, -1):
                if self._buf.endswith(stop[:n]):
                    hold = max(hold, n)
                    break
        if hold:
            out, self._buf = self._buf[:-hold], self._buf[-hold:]
        else:
            out, self._buf = self._buf, ""
        return out

    def flush(self) -> str:
        out, self._buf = self._buf, ""
        return "" if self.stopped else out


# Back-compat alias (pre-round-9 name).
StopChecker = StopWordTrap
