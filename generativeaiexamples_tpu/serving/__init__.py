"""Model serving: HTTP APIs over the continuous-batching engine.

The replacement for the reference's L3 serving stack — Triton ensemble +
TRT-LLM backend + model_server orchestrator (reference:
RetrievalAugmentedGeneration/llm-inference-server/). Three pieces:

- ``openai_api``    OpenAI-style ``/v1/completions`` + ``/v1/chat/completions``
                    + ``/v1/embeddings`` (parity with the nemo-infer
                    connectors, reference: integrations/langchain/llms/
                    nemo_infer.py, embeddings/nemo_embed.py).
- ``triton_shim``   Triton-compatible ``/v2/models/{m}/generate[_stream]``
                    with the ensemble's tensor names, ready-polling
                    endpoints included (reference: ensemble_models/llama/
                    ensemble/config.pbtxt:27-117, trt_llm.py:259-271).
- ``model_server``  The CLI orchestrator: device discovery, TP×PP topology,
                    checkpoint sniffing, engine build, server launch
                    (reference: model_server/__main__.py + __init__.py).
"""

from .model_server import build_services, create_server_app

__all__ = ["build_services", "create_server_app"]
