"""Chaos smoke tests (tier-1, CPU): drive fault plans end-to-end.

Single replica (ISSUE 5): vector store down + slow engine — the stack
DEGRADES instead of erroring: /generate returns 200 with an LLM-only
answer and a user-visible notice, ``degraded_total{reason="retrieval"}``
increments, and the request's flight timeline is annotated
``degraded=retrieval``.

Fleet (ISSUE 7): a replica killed mid-stream (the client sees the
machine-readable ``replica_lost`` error frame and the router stops
placing there within one heartbeat) and a router↔replica partition
(``router.forward[r0]`` + ``replica.heartbeat[r0]`` — the replica's
breaker opens, traffic shifts to its sibling, and no request is lost)."""

import asyncio
import json
import time

import pytest

import jax
import jax.numpy as jnp

import aiohttp  # noqa: F401 — skip cleanly where aiohttp is absent
from aiohttp.test_utils import TestClient, TestServer

from generativeaiexamples_tpu.chains.examples.developer_rag import (
    DEGRADED_NOTICE, QAChatbot)
from generativeaiexamples_tpu.chains.llm import EngineLLM
from generativeaiexamples_tpu.chains.server import create_app
from generativeaiexamples_tpu.embed.encoder import HashEmbedder
from generativeaiexamples_tpu.engine import Engine, EngineConfig
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.obs import metrics as obs_metrics
from generativeaiexamples_tpu.utils import faults, resilience
from generativeaiexamples_tpu.utils.app_config import AppConfig
from generativeaiexamples_tpu.utils.configuration import from_dict

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=256)


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    resilience.reset_breakers()
    yield
    faults.clear()
    resilience.reset_breakers()


def _degraded_retrieval_count() -> float:
    return obs_metrics.REGISTRY.snapshot().get(
        'degraded_total{reason="retrieval"}', 0.0)


@pytest.mark.chaos
def test_chaos_retrieval_down_slow_engine_degrades_to_200(tmp_path):
    params = llama.init_params(CFG, jax.random.key(3), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), EngineConfig(
        max_slots=2, max_input_length=256, max_output_length=32,
        prefill_buckets=(64, 128, 256), dtype="float32", max_queue=8))
    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "tpu-jax"},
        "embeddings": {"model_engine": "hash", "dimensions": 32},
        "text_splitter": {"chunk_size": 64, "chunk_overlap": 16},
    })
    ex = QAChatbot(llm=EngineLLM(eng), embedder=HashEmbedder(dim=32),
                   config=cfg, fused_rag=False)
    doc = tmp_path / "kb.txt"
    doc.write_text("The MXU is a systolic array. TPUs use ICI links.")
    ex.ingest_docs(str(doc), "kb.txt")

    # The chaos plan: retrieval hard-down, every engine dispatch slowed.
    faults.set_plan("retrieval.search=fail; engine.dispatch=delay:0.02")
    before = _degraded_retrieval_count()

    import asyncio

    async def fn():
        app = create_app(ex, config=cfg)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            resp = await client.post(
                "/generate",
                json={"question": "What is the MXU?",
                      "use_knowledge_base": True, "num_tokens": 8},
                headers={"X-Request-ID": "chaos-1"})
            # Degraded, not broken: 200 with the notice, then LLM text.
            assert resp.status == 200
            body = (await resp.read()).decode()
            assert body.startswith(DEGRADED_NOTICE)
            assert "[error]" not in body
            rid = resp.headers["X-Request-ID"]

            # the flight timeline carries the degradation annotation
            dbg = await (await client.get("/debug/requests?limit=10")).json()
            tl = next(t for t in dbg["completed"]
                      if t["request_id"] == rid)
            assert tl["meta"]["degraded"] == "retrieval"
            # the engine's finish reason (sub-call stats on the adopted
            # timeline) — anything but error/disconnected
            assert tl["meta"]["finish"] in ("done", "length", "eos", "stop")

            # the degraded counter shows on /metrics
            text = await (await client.get("/metrics")).text()
            assert 'degraded_total{reason="retrieval"}' in text

            # documentSearch against the downed store: typed 500, not a hang
            resp = await client.post("/documentSearch", json={
                "content": "mxu", "num_docs": 1})
            assert resp.status == 500
            assert (await resp.json())["error"]["type"] == "search_error"
        finally:
            await client.close()

    with eng:
        asyncio.get_event_loop_policy().new_event_loop() \
            .run_until_complete(fn())
    assert _degraded_retrieval_count() == before + 1
    assert faults.fired("retrieval.search") >= 1
    assert faults.fired("engine.dispatch") >= 1  # the slow-engine leg ran


@pytest.mark.chaos
def test_deadline_header_through_chain_server(tmp_path):
    """X-Deadline-Ms rides the contextvar into the engine: with slots
    saturated and a 1 ms budget, the queued request is dropped before
    prefill (finish ``deadline_queue``) and the edge returns 504."""
    params = llama.init_params(CFG, jax.random.key(5), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), EngineConfig(
        max_slots=1, max_input_length=256, max_output_length=64,
        prefill_buckets=(64, 128, 256), dtype="float32", max_queue=8))
    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "tpu-jax"},
        "embeddings": {"model_engine": "hash", "dimensions": 32},
    })
    ex = QAChatbot(llm=EngineLLM(eng), embedder=HashEmbedder(dim=32),
                   config=cfg, fused_rag=False)

    import asyncio

    from generativeaiexamples_tpu.engine import SamplingParams

    async def fn():
        app = create_app(ex, config=cfg)
        # Flush the edge admission estimator with fast completed
        # requests (shared global recorder — another test may have left
        # slow ones) so the 1 ms deadline is NOT shed at the edge and
        # reaches the ENGINE's queue-drop path, which this test pins.
        from generativeaiexamples_tpu.obs import flight as obs_flight
        for i in range(32):
            tl = obs_flight.RECORDER.begin(f"fast-seed-{i}", fresh=True)
            tl.stage("engine_admit_pickup", 0.0001)
            obs_flight.RECORDER.complete(tl)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # Occupy the single slot so the HTTP request has to queue.
            blocker = eng.submit([7] * 16, SamplingParams(
                max_tokens=48, ignore_eos=True))
            # wait until the blocker owns the slot (its prefill ran)
            import time as _time
            t0 = _time.monotonic()
            while (eng.stats["prefills"] == 0
                   and _time.monotonic() - t0 < 30):
                _time.sleep(0.01)
            prefills_before = eng.stats["prefills"]
            assert prefills_before == 1
            resp = await client.post(
                "/generate",
                json={"question": "hi", "use_knowledge_base": False,
                      "num_tokens": 8},
                headers={"X-Deadline-Ms": "1"})
            assert resp.status == 504
            body = await resp.json()
            assert body["error"]["type"] == "deadline_exceeded"
            blocker.text()
            assert eng.stats["deadline_queue_drops"] >= 1
            # the dropped request never prefilled; only the blocker did
            assert eng.stats["prefills"] == prefills_before
            rid = resp.headers["X-Request-ID"]
            dbg = await (await client.get(
                "/debug/requests?limit=20")).json()
            tl = next(t for t in dbg["completed"]
                      if t["request_id"] == rid)
            assert tl["meta"]["finish"] == "deadline_queue"
            assert tl["meta"]["deadline_ms"] == 1.0
        finally:
            await client.close()

    with eng:
        asyncio.get_event_loop_policy().new_event_loop() \
            .run_until_complete(fn())


# ----------------------------------------------------- fleet chaos (ISSUE 7)


def _stub_replica(kill_mid_stream: bool = False):
    """A minimal replica app for kill scenarios: /generate streams two
    chunks; with ``kill_mid_stream`` it hard-closes the TCP transport
    after the first (a crashed pod, not a graceful error), and its
    /health dies with it — the shape a real replica kill has."""
    from aiohttp import web

    state = {"dead": False}

    async def generate(request):
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "X-Request-ID": request.headers.get("X-Request-ID", "stub")})
        await resp.prepare(request)
        await resp.write(b"partial answer ")
        if kill_mid_stream:
            await asyncio.sleep(0.05)  # let the first chunk flush
            state["dead"] = True
            request.transport.close()  # SIGKILL, as seen from the wire
            return resp
        await resp.write(b"complete")
        await resp.write_eof()
        return resp

    async def health(request):
        if state["dead"]:
            request.transport.close()
            return web.Response()
        return web.json_response({
            "status": "ok", "draining": False, "breaker": "closed",
            "load": {"in_flight": 0, "queue_depth": 0,
                     "rejected_total": 0}})

    app = web.Application()
    app.router.add_post("/generate", generate)
    app.router.add_get("/health", health)
    return app


@pytest.mark.chaos
def test_chaos_replica_kill_mid_stream_error_frame_and_failover():
    """Replica dies mid-stream: the caller's 200 degrades with the
    machine-readable ``replica_lost`` frame (not a hang, not silent
    truncation), the router stops placing on the corpse within one
    heartbeat, and a runtime-added healthy replica restores service."""
    from aiohttp.test_utils import TestClient, TestServer

    from generativeaiexamples_tpu.frontend.chat_client import (
        ERROR_EVENT_MARK)
    from generativeaiexamples_tpu.router.server import create_router_app

    async def fn():
        dying = TestServer(_stub_replica(kill_mid_stream=True))
        healthy = TestServer(_stub_replica())
        await dying.start_server()
        await healthy.start_server()
        router_app = create_router_app(
            [("r0", f"http://127.0.0.1:{dying.port}")],
            policy="affinity", heartbeat_s=30, run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        try:
            resp = await client.post(
                "/generate", json={"question": "q"},
                headers={"X-Request-ID": "kill-1"})
            assert resp.status == 200
            assert resp.headers["X-Routed-Replica"] == "r0"
            body = (await resp.read()).decode()
            # partial output stands; the failure is machine-readable
            assert body.startswith("partial answer ")
            assert "[error]" in body and ERROR_EVENT_MARK in body
            frame = json.loads(
                body.split(ERROR_EVENT_MARK, 1)[1].strip().split("\n")[0])
            assert frame["error"] == "replica_lost"
            assert frame["replica"] == "r0"
            assert frame["request_id"] == "kill-1"
            # placement stopped IMMEDIATELY (mid-stream loss marks the
            # replica unreachable without waiting for the heartbeat) ...
            snap = await (await client.get("/router/replicas")).json()
            r0 = next(r for r in snap["replicas"] if r["name"] == "r0")
            assert not r0["placeable"] and not r0["reachable"]
            # ... and the next heartbeat agrees (the probe hits the dead
            # transport), so the exclusion survives the next cycle too.
            await client.post("/control/heartbeat")
            snap = await (await client.get("/router/replicas")).json()
            r0 = next(r for r in snap["replicas"] if r["name"] == "r0")
            assert not r0["placeable"]
            # with the only replica dead: typed 503, NOT a hang
            resp = await client.post("/generate", json={"question": "q"})
            assert resp.status == 503
            assert (await resp.json())["error"]["type"] == "no_replicas"
            # rollouts recover at runtime: add a healthy replica
            resp = await client.post("/control/replicas", json={
                "op": "add", "name": "r1",
                "url": f"http://127.0.0.1:{healthy.port}"})
            assert resp.status == 200
            resp = await client.post("/generate", json={"question": "q"})
            assert resp.status == 200
            assert resp.headers["X-Routed-Replica"] == "r1"
            assert (await resp.read()).decode().endswith("complete")
        finally:
            await client.close()
            await dying.close()
            await healthy.close()

    asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(fn())


@pytest.mark.chaos
def test_chaos_disagg_transfer_hang_degrades_to_recompute(monkeypatch):
    """FAULT_PLAN kv.transfer=hang mid-handoff: the donor's page push
    times out, leg 1 reports pushed=false, and the router falls back to
    a full recompute on the SAME decode replica — token-identical to
    the unified path, no error frame, and no orphaned host-tier bytes
    on the decode side."""
    from generativeaiexamples_tpu.router.server import create_router_app
    from tests.test_disagg import (_run, _snap, build_engine, long_body,
                                   replica_app)
    from tests.test_disagg import params as _params_fixture  # noqa: F401

    monkeypatch.delenv("ENGINE_ROLE", raising=False)
    monkeypatch.delenv("KV_HOST_POOL_TOKENS", raising=False)
    monkeypatch.setenv("ROUTER_DISAGG_MIN_PROMPT_BYTES", "400")
    from tests.test_disagg import CFG as DCFG
    params = llama.init_params(DCFG, jax.random.key(29),
                               dtype=jnp.float32)
    prefill_eng = build_engine(params, role="prefill")
    prefill_eng._kv_tier.transfer_timeout_s = 0.3
    decode_eng = build_engine(params, role="decode")
    unified_eng = build_engine(params)
    body = long_body("hang-chaos")

    async def fn():
        ref_server = TestServer(replica_app(unified_eng))
        p_server = TestServer(replica_app(prefill_eng))
        d_server = TestServer(replica_app(decode_eng))
        for s in (ref_server, p_server, d_server):
            await s.start_server()
        router_app = create_router_app(
            [("p0", f"http://127.0.0.1:{p_server.port}"),
             ("d0", f"http://127.0.0.1:{d_server.port}")],
            policy="affinity", heartbeat_s=30, kv_transfer=False,
            run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        ref_client = TestClient(ref_server)
        try:
            resp = await ref_client.post("/generate", json=body)
            assert resp.status == 200
            reference = (await resp.read()).decode()
            await client.post("/control/heartbeat")

            h0 = _snap("router_disagg_handoffs_total")
            f0 = _snap('router_disagg_fallbacks_total'
                       '{reason="no_pages"}')
            faults.set_plan("kv.transfer=hang")
            resp = await client.post("/generate", json=body,
                                     headers={"X-Request-ID": "hang-1"})
            assert resp.status == 200
            assert resp.headers["X-Routed-Replica"] == "d0"
            answer = (await resp.read()).decode()
            # degraded to recompute: token-identical, no error frame
            assert answer == reference
            assert "[error]" not in answer
            assert faults.fired("kv.transfer") >= 1
            assert _snap("router_disagg_handoffs_total") == h0
            assert _snap('router_disagg_fallbacks_total'
                         '{reason="no_pages"}') == f0 + 1
            # the failed push left NOTHING behind on the decode side
            assert decode_eng.stats["kv_tier_resumed_blocks"] == 0
            assert decode_eng.stats["kv_tier_host_pages"] == 0
            # the fallback is visible on the request's timeline
            dbg = await (await client.get(
                "/debug/requests?limit=10")).json()
            tl = next(t for t in dbg["completed"]
                      if t["request_id"] == "hang-1")
            assert "disagg_fallback" \
                in [e["event"] for e in tl["events"]]
        finally:
            faults.clear()
            await client.close()
            await ref_client.close()
            for s in (p_server, d_server):
                await s.close()

    with prefill_eng, decode_eng, unified_eng:
        _run(fn())


@pytest.mark.chaos
def test_chaos_disagg_prefill_kill_falls_back_token_identical(
        monkeypatch):
    """Prefill replica killed mid-handoff (dead before leg 1 connects):
    the router counts a prefill_error fallback and serves the request
    by recompute on the pinned decode replica — token-identical, no
    error frame, caller never sees the kill."""
    from generativeaiexamples_tpu.router.server import create_router_app
    from tests.test_disagg import (_run, _snap, build_engine, long_body,
                                   replica_app)

    monkeypatch.delenv("ENGINE_ROLE", raising=False)
    monkeypatch.delenv("KV_HOST_POOL_TOKENS", raising=False)
    monkeypatch.setenv("ROUTER_DISAGG_MIN_PROMPT_BYTES", "400")
    from tests.test_disagg import CFG as DCFG
    params = llama.init_params(DCFG, jax.random.key(29),
                               dtype=jnp.float32)
    prefill_eng = build_engine(params, role="prefill")
    decode_eng = build_engine(params, role="decode")
    unified_eng = build_engine(params)
    body = long_body("kill-chaos")

    async def fn():
        ref_server = TestServer(replica_app(unified_eng))
        p_server = TestServer(replica_app(prefill_eng))
        d_server = TestServer(replica_app(decode_eng))
        for s in (ref_server, p_server, d_server):
            await s.start_server()
        router_app = create_router_app(
            [("p0", f"http://127.0.0.1:{p_server.port}"),
             ("d0", f"http://127.0.0.1:{d_server.port}")],
            policy="affinity", heartbeat_s=30, kv_transfer=False,
            run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        ref_client = TestClient(ref_server)
        try:
            resp = await ref_client.post("/generate", json=body)
            assert resp.status == 200
            reference = (await resp.read()).decode()
            # the router learns the roles, THEN the prefill pod dies —
            # the table still lists p0 as placeable when the long
            # prompt arrives (no heartbeat poller to notice the kill)
            await client.post("/control/heartbeat")
            await p_server.close()

            h0 = _snap("router_disagg_handoffs_total")
            f0 = _snap('router_disagg_fallbacks_total'
                       '{reason="prefill_error"}')
            resp = await client.post("/generate", json=body)
            assert resp.status == 200
            assert resp.headers["X-Routed-Replica"] == "d0"
            answer = (await resp.read()).decode()
            assert answer == reference
            assert "[error]" not in answer
            assert _snap("router_disagg_handoffs_total") == h0
            assert _snap('router_disagg_fallbacks_total'
                         '{reason="prefill_error"}') == f0 + 1
            # the decode replica recomputed — nothing was pushed
            assert decode_eng.stats["kv_tier_resumed_blocks"] == 0
            assert prefill_eng.stats["kv_tier_export_pages"] == 0
        finally:
            await client.close()
            await ref_client.close()
            await d_server.close()

    with prefill_eng, decode_eng, unified_eng:
        _run(fn())


@pytest.mark.chaos
def test_chaos_router_replica_partition_breaker_opens_traffic_shifts():
    """Partition ONE replica from the router (forwards AND heartbeats
    fail at connect for r0 only): every caller request still succeeds on
    the sibling (no request lost, none run twice — connect-phase
    failures are the only retried kind), r0's breaker opens after the
    configured consecutive failures, and the heartbeat confirms the
    partition."""
    from aiohttp.test_utils import TestClient, TestServer

    from generativeaiexamples_tpu.chains.server import create_app
    from generativeaiexamples_tpu.router.server import create_router_app
    from tests.test_router import EchoExample, _snapshot

    faults.set_plan("router.forward[r0]=fail:conn; "
                    "replica.heartbeat[r0]=fail:conn")

    async def fn():
        servers = [TestServer(create_app(EchoExample())) for _ in range(2)]
        for s in servers:
            await s.start_server()
        router_app = create_router_app(
            [(f"r{i}", f"http://127.0.0.1:{s.port}")
             for i, s in enumerate(servers)],
            policy="affinity", heartbeat_s=30, run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        try:
            retries0 = _snapshot('router_retries_total{reason="connect"}')
            statuses = []
            for i in range(6):
                resp = await client.post(
                    "/generate", json={"question": f"q{i}",
                                       "use_knowledge_base": False})
                statuses.append(resp.status)
                if resp.status == 200:
                    assert resp.headers["X-Routed-Replica"] == "r1"
                    assert (await resp.read()).decode() == f"echo:q{i}"
            # no request lost: the partition is invisible to callers
            assert statuses == [200] * 6
            assert faults.fired("router.forward[r0]") >= 3
            assert _snapshot('router_retries_total{reason="connect"}') \
                - retries0 == faults.fired("router.forward[r0]")
            snap = await (await client.get("/router/replicas")).json()
            r0 = next(r for r in snap["replicas"] if r["name"] == "r0")
            # breaker opened after ROUTER_BREAKER_FAILURES consecutive
            # connect failures -> placement stops even without heartbeat
            assert r0["breaker"] == "open" and not r0["placeable"]
            # the heartbeat sees the same partition
            await client.post("/control/heartbeat")
            assert faults.fired("replica.heartbeat[r0]") >= 1
            snap = await (await client.get("/router/replicas")).json()
            r0 = next(r for r in snap["replicas"] if r["name"] == "r0")
            assert not r0["reachable"]
            r1 = next(r for r in snap["replicas"] if r["name"] == "r1")
            assert r1["placeable"] and r1["placements"] == 6
            # partition heals: plan cleared, heartbeat restores r0
            faults.clear()
            await client.post("/control/heartbeat")
            snap = await (await client.get("/router/replicas")).json()
            r0 = next(r for r in snap["replicas"] if r["name"] == "r0")
            assert r0["reachable"]  # breaker still cooling down is fine
        finally:
            await client.close()
            for s in servers:
                await s.close()

    asyncio.get_event_loop_policy().new_event_loop() \
        .run_until_complete(fn())


# ------------------------------------------- incident black-box (ISSUE 19)


@pytest.mark.chaos
def test_chaos_watchdog_stall_fires_alert_and_captures_incident(
        tmp_path, monkeypatch):
    """FAULT_PLAN engine.dispatch=hang end-to-end: the hung dispatch
    trips the engine watchdog, the watchdog alert goes pending→firing
    with real evidence (the stall delta over the history window), and
    the firing transition freezes EXACTLY ONE incident bundle on disk —
    joining the history window with the stalled replica's flight
    timelines and round records. Second arm: the fault clears, the
    breach ages out of the rule window, the alert resolves, and NO
    second bundle is captured."""
    from generativeaiexamples_tpu.obs import history as obs_history

    monkeypatch.setenv("GAIE_RUN_DIR", str(tmp_path / "run"))
    monkeypatch.setenv("ENGINE_WATCHDOG_STALL_S", "0.2")
    monkeypatch.setenv("ALERT_WATCHDOG_WINDOW_S", "3.0")
    # CPU-jit compile rounds legitimately run far over the cost model's
    # prediction — keep the drift rule out of this test's episode count.
    monkeypatch.setenv("ALERT_DRIFT_RATIO_MAX", "1e9")
    # Arm the layer at a chaos-speed sampling interval (the production
    # default is 5 s; the state machine under test is interval-relative).
    monkeypatch.setattr(obs_history, "HISTORY_INTERVAL_S", 0.05)
    monkeypatch.setattr(obs_history, "HISTORY_WINDOW_S", 30.0)

    params = llama.init_params(CFG, jax.random.key(17), dtype=jnp.float32)
    eng = Engine(params, CFG, ByteTokenizer(), EngineConfig(
        max_slots=2, max_input_length=256, max_output_length=32,
        prefill_buckets=(64, 128, 256), dtype="float32", max_queue=8))
    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "tpu-jax"},
        "embeddings": {"model_engine": "hash", "dimensions": 32},
    })
    ex = QAChatbot(llm=EngineLLM(eng), embedder=HashEmbedder(dim=32),
                   config=cfg, fused_rag=False)

    from generativeaiexamples_tpu.engine import SamplingParams

    async def _poll(fn, deadline_s=20.0, every_s=0.05):
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            got = await fn()
            if got is not None:
                return got
            await asyncio.sleep(every_s)
        raise AssertionError("condition not reached before deadline")

    async def fn():
        app = create_app(ex, config=cfg)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # A healthy request first, so the flight/round rings carry
            # the evidence the bundle must freeze.
            resp = await client.post(
                "/generate",
                json={"question": "hello", "use_knowledge_base": False,
                      "num_tokens": 8},
                headers={"X-Request-ID": "blackbox-ok-1"})
            assert resp.status == 200
            await resp.read()

            # Phase 1: hang every dispatch, then queue work so the
            # watchdog sees pending work with frozen progress counters —
            # the first submit hangs the scheduler thread at its
            # dispatch, the second stays queued behind it (the "work
            # pending, nothing moving" stall signature).
            faults.set_plan("engine.dispatch=hang")
            eng.submit([7] * 16, SamplingParams(max_tokens=8))
            eng.submit([9] * 16, SamplingParams(max_tokens=8))

            async def alert_firing():
                body = await (await client.get("/debug/alerts")).json()
                assert body["enabled"]
                if "engine_watchdog_stall" in body["firing"]:
                    return body
                return None

            body = await _poll(alert_firing)
            row = next(r for r in body["rules"]
                       if r["rule"] == "engine_watchdog_stall")
            assert row["state"] == "firing"
            assert row["severity"] == "critical"
            # the evidence is the breach itself, not a restatement
            series = row["evidence"]["series"]
            assert series["engine_watchdog_stalls"]["value"] > 0
            # firing is visible on /metrics too
            text = await (await client.get("/metrics")).text()
            assert 'alerts_firing{rule="engine_watchdog_stall"} 1' in text

            # Exactly one bundle froze on disk (capture rides the firing
            # transition, which happens once per episode).
            async def one_incident():
                body = await (await client.get("/debug/incidents")).json()
                return body if body["count"] >= 1 else None

            listing = await _poll(one_incident)
            assert listing["enabled"] and listing["count"] == 1
            entry = listing["incidents"][0]
            assert entry["rule"] == "engine_watchdog_stall"
            bundle = await (await client.get(
                f"/debug/incidents?id={entry['id']}")).json()
            assert bundle["schema"] == "incident/v1"
            assert bundle["server"] == "chain"
            assert bundle["trigger"]["kind"] == "alert"
            assert bundle["trigger"]["rule"] == "engine_watchdog_stall"
            assert bundle["trigger"]["evidence"]["series"]
            # the joined evidence: a non-empty history window, the
            # stalled replica's round records, and the flight timeline
            # of the request that ran before the stall
            assert bundle["history"]["window"]
            assert bundle["history"]["aggregates"]["series"]
            assert bundle["rounds"]["rounds"]
            completed_ids = [t["request_id"]
                             for t in bundle["flight"]["completed"]]
            assert "blackbox-ok-1" in completed_ids
            # the bundle is on disk under $GAIE_RUN_DIR/incidents, and
            # the report tool renders it with the trace join intact
            import glob as _glob
            paths = _glob.glob(str(tmp_path / "run" / "incidents"
                                   / "*.json"))
            assert len(paths) == 1
            from tools.incident_report import render_markdown
            report = render_markdown(bundle)
            assert "engine_watchdog_stall" in report
            assert "blackbox-ok-1" in report

            # Phase 2: the fault clears, the engine recovers, the breach
            # ages out of the rule window -> firing→resolved ...
            # (clear() resets the fired counters, so pin the injection
            # count first)
            assert faults.fired("engine.dispatch") >= 1
            faults.clear()

            async def alert_cleared():
                body = await (await client.get("/debug/alerts")).json()
                if body["firing"]:
                    return None
                row = next(r for r in body["rules"]
                           if r["rule"] == "engine_watchdog_stall")
                return row if row["state"] in ("resolved", "ok") else None

            row = await _poll(alert_cleared)
            assert row["episodes"] == 1
            text = await (await client.get("/metrics")).text()
            assert 'alerts_firing{rule="engine_watchdog_stall"} 0' in text
            # ... and resolving does NOT re-capture: still exactly one
            listing = await (await client.get("/debug/incidents")).json()
            assert listing["count"] == 1
        finally:
            faults.clear()
            await client.close()

    with eng:
        asyncio.get_event_loop_policy().new_event_loop() \
            .run_until_complete(fn())
    assert eng.stats["watchdog_stalls"] >= 1
