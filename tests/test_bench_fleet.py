"""Tier-1 CPU smoke of the multi-replica fleet bench scenario: Poisson
session load through the fleet router over two real tiny-engine
replicas, affinity vs round-robin, and the schema contract for the new
``fleet`` section (cross-replica prefix_hit_rate + SLO attainment — the
headline the single-engine scenarios cannot produce)."""

import pytest

import jax
import jax.numpy as jnp

import bench
from generativeaiexamples_tpu.engine import Engine, EngineConfig
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from tools.check_bench_schema import (BenchSchemaError, load_schema,
                                      validate_result)

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=1024)


@pytest.fixture(scope="module")
def engines():
    params = llama.init_params(CFG, jax.random.key(13), dtype=jnp.float32)
    ecfg = EngineConfig(
        max_slots=2, max_input_length=1024, max_output_length=16,
        prefill_buckets=(64,), max_prefill_bucket=64, dtype="float32",
        page_size=16, kv_pool_tokens=4096, max_queue=32,
        steps_per_round=4)
    engs = [Engine(params, CFG, ByteTokenizer(), ecfg) for _ in range(2)]
    yield engs
    for e in engs:
        e.stop()


@pytest.fixture(scope="module")
def fleet_section(engines):
    # THREE sessions over TWO replicas: with an even session count,
    # round-robin's strict global alternation can accidentally pin every
    # perfectly-interleaved session to one replica (full prefix reuse —
    # the baseline ties affinity). An odd count makes that parity
    # alignment impossible for all sessions at once, so affinity's
    # prefix-hit headline strictly beats RR by construction.
    return bench.run_fleet_bench(
        engines, sessions=3, turns=3, session_rps=4.0,
        system_chars=300, user_chars=40, num_tokens=4,
        slo_ttft_ms=30000.0, seed=3, transfer_arm=True,
        heartbeat_s=0.3)


def _synthetic_with(fleet):
    pipeline = bench.pipeline_snapshot({})
    return bench.assemble_result(
        kind="engine", model="llama-tiny", headline=10.0,
        engine_p50=8.0, engine_p99=12.0, tput=100.0,
        achieved_bw=1e9, bw_util=0.1, bw_steady=True,
        chat=None, e2e_p50=None, e2e_dist=None, e2e_breakdown=None,
        e2e_tps_p50=None, pipeline=pipeline, quant="none", kv_quant=None,
        weights="random-init", prompt_len=16, out_len=4, slots=2,
        steps_per_round=4, kv_pool_pages=8, device="cpu", rtt_ms=None,
        n_devices=1, bench_seconds=1.0, fleet=fleet)


def test_fleet_bench_end_to_end(fleet_section):
    section = fleet_section
    assert section["replicas"] == 2
    assert [p["policy"] for p in section["policies"]] \
        == ["round_robin", "affinity", "affinity_transfer"]
    for p in section["policies"]:
        assert p["offered_turns"] == 9
        assert p["errors"] == 0 and p["completed"] == 9
        assert 0.0 <= p["slo_attainment"] <= 1.0
        assert p["ttft_p50_ms"] and p["ttft_p50_ms"] > 0
        assert sum(p["placed"].values()) == 9
    rr, aff = section["policies"][:2]
    # the headline the router exists to move: cross-replica prefix reuse
    assert aff["prefix_hit_tokens"] > rr["prefix_hit_tokens"]
    assert aff["prefix_hit_rate"] >= rr["prefix_hit_rate"]
    # affinity placements actually matched sketched prefixes
    assert aff["affinity_hit_placements"] > 0
    # round-robin really alternated replicas (the baseline is honest):
    # 9 placements strictly alternate into a 5/4 split
    assert sorted(rr["placed"].values()) == [4, 5]
    # the transfer arm ran with donor hints enabled; these replicas
    # have no host KV tier, so the hint is inert and no pages move
    # (real page movement over /control/kv_pages is pinned by
    # tests/test_kv_tier.py::test_cross_replica_transfer_end_to_end)
    transfer = section["policies"][2]
    assert transfer["kv_transfer"] is True
    assert transfer["kv_transfer_pages"] == 0
    assert not rr["kv_transfer"] and not aff["kv_transfer"]
    # fleet_obs rides along, sourced from the router's /debug/fleet and
    # schema-validated at capture time (None would mean the capture
    # failed — the spine is part of the scenario's contract)
    obs = section["fleet_obs"]
    assert obs is not None
    assert obs["window_requests"] > 0
    assert obs["slo_attainment"] is not None
    assert obs["capacity_tokens_per_sec"] > 0
    assert len(obs["replicas"]) == 2
    for row in obs["replicas"]:
        assert row["headroom_tokens_per_sec"] is not None
        # window rows cover the arm's turns; headroom never exceeds the
        # replica's modeled capacity share
        assert row["headroom_tokens_per_sec"] \
            <= obs["capacity_tokens_per_sec"]


def test_fleet_section_schema_valid(fleet_section):
    validate_result(_synthetic_with(fleet_section))
    validate_result(_synthetic_with(None))  # fleet-less runs still pass


def test_fleet_section_matches_schema_keys(fleet_section):
    schema = load_schema()
    assert set(fleet_section) == set(schema["fleet"])
    for p in fleet_section["policies"]:
        assert set(p) == set(schema["fleet_policy"])


def test_fleet_policy_field_rename_fails_fast(fleet_section):
    import copy
    section = copy.deepcopy(fleet_section)
    section["policies"][0]["hit_rate"] = \
        section["policies"][0].pop("prefix_hit_rate")
    with pytest.raises(BenchSchemaError, match="fleet.policies"):
        validate_result(_synthetic_with(section))
