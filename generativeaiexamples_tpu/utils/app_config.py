"""Application configuration schema.

Parity with the reference's config tree
(reference: RetrievalAugmentedGeneration/common/configuration.py:20-170):
``VectorStoreConfig`` / ``LLMConfig`` / ``TextSplitterConfig`` /
``EmbeddingConfig`` / ``PromptsConfig`` / ``AppConfig`` — extended with
TPU-native ``EngineConfig``/``MeshConfig`` sections that replace the
reference's TRT-LLM engine-build flags
(reference: llm-inference-server/model_server/__main__.py:33-135).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .configuration import configfield, from_file

# Default prompt templates: Llama-2 [INST] chat formats, parity with
# reference common/configuration.py:124-156 (PromptsConfig defaults).
CHAT_TEMPLATE = (
    "<s>[INST] <<SYS>>\n"
    "You are a helpful, respectful and honest assistant. Always answer as "
    "helpfully as possible, while being safe. Please ensure that your "
    "responses are positive in nature.\n"
    "<</SYS>>\n\n"
    "{context_str} {query_str} [/INST]"
)

RAG_TEMPLATE = (
    "<s>[INST] <<SYS>>\n"
    "Use the following context to answer the user's question. If you don't "
    "know the answer, just say that you don't know, don't try to make up an "
    "answer.\n"
    "<</SYS>>\n\n"
    "<s>[INST] Context: {context_str} Question: {query_str} Only return the "
    "helpful answer below and nothing else. Helpful answer: [/INST]"
)


@dataclass(frozen=True)
class VectorStoreConfig:
    """Reference: common/configuration.py:20-47."""
    name: str = configfield("name", default="exact",
                            help_txt="vector store backend: exact | exact-tpu | ivfflat | milvus | pgvector")
    url: str = configfield("url", default="",
                           help_txt="remote store URL (milvus/pgvector only)")
    nlist: int = configfield("nlist", default=64,
                             help_txt="IVF cluster count (reference milvus GPU_IVF_FLAT nlist)")
    nprobe: int = configfield("nprobe", default=16,
                              help_txt="IVF clusters probed per query")


@dataclass(frozen=True)
class LLMConfig:
    """Reference: common/configuration.py:50-72."""
    server_url: str = configfield("server_url", default="",
                                  help_txt="URL of the TPU inference server ('' = in-process engine)")
    model_name: str = configfield("model_name", default="llama-2-7b-chat",
                                  help_txt="served model name")
    model_engine: str = configfield("model_engine", default="tpu-jax",
                                    help_txt="tpu-jax | tpu-http | openai-compat | echo (testing)")


@dataclass(frozen=True)
class TextSplitterConfig:
    """Reference: common/configuration.py:75-92 (510/200 on e5 tokenizer)."""
    chunk_size: int = configfield("chunk_size", default=510,
                                  help_txt="tokens per chunk")
    chunk_overlap: int = configfield("chunk_overlap", default=200,
                                     help_txt="token overlap between chunks")


@dataclass(frozen=True)
class EmbeddingConfig:
    """Reference: common/configuration.py:95-121 (e5-large-v2, 1024-d)."""
    model_name: str = configfield("model_name", default="intfloat/e5-large-v2",
                                  help_txt="embedding model")
    dimensions: int = configfield("dimensions", default=1024,
                                  help_txt="embedding dimensionality")
    model_engine: str = configfield("model_engine", default="tpu-jax",
                                    help_txt="tpu-jax | tpu-http | hash (testing)")


@dataclass(frozen=True)
class PromptsConfig:
    """Reference: common/configuration.py:124-156."""
    chat_template: str = configfield("chat_template", default=CHAT_TEMPLATE,
                                     help_txt="non-KB chat prompt template")
    rag_template: str = configfield("rag_template", default=RAG_TEMPLATE,
                                    help_txt="KB-augmented prompt template")


@dataclass(frozen=True)
class RetrieverConfig:
    """Retrieval behavior defaults (reference: chains.py:117 top-4,
    common/utils.py:91 1500-token context cap)."""
    top_k: int = configfield("top_k", default=4, help_txt="documents retrieved per query")
    max_context_tokens: int = configfield("max_context_tokens", default=1500,
                                          help_txt="token cap on stuffed retrieved context")


@dataclass(frozen=True)
class MeshConfig:
    """TPU device-mesh layout — replaces the reference's TP×PP=world-size
    process topology (reference: model_server/__init__.py:103-110)."""
    tp: int = configfield("tp", default=0,
                          help_txt="tensor-parallel size (0 = all local devices)")
    pp: int = configfield("pp", default=1, help_txt="pipeline-parallel stages")
    dp: int = configfield("dp", default=1, help_txt="data-parallel replicas")
    ep: int = configfield("ep", default=1, help_txt="expert-parallel size (MoE)")


@dataclass(frozen=True)
class EngineConfig:
    """Serving-engine limits — parity with the reference's engine-build
    defaults (reference: model_server/__main__.py:81-92 max in/out,
    ensemble_models/llama/tensorrt_llm/config.pbtxt.j2:29 max batch)."""
    max_input_length: int = configfield("max_input_length", default=3000)
    max_output_length: int = configfield("max_output_length", default=512)
    max_batch_size: int = configfield("max_batch_size", default=128)
    page_size: int = configfield("page_size", default=128,
                                 help_txt="KV-cache page size in tokens")
    prefill_buckets: list[int] = configfield(
        "prefill_buckets", default_factory=lambda: [128, 512, 1024, 2048, 3072],
        help_txt="static prefill padding buckets (XLA static shapes)")
    dtype: str = configfield("dtype", default="bfloat16",
                             help_txt="activation/weight dtype on TPU")
    quantization: str = configfield("quantization", default="",
                                    help_txt="'' | int8 | int4_awq (reference: conversion/llama.py:81-97)")


@dataclass(frozen=True)
class ServingRobustnessConfig:
    """Deadline/overload behavior at the HTTP edges (chain server and
    OpenAI API). Env overlay: ``APP_SERVING_*`` (configuration.py)."""
    default_deadline_ms: float = configfield(
        "default_deadline_ms", default=0.0,
        help_txt="deadline applied when no X-Deadline-Ms header is sent "
                 "(0 = none)")
    request_timeout_s: float = configfield(
        "request_timeout_s", default=30.0,
        help_txt="executor timeout for documentSearch; a hung store "
                 "returns 504 instead of pinning a worker")
    ingest_timeout_s: float = configfield(
        "ingest_timeout_s", default=300.0,
        help_txt="executor timeout for uploadDocument ingest — separate "
                 "knob: chunking+embedding a large file is legitimately "
                 "slow where a search is not")
    breaker_failures: int = configfield(
        "breaker_failures", default=5,
        help_txt="consecutive generate failures before the engine "
                 "breaker opens (fast-503)")
    breaker_cooldown_s: float = configfield(
        "breaker_cooldown_s", default=15.0,
        help_txt="seconds an open breaker waits before a half-open probe")
    admission_min_samples: int = configfield(
        "admission_min_samples", default=4,
        help_txt="completed requests needed before queue-wait-based "
                 "admission shedding activates")


@dataclass(frozen=True)
class TracingConfig:
    enabled: bool = configfield("enabled", default=False,
                                help_txt="enable OpenTelemetry tracing (reference gates on ENABLE_TRACING)")
    otlp_endpoint: str = configfield("otlp_endpoint", default="http://localhost:4317")


@dataclass(frozen=True)
class AppConfig:
    """Root config (reference: common/configuration.py:158-170)."""
    vector_store: VectorStoreConfig = field(default_factory=VectorStoreConfig)
    llm: LLMConfig = field(default_factory=LLMConfig)
    text_splitter: TextSplitterConfig = field(default_factory=TextSplitterConfig)
    embeddings: EmbeddingConfig = field(default_factory=EmbeddingConfig)
    prompts: PromptsConfig = field(default_factory=PromptsConfig)
    retriever: RetrieverConfig = field(default_factory=RetrieverConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    serving: ServingRobustnessConfig = field(
        default_factory=ServingRobustnessConfig)


_CONFIG_SINGLETON: AppConfig | None = None


def get_config(path: str | None = None, *, reload: bool = False) -> AppConfig:
    """Load-once config accessor.

    Parity with ``get_config`` (reference: common/utils.py:133-140): reads
    the file named by ``$APP_CONFIG_FILE`` unless an explicit path is given.
    """
    global _CONFIG_SINGLETON
    if path is not None:
        # Explicit-path loads are one-off: they must not reconfigure every
        # later bare get_config() caller in the process.
        return from_file(AppConfig, path)
    if _CONFIG_SINGLETON is None or reload:
        import os
        _CONFIG_SINGLETON = from_file(AppConfig, os.environ.get("APP_CONFIG_FILE"))
    return _CONFIG_SINGLETON
