"""Incident black-box: freeze the joined evidence before the rings
recycle it.

When an alert rule fires (obs/alerts.py) — or an operator POSTs
``/control/incident`` — the server captures one bounded JSON bundle
joining everything the process knows about the affected window:

- the metric-history window (obs/history.py raw samples + aggregates);
- the firing rule's evidence and the full alert state;
- the last-N flight timelines (obs/flight.py) and round records
  (obs/rounds.py) — request-ID keyed, so the bundle preserves the
  X-Request-ID trace-join across layers;
- extras per tier: the router adds its fleet snapshot, autoscale
  decision ring, and a per-replica pull of each replica's
  ``/debug/requests`` + ``/debug/rounds`` slice.

Bundles land in a count/byte-capped on-disk store under
``$GAIE_RUN_DIR/incidents`` (atomic tmp+rename writes; oldest evicted
first), are listed at ``GET /debug/incidents``, and render to a
markdown post-mortem via ``tools/incident_report.py``. Capture happens
once per firing episode — a rule that STAYS firing does not re-capture
(pinned by the chaos suite); a resolved-then-refired rule starts a new
episode and captures again.

``ObservabilityStack`` is the one wiring point all three servers share:
history + alerts + incidents built together, inert as a unit when
``HISTORY_INTERVAL_S=0`` (no sampler thread, no alert ticks, no disk
writes — the store directory is not even created).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional, Sequence

from ..utils.logging import get_logger, log_event
from . import metrics as obs_metrics
from .alerts import AlertEngine, AlertRule
from .history import MetricHistory

logger = get_logger(__name__)

BUNDLE_SCHEMA = "incident/v1"

INCIDENT_MAX_COUNT = int(os.environ.get("INCIDENT_MAX_COUNT", "20"))
INCIDENT_MAX_BYTES = int(os.environ.get("INCIDENT_MAX_BYTES",
                                        str(32 * 1024 * 1024)))
#: flight timelines / round records retained per bundle.
INCIDENT_SLICE_LIMIT = int(os.environ.get("INCIDENT_SLICE_LIMIT", "50"))


def incident_root() -> str:
    run_dir = os.environ.get("GAIE_RUN_DIR",
                             "/tmp/generativeaiexamples_tpu/run")
    return os.path.join(run_dir, "incidents")


class IncidentStore:
    """Count/byte-capped directory of incident bundles.

    Writes are atomic (tmp + rename) and serialized by one lock;
    eviction drops oldest-first until both caps hold. The directory is
    created lazily on the FIRST capture — an inert deployment writes
    nothing to disk, not even an empty dir."""

    def __init__(self, root: Optional[str] = None,
                 max_count: int = INCIDENT_MAX_COUNT,
                 max_bytes: int = INCIDENT_MAX_BYTES):
        self.root = root or incident_root()
        self.max_count = max_count
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._seq = 0

    # -------------------------------------------------------------- write

    def capture(self, bundle: dict) -> Optional[str]:
        """Persist one bundle; returns its path (None on IO failure —
        capture is best-effort and must never take down serving)."""
        with self._lock:
            self._seq += 1
            incident_id = bundle.get("id") or (
                f"inc-{time.strftime('%Y%m%dT%H%M%S')}-"
                f"{os.getpid()}-{self._seq}-"
                f"{bundle.get('trigger', {}).get('rule') or 'manual'}")
            bundle = dict(bundle)
            bundle["id"] = incident_id
            bundle.setdefault("schema", BUNDLE_SCHEMA)
            path = os.path.join(self.root, f"{incident_id}.json")
            tmp = path + ".tmp"
            try:
                os.makedirs(self.root, exist_ok=True)
                with open(tmp, "w", encoding="utf-8") as fh:
                    json.dump(bundle, fh, default=str)
                os.replace(tmp, path)
            except OSError:
                logger.warning("incident capture failed: %s", path,
                               exc_info=True)
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return None
            self._evict()
        log_event(logger, "incident_captured", id=incident_id, path=path,
                  rule=bundle.get("trigger", {}).get("rule"),
                  bytes=os.path.getsize(path))
        return path

    def _evict(self) -> None:
        entries = self._entries()
        total = sum(e["bytes"] for e in entries)
        while entries and (len(entries) > self.max_count
                           or total > self.max_bytes):
            victim = entries.pop(0)          # oldest first
            total -= victim["bytes"]
            try:
                os.unlink(victim["path"])
            except OSError:
                pass

    # --------------------------------------------------------------- read

    def _entries(self) -> list[dict]:
        try:
            names = [n for n in os.listdir(self.root)
                     if n.endswith(".json")]
        except OSError:
            return []
        rows = []
        for name in names:
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            rows.append({"id": name[:-5], "path": path,
                         "bytes": st.st_size, "mtime": st.st_mtime})
        rows.sort(key=lambda e: (e["mtime"], e["id"]))
        return rows

    def list(self, limit: int = 50) -> dict:
        entries = self._entries()
        for e in entries:
            # Surface the trigger without shipping whole bundles in a
            # listing: read just the header fields.
            try:
                with open(e["path"], encoding="utf-8") as fh:
                    b = json.load(fh)
                e["rule"] = b.get("trigger", {}).get("rule")
                e["kind"] = b.get("trigger", {}).get("kind")
                e["server"] = b.get("server")
                e["ts"] = b.get("ts")
            except (OSError, ValueError):
                e["rule"] = e["kind"] = e["server"] = e["ts"] = None
        entries.reverse()                    # newest first for operators
        return {"root": self.root, "count": len(entries),
                "total_bytes": sum(e["bytes"] for e in entries),
                "max_count": self.max_count, "max_bytes": self.max_bytes,
                "incidents": entries[:limit]}

    def load(self, incident_id: str) -> Optional[dict]:
        path = os.path.join(self.root, f"{incident_id}.json")
        if os.path.realpath(path).rpartition(os.sep)[0] != \
                os.path.realpath(self.root):
            return None                      # path traversal guard
        try:
            with open(path, encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None


# ------------------------------------------------------------------ bundles


def build_bundle(*, server: str, trigger: dict,
                 history: Optional[MetricHistory],
                 alerts: Optional[AlertEngine],
                 flight=None, rounds=None,
                 extras: Optional[dict] = None,
                 slice_limit: int = INCIDENT_SLICE_LIMIT) -> dict:
    """Join the local evidence into one bundle. ``flight``/``rounds``
    are recorder objects (obs/flight.py / obs/rounds.py) or None;
    ``extras`` merges tier-specific sections (fleet, autoscale,
    replicas) at the top level."""
    bundle = {
        "schema": BUNDLE_SCHEMA,
        "server": server,
        "ts": time.time(),
        "trigger": trigger,
        "alerts": alerts.snapshot() if alerts is not None else None,
        "history": {
            "aggregates": history.query() if history is not None else None,
            "window": history.raw() if history is not None else [],
        },
        "flight": flight.snapshot(limit=slice_limit)
        if flight is not None else None,
        "rounds": rounds.snapshot(limit=slice_limit)
        if rounds is not None else None,
    }
    if extras:
        bundle.update(extras)
    return bundle


# ------------------------------------------------------------------- stack


class ObservabilityStack:
    """History + alerts + incident store, wired as one unit.

    ``interval_s <= 0`` (HISTORY_INTERVAL_S=0) builds the parity-pinned
    inert stack: no sampler thread is ever started, the alert engine is
    None (zero ticks), the store is None (zero disk writes). The debug
    endpoints stay mounted and answer ``{"enabled": false}``.

    ``capture_extras`` (optional) returns tier-specific bundle sections
    at capture time; ``capture_async`` (router) replaces the default
    synchronous capture with a scheduler that may gather remote
    evidence — it receives the (rule-or-None, trigger dict).
    """

    def __init__(self, server: str,
                 pre_sample: Sequence[Callable[[], None]] = (),
                 flight=None, rounds=None,
                 rules: Optional[tuple[AlertRule, ...]] = None,
                 capture_extras: Optional[Callable[[], dict]] = None,
                 capture_async: Optional[Callable] = None,
                 registry: obs_metrics.Registry = obs_metrics.REGISTRY,
                 window_s: Optional[float] = None,
                 interval_s: Optional[float] = None):
        self.server = server
        self.flight = flight
        self.rounds = rounds
        self.capture_extras = capture_extras
        self.capture_async = capture_async
        self.history = MetricHistory(registry=registry, window_s=window_s,
                                     interval_s=interval_s,
                                     pre_sample=pre_sample)
        if self.history.enabled:
            self.store: Optional[IncidentStore] = IncidentStore()
            self.alerts: Optional[AlertEngine] = AlertEngine(
                self.history, rules=rules, registry=registry,
                on_fire=self._on_fire, server=server).attach()
        else:
            self.store = None
            self.alerts = None

    @property
    def enabled(self) -> bool:
        return self.history.enabled

    def start(self) -> None:
        self.history.start()

    def stop(self) -> None:
        self.history.stop()

    # ------------------------------------------------------------- capture

    def _on_fire(self, rule: AlertRule, record: dict) -> None:
        trigger = {"kind": "alert", "rule": rule.name,
                   "severity": rule.severity, "summary": rule.summary,
                   "state": record.get("state"),
                   "evidence": record.get("evidence", {})}
        if self.capture_async is not None:
            self.capture_async(rule, trigger)
        else:
            self.capture(trigger)

    def capture(self, trigger: dict,
                extras: Optional[dict] = None) -> Optional[str]:
        """Synchronous local capture; returns the bundle path. No-op
        (None) when inert."""
        if self.store is None:
            return None
        merged = dict(extras or {})
        if self.capture_extras is not None:
            try:
                merged.update(self.capture_extras() or {})
            except Exception:  # noqa: BLE001 — extras are best-effort
                logger.debug("incident capture_extras failed",
                             exc_info=True)
        bundle = build_bundle(server=self.server, trigger=trigger,
                              history=self.history, alerts=self.alerts,
                              flight=self.flight, rounds=self.rounds,
                              extras=merged)
        return self.store.capture(bundle)


# ------------------------------------------------------------ HTTP handlers


def debug_incidents_response(request, stack: Optional[ObservabilityStack]):
    from aiohttp import web

    from .history import query_int

    if stack is None or stack.store is None:
        return web.json_response({"enabled": False, "count": 0,
                                  "incidents": []})
    limit = query_int(request, "limit", 50, minimum=0)
    body = stack.store.list(limit=limit)
    body["enabled"] = True
    incident_id = request.query.get("id")
    if incident_id:
        bundle = stack.store.load(incident_id)
        if bundle is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": {
                    "type": "unknown_incident",
                    "message": f"no incident {incident_id!r}"}}),
                content_type="application/json")
        return web.json_response(bundle)
    return web.json_response(body)


async def control_incident_response(request,
                                    stack: Optional[ObservabilityStack]):
    """``POST /control/incident``: manual black-box capture (operator
    'freeze the evidence NOW' button). 409 when the layer is inert."""
    from aiohttp import web

    if stack is None or stack.store is None:
        raise web.HTTPConflict(
            text=json.dumps({"error": {
                "type": "incidents_disabled",
                "message": "retained telemetry is disarmed "
                           "(HISTORY_INTERVAL_S=0)"}}),
            content_type="application/json")
    try:
        body = await request.json()
    except Exception:  # noqa: BLE001 — empty body is fine
        body = {}
    reason = str((body or {}).get("reason", "manual"))[:200]
    trigger = {"kind": "manual", "rule": None, "reason": reason,
               "state": None, "evidence": {}}
    if stack.capture_async is not None:
        stack.capture_async(None, trigger)
        return web.json_response({"status": "capturing",
                                  "kind": "manual"})
    path = stack.capture(trigger)
    if path is None:
        raise web.HTTPInternalServerError(
            text=json.dumps({"error": {
                "type": "capture_failed",
                "message": "incident bundle could not be written"}}),
            content_type="application/json")
    return web.json_response({"status": "captured", "path": path,
                              "id": os.path.basename(path)[:-5]})
