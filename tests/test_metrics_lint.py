"""Prometheus text-format lint (obs/metrics.py lint_prometheus): HELP
lines present, sample lines match their declared family, no duplicate
families, counters end in _total (or are documented exceptions) — run
over the repo's real metric surfaces rendered into a fresh registry."""

from generativeaiexamples_tpu.engine.engine import _STATS_TEMPLATE
from generativeaiexamples_tpu.obs import metrics as obs_metrics
from generativeaiexamples_tpu.obs.metrics import (
    COUNTER_NAME_EXCEPTIONS, Registry, RequestTimer, lint_prometheus)


def _populated_registry() -> Registry:
    """A fresh registry carrying every declared metric surface, built
    through the same helpers production uses."""
    reg = Registry()
    # Engine gauge mirror (chain + model servers' /metrics).
    stats = dict(_STATS_TEMPLATE)
    stats["harvest_rounds"] = 2
    stats["harvest_wait_ms"] = 10.0
    obs_metrics.record_engine_stats(stats, registry=reg)
    # Stage histogram + request-class timers.
    obs_metrics.observe_stage("engine_ttft", 0.1, registry=reg)
    timer = RequestTimer("chain_generate", registry=reg)
    timer.token(4)
    timer.finish()
    # Round telemetry surface (obs/rounds.py declarations).
    from generativeaiexamples_tpu.obs.rounds import (ROUND_METRICS,
                                                     ROUND_TOKEN_BUCKETS)
    for name, (kind, help_txt) in ROUND_METRICS.items():
        if kind == "counter":
            reg.counter(name, help_txt).inc()
        elif kind == "gauge":
            reg.gauge(name, help_txt).set(1.0)
        else:
            buckets = (ROUND_TOKEN_BUCKETS
                       if name == "engine_round_tokens"
                       else obs_metrics.STAGE_BUCKETS)
            reg.histogram(name, help_txt, buckets=buckets).observe(1.0)
    # Router surface (its declared rows carry kind/labels/help).
    from generativeaiexamples_tpu.router.metrics import ROUTER_METRICS
    for name, (kind, labels, help_txt) in ROUTER_METRICS.items():
        m = (reg.counter if kind == "counter" else reg.gauge)(
            name, help_txt, labelnames=labels)
        leaf = m.labels(*(["r0"] * len(labels))) if labels else m
        leaf.inc() if kind == "counter" else leaf.set(1.0)
    # Robustness surface.
    reg.counter("shed_total", "requests rejected at admission, by reason",
                labelnames=("reason",)).labels("queue_full").inc()
    reg.gauge("breaker_state",
              "circuit breaker state (0 closed, 1 half-open, 2 open)",
              labelnames=("name",)).labels("retrieval").set(0)
    reg.counter("breaker_trips_total",
                "breaker closed/half-open -> open transitions",
                labelnames=("name",)).labels("retrieval").inc()
    return reg


def test_real_surfaces_render_clean():
    text = _populated_registry().render_prometheus()
    assert lint_prometheus(text) == []
    # HELP lines actually present, before their TYPE line
    lines = text.splitlines()
    idx_help = lines.index(
        "# HELP engine_rounds_total engine rounds completed: plan "
        "sealed AND every device output of the round harvested")
    assert lines[idx_help + 1].startswith("# TYPE engine_rounds_total ")


def test_lint_flags_counter_without_total_suffix():
    reg = Registry()
    reg.counter("oops_count", "a misnamed counter").inc()
    errors = lint_prometheus(reg.render_prometheus())
    assert any("oops_count" in e and "_total" in e for e in errors)
    # a documented exception passes
    errors = lint_prometheus(reg.render_prometheus(),
                             counter_exceptions={"oops_count": "legacy"})
    assert errors == []


def test_lint_flags_missing_help():
    reg = Registry()
    reg.counter("things_total").inc()
    errors = lint_prometheus(reg.render_prometheus())
    assert any("no # HELP" in e for e in errors)


def test_lint_flags_duplicate_family_and_family_mismatch():
    text = ("# HELP a_total x\n# TYPE a_total counter\n"
            "a_total 1\n"
            "# HELP b_total x\n# TYPE b_total counter\n"
            "rogue_sample 2\n"
            "# HELP a_total x\n# TYPE a_total counter\n"
            "a_total 3\n")
    errors = lint_prometheus(text)
    assert any("duplicate family 'a_total'" in e for e in errors)
    assert any("rogue_sample" in e for e in errors)


def test_lint_accepts_histogram_suffixes_and_labels():
    reg = Registry()
    h = reg.histogram("lat_seconds", "latency", labelnames=("stage",))
    h.labels("prefill").observe(0.2)
    assert lint_prometheus(reg.render_prometheus()) == []


def test_exception_table_documents_reasons():
    for name, reason in COUNTER_NAME_EXCEPTIONS.items():
        assert isinstance(reason, str) and len(reason) > 10, name
