"""Microbenchmark the decode round on the real chip.

Times a jitted 16-step decode round (the engine's actual dispatch unit)
and ablations of it — per-dispatch tunnel latency here is ~4-5 ms, so
only multi-step fused programs give honest per-step numbers.
Run on TPU: python tools/profile_decode.py

``--json PATH`` additionally writes the roofline attribution (unembed /
KV window stream / weight-stream floor, ms per step) as a machine-
readable artifact — committed each round as ``PROFILE_rNN.json`` next
to BENCH so perf attribution is driver-verifiable rather than narrated
(VERDICT r5 "Next round" #8).

``--slots 8,16,32,64`` switches to SWEEP mode: the same attribution is
measured at every slot rung (shared params, per-rung pool) and the
artifact carries one entry per rung plus each rung's achieved-HBM-
bandwidth fraction — the 8→64 utilization decay of BENCH_SWEEP_r05 as
one reproducible command instead of N hand-rolled runs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from generativeaiexamples_tpu.utils.hbm import peak_bw as _peak_bw


def profile_rung(params, cfg, *, slots: int, window: int, live_pages: int,
                 steps: int, page: int, dtype, kv_quant: bool,
                 param_bytes: int, use_kernel: bool,
                 verify_tokens: int = 8, mesh=None) -> dict:
    """Measure one slot-count rung: the full decode round and its
    ablations (no-unembed, window=1), per step, plus the speculative
    VERIFY step (one ``verify_tokens``-position multi-token forward at
    this decode occupancy — the dispatch unit of engine/spec_decode.py,
    priced against the round budget via StepCostModel's
    ``verify_ms_per_token``). Returns the per-rung attribution dict the
    sweep artifact collects."""
    from generativeaiexamples_tpu.models import llama

    B, W, K = slots, window, steps
    n_pages = B * W + 1
    cache = llama.init_paged_kv_cache(cfg, n_pages, page, dtype,
                                      quantized=kv_quant)
    if mesh is not None:
        # Honest tp rungs: the pool lives sharded exactly as the
        # engine's device state does (KV heads over tp when they
        # divide), so the measured step includes the same collectives.
        from jax.sharding import NamedSharding
        from generativeaiexamples_tpu.parallel.sharding import (
            paged_kv_cache_spec)
        cache = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            cache, paged_kv_cache_spec(cfg, mesh, quantized=kv_quant))
    table = jnp.asarray(
        np.arange(1, 1 + B * W, dtype=np.int32).reshape(B, W))
    pos0 = jnp.full((B,), live_pages * page - K - 2, jnp.int32)
    tokens0 = jnp.ones((B,), jnp.int32)

    def make_round(ablate=None):
        def round_fn(params, cache, tok, pos):
            def body(carry, _):
                cache, tok, pos = carry
                wp = jnp.take_along_axis(table, (pos // page)[:, None],
                                         axis=1)[:, 0]
                if ablate == "window1":
                    tbl, p_eff = table[:, :1], jnp.minimum(pos, page - 1)
                else:
                    tbl, p_eff = table, pos
                logits, cache = llama.apply_decode_paged(
                    params, cfg, tok[:, None], p_eff[:, None], cache, tbl,
                    p_eff + 1, wp, p_eff % page, use_kernel=use_kernel,
                    mesh=mesh)
                if ablate == "no_unembed":
                    tok = (logits[:, 0, :8].sum(-1) * 0).astype(
                        jnp.int32) + tok
                else:
                    tok = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
                return (cache, tok, pos + 1), tok
            (cache, tok, pos), toks = jax.lax.scan(
                body, (cache, tok, pos), None, length=K)
            return cache, tok, pos, toks
        return jax.jit(round_fn, donate_argnums=(1,))

    state = {"cache": cache}

    def run(label, f, extra_bytes=0):
        c, tok, pos = state["cache"], tokens0, pos0
        for _ in range(2):
            c, tok, pos, toks = f(params, c, tok, pos0)
        jax.block_until_ready(toks)
        n = 6
        t0 = time.perf_counter()
        for _ in range(n):
            c, tok, pos, toks = f(params, c, tok, pos0)
        jax.block_until_ready((c, toks))
        ms = (time.perf_counter() - t0) / n / K * 1e3
        state["cache"] = c
        bw = (param_bytes + extra_bytes) / ms * 1e3 / 1e9
        print(f"[{B:>3} slots] {label}: {ms:.2f} ms/step "
              f"({bw:.0f} GB/s apparent, {B/ms*1e3:.0f} tok/s)")
        return ms

    # bytes per cached token: int8 rows + bf16 scales under PROF_KV_QUANT
    row_bytes = (cfg.head_dim + 2) if kv_quant else cfg.head_dim * 2
    kv_live = (live_pages * page * cfg.num_layers * cfg.num_kv_heads
               * row_bytes * 2 * B)
    full = run("full round   ", make_round(), kv_live)
    nou = run("no unembed   ", make_round("no_unembed"), kv_live)
    w1 = run("window=1     ", make_round("window1"),
             kv_live // max(live_pages, 1))
    peak = _peak_bw(jax.local_devices()[0])
    achieved = (param_bytes + kv_live) / full * 1e3  # bytes/s

    # Speculative verify step: S = verify_tokens positions per slot in
    # ONE forward (llama.apply_verify_paged — the jnp gather path the
    # engine's verify rounds take on every backend). Measured at the
    # same occupancy as the decode round above, so the scheduler's
    # budget pricing compares like with like; per-token = the call
    # divided by its slots x S scored positions (the unit
    # StepCostModel.verify_cost_tokens ratios against
    # prefill_ms_per_token).
    S = verify_tokens
    base_pos = max(0, live_pages * page - S - 2)

    def verify_fn(params, cache, tok, pos):
        positions = pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
        tokens = jnp.broadcast_to(tok[:, None], (B, S))
        wp = jnp.take_along_axis(table, positions // page, axis=1)
        out, cache = llama.apply_verify_paged(
            params, cfg, tokens, positions, cache, table, pos + S,
            wp, positions % page)
        nxt = jnp.argmax(out[:, -1], -1).astype(jnp.int32)
        return cache, nxt

    vfn = jax.jit(verify_fn, donate_argnums=(1,))
    c, tok, posv = state["cache"], tokens0, jnp.full((B,), base_pos,
                                                     jnp.int32)
    for _ in range(2):
        c, tok = vfn(params, c, tok, posv)
    jax.block_until_ready(tok)
    n = 6
    t0 = time.perf_counter()
    for _ in range(n):
        c, tok = vfn(params, c, tok, posv)
    jax.block_until_ready(tok)
    verify_ms = (time.perf_counter() - t0) / n * 1e3
    state["cache"] = c
    print(f"[{B:>3} slots] verify x{S}   : {verify_ms:.2f} ms/step "
          f"({verify_ms / (B * S):.4f} ms/token)")

    del state["cache"]  # free this rung's pool before the next builds
    return {
        "slots": B,
        "window_pages": W,
        "live_pages": live_pages,
        "kv_live_bytes": kv_live,
        "full_ms_per_step": round(full, 3),
        "no_unembed_ms_per_step": round(nou, 3),
        "window1_ms_per_step": round(w1, 3),
        "unembed_ms_per_step": round(full - nou, 3),
        "window_stream_ms_per_step": round(full - w1, 3),
        "tokens_per_sec": round(B / full * 1e3, 1),
        # Roofline: bytes the step MUST move (weights once + live KV
        # window) over measured step time, as a fraction of the chip's
        # peak — the ladder whose 8→64 decay this round exists to close.
        "achieved_bw_gbps": round(achieved / 1e9, 1),
        "achieved_bw_fraction": round(achieved / peak, 3),
        # Speculative verify cost at this occupancy: the S-position
        # dispatch and its per-scored-token cost (StepCostModel input —
        # prices verify rounds against the PR-6 token budget).
        "verify_ms_per_step": round(verify_ms, 3),
        "verify_ms_per_token": round(verify_ms / (B * S), 4),
    }


def parse_mesh_arg(spec: str) -> dict:
    """``tp=2`` / ``tp=2,sp=2`` -> {"tp": 2, "sp": 2}; the shared
    ``parallel.mesh.parse_mesh_spec`` grammar, surfaced as the CLI exit
    (a typo'd axis would silently profile single-chip)."""
    from generativeaiexamples_tpu.parallel.mesh import parse_mesh_spec
    try:
        return parse_mesh_spec(spec)
    except ValueError as exc:
        raise SystemExit(f"--mesh {exc}")


def main(json_path: str = "", slots_arg: str = "", mesh_arg: str = ""):
    from generativeaiexamples_tpu.models import llama
    from generativeaiexamples_tpu.models.configs import get_model_config
    from generativeaiexamples_tpu.ops.quant import quantize_params

    model = os.environ.get("PROF_MODEL", "llama-2-7b-chat")
    B = int(os.environ.get("PROF_SLOTS", "8"))
    W = int(os.environ.get("PROF_WINDOW", "8"))
    K = int(os.environ.get("PROF_STEPS", "16"))
    live_pages = int(os.environ.get("PROF_LIVE_PAGES", str(W)))
    page = 128
    cfg = get_model_config(model)
    dt = jnp.bfloat16
    quant = os.environ.get("PROF_QUANT", "int8")
    slots_arg = slots_arg or os.environ.get("PROF_SLOTS_SWEEP", "")
    sweep = [int(s) for s in slots_arg.split(",") if s] if slots_arg \
        else []

    def make(k):
        p = llama.init_params(cfg, k, dtype=dt)
        return quantize_params(p, quant) if quant != "none" else p
    params = jax.jit(make)(jax.random.key(0))
    jax.block_until_ready(params)

    # --mesh tp=N (or PROF_MESH): measure the SHARDED decode round —
    # params placed per llama_param_specs, the pool per
    # paged_kv_cache_spec, kernel shard_mapped when the heads divide —
    # so the artifact carries per-TOPOLOGY costs. The topology label
    # (engine/scheduler.py topology_key) keys the row; the engine's
    # StepCostModel.load(topology=...) picks the matching one, which is
    # what makes a tp engine's first-round budget honest.
    mesh = None
    mesh_arg = mesh_arg or os.environ.get("PROF_MESH", "")
    topo = "tp=1"
    if mesh_arg:
        from generativeaiexamples_tpu.engine.scheduler import topology_key
        from generativeaiexamples_tpu.parallel import (
            MeshPlan, llama_param_specs, make_mesh, shard_params)
        axes = parse_mesh_arg(mesh_arg)
        n_dev = 1
        for v in axes.values():
            n_dev *= v
        mesh = make_mesh(MeshPlan(**axes), jax.devices()[:n_dev])
        params = shard_params(params, mesh, llama_param_specs(cfg, mesh))
        topo = topology_key(dict(mesh.shape))
        print(f"mesh: {dict(mesh.shape)} -> topology {topo!r}")
    param_bytes = sum(x.nbytes for x in jax.tree.leaves(params))
    print(f"params: {param_bytes/1e9:.2f} GB  "
          f"slots={sweep or B} window={W} live={live_pages} steps={K}")

    kv_quant = os.environ.get("PROF_KV_QUANT", "") == "int8"
    use_kernel = jax.default_backend() == "tpu" \
        and llama.kernel_tp_compatible(cfg, mesh)
    floor = param_bytes / _peak_bw(jax.local_devices()[0]) * 1e3
    verify_tokens = int(os.environ.get("PROF_VERIFY_TOKENS", "8"))

    rungs = [profile_rung(
        params, cfg, slots=s, window=W, live_pages=live_pages, steps=K,
        page=page, dtype=dt, kv_quant=kv_quant, param_bytes=param_bytes,
        use_kernel=use_kernel, verify_tokens=verify_tokens, mesh=mesh)
        for s in (sweep or [B])]
    r0 = rungs[0]
    print(f"=> unembed+argmax ~{r0['unembed_ms_per_step']:.2f} ms/step, "
          f"window stream ~{r0['window_stream_ms_per_step']:.2f} ms/step, "
          f"matmul floor {floor:.2f} ms/step @peak")
    if sweep:
        ladder = " -> ".join(f"{r['slots']}:{r['achieved_bw_fraction']}"
                             for r in rungs)
        print(f"=> bandwidth ladder (fraction of peak): {ladder}")

    # Prefill token cost: one bucket-shaped forward (the engine's
    # admission program minus insert), timed per token. This is the
    # OTHER half of the scheduler's step-cost model
    # (engine/scheduler.py StepCostModel): the per-round chunk budget is
    # decode_round_ms / prefill_ms_per_token, so regenerating this
    # artifact per deployment re-derives the budget for that hardware.
    S = min(int(os.environ.get("PROF_PREFILL_BUCKET", "512")),
            cfg.max_position_embeddings)

    def prefill_fn(p, tokens, positions):
        c = llama.init_kv_cache(cfg, 1, S, dt)
        logits, _ = llama.apply(p, cfg, tokens, positions, c)
        return logits[:, -1]

    pf = jax.jit(prefill_fn)
    tok1 = jnp.ones((1, S), jnp.int32)
    pos1 = jnp.arange(S, dtype=jnp.int32)[None, :]
    for _ in range(2):
        jax.block_until_ready(pf(params, tok1, pos1))
    n = 4
    t0 = time.perf_counter()
    for _ in range(n):
        out = pf(params, tok1, pos1)
    jax.block_until_ready(out)
    prefill_ms_tok = (time.perf_counter() - t0) / n / S * 1e3
    print(f"prefill@{S}: {prefill_ms_tok:.4f} ms/token "
          f"({S/( (time.perf_counter()-t0)/n ):.0f} tok/s-equivalent)")

    if json_path:
        # Roofline attribution as a committed round artifact: the same
        # shape every round, so the driver diffs attribution (did the
        # window stream shrink? did unembed grow?) not just the headline.
        shared = {
            "tool": "profile_decode",
            "model": model,
            "device": str(jax.local_devices()[0].device_kind),
            "platform": jax.default_backend(),
            "quant": quant,
            "kv_quant": "int8" if kv_quant else "",
            "steps_per_round": K, "page_size": page,
            "param_gb": round(param_bytes / 1e9, 3),
            "matmul_floor_ms_per_step": round(floor, 3),
            # Step-cost model inputs for the token-budget scheduler
            # (engine/scheduler.py): prefill cost per prompt token at
            # the measured bucket, and the verify-round geometry the
            # per-rung verify_ms_per_token was measured at.
            "prefill_bucket_tokens": S,
            "prefill_ms_per_token": round(prefill_ms_tok, 4),
            "verify_positions": verify_tokens,
            # Topology row key (engine/scheduler.py topology_key):
            # which mesh shape these costs were measured at. "tp=1" =
            # single chip; StepCostModel.load(topology=...) matches an
            # engine's mesh against this label (or a "topologies" dict
            # of per-mesh rows merged over the shared fields).
            "topology": topo,
            "mesh_devices": mesh.devices.size if mesh is not None else 1,
        }
        if sweep:
            # Sweep shape: one attribution entry per slot rung. The
            # single-rung keys the scheduler's StepCostModel reads
            # (full_ms_per_step, verify_ms_per_token, slots,
            # prefill_ms_per_token) are mirrored at top level from the
            # FIRST rung so an _rNN sweep artifact still feeds the cost
            # model unchanged.
            artifact = dict(
                shared,
                slots_sweep=sweep,
                slots=r0["slots"],
                full_ms_per_step=r0["full_ms_per_step"],
                verify_ms_per_token=r0["verify_ms_per_token"],
                rungs=rungs,
            )
        else:
            artifact = dict(shared, **r0)
        with open(json_path, "w") as f:
            json.dump(artifact, f, indent=2)
            f.write("\n")
        print(f"wrote {json_path}")
        return artifact


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default="", metavar="PATH",
                    help="also write the roofline attribution as a JSON "
                         "artifact (PROFILE_rNN.json round record)")
    ap.add_argument("--slots", default="", metavar="A,B,C",
                    help="sweep mode: comma-separated slot rungs "
                         "(e.g. 8,16,32,64) measured with shared params; "
                         "the artifact carries per-rung attribution + "
                         "achieved-bandwidth fraction")
    ap.add_argument("--mesh", default="", metavar="tp=N",
                    help="measure the TP-SHARDED decode round on a mesh "
                         "(axis=N pairs, e.g. tp=2 or tp=2,sp=2): params "
                         "+ paged pool placed per the serving shardings, "
                         "artifact stamped with the topology_key row the "
                         "engine's cost model matches against")
    args = ap.parse_args()
    main(json_path=args.json, slots_arg=args.slots, mesh_arg=args.mesh)
