"""Per-row symmetric int8 quantization for the paged KV cache.

Decode on TPU is HBM-bandwidth-bound and the KV pool is most of the
traffic, so halving its bytes is the direct lever on both batch capacity
(2x pages at fixed HBM) and decode throughput (the reference reaches
batch 128 on the strength of the same lever family — TRT-LLM's KV-cache
quantization; reference: ensemble_models/llama/tensorrt_llm/
config.pbtxt.j2:29 max_batch_size, llm-inference-server quantization
flags model_server/__main__.py:60-66).

Scheme: one symmetric scale per cached ROW (per token, per kv head,
per layer) over the head dim — the standard int8-KV granularity:

    scale = max|row| / 127        (stored bf16)
    q     = clip(round(row / scale), -127, 127)   int8

The scale is cast to bf16 BEFORE the division so quantization and
dequantization use the exact same value — storing a rounded copy of the
scale used for quantization would add a systematic ~0.4% bias on top of
the rounding error.

Scale-pool layout: ``(L, N, KV, page)`` bf16 next to the int8 pools'
``(L, N, KV, page, hd)`` — a page's scales arrive in VMEM as
``(KV, page)``, broadcasting straight onto the kernel's ``(KV, G, page)``
score/probability tiles with no in-kernel transpose. Applied AFTER the
QK^T dot (scores scale linearly in each K row) and folded INTO the
probabilities before the PV dot (each V row scales its contribution), so
the MXU always sees bf16 operands and the int8->bf16 widen happens once
per streamed page in VMEM.
"""

from __future__ import annotations

import jax.numpy as jnp

SCALE_DTYPE = jnp.bfloat16
QMAX = 127.0


def quantize_rows(x, out_dtype=jnp.int8):
    """Quantize ``x`` per row over its LAST axis.

    Returns ``(q, scale)`` with ``q`` int8 shaped like ``x`` and
    ``scale`` bf16 shaped ``x.shape[:-1]`` such that
    ``q * scale ~= x`` (scale applied broadcast over the last axis).
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = (jnp.maximum(amax, 1e-8) / QMAX).astype(SCALE_DTYPE)
    q = jnp.clip(jnp.round(xf / scale.astype(jnp.float32)[..., None]),
                 -QMAX, QMAX).astype(out_dtype)
    return q, scale


def dequantize_rows(q, scale, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize_rows` (scale broadcast over last axis)."""
    return (q.astype(jnp.float32)
            * scale.astype(jnp.float32)[..., None]).astype(dtype)
