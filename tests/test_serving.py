"""Serving-layer tests: topology rules, quantization, OpenAI API, Triton
shim, and the real HTTP clients against a live server thread."""

import asyncio
import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from aiohttp import web

from generativeaiexamples_tpu.serving.model_server import (
    build_services, create_server_app, fast_hash_dir, resolve_topology)
from generativeaiexamples_tpu.utils.errors import ConfigError


# ------------------------------------------------------------- topology

def test_resolve_topology_defaults():
    # tp defaults to world/pp; TPxPP must equal world
    # (reference: model_server/__init__.py:103-110)
    assert resolve_topology(available=8) == (8, 8, 1)
    assert resolve_topology(world_size=4, tp=4, available=8) == (4, 4, 1)
    with pytest.raises(ConfigError):
        resolve_topology(world_size=16, available=8)


def test_resolve_topology_rejects_pp_serving():
    """pp>1 serving is a validated rejection (VERDICT r5 #6): decode
    dispatches all layers as one program per round, so pipeline stages
    would idle 1/pp of each round. Must fail at topology resolution —
    milliseconds into startup, before checkpoint conversion — with the
    documented message."""
    with pytest.raises(ConfigError, match=r"serving requires pp == 1"):
        resolve_topology(pp=2, available=8)
    with pytest.raises(ConfigError, match=r"training-only"):
        resolve_topology(world_size=4, tp=2, pp=2, available=8)


def test_fast_hash_dir_changes_with_content(tmp_path):
    (tmp_path / "a.bin").write_bytes(b"hello")
    h1 = fast_hash_dir(str(tmp_path))
    assert h1 == fast_hash_dir(str(tmp_path))
    (tmp_path / "a.bin").write_bytes(b"world")
    assert fast_hash_dir(str(tmp_path)) != h1


# ----------------------------------------------------------------- quant

def test_quantize_roundtrip_int8_int4():
    from generativeaiexamples_tpu.ops.quant import (
        dequantize, matmul, quantize_tensor)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)

    q8 = quantize_tensor(w, 8)
    err8 = float(jnp.abs(dequantize(q8, jnp.float32) - w).max())
    assert err8 < 0.05
    np.testing.assert_allclose(np.asarray(matmul(x, q8)),
                               np.asarray(x @ dequantize(q8, jnp.float32)),
                               rtol=2e-2, atol=2e-2)

    q4 = quantize_tensor(w, 4)
    assert q4["q4"].shape == (32, 32)  # packed along reduction dim
    err4 = float(jnp.abs(dequantize(q4, jnp.float32) - w).max())
    assert err8 < err4 < 0.6
    np.testing.assert_allclose(np.asarray(matmul(x, q4)),
                               np.asarray(x @ dequantize(q4, jnp.float32)),
                               rtol=2e-2, atol=2e-2)


def test_quantized_model_forward_close():
    from generativeaiexamples_tpu.models import llama
    from generativeaiexamples_tpu.models.configs import LLAMA_TINY
    from generativeaiexamples_tpu.ops.quant import quantize_params

    params = llama.init_params(LLAMA_TINY, jax.random.key(0), jnp.float32)
    qparams = quantize_params(params, "int8")
    tokens = jnp.asarray([[1, 5, 9, 20]], jnp.int32)
    pos = jnp.arange(4, dtype=jnp.int32)[None, :]
    ref, _ = llama.apply(params, LLAMA_TINY, tokens, pos)
    got, _ = llama.apply(qparams, LLAMA_TINY, tokens, pos)
    # int8 weight-only keeps argmax parity on the tiny model
    assert (jnp.argmax(ref[0, -1]) == jnp.argmax(got[0, -1]))
    rel = float(jnp.abs(ref - got).max() / (jnp.abs(ref).max() + 1e-9))
    assert rel < 0.1, rel


def test_quantized_params_shard_on_mesh(cpu_devices):
    from generativeaiexamples_tpu.models import llama
    from generativeaiexamples_tpu.models.configs import LlamaConfig
    from generativeaiexamples_tpu.ops.quant import quantize_params
    from generativeaiexamples_tpu.parallel import (
        MeshPlan, llama_param_specs, make_mesh, shard_params)

    cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=8, num_kv_heads=8, head_dim=8,
                      max_position_embeddings=64)
    params = quantize_params(
        llama.init_params(cfg, jax.random.key(0), jnp.float32), "int8")
    mesh = make_mesh(MeshPlan(tp=8), cpu_devices)
    sharded = shard_params(params, mesh, llama_param_specs(cfg, mesh))
    tokens = jnp.zeros((1, 8), jnp.int32)
    pos = jnp.arange(8, dtype=jnp.int32)[None, :]
    with mesh:
        logits, _ = jax.jit(
            lambda p, t, x: llama.apply(p, cfg, t, x))(sharded, tokens, pos)
    assert logits.shape == (1, 8, 256)
    assert bool(jnp.isfinite(logits).all())


# ------------------------------------------------------- live HTTP server

@pytest.fixture(scope="module")
def served():
    """Dev engine + app served on a real port in a daemon thread, so the
    blocking `requests` clients get exercised for real."""
    engine, embed_service, name = build_services(
        model_type="dev", max_slots=2, max_input_length=64,
        max_output_length=32, world_size=1, dtype="float32")
    app = create_server_app(engine, embed_service, name)

    loop = asyncio.new_event_loop()
    started = threading.Event()
    port_box = {}

    def run():
        asyncio.set_event_loop(loop)

        async def start():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port_box["port"] = runner.addresses[0][1]
            started.set()

        loop.run_until_complete(start())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(30)
    engine.start()
    yield f"http://127.0.0.1:{port_box['port']}", engine
    loop.call_soon_threadsafe(loop.stop)
    engine.stop()


def test_openai_completions(served):
    import requests
    base, _ = served
    resp = requests.post(f"{base}/v1/completions", json={
        "prompt": "hello", "max_tokens": 8, "top_k": 1}, timeout=60)
    assert resp.ok, resp.text
    out = resp.json()
    assert out["object"] == "text_completion"
    assert out["choices"][0]["finish_reason"] in ("length", "eos", "stop")
    assert out["usage"]["completion_tokens"] >= 1


def test_openai_completions_stream(served):
    import requests
    base, _ = served
    with requests.post(f"{base}/v1/completions", json={
            "prompt": "hello", "max_tokens": 8, "top_k": 1, "stream": True},
            stream=True, timeout=60) as resp:
        assert resp.ok
        events = [ln for ln in resp.iter_lines(decode_unicode=True)
                  if ln.startswith("data:")]
    assert events[-1] == "data: [DONE]"
    deltas = [json.loads(e[5:]) for e in events[:-1]]
    assert all(d["object"] == "text_completion" for d in deltas)
    # deterministic: stream concat == non-stream text
    text = "".join(d["choices"][0]["text"] for d in deltas)
    import requests as rq
    full = rq.post(f"{base}/v1/completions", json={
        "prompt": "hello", "max_tokens": 8, "top_k": 1}, timeout=60).json()
    assert text == full["choices"][0]["text"]


def test_openai_chat_and_models(served):
    import requests
    base, _ = served
    resp = requests.post(f"{base}/v1/chat/completions", json={
        "messages": [{"role": "system", "content": "be brief"},
                     {"role": "user", "content": "hi"}],
        "max_tokens": 6, "top_k": 1}, timeout=60)
    assert resp.ok, resp.text
    msg = resp.json()["choices"][0]["message"]
    assert msg["role"] == "assistant"
    models = requests.get(f"{base}/v1/models", timeout=10).json()
    assert any(m["id"] == "llama-tiny" for m in models["data"])


def test_openai_embeddings(served):
    import requests
    base, _ = served
    resp = requests.post(f"{base}/v1/embeddings", json={
        "input": ["a cat", "a dog"], "input_type": "passage"}, timeout=60)
    assert resp.ok, resp.text
    data = resp.json()["data"]
    assert len(data) == 2
    assert len(data[0]["embedding"]) == 64  # encoder-tiny hidden size


def test_triton_shim_generate_and_stream(served):
    from generativeaiexamples_tpu.serving.client import TritonShimClient
    base, _ = served
    client = TritonShimClient(base, model_name="llama-tiny")
    client.wait_ready(timeout=10)
    text = client.generate("hello", max_tokens=8, top_k=1)
    assert isinstance(text, str)
    chunks = list(client.generate_stream("hello", max_tokens=8, top_k=1))
    assert "".join(chunks) == text
    # 'ensemble' alias works (reference clients default to it)
    assert isinstance(TritonShimClient(base).generate("hi", max_tokens=4,
                                                      top_k=1), str)


def test_triton_shim_validation(served):
    import requests
    base, _ = served
    resp = requests.post(f"{base}/v2/models/nope/generate",
                         json={"text_input": "x"}, timeout=10)
    assert resp.status_code == 404
    resp = requests.post(f"{base}/v2/models/llama-tiny/generate",
                         json={"text_input": ""}, timeout=10)
    assert resp.status_code == 400
    resp = requests.post(f"{base}/v2/models/llama-tiny/generate",
                         json={"text_input": "x", "beam_width": 4}, timeout=10)
    assert resp.status_code == 400
    # scalar-wrapped triton-style inputs unwrap
    resp = requests.post(f"{base}/v2/models/llama-tiny/generate",
                         json={"text_input": ["hi"], "max_tokens": [[4]],
                               "top_k": [1]}, timeout=60)
    assert resp.ok


def test_health_and_metrics(served):
    import requests
    base, _ = served
    health = requests.get(f"{base}/health", timeout=10).json()
    assert health["status"] == "ok" and health["model"] == "llama-tiny"
    metrics = requests.get(f"{base}/metrics", timeout=10).text
    assert "serve_completion_requests_total" in metrics


def test_azureml_model_dir_resolution(tmp_path, monkeypatch):
    """AzureML managed endpoints mount the model one level under
    AZUREML_MODEL_DIR (reference: model_server/__init__.py:36-69)."""
    from generativeaiexamples_tpu.serving.model_server import (
        resolve_azureml_model_dir)
    from generativeaiexamples_tpu.utils.errors import ConfigError

    # explicit path wins
    assert resolve_azureml_model_dir("/explicit") == "/explicit"
    # no env: passthrough
    monkeypatch.delenv("AZUREML_MODEL_DIR", raising=False)
    assert resolve_azureml_model_dir("") == ""
    # env set: resolve one level down
    (tmp_path / "llama-2-7b").mkdir()
    monkeypatch.setenv("AZUREML_MODEL_DIR", str(tmp_path))
    assert resolve_azureml_model_dir("") == str(tmp_path / "llama-2-7b")
    # empty dir: loud failure
    empty = tmp_path / "llama-2-7b" / "nothing"
    empty.mkdir()
    monkeypatch.setenv("AZUREML_MODEL_DIR", str(empty))
    import pytest as _pytest
    with _pytest.raises(ConfigError):
        resolve_azureml_model_dir("")


def test_profiler_endpoints(tmp_path, monkeypatch):
    """On-demand jax.profiler trace capture through the serving API
    (SURVEY §5).

    Root cause of the former tier-1 "stop_trace hang": on CPU-only jax
    builds without tensorflow installed, the FIRST ``start_trace`` of
    the process pays a one-shot ~25-30 s python-hooks init (XLA's
    profiler probes ``tensorflow.python.profiler.trace`` and logs
    "Can't import tensorflow" — measured 24-29 s here, 0.0 s on every
    later start). The old 10 s client timeout expired inside that init,
    abandoned the HTTP call mid-start, and the suite then sat on the
    server's wedged-looking executor thread. The capture is bounded two
    ways now: the server's PR-5 ``PROFILER_TIMEOUT_S`` path turns a
    genuinely wedged profiler into a 504 (which this test records as a
    skip, not a hang), and the client timeouts cover the measured
    one-shot init cost."""
    import glob as _glob
    import threading as _threading

    import pytest as _pytest

    # Bound the server-side start/stop executor calls below the tier-1
    # suite budget; the 100 s client timeouts sit just above it.
    monkeypatch.setenv("PROFILER_TIMEOUT_S", "90")

    import jax as _jax
    import jax.numpy as _jnp
    import requests as _requests
    from aiohttp import web as _web

    from generativeaiexamples_tpu.engine import Engine, EngineConfig
    from generativeaiexamples_tpu.models import llama as _llama
    from generativeaiexamples_tpu.models.configs import LLAMA_TINY
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
    from generativeaiexamples_tpu.serving.model_server import (
        create_server_app)

    params = _llama.init_params(LLAMA_TINY, _jax.random.key(0), _jnp.float32)
    engine = Engine(params, LLAMA_TINY, ByteTokenizer(), EngineConfig(
        max_slots=2, max_input_length=64, max_output_length=32,
        prefill_buckets=(32, 64), dtype="float32", page_size=16,
        kv_pool_tokens=None, steps_per_round=4))
    app = create_server_app(engine, None, "tiny")

    import asyncio as _asyncio
    loop = _asyncio.new_event_loop()
    box = {}
    started = _threading.Event()

    def run():
        _asyncio.set_event_loop(loop)

        async def boot():
            runner = _web.AppRunner(app)
            await runner.setup()
            site = _web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            box["port"] = runner.addresses[0][1]
        loop.run_until_complete(boot())
        started.set()
        loop.run_forever()

    _threading.Thread(target=run, daemon=True).start()
    started.wait(30)
    base = f"http://127.0.0.1:{box['port']}"

    trace_dir = str(tmp_path / "trace")
    # 100 s client timeout > the 90 s server bound: the slow path is the
    # SERVER's to bound (504), never an abandoned client socket.
    r = _requests.post(f"{base}/profiler/start", json={"dir": trace_dir},
                       timeout=100)
    if r.status_code == 504:
        _pytest.skip("jax.profiler.start_trace exceeded PROFILER_TIMEOUT_S "
                     "on this build (CPU python-hooks init wedged beyond "
                     "its usual ~30 s) — the 504 path worked; profiler "
                     "capture itself is unavailable here")
    assert r.ok and r.json()["status"] == "tracing"
    # double-start conflicts
    assert _requests.post(f"{base}/profiler/start", timeout=10
                          ).status_code == 409
    # do some device work under the trace
    _jnp.ones((64, 64)).sum().block_until_ready()
    r = _requests.post(f"{base}/profiler/stop", timeout=100)
    if r.status_code == 504:
        _pytest.skip("jax.profiler.stop_trace exceeded PROFILER_TIMEOUT_S "
                     "on this build — bounded to a 504 instead of wedging "
                     "the suite")
    assert r.ok and r.json()["dir"] == trace_dir
    assert _glob.glob(f"{trace_dir}/**/*.pb*", recursive=True) or \
        _glob.glob(f"{trace_dir}/**/*.json*", recursive=True)
    assert _requests.post(f"{base}/profiler/stop", timeout=10
                          ).status_code == 409
    loop.call_soon_threadsafe(loop.stop)
    engine.stop()


def test_jobs_api_202_poll_contract(tmp_path):
    """Submit-then-poll generation (the NVCF 202 semantics of the
    reference's cloud connector, nv_aiplay.py:222-239)."""
    import asyncio as _asyncio
    import threading as _threading

    import jax as _jax
    import jax.numpy as _jnp
    import requests as _requests
    from aiohttp import web as _web

    from generativeaiexamples_tpu.engine import Engine, EngineConfig
    from generativeaiexamples_tpu.models import llama as _llama
    from generativeaiexamples_tpu.models.configs import LLAMA_TINY
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
    from generativeaiexamples_tpu.serving.client import JobsClient
    from generativeaiexamples_tpu.serving.model_server import (
        create_server_app)

    params = _llama.init_params(LLAMA_TINY, _jax.random.key(0), _jnp.float32)
    engine = Engine(params, LLAMA_TINY, ByteTokenizer(), EngineConfig(
        max_slots=2, max_input_length=64, max_output_length=64,
        prefill_buckets=(32, 64), dtype="float32", page_size=16,
        kv_pool_tokens=None, steps_per_round=4))
    app = create_server_app(engine, None, "tiny")

    loop = _asyncio.new_event_loop()
    box = {}
    started = _threading.Event()

    def run():
        _asyncio.set_event_loop(loop)

        async def boot():
            runner = _web.AppRunner(app)
            await runner.setup()
            site = _web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            box["port"] = runner.addresses[0][1]
        loop.run_until_complete(boot())
        started.set()
        loop.run_forever()

    _threading.Thread(target=run, daemon=True).start()
    started.wait(30)
    base = f"http://127.0.0.1:{box['port']}"
    client = JobsClient(base, timeout=240)

    # end-to-end: submit (may 200 fast-path or 202) then poll to done
    text = client.generate("job prompt", max_tokens=8, top_k=1)
    assert isinstance(text, str) and text

    # explicit 202 path: first request compiles, so poll sees "running"
    job = client.submit("second prompt", max_tokens=32, top_k=1)
    assert job["status"] in ("running", "done")
    final = client.wait(job["id"]) if job["status"] != "done" else job
    assert final["status"] == "done"
    assert final["finish_reason"] in ("length", "eos", "stop")

    # unknown id -> 404; validation -> 422
    assert _requests.get(f"{base}/v1/jobs/nope", timeout=10
                         ).status_code == 404
    assert _requests.post(f"{base}/v1/jobs", json={}, timeout=10
                          ).status_code == 422

    # model registry: exact + substring resolution (the reference
    # connector's get_available_models/_get_invoke_url, nv_aiplay.py:287)
    assert "tiny" in client.available_models()
    assert client.resolve_model("tiny") == "tiny"
    assert client.resolve_model("tin") == "tiny"
    import pytest as _pytest
    with _pytest.raises(ValueError, match="unknown model"):
        client.resolve_model("gpt-17")

    # the LangChain wrapper rides the same poll loop
    from generativeaiexamples_tpu.integrations.langchain_tpu import (
        TpuJobsLLM)
    llm = TpuJobsLLM(server_url=base, model_name="tiny", tokens=8,
                     timeout=240)
    out = llm.invoke("langchain job prompt")
    assert isinstance(out, str) and out

    loop.call_soon_threadsafe(loop.stop)
    engine.stop()


def test_build_services_long_prompt_cap():
    """--max-prefill-bucket + --page-size plumb through build_services to
    the engine: a dev server with a 32-token cap (and matching 32-token
    pages) serves a prompt far beyond it via the chunked paged-prefill
    admission."""
    from generativeaiexamples_tpu.engine import SamplingParams
    from generativeaiexamples_tpu.serving.model_server import build_services

    engine, _, _ = build_services(
        model_type="dev", max_slots=2, max_input_length=128,
        max_output_length=16, dtype="float32", with_embedder=False,
        max_prefill_bucket=32, page_size=32)
    assert engine._buckets[-1] == 32
    with engine:
        s = engine.submit(list(range(3, 103)),   # 100 tokens > bucket 32
                          SamplingParams(max_tokens=6, top_k=1,
                                         ignore_eos=True))
        s.text()
    assert s.finish_reason == "length" and len(s.token_ids) == 6


def test_build_services_rejects_sub_page_prefill_cap():
    """A max_prefill_bucket that is not a page multiple >= page_size is
    invalid engine geometry (buckets scatter into whole pages) and must
    fail loudly at build time, never silently round up (reference errors
    on impossible engine shapes, model_server/__init__.py:103-110)."""
    import pytest

    from generativeaiexamples_tpu.serving.model_server import build_services
    from generativeaiexamples_tpu.utils.errors import ConfigError

    # below one (default 128-token) page
    with pytest.raises(ConfigError, match="max_prefill_bucket"):
        build_services(model_type="dev", max_slots=2, max_input_length=128,
                       max_output_length=16, dtype="float32",
                       with_embedder=False, max_prefill_bucket=32)
    # not a multiple of the explicit page size
    with pytest.raises(ConfigError, match="multiple of page_size"):
        build_services(model_type="dev", max_slots=2, max_input_length=128,
                       max_output_length=16, dtype="float32",
                       with_embedder=False, max_prefill_bucket=48,
                       page_size=32)
    # nonsense page sizes fail at config construction, before any
    # checkpoint work (validation lives in EngineConfig.__post_init__)
    from generativeaiexamples_tpu.engine.engine import EngineConfig
    for bad in (-16, 0):
        with pytest.raises(ConfigError, match="page_size"):
            EngineConfig(page_size=bad)
