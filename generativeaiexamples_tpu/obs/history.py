"""In-process metric history: a fixed-interval ring sampler over the
metrics registry.

Every surface in this repo publishes point-in-time state — ``/metrics``
is a scrape, ``/debug/fleet`` a snapshot, the flight/round recorders
bounded rings — so "what happened in the last ten minutes?" has no
answer unless an external scraper happened to be attached. The
``MetricHistory`` sampler closes that gap in-process: every
``HISTORY_INTERVAL_S`` it snapshots the registry (every gauge value,
every counter's cumulative value so deltas/rates derive at query time)
into a ``deque`` ring bounded by ``HISTORY_WINDOW_S``, the same
lock-light shape as the round ring (``obs/rounds.py``): one lock guards
ring mutation only, samples are immutable once appended, readers copy
under the lock and aggregate outside it.

Served as ``GET /debug/history?metrics=<glob>&window=<s>`` on the chain
server, the model server, and the router — windowed aggregates
(last/min/max/avg; counters additionally delta + rate) per series.

Arming is a deployment decision: ``HISTORY_INTERVAL_S=0`` makes the
layer INERT — no sampler thread, no alert ticks downstream, no disk
writes — pinned by tests/test_history_alerts.py. The sampler is also
where the alert engine (``obs/alerts.py``) ticks from and what the
incident black-box (``obs/incidents.py``) freezes.

This module additionally hosts the one shared ``?limit=``/``?window=``
query parser every ``/debug/*`` endpoint uses (non-integer → 400 with
the repo's JSON error body + ``X-Request-ID``), replacing the
hand-rolled per-endpoint ``int(request.query...)`` parses.
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading
import time
from typing import Callable, Optional, Sequence

from ..utils.logging import get_logger
from . import metrics as obs_metrics

logger = get_logger(__name__)

#: Ring span: how far back /debug/history (and incident bundles) can
#: look. Interval: sampling period; 0 disarms the whole retained-
#: telemetry layer (sampler, alerts, incident capture).
HISTORY_WINDOW_S = float(os.environ.get("HISTORY_WINDOW_S", "600"))
HISTORY_INTERVAL_S = float(os.environ.get("HISTORY_INTERVAL_S", "5.0"))


# --------------------------------------------------------------- query parse


def query_int(request, name: str, default: int, *, minimum: int = 0,
              maximum: Optional[int] = None) -> int:
    """Parse an integer query parameter uniformly across every
    ``/debug/*`` endpoint (all three servers): absent/empty → default;
    non-integer or out of range → 400 with the repo's JSON error body
    (``{"error": {"type", "message"}, "request_id"}``) and the
    ``X-Request-ID`` header, matching the error contract of the work
    endpoints instead of a bare-text 400."""
    raw = request.query.get(name, "")
    if raw == "":
        return default
    try:
        value = int(raw)
    except (TypeError, ValueError):
        raise _bad_query(request, name, raw, "must be an integer")
    if value < minimum:
        raise _bad_query(request, name, raw, f"must be >= {minimum}")
    if maximum is not None and value > maximum:
        raise _bad_query(request, name, raw, f"must be <= {maximum}")
    return value


def _bad_query(request, name: str, raw: str, why: str):
    from aiohttp import web

    from .flight import adopt_request_id

    rid = adopt_request_id(request.headers)
    body = {"error": {"type": "bad_query",
                      "message": f"query parameter {name}={raw!r} {why}"},
            "request_id": rid}
    return web.HTTPBadRequest(text=json.dumps(body),
                              content_type="application/json",
                              headers={"X-Request-ID": rid})


# ------------------------------------------------------------------ sampler


class MetricHistory:
    """Fixed-interval ring of registry snapshots with windowed
    aggregation.

    ``interval_s <= 0`` builds a permanently-disabled history: ``start``
    is a no-op, ``enabled`` is False, queries answer
    ``{"enabled": false}`` — the parity-pinned inert configuration.
    """

    def __init__(self, registry: obs_metrics.Registry = obs_metrics.REGISTRY,
                 window_s: float = None, interval_s: float = None,
                 pre_sample: Sequence[Callable[[], None]] = ()):
        self.registry = registry
        self.window_s = HISTORY_WINDOW_S if window_s is None else \
            float(window_s)
        self.interval_s = HISTORY_INTERVAL_S if interval_s is None else \
            float(interval_s)
        #: hooks run before each snapshot (mirror engine stats, process
        #: stats) so history carries them even between /metrics scrapes.
        self.pre_sample = list(pre_sample)
        #: called with this history after every sample — the alert
        #: engine's tick point.
        self.on_sample: list[Callable[["MetricHistory"], None]] = []
        from collections import deque
        cap = 2
        if self.enabled:
            cap = max(2, int(self.window_s / self.interval_s) + 1)
        self._ring: "deque[tuple[float, float, dict[str, float]]]" = \
            deque(maxlen=cap)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.interval_s > 0

    @property
    def samples(self) -> int:
        return len(self._ring)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        """Spawn the sampler thread. A no-op when disabled (the inert
        pin: HISTORY_INTERVAL_S=0 must start NO thread) or already
        running."""
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metric-history")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _loop(self) -> None:
        # Sample immediately so short-lived processes still leave a
        # first snapshot, then on the interval.
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — sampling must never die
                logger.debug("history sample failed", exc_info=True)
            self._stop.wait(self.interval_s)

    # ------------------------------------------------------------- sampling

    def sample_once(self) -> dict[str, float]:
        """Take one snapshot now (also the deterministic tick tests and
        the bench overhead arm drive). Runs pre_sample hooks, appends
        the immutable sample under the ring lock, then notifies
        on_sample subscribers OUTSIDE the lock."""
        for hook in self.pre_sample:
            try:
                hook()
            except Exception:  # noqa: BLE001
                logger.debug("history pre_sample hook failed",
                             exc_info=True)
        values = self.registry.snapshot()
        sample = (time.time(), time.monotonic(), values)
        with self._lock:
            self._ring.append(sample)
        for cb in list(self.on_sample):
            try:
                cb(self)
            except Exception:  # noqa: BLE001
                logger.debug("history on_sample subscriber failed",
                             exc_info=True)
        return values

    def window(self, window_s: Optional[float] = None
               ) -> list[tuple[float, float, dict[str, float]]]:
        """Samples within the trailing ``window_s`` (default: the whole
        ring), oldest first. Samples are immutable — callers may hold
        them without copying."""
        with self._lock:
            samples = list(self._ring)
        if window_s is None or not samples:
            return samples
        horizon = samples[-1][1] - float(window_s)
        return [s for s in samples if s[1] >= horizon]

    # ---------------------------------------------------------- aggregation

    def _kind(self, key: str, kinds: dict[str, str]) -> str:
        """counter vs gauge for one snapshot key. Labeled keys carry the
        base name before ``{``; histogram samples surface as
        ``_count``/``_sum`` — both cumulative, i.e. counter-like."""
        base = key.split("{", 1)[0]
        kind = kinds.get(base)
        if kind is not None:
            return kind
        for suffix in ("_count", "_sum"):
            if base.endswith(suffix) and \
                    kinds.get(base[: -len(suffix)]) == "histogram":
                return "counter"
        return "gauge"

    def query(self, metrics: str = "", window_s: Optional[float] = None
              ) -> dict:
        """Windowed aggregates per series: last/min/max/avg for every
        matching key; counters (and histogram _count/_sum samples)
        additionally ``delta`` (reset-aware) and ``rate_per_s``."""
        if not self.enabled:
            return {"enabled": False, "interval_s": self.interval_s,
                    "window_s": self.window_s, "samples": 0, "span_s": 0.0,
                    "series": {}}
        samples = self.window(window_s)
        out = {"enabled": True, "interval_s": self.interval_s,
               "window_s": self.window_s, "samples": len(samples),
               "span_s": round(samples[-1][1] - samples[0][1], 3)
               if len(samples) >= 2 else 0.0,
               "series": {}}
        if not samples:
            return out
        kinds = self.registry.kinds()
        keys = set()
        for _, _, values in samples:
            keys.update(values)
        if metrics:
            keys = {k for k in keys if fnmatch.fnmatchcase(k, metrics)
                    or fnmatch.fnmatchcase(k.split("{", 1)[0], metrics)}
        span = out["span_s"]
        for key in sorted(keys):
            points = [(mono, values[key]) for _, mono, values in samples
                      if key in values]
            if not points:
                continue
            vals = [v for _, v in points]
            entry = {"kind": self._kind(key, kinds),
                     "points": len(points),
                     "last": vals[-1],
                     "min": min(vals), "max": max(vals),
                     "avg": round(sum(vals) / len(vals), 6)}
            if entry["kind"] == "counter":
                # Reset-aware delta: a process restart drops the
                # cumulative value; count only forward movement.
                delta = sum(max(0.0, b - a)
                            for a, b in zip(vals, vals[1:]))
                entry["delta"] = round(delta, 6)
                entry["rate_per_s"] = round(delta / span, 6) if span > 0 \
                    else 0.0
            out["series"][key] = entry
        return out

    def raw(self, window_s: Optional[float] = None,
            metrics: str = "") -> list[dict]:
        """The window itself — wall-clock stamped samples for the
        incident bundle (values optionally glob-filtered to keep
        bundles bounded)."""
        rows = []
        for wall, mono, values in self.window(window_s):
            if metrics:
                values = {k: v for k, v in values.items()
                          if fnmatch.fnmatchcase(k, metrics)
                          or fnmatch.fnmatchcase(k.split("{", 1)[0],
                                                 metrics)}
            rows.append({"t": round(wall, 3), "mono": round(mono, 3),
                         "values": values})
        return rows


# ------------------------------------------------------------ HTTP handler


def debug_history_response(request, history: Optional[MetricHistory]):
    """Shared ``GET /debug/history`` body for all three servers:
    ``?metrics=<glob>`` filters series, ``?window=<s>`` trims the
    aggregation window (default: the whole ring)."""
    from aiohttp import web

    if history is None:
        return web.json_response({"enabled": False, "series": {},
                                  "samples": 0})
    window = query_int(request, "window", 0, minimum=0)
    metrics_glob = request.query.get("metrics", "")
    return web.json_response(
        history.query(metrics=metrics_glob,
                      window_s=float(window) if window else None))
