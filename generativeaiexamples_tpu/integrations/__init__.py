"""Published connector classes for third-party orchestration frameworks.

The reference ships LangChain connector classes as its public integration
surface (reference: integrations/langchain/llms/triton_trt_llm.py:48
``TensorRTLLM(LLM)``, nemo_infer.py, embeddings/nemo_embed.py). The TPU
stack's equivalents:

- ``langchain_tpu``  — ``TpuLLM`` (LangChain ``LLM``) and
  ``TpuEmbeddings`` (LangChain ``Embeddings``) over the serving stack's
  gRPC or OpenAI-compatible HTTP endpoints.
- ``llamaindex_tpu`` — ``TpuLlamaIndexLLM`` (LlamaIndex ``CustomLLM``)
  and ``TpuLlamaIndexEmbedding`` over the same endpoints.

Both modules import-degrade: when langchain/llama_index are not
installed, the classes derive from small structural stand-ins with the
same method contracts, so the connector logic stays importable and
testable anywhere (the reference's connectors hard-require their
frameworks).
"""
