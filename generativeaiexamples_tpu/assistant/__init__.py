"""Multimodal assistant: office-document RAG with memory + guardrails.

The TPU-stack version of the reference's experimental multimodal
assistant (reference: experimental/multimodal_assistant/ — Streamlit app
over PPTX/PDF with custom parsers, Milvus/Qdrant retrievers, conversation
memory, an LLM fact-check guardrail, and feedback capture). Here it is a
first-class ``BaseExample``: the existing chain server and web frontend
serve it (``--example assistant``), and its pieces are importable:

  parsers.py     self-contained PPTX/DOCX extraction (zip + XML — no
                 python-pptx/docx wheels needed) incl. slide notes and
                 an image inventory per slide
  memory.py      bounded conversation memory folded into the prompt
  guardrails.py  LLM fact-check of answers against retrieved evidence
  feedback.py    JSONL feedback capture
  assistant.py   the MultimodalAssistant example class
"""

from .assistant import MultimodalAssistant
from .feedback import FeedbackStore
from .guardrails import fact_check
from .memory import ConversationMemory
from .parsers import read_docx, read_pptx

__all__ = ["MultimodalAssistant", "ConversationMemory", "fact_check",
           "FeedbackStore", "read_pptx", "read_docx"]
