"""Encoder golden tests vs transformers BertModel + embedding service."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.embed import HashEmbedder, get_embedder
from generativeaiexamples_tpu.models import encoder as enc
from generativeaiexamples_tpu.models.configs import ENCODER_TINY

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def hf_bert_and_params():
    hf_cfg = transformers.BertConfig(
        vocab_size=ENCODER_TINY.vocab_size,
        hidden_size=ENCODER_TINY.hidden_size,
        intermediate_size=ENCODER_TINY.intermediate_size,
        num_hidden_layers=ENCODER_TINY.num_layers,
        num_attention_heads=ENCODER_TINY.num_heads,
        max_position_embeddings=ENCODER_TINY.max_position_embeddings,
        type_vocab_size=ENCODER_TINY.type_vocab_size,
        layer_norm_eps=ENCODER_TINY.layer_norm_eps,
        hidden_act="gelu",
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.BertModel(hf_cfg).eval()
    params = enc.params_from_named_tensors(iter(model.state_dict().items()),
                                           ENCODER_TINY)
    return model, params


def test_encoder_matches_hf(hf_bert_and_params):
    model, params = hf_bert_and_params
    rng = np.random.default_rng(0)
    B, S = 2, 12
    tokens = rng.integers(0, ENCODER_TINY.vocab_size, (B, S), dtype=np.int32)
    mask = np.ones((B, S), np.int32)
    mask[1, 8:] = 0

    ours = enc.apply(params, ENCODER_TINY, jnp.asarray(tokens),
                     jnp.asarray(mask))
    with torch.no_grad():
        theirs = model(torch.from_numpy(tokens).long(),
                       attention_mask=torch.from_numpy(mask).long()
                       ).last_hidden_state.numpy()
    # Positions under the mask are free to differ; compare valid ones.
    np.testing.assert_allclose(np.asarray(ours)[0], theirs[0],
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(ours)[1, :8], theirs[1, :8],
                               rtol=2e-4, atol=2e-4)


def test_mean_pool_masked():
    hidden = jnp.asarray(np.arange(24, dtype=np.float32).reshape(1, 4, 6))
    mask = jnp.asarray([[1, 1, 0, 0]])
    pooled = enc.mean_pool(hidden, mask, normalize=False)
    np.testing.assert_allclose(np.asarray(pooled)[0],
                               np.arange(24).reshape(4, 6)[:2].mean(0))


def test_embedding_service_roundtrip():
    svc = get_embedder("tpu-jax", "encoder-tiny")
    docs = svc.embed_documents(["the cat sat", "quantum computing"])
    q = svc.embed_query("a cat was sitting")
    assert docs.shape == (2, ENCODER_TINY.hidden_size)
    assert q.shape == (ENCODER_TINY.hidden_size,)
    # normalized
    np.testing.assert_allclose(np.linalg.norm(docs, axis=-1), 1.0, rtol=1e-4)


def test_embedding_batch_padding_invariance():
    """Embedding a text alone vs inside a batch must agree (mask/bucket
    correctness)."""
    svc = get_embedder("tpu-jax", "encoder-tiny")
    alone = svc.embed_documents(["hello world"])[0]
    batched = svc.embed_documents(["hello world", "x", "yy", "zzz"])[0]
    np.testing.assert_allclose(alone, batched, rtol=1e-4, atol=1e-5)


def test_hash_embedder_similarity():
    emb = HashEmbedder(dim=128)
    a = emb.embed_query("retrieval augmented generation")
    b = emb.embed_query("retrieval augmented generation!")
    c = emb.embed_query("completely different topic")
    assert a @ b > a @ c
