"""Bounded conversation memory (reference: experimental/
multimodal_assistant/utils/memory.py — chat history folded into the
prompt so follow-up questions resolve pronouns against earlier turns)."""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass


@dataclass
class Turn:
    question: str
    answer: str


class ConversationMemory:
    def __init__(self, max_turns: int = 6, max_chars: int = 2000):
        self._turns: deque[Turn] = deque(maxlen=max_turns)
        self.max_chars = max_chars

    def add(self, question: str, answer: str) -> None:
        self._turns.append(Turn(question, answer))

    def clear(self) -> None:
        self._turns.clear()

    def __len__(self) -> int:
        return len(self._turns)

    def render(self) -> str:
        """Newest-last history string, trimmed to the char budget by
        dropping oldest turns first."""
        lines = [f"User: {t.question}\nAssistant: {t.answer}"
                 for t in self._turns]
        while lines and sum(len(ln) + 1 for ln in lines) > self.max_chars:
            lines.pop(0)
        return "\n".join(lines)
