"""Deploy-layer tests: helm renderer, reconciler, drain, CRD + sample CR.

The reference covers this tier with Ginkgo specs over gomock/fake clients
plus envtest (reference: pkg/filter/filter_test.go, pkg/storage/
storage_test.go, controllers/suite_test.go:50-60). Equivalent here:
template-engine semantics pinned against hand-computed Helm behavior,
golden renders of both first-party charts, and reconciler specs on the
InMemoryKube fake (install order, owner labels, unchanged-skip, upgrade
diffs, prune, error->requeue, delete drain)."""

import json
import os

import pytest
import yaml

from generativeaiexamples_tpu.deploy.helm import (Chart, ChartError,
                                                  deep_merge, load_chart,
                                                  render_chart)
from generativeaiexamples_tpu.deploy.kube import (ConflictError,
                                                  InMemoryKube,
                                                  RejectedError, drain_order,
                                                  iter_json_stream, obj_key)
from generativeaiexamples_tpu.deploy.operator import PipelineOperator
from generativeaiexamples_tpu.deploy.types import (API_VERSION, KIND,
                                                   OWNED_BY_LABEL,
                                                   HelmPackage, HelmPipeline)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHARTS = os.path.join(REPO, "deploy", "helm")
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "deploy")


# ------------------------------------------------------------ helm engine

def _render_one(template: str, values: dict, release="r", ns="ns"):
    chart = Chart(name="t", version="1.0.0", path="",
                  values=values, templates={"t.yaml": template})
    return render_chart(chart, release, ns)


def test_template_values_and_builtins():
    objs = _render_one(
        "a: {{ .Values.x.y }}\n"
        "b: {{ .Release.Name }}-{{ .Release.Namespace }}\n"
        "c: {{ .Chart.Name }}@{{ .Chart.Version }}\n",
        {"x": {"y": 7}})
    assert objs == [{"a": 7, "b": "r-ns", "c": "t@1.0.0"}]


def test_template_pipes_match_helm_semantics():
    objs = _render_one(
        "a: {{ .Values.miss | default 5 }}\n"
        "b: {{ .Values.s | quote }}\n"
        "c: {{ .Values.n | int }}\n",
        {"s": 'say "hi"', "n": "42"})
    assert objs == [{"a": 5, "b": 'say "hi"', "c": 42}]


def test_template_toyaml_nindent():
    objs = _render_one(
        "outer:\n  inner:{{ .Values.m | toYaml | nindent 4 }}\n",
        {"m": {"k1": "v1", "k2": 2}})
    assert objs == [{"outer": {"inner": {"k1": "v1", "k2": 2}}}]


def test_template_if_else_truthiness():
    tpl = ("{{- if .Values.flag }}\nkind: A\n{{- else }}\nkind: B\n"
           "{{- end }}\n")
    assert _render_one(tpl, {"flag": True})[0]["kind"] == "A"
    # Helm truthiness: absent / empty / 0 / False are all false
    for falsy in ({}, {"flag": False}, {"flag": 0}, {"flag": ""},
                  {"flag": {}}):
        assert _render_one(tpl, falsy)[0]["kind"] == "B"


def test_template_range():
    objs = _render_one(
        "args:\n{{- range .Values.items }}\n  - {{ . }}\n{{- end }}\n",
        {"items": ["a", "b"]})
    assert objs == [{"args": ["a", "b"]}]


def test_template_errors():
    with pytest.raises(ChartError):
        _render_one("a: {{ .Values.missing }}", {})
    with pytest.raises(ChartError):
        _render_one("{{- if .Values.x }}\nno end\n", {"x": 1})


def test_deep_merge_helm_values_semantics():
    base = {"a": {"x": 1, "y": 2}, "b": [1], "c": 3}
    over = {"a": {"y": 9}, "b": [2, 3]}
    assert deep_merge(base, over) == {"a": {"x": 1, "y": 9},
                                      "b": [2, 3], "c": 3}


# --------------------------------------------------------- golden renders

@pytest.mark.parametrize("name,expected_kinds", [
    ("rag-llm-pipeline", {"Deployment", "Service"}),
    ("tpu-llm-operator", {"Deployment", "ServiceAccount", "ClusterRole",
                          "ClusterRoleBinding"}),
])
def test_chart_golden_render(name, expected_kinds):
    """Pin the full render of the shipped charts (regression goldens),
    plus structural sanity every k8s object needs."""
    chart = load_chart(os.path.join(CHARTS, name))
    objs = render_chart(chart, "golden", "golden-ns")
    for obj in objs:
        assert obj.get("apiVersion"), obj
        assert obj.get("kind"), obj
        assert obj.get("metadata", {}).get("name"), obj
    assert {o["kind"] for o in objs} == expected_kinds
    with open(os.path.join(FIXTURES, f"{name}.golden.json")) as f:
        golden = json.load(f)
    assert json.loads(json.dumps(objs, sort_keys=True)) == golden


def test_jupyter_requires_token():
    """jupyter.enabled without a token must refuse to render — an
    unauthenticated NodePort JupyterLab is remote code execution."""
    chart = load_chart(os.path.join(CHARTS, "rag-llm-pipeline"))
    with pytest.raises(ChartError, match="jupyter.token"):
        render_chart(chart, "r", "ns", values={"jupyter": {"enabled": True}})
    objs = render_chart(chart, "r", "ns", values={
        "jupyter": {"enabled": True, "token": "s3cret"}})
    jup = [o for o in objs if "jupyter" in o["metadata"]["name"]]
    assert {o["kind"] for o in jup} == {"Secret", "Deployment", "Service"}
    # the token must ride the Secret + env var, never a literal arg
    # (args are readable via the pod spec and node process list)
    secret = next(o for o in jup if o["kind"] == "Secret")
    assert secret["stringData"]["token"] == "s3cret"
    container = next(o for o in jup if o["kind"] == "Deployment")[
        "spec"]["template"]["spec"]["containers"][0]
    assert "--NotebookApp.token=$(JUPYTER_TOKEN)" in container["args"]
    assert not any("s3cret" in a for a in container["args"])
    env = {e["name"]: e for e in container["env"]}
    ref = env["JUPYTER_TOKEN"]["valueFrom"]["secretKeyRef"]
    assert ref == {"name": "r-jupyter-token", "key": "token"}
    # disabled by default
    assert not any("jupyter" in o["metadata"]["name"]
                   for o in render_chart(chart, "r", "ns"))


def test_chart_values_toggle_components():
    chart = load_chart(os.path.join(CHARTS, "rag-llm-pipeline"))
    full = render_chart(chart, "r", "ns")
    trimmed = render_chart(chart, "r", "ns",
                           values={"milvus": {"enabled": False}})
    names = {o["metadata"]["name"] for o in trimmed}
    assert len(trimmed) < len(full)
    assert not any("milvus" in n for n in names)


# ------------------------------------------------------------- reconciler

def _pipeline(values=None, releases=("rag",)):
    pkgs = [HelmPackage(repo_name="local", repo_url=f"file://{CHARTS}",
                        chart_name="rag-llm-pipeline", namespace="ns",
                        release_name=rel, values=dict(values or {}))
            for rel in releases]
    return HelmPipeline(name="pipe", namespace="ns", packages=pkgs)


def test_reconcile_installs_objects_with_owner_labels():
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    result = op.reconcile(_pipeline())
    assert not result.requeue and result.error is None
    assert result.installed == ["rag"]
    owned = kube.list_labeled(OWNED_BY_LABEL, "pipe")
    # every rendered object carries the owner label (state CM excluded
    # from the render but also labeled)
    assert len(owned) >= 12
    assert all(o["metadata"]["labels"][OWNED_BY_LABEL] == "pipe"
               for o in owned)


def test_reconcile_package_order_is_pipeline_order():
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    op.reconcile(_pipeline(releases=("first", "second")))
    creates = [k for v, k in kube.events if v == "create"]
    firsts = [i for i, k in enumerate(creates) if "first-" in k]
    seconds = [i for i, k in enumerate(creates) if "second-" in k]
    assert firsts and seconds and max(firsts) < min(seconds)


def test_reconcile_unchanged_release_is_skipped():
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    op.reconcile(_pipeline())
    n_events = len(kube.events)
    result = op.reconcile(_pipeline())
    assert result.skipped == ["rag"] and result.installed == []
    # only the state ConfigMap and the CR status are re-written;
    # no workload churn
    new = kube.events[n_events:]
    assert all("helmpipeline-pipe-state" in key or verb.startswith("status")
               for verb, key in new)


def test_reconcile_upgrade_applies_diff_and_prunes():
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    op.reconcile(_pipeline())
    assert kube.get(("apps/v1", "Deployment", "ns", "rag-milvus-etcd"))
    result = op.reconcile(_pipeline(values={"milvus": {"enabled": False}}))
    assert result.installed == ["rag"]
    # milvus objects dropped by the new rendering are pruned
    assert kube.get(("apps/v1", "Deployment", "ns", "rag-milvus-etcd")) is None
    assert kube.get(("apps/v1", "Deployment", "ns", "rag-chain-server"))


def test_reconcile_error_aborts_walk_and_requeues():
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    pipe = _pipeline(releases=("ok",))
    pipe.packages.append(HelmPackage(
        repo_name="local", repo_url="file:///nowhere",
        chart_name="missing-chart", namespace="ns", release_name="broken"))
    pipe.packages.append(HelmPackage(
        repo_name="local", repo_url=f"file://{CHARTS}",
        chart_name="rag-llm-pipeline", namespace="ns",
        release_name="after"))
    result = op.reconcile(pipe)
    assert result.requeue
    assert "broken" in result.error
    assert result.installed == ["ok"]          # walk stopped at the error
    assert not kube.list_labeled(OWNED_BY_LABEL, "after")
    # earlier release state survives for the next (requeued) reconcile
    assert kube.get(("v1", "ConfigMap", "ns", "helmpipeline-pipe-state"))


def test_delete_drains_workloads_first():
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    op.reconcile(_pipeline())
    n = op.delete(_pipeline())
    assert n >= 12
    deletes = [k for v, k in kube.events if v == "delete"]
    dep_idx = [i for i, k in enumerate(deletes) if "/Deployment/" in k]
    svc_idx = [i for i, k in enumerate(deletes) if "/Service/" in k]
    assert dep_idx and svc_idx and max(dep_idx) < min(svc_idx)
    assert kube.objects == {}   # nothing left, state CM included


def _cr_status(kube, pipe):
    obj = kube.get((API_VERSION, KIND, pipe.namespace, pipe.name))
    return (obj or {}).get("status")


def test_reconcile_writes_cr_status():
    """The pass outcome lands on the CR's status subresource — phase per
    release, observedGeneration, Ready condition (the reference
    controller's status reporting, helmpipeline_controller.go:62-116)."""
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    pipe = _pipeline()
    kube.apply(pipe.to_manifest())
    op.reconcile(pipe)
    st = _cr_status(kube, pipe)
    assert st["observedGeneration"] == pipe.generation
    assert st["releases"]["rag"]["phase"] == "installed"
    assert st["releases"]["rag"]["objects"] >= 12
    assert st["conditions"][0] == {
        "type": "Ready", "status": "True", "reason": "Reconciled",
        "message": "1 installed, 0 unchanged"}
    op.reconcile(pipe)
    st = _cr_status(kube, pipe)
    assert st["releases"]["rag"]["phase"] == "unchanged"
    assert st["conditions"][0]["status"] == "True"


def test_reconcile_status_reports_error_and_pending():
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    pipe = _pipeline(releases=("ok",))
    pipe.packages.append(HelmPackage(
        repo_name="local", repo_url="file:///nowhere",
        chart_name="missing-chart", namespace="ns", release_name="broken"))
    pipe.packages.append(HelmPackage(
        repo_name="local", repo_url=f"file://{CHARTS}",
        chart_name="rag-llm-pipeline", namespace="ns",
        release_name="after"))
    kube.apply(pipe.to_manifest())
    op.reconcile(pipe)
    st = _cr_status(kube, pipe)
    assert st["releases"]["ok"]["phase"] == "installed"
    assert st["releases"]["broken"]["phase"] == "error"
    assert st["releases"]["after"]["phase"] == "pending"
    cond = st["conditions"][0]
    assert cond["status"] == "False" and cond["reason"] == "ReconcileError"
    assert "broken" in cond["message"]


def test_status_write_survives_missing_cr():
    """Reconcile must not crash when the CR vanished (deletion race) —
    the status write is best-effort."""
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    result = op.reconcile(_pipeline())  # CR never applied to the fake
    assert result.error is None
    assert ("status-miss",
            f"{API_VERSION}/{KIND}/ns/pipe") in kube.events


def test_fake_enforces_resource_version_conflict():
    """The fake carries apiserver optimistic-concurrency semantics so a
    controller bug that replays stale objects fails in tests, not prod."""
    kube = InMemoryKube()
    kube.apply({"apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "cm", "namespace": "ns"}})
    stored = kube.get(("v1", "ConfigMap", "ns", "cm"))
    rv = stored["metadata"]["resourceVersion"]
    kube.apply(json.loads(json.dumps(stored)))  # fresh rv: fine
    stale = json.loads(json.dumps(stored))
    stale["metadata"]["resourceVersion"] = rv  # now one behind
    with pytest.raises(ConflictError):
        kube.apply(stale)
    # rv-less apply is an SSA-style upsert (what the reconciler sends)
    kube.apply({"apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "cm", "namespace": "ns"}})


def test_apply_rejection_requeues_then_recovers():
    """An admission rejection mid-walk aborts with requeue and a False
    Ready condition; once the webhook clears, the next pass completes —
    no state corruption in between."""
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    pipe = _pipeline()
    kube.apply(pipe.to_manifest())
    kube.reject = (lambda obj: "denied by policy"
                   if obj.get("kind") == "Deployment" else None)
    result = op.reconcile(pipe)
    assert result.requeue and "denied by policy" in result.error
    st = _cr_status(kube, pipe)
    assert st["conditions"][0]["status"] == "False"
    kube.reject = None
    result = op.reconcile(pipe)
    assert not result.requeue and result.installed == ["rag"]
    assert _cr_status(kube, pipe)["conditions"][0]["status"] == "True"


def test_watch_404_raises_crd_missing_not_nonetype_loop(monkeypatch):
    """Regression (ADVICE r5): a 404 on the watch stream (CRD not yet
    installed) used to map to None like any GET miss, so the caller's
    iteration died with 'NoneType is not iterable' and the finally's
    resp.close() raised AttributeError — a confusing busy loop instead
    of the actual problem. Stream requests must raise the real cause;
    plain GET misses still map to None."""
    import io
    from urllib import error as urlerror

    from generativeaiexamples_tpu.deploy import apiserver as apimod

    client = apimod.ApiServerKube(base_url="http://fake.invalid", token="t")

    def raise_404(req, timeout=None, context=None):
        raise urlerror.HTTPError(req.full_url, 404, "not found", {},
                                 io.BytesIO(b"no helmpipelines here"))

    monkeypatch.setattr(apimod.urlrequest, "urlopen", raise_404)
    with pytest.raises(RuntimeError, match="CRD not installed"):
        list(client.watch(API_VERSION, KIND))
    assert client.get((API_VERSION, KIND, "default", "missing")) is None


def test_iter_json_stream_reassembles_watch_events():
    """kubectl --watch emits unframed concatenated JSON documents; the
    parser must reassemble them across arbitrary chunk boundaries."""
    events = [{"type": "ADDED", "object": {"metadata": {"name": "a"}}},
              {"type": "MODIFIED",
               "object": {"metadata": {"name": "b"},
                          "spec": {"pipeline": []}}},
              {"type": "DELETED", "object": {"metadata": {"name": "c"}}}]
    text = "".join(json.dumps(e, indent=2) + "\n" for e in events)
    # 7-byte chunks: every document spans many chunks
    chunks = [text[i:i + 7] for i in range(0, len(text), 7)]
    assert list(iter_json_stream(chunks)) == events
    # and one giant chunk
    assert list(iter_json_stream([text])) == events


def test_drain_order_ranks():
    objs = [{"kind": k, "metadata": {"name": k}} for k in
            ("ClusterRole", "Service", "Deployment", "ConfigMap", "Pod")]
    ranked = [o["kind"] for o in drain_order(objs)]
    assert ranked.index("Deployment") < ranked.index("Service")
    assert ranked.index("Pod") < ranked.index("Service")
    assert ranked.index("Service") < ranked.index("ConfigMap")
    assert ranked.index("ConfigMap") < ranked.index("ClusterRole")


def test_release_state_round_trips_through_configmap():
    kube = InMemoryKube()
    op = PipelineOperator(kube)
    op.reconcile(_pipeline())
    state = op._load_state(_pipeline())
    assert "rag" in state
    st = state["rag"]
    assert st.chart == "rag-llm-pipeline"
    assert st.manifest_hash and len(st.object_keys) >= 12
    from generativeaiexamples_tpu.deploy.kube import parse_key
    for key in st.object_keys:
        assert kube.get(parse_key(key)) is not None


# ----------------------------------------------------------- CRD + sample

def _validate(schema: dict, value, path="$"):
    """Minimal openAPIV3Schema validator (type/properties/required/items)
    — the envtest-style check that the sample CR satisfies the CRD."""
    t = schema.get("type")
    if t == "object":
        assert isinstance(value, dict), f"{path}: expected object"
        for req in schema.get("required", []):
            assert req in value, f"{path}: missing required {req!r}"
        props = schema.get("properties", {})
        for k, v in value.items():
            if k in props:
                _validate(props[k], v, f"{path}.{k}")
            elif not schema.get("x-kubernetes-preserve-unknown-fields"):
                assert "additionalProperties" not in schema or \
                    schema["additionalProperties"] is not False, \
                    f"{path}: unexpected field {k!r}"
    elif t == "array":
        assert isinstance(value, list), f"{path}: expected array"
        for i, item in enumerate(value):
            _validate(schema.get("items", {}), item, f"{path}[{i}]")
    elif t == "string":
        assert isinstance(value, str), f"{path}: expected string"
    elif t == "integer":
        assert isinstance(value, int), f"{path}: expected integer"


def _load_crd_schema():
    path = os.path.join(REPO, "generativeaiexamples_tpu", "deploy", "crd",
                        "helmpipeline-crd.yaml")
    with open(path) as f:
        crd = yaml.safe_load(f)
    version = crd["spec"]["versions"][0]
    return crd, version["schema"]["openAPIV3Schema"]


def test_sample_cr_validates_against_crd_schema():
    crd, schema = _load_crd_schema()
    with open(os.path.join(REPO, "deploy", "samples",
                           "rag-llm-pipeline.yaml")) as f:
        sample = yaml.safe_load(f)
    group = crd["spec"]["group"]
    version = crd["spec"]["versions"][0]["name"]
    assert sample["apiVersion"] == f"{group}/{version}"
    assert sample["kind"] == crd["spec"]["names"]["kind"]
    _validate(schema, sample)


def test_sample_cr_parses_and_round_trips():
    with open(os.path.join(REPO, "deploy", "samples",
                           "rag-llm-pipeline.yaml")) as f:
        sample = yaml.safe_load(f)
    pipe = HelmPipeline.from_manifest(sample)
    assert pipe.name == "rag-llm"
    assert pipe.packages[0].chart_name == "rag-llm-pipeline"
    assert pipe.packages[0].values["modelServer"]["tensorParallelism"] == 8
    again = HelmPipeline.from_manifest(pipe.to_manifest())
    assert again == pipe


# ------------------------------------------------- autoscale scale target


def test_set_scale_target_patches_chart_values_and_reconciles():
    """ISSUE 13: the autoscaler's k8s write path. set_scale_target
    patches the named package's chartValues replica count on the live
    CR; a subsequent reconcile renders the Deployment at the new count
    — the same path every other spec change takes."""
    from generativeaiexamples_tpu.deploy.operator import set_scale_target

    kube = InMemoryKube()
    pipe = _pipeline(values={"chainServer": {"enabled": True}})
    kube.apply(pipe.to_manifest())
    patched = set_scale_target(
        kube, namespace="ns", pipeline="pipe", release="rag",
        replicas=5, values_path=("chainServer", "replicas"))
    pkg = patched["spec"]["pipeline"][0]["helmPackage"]
    assert pkg["chartValues"]["chainServer"]["replicas"] == 5
    # the stored CR carries the patch...
    stored = kube.get((API_VERSION, KIND, "ns", "pipe"))
    assert stored["spec"]["pipeline"][0]["helmPackage"][
        "chartValues"]["chainServer"]["replicas"] == 5
    # ... and reconciling it rolls the Deployment to 5 replicas.
    op = PipelineOperator(kube)
    op.reconcile(HelmPipeline.from_manifest(stored))
    dep = next(o for key, o in kube.objects.items()
               if key[1] == "Deployment"
               and "chain-server" in o["metadata"]["name"])
    assert dep["spec"]["replicas"] == 5


def test_set_scale_target_single_writer_conflict_and_missing():
    """Optimistic concurrency: the PUT carries the resourceVersion the
    read observed, so a raced writer surfaces as ConflictError (the
    decision record's executor.error) instead of clobbering — and the
    store keeps the OTHER writer's value."""
    from generativeaiexamples_tpu.deploy.operator import set_scale_target

    kube = InMemoryKube()
    pipe = _pipeline()
    kube.apply(pipe.to_manifest())
    key = (API_VERSION, KIND, "ns", "pipe")
    stale = json.loads(json.dumps(kube.get(key)))

    # A second writer lands between our read and our write.
    other = json.loads(json.dumps(kube.get(key)))
    other["spec"]["pipeline"][0]["helmPackage"]["chartValues"] = {
        "chainServer": {"replicas": 9}}
    kube.apply(other)

    real_get = kube.get
    kube.get = lambda k: stale if k == key else real_get(k)
    with pytest.raises(ConflictError):
        set_scale_target(kube, namespace="ns", pipeline="pipe",
                         release="rag", replicas=2,
                         values_path=("chainServer", "replicas"))
    kube.get = real_get
    kept = kube.get(key)["spec"]["pipeline"][0]["helmPackage"]
    assert kept["chartValues"]["chainServer"]["replicas"] == 9

    # Missing CR / unknown release are loud config errors, not no-ops.
    with pytest.raises(KeyError):
        set_scale_target(kube, namespace="ns", pipeline="ghost",
                         release="rag", replicas=2)
    with pytest.raises(KeyError):
        set_scale_target(kube, namespace="ns", pipeline="pipe",
                         release="ghost-release", replicas=2)
