"""CLI: ``python -m generativeaiexamples_tpu.tools.eval``.

Runs the full evaluation pipeline against a corpus directory (or a small
built-in TPU-docs corpus) and prints the metrics JSON. Defaults to the dev
stack — echo LLM + hash embedder — so it runs headless in CI with no
accelerator; point ``--llm-engine openai-compat --server-url ...`` at a
live serving stack for real scores (the reference's notebooks require a
live AI-Playground key even to smoke-test; this runs anywhere).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

_BUILTIN_CORPUS = {
    "mxu.txt": (
        "The MXU is a 128x128 systolic array that performs matrix "
        "multiplies in bfloat16 with float32 accumulation. Large, batched "
        "matmuls keep the MXU busy; scalar loops and dynamic shapes "
        "prevent XLA from tiling work onto it."),
    "ici.txt": (
        "TPU chips in a slice communicate over ICI links. XLA compiles "
        "collectives such as all-reduce, all-gather, and reduce-scatter "
        "directly into the program, so no separate communication library "
        "is needed at runtime."),
    "paging.txt": (
        "Paged KV caching shares a pool of fixed-size pages between "
        "decode slots. Each slot holds a block table mapping logical to "
        "physical pages, so cache capacity is sized to HBM instead of "
        "batch size times maximum length."),
    "batching.txt": (
        "Continuous batching admits new requests into the decode batch "
        "between steps without recompiling the program. Prefill uses "
        "bucketed static shapes; decode masks inactive slots."),
}


def build_example(args):
    from generativeaiexamples_tpu.chains.examples.developer_rag import QAChatbot
    from generativeaiexamples_tpu.utils.app_config import AppConfig
    from generativeaiexamples_tpu.utils.configuration import from_dict

    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": args.llm_engine,
                "server_url": args.server_url or ""},
        "embeddings": {"model_engine": args.embedder,
                       "dimensions": args.embedding_dim},
        "vector_store": {"name": "exact"},
        "text_splitter": {"chunk_size": args.chunk_size,
                          "chunk_overlap": args.chunk_overlap},
    })
    return QAChatbot(config=cfg)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m generativeaiexamples_tpu.tools.eval",
        description="RAG evaluation: synthetic QA + RAGAS-style metrics + "
                    "retrieval nDCG + LLM judge")
    parser.add_argument("--corpus", default=None,
                        help="directory of text/PDF files (default: "
                             "built-in TPU-docs corpus)")
    parser.add_argument("--llm-engine", default="echo",
                        choices=["echo", "openai-compat"],
                        help="LLM for the chain AND the judge")
    parser.add_argument("--server-url", default=os.environ.get(
        "APP_LLM_SERVERURL", ""))
    parser.add_argument("--embedder", default="hash",
                        choices=["hash", "tpu-jax"])
    parser.add_argument("--embedding-dim", type=int, default=256)
    parser.add_argument("--chunk-size", type=int, default=120)
    parser.add_argument("--chunk-overlap", type=int, default=20)
    parser.add_argument("--top-k", type=int, default=4)
    parser.add_argument("--max-questions", type=int, default=16)
    parser.add_argument("--max-chunks", type=int, default=8)
    parser.add_argument("--pairs-per-chunk", type=int, default=2)
    parser.add_argument("--num-tokens", type=int, default=150)
    parser.add_argument("--no-judge", action="store_true")
    parser.add_argument("--no-ragas", action="store_true")
    parser.add_argument("--output", default="eval_report.json")
    args = parser.parse_args(argv)

    example = build_example(args)

    if args.corpus:
        files = sorted(os.listdir(args.corpus))
        for name in files:
            path = os.path.join(args.corpus, name)
            if os.path.isfile(path):
                example.ingest_docs(path, name)
    else:
        with tempfile.TemporaryDirectory() as td:
            for name, text in _BUILTIN_CORPUS.items():
                path = os.path.join(td, name)
                with open(path, "w") as f:
                    f.write(text)
                example.ingest_docs(path, name)

    from .runner import EvalConfig, run_eval
    cfg = EvalConfig(top_k=args.top_k, num_tokens=args.num_tokens,
                     pairs_per_chunk=args.pairs_per_chunk,
                     max_questions=args.max_questions,
                     max_chunks=args.max_chunks,
                     judge=not args.no_judge, ragas=not args.no_ragas,
                     output_path=args.output)
    report = run_eval(example, example.llm, cfg)
    json.dump(report.metrics, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
