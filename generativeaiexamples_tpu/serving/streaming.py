"""Bridging the engine's thread-world streams into asyncio responses."""

from __future__ import annotations

import asyncio
import queue as _queue
from typing import AsyncIterator, Iterator

_SENTINEL = object()


async def iterate_in_thread(it: Iterator[str]) -> AsyncIterator[str]:
    """Drive a blocking iterator on the default executor, yielding into the
    event loop. Never lets the producer block on a dead consumer (client
    disconnects propagate as cancellation; the producer thread drains out).
    """
    loop = asyncio.get_running_loop()
    q: "_queue.SimpleQueue" = _queue.SimpleQueue()
    done = False

    def produce() -> None:
        try:
            for chunk in it:
                if done:
                    break
                q.put(chunk)
        except BaseException as exc:  # noqa: BLE001 — surface in consumer
            q.put(exc)
        finally:
            q.put(_SENTINEL)

    producer = loop.run_in_executor(None, produce)
    try:
        while True:
            try:
                item = q.get_nowait()
            except _queue.Empty:
                await asyncio.sleep(0.002)
                continue
            if item is _SENTINEL:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        done = True
        await producer
