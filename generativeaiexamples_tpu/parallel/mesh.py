"""Device-mesh construction.

Replaces the reference's world-size arithmetic and TP×PP==world assertion
(reference: model_server/__init__.py:103-110; GPU discovery via nvidia-smi in
model_server/model.py:111-138) with a ``jax.sharding.Mesh``. Axis order puts
``tp`` innermost so tensor-parallel collectives ride adjacent-chip ICI links;
``dp`` is outermost (crosses DCN first on multi-host topologies).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.errors import ShardingError

# Canonical mesh axes: data, pipeline, expert, sequence, tensor.
AXES = ("dp", "pp", "ep", "sp", "tp")


def parse_mesh_spec(spec: str) -> dict:
    """``"tp=2"`` / ``"tp=2,sp=2"`` -> ``{"tp": 2, "sp": 2}``. The one
    grammar for every mesh-spec surface (``BENCH_MESH`` rungs,
    ``tools/profile_decode.py --mesh``): unknown axes and non-positive
    sizes are a loud ``ValueError`` — a typo'd axis would otherwise
    silently measure or serve a topology the caller never asked for."""
    axes: dict = {}
    for part in (p.strip() for p in spec.split(",") if p.strip()):
        axis, sep, n = part.partition("=")
        axis = axis.strip()
        if not sep or axis not in AXES:
            raise ValueError(f"mesh spec {spec!r}: want axis=N pairs "
                             f"over {AXES}")
        if axis in axes:
            raise ValueError(f"mesh spec {spec!r}: axis {axis} given "
                             f"twice")
        size = int(n)
        if size < 1:
            raise ValueError(f"mesh spec {spec!r}: axis {axis} size "
                             f"must be >= 1")
        axes[axis] = size
    return axes

_distributed_initialized = False


def maybe_init_distributed(coordinator: str = "", num_processes: int = 0,
                           process_id: int = -1) -> bool:
    """Multi-host DCN bootstrap: ``jax.distributed.initialize``.

    The multi-controller replacement for the reference's mpirun launcher
    (reference: model_server/server.py:78-101 — one Triton process per
    rank): every host runs the same program; JAX wires the hosts over DCN
    and ``jax.devices()`` becomes the global device list, so the same mesh
    code spans hosts. Args fall back to the standard env vars
    (GAIE_COORDINATOR / GAIE_NUM_PROCESSES / GAIE_PROCESS_ID, or JAX's own
    auto-detection on Cloud TPU pods). Returns True if distributed mode
    was (already) initialized; single-host setups no-op.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return True
    coordinator = coordinator or os.environ.get("GAIE_COORDINATOR", "")
    num_processes = num_processes or int(
        os.environ.get("GAIE_NUM_PROCESSES", "0"))
    process_id = process_id if process_id >= 0 else int(
        os.environ.get("GAIE_PROCESS_ID", "-1"))
    if not coordinator and num_processes <= 1:
        return False
    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if num_processes:
        kwargs["num_processes"] = num_processes
    if process_id >= 0:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    _distributed_initialized = True
    return True


@dataclass(frozen=True)
class MeshPlan:
    """Requested parallelism degrees. 0 ⇒ infer from device count."""
    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 0

    def resolve(self, n_devices: int) -> "MeshPlan":
        plan = self
        if plan.tp == 0:
            fixed = plan.dp * plan.pp * plan.ep * plan.sp
            if n_devices % fixed:
                raise ShardingError(
                    f"{n_devices} devices not divisible by dp*pp*ep*sp={fixed}")
            plan = MeshPlan(plan.dp, plan.pp, plan.ep, plan.sp,
                            n_devices // fixed)
        total = plan.dp * plan.pp * plan.ep * plan.sp * plan.tp
        if total != n_devices:
            raise ShardingError(
                f"dp*pp*ep*sp*tp = {total} != {n_devices} devices "
                "(the TP·PP=world check of the reference, "
                "model_server/__init__.py:103-110, generalized)")
        return plan


def make_mesh(plan: MeshPlan | None = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the device mesh.

    Uses all local devices by default. Device ordering follows
    ``jax.devices()`` which on TPU enumerates chips in torus-adjacent order,
    so the innermost (tp) axis lands on neighboring chips.
    """
    devices = list(devices if devices is not None else jax.devices())
    plan = (plan or MeshPlan()).resolve(len(devices))
    arr = np.array(devices).reshape(plan.dp, plan.pp, plan.ep, plan.sp, plan.tp)
    return Mesh(arr, AXES)
