"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

The JAX analogue of the reference's "multi-node without a cluster" envtest
strategy (SURVEY.md §4): numerical parity between sharded and single-device
execution IS the distributed test.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.parallel import (
    MeshPlan, activation_spec, kv_cache_spec, llama_param_specs, make_mesh,
    shard_params)
from generativeaiexamples_tpu.utils.errors import ShardingError

# Geometry chosen so tp=4 divides heads (8) and kv heads (4).
CFG = LlamaConfig(vocab_size=256, hidden_size=128, intermediate_size=256,
                  num_layers=2, num_heads=8, num_kv_heads=4, head_dim=16,
                  max_position_embeddings=256)


def test_mesh_plan_resolution(cpu_devices):
    plan = MeshPlan(dp=2).resolve(8)
    assert plan.tp == 4 and plan.dp == 2
    with pytest.raises(ShardingError):
        MeshPlan(dp=3).resolve(8)
    with pytest.raises(ShardingError):
        MeshPlan(dp=2, tp=8).resolve(8)


def test_mesh_axes(cpu_devices):
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    assert mesh.shape == {"dp": 2, "pp": 1, "ep": 1, "sp": 1, "tp": 4}


def test_tp_sharded_forward_matches_single_device(cpu_devices):
    params = llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 10), np.int32))
    positions = jnp.broadcast_to(jnp.arange(10, dtype=jnp.int32), (4, 10))

    ref_logits, _ = llama.apply(params, CFG, tokens, positions)

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    specs = llama_param_specs(CFG, mesh)
    sharded = shard_params(params, mesh, specs)
    act = NamedSharding(mesh, activation_spec(mesh))
    tokens_s = jax.device_put(tokens, act)
    pos_s = jax.device_put(positions, act)

    @jax.jit
    def fwd(p, t, pos):
        return llama.apply(p, CFG, t, pos)[0]

    out = fwd(sharded, tokens_s, pos_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)


def test_tp_sharded_decode_with_cache(cpu_devices):
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = llama.init_params(CFG, jax.random.key(1), dtype=jnp.float32)
    sharded = shard_params(params, mesh, llama_param_specs(CFG, mesh))
    cache = llama.init_kv_cache(CFG, 4, max_len=32, dtype=jnp.float32)
    cache = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        cache, kv_cache_spec(CFG, mesh))

    tokens = jnp.zeros((4, 4), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32), (4, 4))

    @jax.jit
    def prefill(p, t, pos, c):
        return llama.apply(p, CFG, t, pos, c)

    logits, cache = prefill(sharded, tokens, positions, cache)
    assert logits.shape == (4, 4, 256)

    @jax.jit
    def decode(p, t, pos, c):
        return llama.apply(p, CFG, t, pos, c)

    step_tok = jnp.ones((4, 1), jnp.int32)
    step_pos = jnp.full((4, 1), 4, jnp.int32)
    logits2, cache = decode(sharded, step_tok, step_pos, cache)
    assert logits2.shape == (4, 1, 256)
    assert bool(jnp.isfinite(logits2).all())


def test_gqa_tp_exceeding_kv_heads_degrades_gracefully(cpu_devices):
    """tp=8 > kv_heads=4: wk/wv fall back to replicated (the XLA version of
    the reference's KV duplication, weight.py:150-157)."""
    mesh = make_mesh(MeshPlan(tp=8))
    specs = llama_param_specs(CFG, mesh)
    assert specs["layers"]["wk"] == P(None, None, None)
    assert specs["layers"]["wq"] == P(None, None, "tp")

    params = llama.init_params(CFG, jax.random.key(2), dtype=jnp.float32)
    sharded = shard_params(params, mesh, specs)
    tokens = jnp.zeros((2, 6), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(6, dtype=jnp.int32), (2, 6))
    logits, _ = jax.jit(lambda p, t, s: llama.apply(p, CFG, t, s))(
        sharded, tokens, positions)
    assert bool(jnp.isfinite(logits).all())
