"""Resilience primitives: circuit breakers and bounded retry.

The reference stack leaned on Triton's ready-polling and LangChain's
broad ``except`` blocks; this framework makes failure handling explicit:

- :class:`CircuitBreaker` — the classic closed → open → half-open state
  machine. Wraps a dependency (vector store, embedder, the engine edge);
  after ``failure_threshold`` consecutive failures the breaker OPENS and
  every call fails fast with :class:`~.errors.BreakerOpenError` until
  ``cooldown_s`` elapses, at which point ONE probe call is let through
  (half-open): success re-closes, failure re-opens. Callers catch
  ``BreakerOpenError`` to take their degradation path (e.g. ``rag_chain``
  falling back to ``llm_chain``) instead of stalling on a dead backend.

- :func:`retry_call` — bounded retry with exponential backoff and full
  jitter for idempotent operations (HTTP connects whose first byte never
  arrived: request IDs make the replay idempotent at the flight
  recorder). Gives up after the attempt budget, re-raising the last
  failure.

Every breaker registers itself in a process-wide table so ``/metrics``
can publish ``breaker_state{name=...}`` (0 closed / 1 half-open /
2 open) and ``breaker_trips_total{name=...}`` without the serving code
threading breaker handles around — gauges update on state transitions,
never on the per-call fast path.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Optional, Tuple, Type

from .errors import BreakerOpenError
from .logging import get_logger

logger = get_logger(__name__)

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


def _metrics():
    # Late import: utils must stay importable before obs (and without it
    # in stripped-down tools).
    from ..obs import metrics as obs_metrics
    return obs_metrics.REGISTRY


class CircuitBreaker:
    """Closed/open/half-open breaker over consecutive failure counts.

    Thread-safe; the lock is held only for the state bookkeeping, never
    across the protected call itself.
    """

    def __init__(self, name: str, failure_threshold: int = 5,
                 cooldown_s: float = 15.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.trips = 0  # cumulative open transitions
        self._publish()

    # ------------------------------------------------------------- state

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def retry_after_s(self) -> float:
        """Seconds until the next probe is allowed (0 when not open)."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._opened_at + self.cooldown_s
                       - self._clock())

    def _maybe_half_open(self) -> None:
        # caller holds the lock
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.cooldown_s):
            self._state = HALF_OPEN
            self._probe_inflight = False
            self._publish()

    def _publish(self) -> None:
        try:
            _metrics().gauge(
                "breaker_state",
                "circuit breaker state (0 closed, 1 half-open, 2 open)",
                labelnames=("name",)).labels(self.name).set(
                    _STATE_CODE[self._state])
        except Exception:  # noqa: BLE001 — metrics must never break serving
            pass

    # ------------------------------------------------------------- calls

    def allow(self) -> bool:
        """May a call proceed right now? Half-open admits exactly one
        probe at a time; callers that use ``allow()`` directly MUST
        report the outcome via record_success/record_failure."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            changed = self._state != CLOSED
            self._state = CLOSED
            self._failures = 0
            self._probe_inflight = False
            if changed:
                self._publish()
                logger.info("breaker %s closed", self.name)

    def release_probe(self) -> None:
        """Walk back an ``allow()`` WITHOUT recording an outcome: the
        admitted call never actually probed the dependency (shed at
        admission, cancelled by the client, failed upstream of it).
        State and failure counts are untouched — a half-open breaker
        goes back to waiting for a real probe instead of being wedged
        (probe lost) or wrongly re-closed (fake success)."""
        with self._lock:
            self._probe_inflight = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probe_inflight = False
            if (self._state == HALF_OPEN
                    or self._failures >= self.failure_threshold):
                if self._state != OPEN:
                    self.trips += 1
                    try:
                        _metrics().counter(
                            "breaker_trips_total",
                            "breaker closed/half-open -> open transitions",
                            labelnames=("name",)).labels(self.name).inc()
                    except Exception:  # noqa: BLE001
                        pass
                    logger.warning(
                        "breaker %s OPEN after %d consecutive failures "
                        "(cooldown %.1fs)", self.name, self._failures,
                        self.cooldown_s)
                self._state = OPEN
                self._opened_at = self._clock()
                self._publish()

    def call(self, fn: Callable, *args, **kwargs):
        """Run ``fn`` under the breaker: fail fast when open, count the
        outcome otherwise. The raised ``BreakerOpenError`` carries the
        breaker's name so degradation paths can label their fallback."""
        if not self.allow():
            raise BreakerOpenError(
                f"circuit '{self.name}' is open "
                f"(retry in {self.retry_after_s():.1f}s)", breaker=self.name,
                retry_after_s=self.retry_after_s())
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result

    def reset(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._failures = 0
            self._probe_inflight = False
            self._publish()


# Process-wide named breakers: the serving path, the chains, and the
# /metrics exporter all resolve the same instance by name.
_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def get_breaker(name: str, failure_threshold: Optional[int] = None,
                cooldown_s: Optional[float] = None) -> CircuitBreaker:
    """The process-wide breaker called ``name`` (created on first use).
    Threshold/cooldown apply only at creation; env overrides
    ``BREAKER_FAILURES`` / ``BREAKER_COOLDOWN_S`` set the defaults."""
    with _breakers_lock:
        br = _breakers.get(name)
        if br is None:
            if failure_threshold is None:
                failure_threshold = int(
                    os.environ.get("BREAKER_FAILURES", "5"))
            if cooldown_s is None:
                cooldown_s = float(
                    os.environ.get("BREAKER_COOLDOWN_S", "15"))
            br = CircuitBreaker(name, failure_threshold, cooldown_s)
            _breakers[name] = br
        return br


def reset_breakers() -> None:
    """Forget every named breaker (tests)."""
    with _breakers_lock:
        _breakers.clear()


def retry_call(fn: Callable, *, attempts: Optional[int] = None,
               base_delay: float = 0.1, max_delay: float = 2.0,
               retry_on: Tuple[Type[BaseException], ...] = (ConnectionError,),
               should_retry: Optional[Callable[[BaseException], bool]] = None,
               sleep: Callable[[float], None] = time.sleep,
               rng: Callable[[], float] = random.random,
               on_retry: Optional[Callable] = None):
    """Call ``fn()``; on an exception in ``retry_on`` (and passing the
    optional ``should_retry`` predicate — for cases type alone can't
    decide, like requests.ConnectionError covering both connect refusal
    and mid-response resets), retry with exponential backoff and FULL
    jitter (delay uniformly drawn from
    ``[0, min(max_delay, base_delay * 2**i)]`` — the AWS-architecture
    jitter that decorrelates a thundering herd). Any other exception, or
    exhausting the ``attempts`` budget, re-raises immediately.

    Only use for operations that are safe to replay — here, HTTP calls
    whose connection failed before a first byte arrived; the request ID
    carried by the replay keeps the server-side flight record coherent.
    """
    if attempts is None:
        attempts = int(os.environ.get("HTTP_RETRY_ATTEMPTS", "3"))
    attempts = max(1, int(attempts))
    last: Optional[BaseException] = None
    for i in range(attempts):
        try:
            return fn()
        except retry_on as exc:  # noqa: PERF203 — retry loop by design
            if should_retry is not None and not should_retry(exc):
                raise
            last = exc
            if i == attempts - 1:
                break
            delay = min(max_delay, base_delay * (2 ** i)) * rng()
            if on_retry is not None:
                on_retry(i + 1, exc, delay)
            logger.debug("retry %d/%d after %s (sleep %.3fs)", i + 1,
                         attempts, exc, delay)
            sleep(delay)
    raise last  # type: ignore[misc]
