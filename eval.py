"""Round-quality evaluation: the retrieval/answer-quality artifact.

Closes the third clause of BASELINE.md's north star ("retrieval nDCG
parity"): runs the full ``tools/eval`` pipeline — synthetic QA ->
answers THROUGH THE LIVE CHAIN SERVER (HTTP SSE) -> deterministic
retrieval metrics (nDCG/hit/MRR) + RAGAS-style LLM-graded metrics +
Likert judge — and writes ``EVAL_r{NN}.json`` at the repo root, the
quality sibling of the driver's ``BENCH_r{NN}.json``.

The reference defines this methodology across four notebooks
(reference: tools/evaluation/01_synthetic_data_generation.ipynb,
02_filling_RAG_outputs_for_Evaluation.ipynb, 03_eval_ragas.ipynb,
04_Human_Like_RAG_Evaluation-AIP.ipynb) but publishes no scores —
parity is measured by re-running the same pipeline here, every round.

Honesty model (mirrors bench.py's ``weights`` field):

- **Retrieval metrics are always meaningful.** The corpus is the repo's
  own documentation, questions are synthesized from specific chunks,
  and the deterministic hash embedder + exact store rank them — nDCG
  measures the splitter/embedder/store/ranking stack, no LLM involved.
- **LLM-graded metrics are only meaningful with real weights.** With
  the default random-init dev model the judge/RAGAS verdicts rarely
  parse; the artifact publishes the scored counts so a reader can see
  exactly how much signal each number carries. Point EVAL_MODEL_PATH
  (or BENCH_MODEL_PATH) at a real checkpoint to light them up.

Usage::

    python eval.py                  # dev stack, writes EVAL_r05.json
    GAIE_ROUND=6 python eval.py     # next round's artifact
    EVAL_MODEL_PATH=/ckpts/llama-2-7b python eval.py   # real weights
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))

# Honor JAX_PLATFORMS from the environment: the ambient sitecustomize
# pins the tunneled TPU backend, so the env var alone is not enough — the
# config must be updated post-import (same dance as tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


class LiveChainExample:
    """Example adapter that answers THROUGH the live chain server.

    ``tools.eval.runner`` drives an in-process ``BaseExample``; this
    wrapper keeps that interface but routes ``rag_chain`` over the HTTP
    SSE surface (`POST /generate`), so the published answers cover the
    full serving path — aiohttp, streaming, in-stream error degrade —
    not just the chain object (reference: the eval notebooks likewise
    post to the chain server,
    02_filling_RAG_outputs_for_Evaluation.ipynb). Retrieval contexts and
    gold ids come from the server's own index object (shared
    in-process) — the runner's established gold-labeling seam; the
    HTTP ``/documentSearch`` surface itself is covered by
    tests/test_chains.py, not re-measured here.
    """

    def __init__(self, example, base_url: str):
        self._example = example
        self._base = base_url

    @property
    def index(self):
        return self._example.index

    def rag_chain(self, question: str, num_tokens: int):
        import requests
        with requests.post(
                f"{self._base}/generate",
                json={"question": question, "use_knowledge_base": True,
                      "num_tokens": num_tokens},
                stream=True, timeout=600) as resp:
            resp.raise_for_status()
            parts: list[str] = []
            for chunk in resp.iter_content(chunk_size=None,
                                           decode_unicode=True):
                parts.append(chunk)
        text = "".join(parts)
        if "[error]" in text:
            # the server degrades failures into the stream (reference
            # semantics); scoring the error banner would be fiction
            raise RuntimeError(f"in-stream failure: {text[:200]!r}")
        yield text


def serve_http(example):
    """Boot the chain server on an ephemeral port; return (base_url, stop)."""
    from aiohttp import web

    from generativeaiexamples_tpu.chains.server import create_app

    app = create_app(example)
    loop = asyncio.new_event_loop()
    holder: dict = {}
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            holder["port"] = site._server.sockets[0].getsockname()[1]
        loop.run_until_complete(boot())
        started.set()
        loop.run_forever()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    if not started.wait(timeout=60):
        raise RuntimeError("chain server failed to start")

    def stop():
        loop.call_soon_threadsafe(loop.stop)

    return f"http://127.0.0.1:{holder['port']}", stop


def build_stack(args):
    """(example, engine, weights_desc): the canonical QA chatbot over an
    in-process engine + deterministic hash retriever."""
    from generativeaiexamples_tpu.chains.examples.developer_rag import QAChatbot
    from generativeaiexamples_tpu.chains.llm import EngineLLM
    from generativeaiexamples_tpu.serving.model_server import build_services
    from generativeaiexamples_tpu.utils.app_config import AppConfig
    from generativeaiexamples_tpu.utils.configuration import from_dict

    model_path = args.model_path
    model_type = "llama" if model_path else "dev"
    engine, _, model_name = build_services(
        model_type=model_type, model_name=args.model_name,
        model_path=model_path, max_slots=4,
        world_size=args.world_size,
        max_input_length=args.max_input_length,
        max_output_length=256, dtype=args.dtype,
        quantization=args.quantization, with_embedder=False)
    weights = model_path or "random-init"

    cfg = from_dict(AppConfig, {
        "embeddings": {"model_engine": "hash",
                       "dimensions": args.embedding_dim},
        "vector_store": {"name": "exact"},
        "text_splitter": {"chunk_size": args.chunk_size,
                          "chunk_overlap": args.chunk_overlap},
    })
    example = QAChatbot(llm=EngineLLM(engine), config=cfg)
    return example, engine, model_name, weights


def ingest_corpus(example, corpus_dir: str) -> dict:
    exts = (".md", ".txt", ".pdf")
    files = sorted(f for f in os.listdir(corpus_dir)
                   if f.endswith(exts)
                   and os.path.isfile(os.path.join(corpus_dir, f)))
    for name in files:
        example.ingest_docs(os.path.join(corpus_dir, name), name)
    return {"dir": os.path.relpath(corpus_dir, REPO), "files": len(files),
            "chunks": len(example.index._docs)}


def generation_sanity(questions) -> dict:
    """Deterministic answer-stream health, meaningful at any weight
    quality: did every question produce a non-empty, non-error answer
    through the live server?"""
    answers = [q.answer for q in questions]
    non_empty = [a for a in answers if a.strip()]
    return {
        "answers": len(answers),
        "non_empty": len(non_empty),
        "mean_answer_chars": (round(sum(map(len, non_empty))
                                    / len(non_empty), 1)
                              if non_empty else 0),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="RAG quality eval against the live chain server; "
                    "writes EVAL_r{NN}.json")
    parser.add_argument("--round", default=os.environ.get("GAIE_ROUND", "05"))
    parser.add_argument("--output", default=None)
    parser.add_argument("--corpus", default=os.path.join(REPO, "docs"))
    parser.add_argument("--model-path", default=os.environ.get(
        "EVAL_MODEL_PATH", os.environ.get("BENCH_MODEL_PATH", "")))
    parser.add_argument("--model-name", default="")
    parser.add_argument("--dtype", default=os.environ.get(
        "EVAL_DTYPE", "bfloat16"))
    parser.add_argument("--quantization", default=os.environ.get(
        "EVAL_QUANT", ""))
    parser.add_argument("--max-input-length", type=int, default=3000)
    parser.add_argument("--world-size", type=int, default=0,
                        help="devices for the engine (0 = all local)")
    parser.add_argument("--embedding-dim", type=int, default=256)
    parser.add_argument("--chunk-size", type=int, default=150)
    parser.add_argument("--chunk-overlap", type=int, default=30)
    parser.add_argument("--top-k", type=int, default=4)
    parser.add_argument("--num-tokens", type=int, default=100)
    parser.add_argument("--max-questions", type=int, default=24)
    parser.add_argument("--max-chunks", type=int, default=24)
    parser.add_argument("--no-artifact", action="store_true",
                        help="print metrics only, write nothing")
    args = parser.parse_args(argv)

    rnd = str(args.round).zfill(2)
    out_path = args.output or os.path.join(REPO, f"EVAL_r{rnd}.json")

    example, engine, model_name, weights = build_stack(args)
    corpus = ingest_corpus(example, args.corpus)
    base_url, stop = serve_http(example)

    from generativeaiexamples_tpu.tools.eval.runner import (EvalConfig,
                                                            run_eval)
    live = LiveChainExample(example, base_url)
    cfg = EvalConfig(top_k=args.top_k, num_tokens=args.num_tokens,
                     pairs_per_chunk=2, max_questions=args.max_questions,
                     max_chunks=args.max_chunks, judge=True, ragas=True)
    try:
        report = run_eval(live, example.llm, cfg)
    finally:
        stop()
        engine.stop()

    artifact = {
        "round": int(rnd),
        "generated_unix": int(time.time()),
        "stack": {
            "llm": model_name,
            "weights": weights,
            "dtype": args.dtype,
            "quantization": args.quantization or None,
            "embedder": f"hash-{args.embedding_dim} (deterministic)",
            "vector_store": "exact",
            "transport": "live chain-server HTTP (streamed /generate)",
        },
        "corpus": corpus,
        "metrics": report.metrics,
        "generation": generation_sanity(report.questions),
        "notes": (
            "retrieval.* (nDCG/hit/MRR vs each question's source chunk) "
            "is deterministic and meaningful on any weights; "
            "faithfulness/context_precision/judge are LLM-graded — on "
            "random-init weights their *_scored counts show how many "
            "verdicts parsed (usually zero). Set EVAL_MODEL_PATH to "
            "score them with a real checkpoint."),
        "questions": [q.to_dict() for q in report.questions],
    }
    if not args.no_artifact:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=2)
            f.write("\n")
    summary = {k: artifact["metrics"].get(k) for k in
               ("num_questions", "retrieval", "faithfulness",
                "context_precision", "judge")}
    summary["weights"] = weights
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
