"""The multimodal assistant example: office-doc RAG + memory + guardrail.

A ``BaseExample`` (reference contract: common/base.py:21-33), so the
standard chain server and frontend serve it unchanged:

    python -m generativeaiexamples_tpu.chains.server --example \
        generativeaiexamples_tpu.assistant.assistant

Differences from the developer-RAG example, mirroring the reference's
assistant (experimental/multimodal_assistant/Multimodal_Assistant.py):
PPTX/DOCX ingestion with slide-aware chunk metadata, conversation memory
folded into the prompt, an LLM fact-check appended to grounded answers,
and feedback capture.
"""

from __future__ import annotations

import os
from typing import Generator, Optional

from ..chains.base import BaseExample
from ..chains.llm import get_llm
from ..chains.readers import read_document
from ..chains.splitter import TokenTextSplitter, cap_context
from ..embed.encoder import get_embedder
from ..retrieval.docstore import Document, DocumentIndex
from ..utils.app_config import get_config
from ..utils.logging import get_logger
from .feedback import FeedbackStore
from .guardrails import fact_check
from .memory import ConversationMemory
from .parsers import parse_pptx, read_docx, read_pptx

logger = get_logger(__name__)

PROMPT = (
    "You are a helpful assistant answering questions about the user's "
    "documents.\n"
    "{history_block}"
    "Context from the documents:\n{context}\n\n"
    "Question: {question}\nAnswer:"
)


class MultimodalAssistant(BaseExample):
    """Office-document assistant with memory, guardrail, feedback."""

    def __init__(self, llm=None, embedder=None,
                 index: Optional[DocumentIndex] = None, config=None,
                 engine=None, check_facts: bool = True,
                 feedback_path: str = "./feedback.jsonl"):
        self.config = config or get_config()
        self.llm = llm or get_llm(self.config, engine=engine)
        embedder = embedder or (index.embedder if index else None) or \
            get_embedder(self.config.embeddings.model_engine,
                         self.config.embeddings.model_name,
                         dim=self.config.embeddings.dimensions)
        if index is None:
            from ..retrieval.store import store_from_config
            index = DocumentIndex(embedder, store=store_from_config(
                self.config.vector_store, embedder.dim))
        self.index = index
        self.splitter = TokenTextSplitter(
            chunk_size=self.config.text_splitter.chunk_size,
            chunk_overlap=self.config.text_splitter.chunk_overlap)
        self.memory = ConversationMemory()
        self.check_facts = check_facts
        self.feedback = FeedbackStore(feedback_path)

    # ----------------------------------------------------------- ingestion

    def ingest_docs(self, data_dir: str, filename: str) -> None:
        """PPTX decks keep per-slide provenance (the reference's parser
        attaches slide metadata for citations); DOCX and everything the
        base readers cover flatten to text first."""
        ext = os.path.splitext(filename)[1].lower()
        docs: list[Document] = []
        if ext == ".pptx":
            for slide in parse_pptx(data_dir):
                body = slide.text + (f"\n(notes: {slide.notes})"
                                     if slide.notes else "")
                for i, chunk in enumerate(self.splitter.split_text(body)):
                    docs.append(Document(text=chunk, metadata={
                        "source": filename, "slide": slide.index,
                        "chunk": i, "images": slide.images}))
        else:
            text = read_docx(data_dir) if ext == ".docx" \
                else read_document(data_dir)
            docs = [Document(text=c, metadata={"source": filename,
                                               "chunk": i})
                    for i, c in enumerate(self.splitter.split_text(text))]
        self.index.add_documents(docs)
        logger.info("assistant ingested %s: %d chunks", filename, len(docs))

    # -------------------------------------------------------------- chains

    def _prompt(self, context: str, question: str) -> str:
        history = self.memory.render()
        history_block = (f"Conversation so far:\n{history}\n\n"
                         if history else "")
        return PROMPT.format(history_block=history_block, context=context,
                             question=question)

    def llm_chain(self, context: str, question: str, num_tokens: int,
                  ) -> Generator[str, None, None]:
        answer_parts: list[str] = []
        for chunk in self.llm.stream(
                self._prompt(context or "(none)", question),
                max_tokens=num_tokens, stop=["</s>", "[INST]"]):
            answer_parts.append(chunk)
            yield chunk
        self.memory.add(question, "".join(answer_parts))

    def rag_chain(self, prompt: str, num_tokens: int,
                  ) -> Generator[str, None, None]:
        docs = self.index.similarity_search(
            prompt, k=self.config.retriever.top_k)
        context_texts = cap_context(
            [d.text for d in docs],
            max_tokens=self.config.retriever.max_context_tokens,
            tokenizer=self.splitter.tok)
        context = "\n\n".join(context_texts)
        answer_parts: list[str] = []
        for chunk in self.llm.stream(self._prompt(context, prompt),
                                     max_tokens=num_tokens,
                                     stop=["</s>", "[INST]"]):
            answer_parts.append(chunk)
            yield chunk
        answer = "".join(answer_parts)
        self.memory.add(prompt, answer)
        if self.check_facts and context:
            verdict = fact_check(self.llm, context, prompt, answer)
            if verdict.supported is True:
                yield "\n\n[fact check: supported by the documents]"
            elif verdict.supported is False:
                yield ("\n\n[fact check: NOT fully supported — "
                       f"{verdict.explanation[:200]}]")

    # ------------------------------------------------------------- search

    def document_search(self, content: str, num_docs: int) -> list[dict]:
        docs = self.index.similarity_search(content, k=num_docs)
        out = []
        for d in docs:
            label = d.metadata.get("source", "")
            if "slide" in d.metadata:
                label += f" (slide {d.metadata['slide']})"
            out.append({"score": d.score, "source": label,
                        "content": d.text})
        return out

    # ------------------------------------------------------------ feedback

    def record_feedback(self, question: str, answer: str, rating: int,
                        comment: str = "") -> dict:
        return self.feedback.record(question, answer, rating, comment)


Example = MultimodalAssistant
