"""Autoscaler + surge admission + scale chaos (tier-1, CPU) — ISSUE 13.

Unit: the control law's leading indicators, cooldowns, and min/max
clamps over synthetic evidence; the surge gate's bounded-queue
semantics (measured Retry-After, deadline-unmeetable fast 429, wait
grants); the decision-record / ``GET /debug/autoscale`` contracts (and
that the validator actually FAILS on doctored payloads); executor
failure injection (``autoscale.execute``) landing in the record instead
of killing the loop.

Chaos acceptance (real engine replicas behind the router):

- **scale-during-burst** — a Poisson burst over a one-replica fleet
  drives queue depth up; the controller records ``scale_up`` with its
  evidence BEFORE the first ``shed_total`` increment; the activated
  replica takes traffic within one probe and — with ``ROUTER_KV_TRANSFER``
  on — its first placement carries the PR-11 donor hint so it warms via
  page transfer instead of a cold prefill.
- **rolling-restart-under-load** — drain → remove → re-add each of a
  3-replica fleet under continuous open-loop traffic: zero mid-stream
  losses, zero 5xx (only 429 backpressure tolerated), restarted
  replicas come back placeable with clean state.
"""

import asyncio
import threading
import time

import pytest

import jax
import jax.numpy as jnp

import aiohttp  # noqa: F401 — skip cleanly where aiohttp is absent
from aiohttp.test_utils import TestClient, TestServer

from generativeaiexamples_tpu.obs import metrics as obs_metrics
from generativeaiexamples_tpu.router import autoscale as rauto
from generativeaiexamples_tpu.router.flight import SloWindow
from generativeaiexamples_tpu.router.server import (ROUTER, FleetRouter,
                                                    create_router_app)
from generativeaiexamples_tpu.router.table import ReplicaTable
from generativeaiexamples_tpu.utils import faults, resilience


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    resilience.reset_breakers()
    yield
    faults.clear()
    resilience.reset_breakers()


def _run(coro):
    loop = asyncio.get_event_loop_policy().new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _evidence(**over) -> dict:
    ev = {
        "snapshot_unix_ms": 0, "replicas_total": 2,
        "replicas_placeable": 2, "in_flight": 0, "queue_depth": 0,
        "queue_per_replica": 0.0, "queue_trend": 0.0,
        "utilization": 0.5, "tokens_per_sec": 1000.0,
        "capacity_tokens_per_sec": 2000.0,
        "headroom_tokens_per_sec": 1000.0, "shed_rate": 0.0,
        "slo_attainment": 1.0, "ttft_p50_ms": 100.0,
        "surge_queue_depth": 0,
    }
    ev.update(over)
    return ev


def _controller(policy=None, **kw) -> rauto.AutoscaleController:
    table = ReplicaTable()
    router = FleetRouter(table)
    return rauto.AutoscaleController(
        router, policy=policy or rauto.AutoscalePolicy(
            min_replicas=1, max_replicas=4),
        slo_ttft_ms=2000.0, **kw)


# ----------------------------------------------------------- control law


def test_decide_scale_up_on_each_leading_indicator():
    for over, needle in [
        ({"utilization": 0.9}, "utilization"),
        ({"queue_per_replica": 5.0, "queue_depth": 10}, "queue/replica"),
        ({"queue_per_replica": 2.5, "queue_depth": 5,
          "queue_trend": 1.5}, "queue rising"),
        ({"ttft_p50_ms": 1800.0}, "slack exhaustion"),
        ({"shed_rate": 0.2}, "late"),
    ]:
        ctl = _controller()
        action, reason, target = ctl._decide(_evidence(**over))
        assert action == "scale_up", (over, action, reason)
        assert needle in reason
        assert target >= 3    # at least current + 1


def test_decide_demand_model_sizes_to_target_util():
    ctl = _controller(policy=rauto.AutoscalePolicy(
        min_replicas=1, max_replicas=10, target_util=0.5))
    # 2 replicas, 1900 of 2000 tok/s consumed: per-replica cap 1000,
    # demand = ceil(1900 / (1000 * 0.5)) = 4.
    action, _, target = ctl._decide(_evidence(
        utilization=0.95, tokens_per_sec=1900.0))
    assert action == "scale_up" and target == 4
    # ... and the max clamps it.
    ctl2 = _controller(policy=rauto.AutoscalePolicy(
        min_replicas=1, max_replicas=3, target_util=0.5))
    action, _, target = ctl2._decide(_evidence(
        utilization=0.95, tokens_per_sec=1900.0))
    assert action == "scale_up" and target == 3


def test_decide_below_min_and_cooldown_and_surge_transitions():
    ctl = _controller(policy=rauto.AutoscalePolicy(
        min_replicas=2, max_replicas=3, up_cooldown_s=100.0))
    action, reason, target = ctl._decide(_evidence(replicas_total=1))
    assert action == "scale_up" and target == 2
    assert "min_replicas" in reason
    # Cooldown: an overloaded fleet right after a scale-up is blocked.
    ctl._last_up_t = ctl._now()
    action, reason, _ = ctl._decide(_evidence(utilization=0.95))
    assert action == "blocked" and "cooldown" in reason
    # At max: overload flips surge ON (once), then holds.
    ctl2 = _controller(policy=rauto.AutoscalePolicy(
        min_replicas=1, max_replicas=2))
    action, _, _ = ctl2._decide(_evidence(utilization=0.95))
    assert action == "surge_on"
    ctl2.surge.set_active(True)
    action, _, _ = ctl2._decide(_evidence(utilization=0.95))
    assert action == "hold"
    # Overload clears -> surge OFF before anything else.
    action, _, _ = ctl2._decide(_evidence(utilization=0.4))
    assert action == "surge_off"


def test_decide_scale_down_needs_stable_quiet_and_respects_min():
    ctl = _controller(policy=rauto.AutoscalePolicy(
        min_replicas=1, max_replicas=4, down_stable_ticks=3,
        down_util=0.4, down_cooldown_s=0.0))
    quiet = _evidence(utilization=0.1, queue_depth=0,
                      queue_per_replica=0.0)
    assert ctl._decide(quiet)[0] == "hold"
    assert ctl._decide(quiet)[0] == "hold"
    action, _, target = ctl._decide(quiet)
    assert action == "scale_down" and target == 1
    # A busy tick resets the quiet counter.
    ctl2 = _controller(policy=rauto.AutoscalePolicy(
        min_replicas=1, max_replicas=4, down_stable_ticks=2,
        down_cooldown_s=0.0))
    assert ctl2._decide(quiet)[0] == "hold"
    ctl2._decide(_evidence(queue_depth=3, queue_per_replica=1.5))
    assert ctl2._decide(quiet)[0] == "hold"   # counter restarted
    # Never below min.
    ctl3 = _controller(policy=rauto.AutoscalePolicy(
        min_replicas=2, max_replicas=4, down_stable_ticks=1,
        down_cooldown_s=0.0))
    at_min = _evidence(replicas_total=2, replicas_placeable=2,
                       utilization=0.05)
    assert ctl3._decide(at_min)[0] == "hold"


def test_scale_down_candidate_prefers_least_loaded_placeable():
    table = ReplicaTable()
    table.add("busy", "http://a")
    table.add("idle", "http://b")
    table.add("draining", "http://c")
    table.update_health("busy", ok=True, body={
        "load": {"in_flight": 4, "queue_depth": 2, "rejected_total": 0}})
    table.update_health("idle", ok=True, body={
        "load": {"in_flight": 0, "queue_depth": 0, "rejected_total": 0}})
    table.mark_draining("draining")
    assert table.scale_down_candidate() == "idle"
    assert table.scale_down_candidate(exclude=["idle"]) == "busy"
    table.mark_draining("busy")
    table.mark_draining("idle")
    assert table.scale_down_candidate() is None


# ------------------------------------------------------------ surge gate


def test_surge_gate_inactive_is_passthrough_and_counts():
    async def fn():
        gate = rauto.SurgeGate(queue_cap=2, concurrency=1)
        t1, rej = await gate.enter()
        t2, rej2 = await gate.enter()
        assert rej is None and rej2 is None
        assert gate.snapshot()["in_flight"] == 2
        gate.exit(t1)
        gate.exit(t2)
        assert gate.snapshot()["in_flight"] == 0
        # hold times fed the EWMA even while inactive
        assert gate.snapshot()["service_ewma_ms"] < 500.0

    _run(fn())


def test_surge_gate_rejections_and_measured_retry_after():
    async def fn():
        gate = rauto.SurgeGate(queue_cap=1, max_wait_s=0.05,
                               concurrency=1, service_prior_ms=400.0)
        gate.set_active(True)
        ticket, rej = await gate.enter()
        assert rej is None
        # Deadline below the estimate: fast 429 before queueing.
        _, rej = await gate.enter(deadline_ms=100.0)
        assert rej is not None and rej[0] == "deadline_unmeetable"
        # est = (0 waiters + 1) * 400 / 1 = the measured-prior estimate
        assert rej[1] == pytest.approx(400.0)
        # Big-deadline request queues... and times out (slot never freed)
        _, rej = await gate.enter(deadline_ms=60000.0)
        assert rej is not None and rej[0] == "surge_timeout"
        # Fill the queue, then overflow it.
        waiter = asyncio.ensure_future(gate.enter())
        await asyncio.sleep(0)   # let it enqueue
        _, rej = await gate.enter()
        assert rej is not None and rej[0] == "surge_queue_full"
        assert rej[1] > 0
        # Releasing the slot grants the queued waiter.
        gate.exit(ticket)
        t2, rej2 = await waiter
        assert rej2 is None
        gate.exit(t2)
        snap = gate.snapshot()
        assert snap["rejected"] == {"deadline_unmeetable": 1,
                                    "surge_timeout": 1,
                                    "surge_queue_full": 1}
        assert snap["admitted_total"] == 2

    _run(fn())


def test_surge_raised_concurrency_grants_queued_waiters():
    """A scale-up raising the gate's bound must admit queued waiters
    NOW — not leave them timing out against free slots (grants
    otherwise only happen on exit())."""
    async def fn():
        gate = rauto.SurgeGate(queue_cap=4, max_wait_s=5.0, concurrency=1)
        gate.set_active(True)
        ticket, _ = await gate.enter()
        waiter = asyncio.ensure_future(gate.enter())
        await asyncio.sleep(0)
        assert gate.snapshot()["queue_depth"] == 1
        gate.set_concurrency(2)
        t2, rej = await waiter
        assert rej is None
        gate.exit(ticket)
        gate.exit(t2)

    _run(fn())


def test_surge_explicit_concurrency_pins_against_controller():
    router = _seeded_router()
    pinned = rauto.SurgeGate(concurrency=4)
    ctl = rauto.AutoscaleController(
        router, policy=rauto.AutoscalePolicy(min_replicas=1,
                                             max_replicas=3),
        surge=pinned)
    _run(ctl.tick())
    assert pinned.concurrency == 4          # operator bound survives
    tracked = rauto.SurgeGate()             # default: controller-owned
    ctl2 = rauto.AutoscaleController(
        router, policy=rauto.AutoscalePolicy(min_replicas=1,
                                             max_replicas=3),
        surge=tracked)
    _run(ctl2.tick())
    assert tracked.concurrency == 8         # 1 placeable x 8/replica


def test_surge_queued_caller_disconnect_retires_timeline():
    """A caller that hangs up while WAITING in the surge queue — the
    common case during the exact overload the gate exists for — must
    retire its router timeline (outcome=disconnect), or the in-flight
    map grows one ghost per impatient caller for the server's life."""

    class _Req:
        headers: dict = {}
        path = "/generate"

    async def fn():
        router = FleetRouter(ReplicaTable())
        router.surge.set_concurrency(1)
        router.surge.set_active(True)
        slot, rej = await router.surge.enter()   # hold the only slot
        assert rej is None
        task = asyncio.ensure_future(router.forward(_Req()))
        await asyncio.sleep(0.05)                # parked in the queue
        assert router.surge.snapshot()["queue_depth"] == 1
        assert len(router.flight.snapshot()["in_flight"]) == 1
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        snap = router.flight.snapshot(limit=5)
        assert snap["in_flight"] == []
        assert snap["completed"][0]["meta"]["outcome"] == "disconnect"
        # the gate's own accounting is clean too
        assert router.surge.snapshot()["queue_depth"] == 0
        router.surge.exit(slot)
        assert router.surge.snapshot()["in_flight"] == 0

    _run(fn())


def test_surge_gate_deactivation_drains_waiters():
    async def fn():
        gate = rauto.SurgeGate(queue_cap=4, max_wait_s=5.0, concurrency=1)
        gate.set_active(True)
        ticket, _ = await gate.enter()
        waiter = asyncio.ensure_future(gate.enter())
        await asyncio.sleep(0)
        assert gate.snapshot()["queue_depth"] == 1
        gate.set_active(False)   # overload over: everyone queued admitted
        t2, rej = await waiter
        assert rej is None
        gate.exit(ticket)
        gate.exit(t2)

    _run(fn())


# -------------------------------------------- tick / record / contract


def _seeded_router(queue_depth=12, util_tps=3800.0) -> FleetRouter:
    table = ReplicaTable()
    table.add("r0", "http://r0:1")
    table.update_health("r0", ok=True, body={
        "load": {"in_flight": 4, "queue_depth": queue_depth,
                 "rejected_total": 0},
        "rounds": {"rounds_completed": 9, "tokens_per_sec": 4000.0,
                   "wall_tokens_per_sec": util_tps, "avg_device_ms": 5.0,
                   "avg_bw_util": 0.6, "avg_drift_ratio": 1.0,
                   "interleaved_share": 0.2},
        "capacity": {"slots": 8, "decode_step_ms": 2.0,
                     "model_source": "test",
                     "capacity_tokens_per_sec": 4000.0},
    })
    return FleetRouter(table)


def test_tick_records_decision_with_fleet_joined_evidence():
    router = _seeded_router()
    ctl = rauto.AutoscaleController(
        router, policy=rauto.AutoscalePolicy(min_replicas=1,
                                             max_replicas=3),
        executor=None, surge=router.surge)
    rec = _run(ctl.tick())
    # Wanted a scale-up (overloaded) but has no executor: blocked, with
    # the evidence still carrying exactly what /debug/fleet showed.
    assert rec["action"] == "blocked" and "no executor" in rec["reason"]
    assert rec["target_replicas"] == 2
    assert rec["evidence"]["queue_depth"] == 12
    assert rec["evidence"]["utilization"] == pytest.approx(0.95)
    fleet = router.refresh_fleet()["fleet"]
    assert rec["evidence"]["capacity_tokens_per_sec"] == \
        fleet["capacity_tokens_per_sec"]
    snap = ctl.snapshot()
    assert rauto.validate_autoscale_snapshot(snap) == []
    assert snap["decisions_total"]["blocked"] == 1
    assert snap["target_replicas"] == 2


def test_tick_not_leader_blocks_execution():
    router = _seeded_router()

    class Boom:
        async def scale_to(self, *a, **kw):  # pragma: no cover
            raise AssertionError("a non-leader must never execute")

    ctl = rauto.AutoscaleController(
        router, policy=rauto.AutoscalePolicy(min_replicas=1,
                                             max_replicas=3),
        executor=Boom(), surge=router.surge, leader=lambda: False)
    rec = _run(ctl.tick())
    assert rec["action"] == "blocked" and "not leader" in rec["reason"]
    assert not rec["executed"] and rec["leader"] is False


def test_tick_executor_fault_lands_in_record_and_retries():
    router = _seeded_router()

    class Flaky:
        calls = 0

        async def scale_to(self, target, **kw):
            Flaky.calls += 1
            return {"ok": True, "added": ["rX"], "removed": [],
                    "error": None, "detail": "t"}

    ctl = rauto.AutoscaleController(
        router, policy=rauto.AutoscalePolicy(
            min_replicas=1, max_replicas=3, up_cooldown_s=0.0),
        executor=Flaky(), surge=router.surge)
    faults.set_plan("autoscale.execute=fail*1")
    rec = _run(ctl.tick())
    assert rec["action"] == "scale_up" and not rec["executed"]
    assert rec["executor"]["ok"] is False
    assert "injected fault" in rec["executor"]["error"]
    assert Flaky.calls == 0
    # The loop survives and the next cycle retries the executor.
    rec2 = _run(ctl.tick())
    assert rec2["executed"] and rec2["executor"]["ok"]
    assert Flaky.calls == 1


def test_validator_actually_fails_on_doctored_payloads():
    router = _seeded_router()
    ctl = rauto.AutoscaleController(
        router, policy=rauto.AutoscalePolicy(min_replicas=1,
                                             max_replicas=3),
        surge=router.surge)
    _run(ctl.tick())
    import copy
    snap = ctl.snapshot()
    broken = copy.deepcopy(snap)
    del broken["decisions"][0]["evidence"]["queue_depth"]
    assert any("queue_depth" in e
               for e in rauto.validate_autoscale_snapshot(broken))
    broken = copy.deepcopy(snap)
    broken["decisions"][0]["action"] = "panic"
    assert any("panic" in e
               for e in rauto.validate_autoscale_snapshot(broken))
    broken = copy.deepcopy(snap)
    del broken["surge"]["queue_cap"]
    assert any("queue_cap" in e
               for e in rauto.validate_autoscale_snapshot(broken))


def test_preflight_autoscale_check_green_and_can_fail(monkeypatch):
    from tools import preflight
    assert preflight.check_autoscale() == []
    orig = rauto.AutoscaleController.snapshot

    def broken(self, limit=50):
        snap = orig(self, limit=limit)
        del snap["surge"]
        return snap

    monkeypatch.setattr(rauto.AutoscaleController, "snapshot", broken)
    errs = preflight.check_autoscale()
    assert any("surge" in e for e in errs)


def test_slo_window_forget_drops_only_that_replica():
    win = SloWindow(window_s=600.0)
    win.record(replica="r0", outcome="error")
    win.record(replica="r0", outcome="ok", ttft_ms=5.0, duration_ms=9.0)
    win.record(replica="r1", outcome="ok", ttft_ms=5.0, duration_ms=9.0)
    assert win.forget("r0") == 2
    snap = win.snapshot(["r0", "r1"])
    assert snap["r0"]["requests"] == 0
    assert snap["r1"]["requests"] == 1


# --------------------------------------------------- live (engine fleet)


@pytest.fixture(scope="module")
def scale_engines():
    from generativeaiexamples_tpu.engine import Engine, EngineConfig
    from generativeaiexamples_tpu.models import llama
    from generativeaiexamples_tpu.models.configs import LlamaConfig
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    cfg = LlamaConfig(vocab_size=259 + 5, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16,
                      max_position_embeddings=1024)
    params = llama.init_params(cfg, jax.random.key(29), dtype=jnp.float32)
    # ONE prefill bucket (compiles happen at warmup, not mid-scenario);
    # host KV tier ON so an activated replica can land transferred
    # pages; 2 slots so a burst builds a real dispatch queue.
    ecfg = EngineConfig(
        max_slots=2, max_input_length=1024, max_output_length=48,
        prefill_buckets=(64,), max_prefill_bucket=64,
        dtype="float32", page_size=16, kv_pool_tokens=4096, max_queue=32,
        steps_per_round=4, kv_host_pool_tokens=4096)
    engines = [Engine(params, cfg, ByteTokenizer(), ecfg)
               for _ in range(3)]
    for e in engines:
        e.start()
    yield engines
    for e in engines:
        e.stop()


def _engine_apps(engines):
    from generativeaiexamples_tpu.chains.examples.developer_rag import (
        QAChatbot)
    from generativeaiexamples_tpu.chains.llm import EngineLLM
    from generativeaiexamples_tpu.chains.server import create_app
    from generativeaiexamples_tpu.embed.encoder import HashEmbedder
    from generativeaiexamples_tpu.utils.app_config import AppConfig
    from generativeaiexamples_tpu.utils.configuration import from_dict

    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "tpu-jax"},
        "embeddings": {"model_engine": "hash", "dimensions": 32},
    })
    return [create_app(QAChatbot(llm=EngineLLM(e),
                                 embedder=HashEmbedder(dim=32),
                                 config=cfg, fused_rag=False), config=cfg)
            for e in engines]


def _shed_total() -> float:
    return sum(v for k, v in obs_metrics.REGISTRY.snapshot().items()
               if k.startswith("shed_total{"))


def _gen_body(question, context, num_tokens=8, deadline_ms=None):
    headers = {}
    if deadline_ms:
        headers["X-Deadline-Ms"] = str(deadline_ms)
    return ({"question": question, "context": context,
             "use_knowledge_base": False, "num_tokens": num_tokens},
            headers)


@pytest.mark.chaos
def test_chaos_scale_up_during_burst_before_first_shed(scale_engines):
    """ISSUE 13 acceptance (a): a Poisson-ish burst builds queue depth
    on the lone active replica; the controller's tick records scale_up
    with the queue evidence BEFORE any shed_total increment; the
    activated replica takes the next placement immediately and its
    first placement carries the KV-transfer donor hint."""
    engines = scale_engines[:2]

    async def fn():
        servers = [TestServer(app) for app in _engine_apps(engines)]
        for s in servers:
            await s.start_server()
        urls = [f"http://127.0.0.1:{s.port}" for s in servers]
        table = ReplicaTable()

        def factory(router):
            executor = rauto.LocalExecutor(router, [("r1", urls[1])],
                                           drain_wait_s=10.0)
            policy = rauto.AutoscalePolicy(
                min_replicas=1, max_replicas=2, queue_high=2.0,
                up_cooldown_s=0.0)
            return rauto.AutoscaleController(
                router, policy=policy, executor=executor,
                surge=router.surge, slo_ttft_ms=60000.0)

        router_app = create_router_app(
            [("r0", urls[0])], table=table, policy="affinity",
            heartbeat_s=30, run_heartbeat=False, kv_transfer=True,
            autoscale_factory=factory, run_autoscale=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        try:
            # Warm every geometry on BOTH replicas directly (compiles
            # happen here, not under the measured burst).
            async with aiohttp.ClientSession() as s:
                for url in urls:
                    body, _ = _gen_body("warm q " + "w" * 30,
                                        "warm ctx " + "c" * 200)
                    async with s.post(f"{url}/generate",
                                      json=body) as resp:
                        assert resp.status == 200, await resp.text()
                        await resp.read()
            # Shared session context. ONE seeded turn through the router
            # while the fleet is idle teaches r0's affinity sketch the
            # context prefix — the donor coverage the post-scale hint
            # will point at.
            context = "burst session " + "x" * 240
            body, headers = _gen_body("seed q " + "q" * 30, context,
                                      num_tokens=4, deadline_ms=120000)
            async with client.post("/generate", json=body,
                                   headers=headers) as resp:
                assert resp.status == 200
                await resp.read()
            assert len(table.get("r0").sketch) >= 2
            shed0 = _shed_total()
            hints0 = obs_metrics.REGISTRY.snapshot().get(
                "router_kv_transfer_hints_total", 0.0)

            async def one(i: int):
                body, headers = _gen_body(
                    f"burst q{i} " + "q" * 30, context,
                    num_tokens=16, deadline_ms=120000)
                async with client.post("/generate", json=body,
                                       headers=headers) as resp:
                    assert resp.status == 200, await resp.text()
                    await resp.read()
                    return resp.headers.get("X-Routed-Replica")

            burst = [asyncio.ensure_future(one(i)) for i in range(6)]
            # Let the burst hit r0's dispatch queue, then observe it the
            # way the production loop does: heartbeat -> tick.
            await asyncio.sleep(0.25)
            await client.post("/control/heartbeat")
            resp = await client.post("/control/autoscale",
                                     json={"op": "tick"})
            rec = await resp.json()
            # The scale-up decision landed BEFORE any shed: honest
            # leading-indicator scaling, not reaction to drops.
            assert rec["action"] == "scale_up", rec
            assert rec["target_replicas"] == 2
            assert rec["evidence"]["queue_depth"] >= 2
            assert _shed_total() == shed0
            assert rec["executed"] and rec["executor"]["added"] == ["r1"]
            # The activated replica is placeable NOW (probe-on-add).
            assert table.get("r1") is not None
            assert table.get("r1").placeable()
            # The fleet snapshot joins the decision's evidence.
            fleet = await (await client.get("/debug/fleet")).json()
            assert fleet["fleet"]["replicas_total"] == 2
            # While r0 still chews the burst, the next same-session
            # request places on the fresh replica WITH a donor hint
            # (r0's sketch covers the context prefix) — the PR-11 warm
            # path instead of a cold prefill.
            body, headers = _gen_body("post-scale q " + "q" * 30,
                                      context, num_tokens=4,
                                      deadline_ms=120000)
            async with client.post("/generate", json=body,
                                   headers=headers) as resp2:
                assert resp2.status == 200
                served = resp2.headers.get("X-Routed-Replica")
                await resp2.read()
            assert served == "r1", served
            hints1 = obs_metrics.REGISTRY.snapshot().get(
                "router_kv_transfer_hints_total", 0.0)
            assert hints1 - hints0 >= 1
            routed = set(await asyncio.gather(*burst))
            assert routed == {"r0"}   # the burst itself stayed home
            # /debug/autoscale is live on the endpoint and validates.
            snap = await (await client.get("/debug/autoscale")).json()
            assert rauto.validate_autoscale_snapshot(snap) == []
            assert snap["decisions_total"].get("scale_up", 0) >= 1
        finally:
            await client.close()
            for s in servers:
                await s.close()

    _run(fn())


@pytest.mark.chaos
def test_chaos_rolling_restart_under_load(scale_engines):
    """ISSUE 13 acceptance (b): drain -> remove -> re-add each replica
    of a 3-replica fleet under continuous open-loop traffic. Zero
    mid-stream losses, zero 5xx — the only tolerated failure is 429
    backpressure — and every replica returns placeable with clean
    state."""
    engines = scale_engines

    async def fn():
        servers = [TestServer(app) for app in _engine_apps(engines)]
        for s in servers:
            await s.start_server()
        urls = [f"http://127.0.0.1:{s.port}" for s in servers]
        names = [f"r{i}" for i in range(3)]
        table = ReplicaTable()
        router_app = create_router_app(
            list(zip(names, urls)), table=table, policy="affinity",
            heartbeat_s=0.2, run_heartbeat=True)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        rows: list[dict] = []
        stop = asyncio.Event()

        async def traffic(worker: int):
            i = 0
            while not stop.is_set():
                body, headers = _gen_body(
                    f"rr w{worker} q{i} " + "q" * 30,
                    f"rolling ctx {worker} " + "y" * 200,
                    num_tokens=8, deadline_ms=120000)
                row = {"status": None, "body": "", "worker": worker}
                try:
                    async with client.post("/generate", json=body,
                                           headers=headers) as resp:
                        row["status"] = resp.status
                        row["body"] = (await resp.read()).decode(
                            "utf-8", errors="replace")
                except aiohttp.ClientError as exc:
                    row["status"] = f"exc:{exc}"
                rows.append(row)
                i += 1
                await asyncio.sleep(0.02)

        try:
            # Warm all three replicas through the router first.
            async with aiohttp.ClientSession() as s:
                for url in urls:
                    body, _ = _gen_body("warm q " + "w" * 30,
                                        "warm ctx " + "c" * 200)
                    async with s.post(f"{url}/generate",
                                      json=body) as resp:
                        assert resp.status == 200
                        await resp.read()
            workers = [asyncio.ensure_future(traffic(w))
                       for w in range(3)]
            await asyncio.sleep(0.3)
            for name, url in zip(names, urls):
                resp = await client.post(
                    "/control/replicas",
                    json={"op": "remove", "name": name, "drain": True,
                          "wait_s": 30})
                assert resp.status == 200
                assert (await resp.json())["drained"]
                # The pod "restarts": the in-process stand-in for a
                # fresh process is reopening its admission.
                await asyncio.sleep(0.1)
                async with aiohttp.ClientSession() as s:
                    await (await s.post(f"{url}/control/undrain")).read()
                resp = await client.post(
                    "/control/replicas",
                    json={"op": "add", "name": name, "url": url})
                assert resp.status == 200
                added = await resp.json()
                # ... and returns CLEAN: fresh sketch, closed breaker.
                assert added["replica"]["sketch_blocks"] == 0
                assert added["replica"]["breaker"] == "closed"
                assert added["replica"]["placeable"]
                await asyncio.sleep(0.2)
            stop.set()
            await asyncio.gather(*workers)
            assert len(rows) >= 10
            statuses = {r["status"] for r in rows}
            # zero 5xx, zero transport errors: rollouts look like
            # backpressure (429) or success, never failure
            assert statuses <= {200, 429}, statuses
            for r in rows:
                if r["status"] == 200:
                    assert "[error]" not in r["body"], r
                    assert "replica_lost" not in r["body"], r
            # no mid-stream loss reached the router's outcome ring
            router = router_app[ROUTER]
            outcomes = router.flight.slo.snapshot()
            for name, stats in outcomes.items():
                if name.startswith("_"):
                    continue
                assert stats["outcomes"].get("midstream_loss", 0) == 0
            # the fleet is whole again
            await client.post("/control/heartbeat")
            fleet = await (await client.get("/debug/fleet")).json()
            assert fleet["fleet"]["replicas_placeable"] == 3
        finally:
            stop.set()
            await client.close()
            for s in servers:
                await s.close()

    _run(fn())


# --------------------------------------------------- heartbeat satellite


def test_heartbeat_stalled_replica_does_not_delay_siblings():
    """One replica's stalled probe (injected delay) must not hold up a
    sibling's health refresh: each probe applies its result the moment
    IT finishes, and the straggler is bounded by its own timeout."""
    from tests.test_router import EchoExample
    from generativeaiexamples_tpu.chains.server import create_app

    async def fn():
        replica = TestServer(create_app(EchoExample()))
        await replica.start_server()
        table = ReplicaTable()
        router_app = create_router_app(
            [("slow", f"http://127.0.0.1:{replica.port}"),
             ("fast", f"http://127.0.0.1:{replica.port}")],
            table=table, heartbeat_s=30, run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        router = router_app[ROUTER]
        try:
            faults.set_plan("replica.heartbeat[slow]=delay:0.8")
            t0 = time.monotonic()
            sweep = asyncio.ensure_future(router.heartbeat_once())
            await asyncio.sleep(0.3)
            fast = table.get("fast")
            # The fast sibling's health landed while the slow probe is
            # still sleeping in its executor thread.
            assert not sweep.done()
            assert fast.last_heartbeat_t >= t0
            assert fast.reachable
            await sweep
            assert table.get("slow").reachable   # delayed, not dead
        finally:
            faults.clear()
            await client.close()
            await replica.close()

    _run(fn())


def test_heartbeat_hung_probe_bounded_by_per_poll_timeout():
    from tests.test_router import EchoExample
    from generativeaiexamples_tpu.chains.server import create_app

    async def fn():
        replica = TestServer(create_app(EchoExample()))
        await replica.start_server()
        table = ReplicaTable()
        router_app = create_router_app(
            [("wedged", f"http://127.0.0.1:{replica.port}"),
             ("ok", f"http://127.0.0.1:{replica.port}")],
            table=table, heartbeat_s=30, run_heartbeat=False)
        router_app[ROUTER].heartbeat_timeout_s = 0.2
        client = TestClient(TestServer(router_app))
        await client.start_server()
        router = router_app[ROUTER]
        try:
            faults.set_plan("replica.heartbeat[wedged]=hang")
            t0 = time.monotonic()
            await router.heartbeat_once()
            # Bounded by timeout + slack, NOT by the 30 s hang cap.
            assert time.monotonic() - t0 < 5.0
            assert not table.get("wedged").reachable
            assert table.get("wedged").heartbeat_failures >= 1
            assert table.get("ok").reachable
        finally:
            faults.clear()
            await client.close()
            await replica.close()

    _run(fn())


def test_heartbeat_sweep_jitter_desynchronizes():
    table = ReplicaTable()
    router = FleetRouter(table, heartbeat_s=2.0, heartbeat_jitter=0.25)
    delays = [router._next_heartbeat_delay() for _ in range(64)]
    assert all(1.5 <= d <= 2.5 for d in delays)
    assert len({round(d, 6) for d in delays}) > 1   # actually jittered
    # jitter 0 pins the period exactly (the bench's determinism knob)
    router0 = FleetRouter(table, heartbeat_s=2.0, heartbeat_jitter=0.0)
    assert router0._next_heartbeat_delay() == 2.0
