"""Smoke for tools/profile_decode.py --json: the roofline-attribution
artifact (PROFILE_rNN.json round record) must be written with a stable
key set, on any backend — the driver diffs these fields round over
round, so a rename here is as breaking as a bench-field rename."""

import json
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


REQUIRED_KEYS = {
    "tool", "model", "device", "platform", "quant", "kv_quant",
    "slots", "window_pages", "live_pages", "steps_per_round", "page_size",
    "param_gb", "kv_live_bytes",
    "full_ms_per_step", "no_unembed_ms_per_step", "window1_ms_per_step",
    "unembed_ms_per_step", "window_stream_ms_per_step",
    "matmul_floor_ms_per_step", "tokens_per_sec",
    # step-cost model inputs for the token-budget scheduler
    "prefill_bucket_tokens", "prefill_ms_per_token",
}


def test_profile_decode_json_artifact(tmp_path, monkeypatch):
    import profile_decode

    monkeypatch.setenv("PROF_MODEL", "llama-tiny")
    monkeypatch.setenv("PROF_QUANT", "none")
    monkeypatch.setenv("PROF_SLOTS", "2")
    monkeypatch.setenv("PROF_WINDOW", "2")
    monkeypatch.setenv("PROF_STEPS", "4")
    path = str(tmp_path / "PROFILE_test.json")
    artifact = profile_decode.main(json_path=path)
    assert os.path.exists(path)
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk == artifact
    assert set(on_disk) == REQUIRED_KEYS
    assert on_disk["tool"] == "profile_decode"
    assert on_disk["full_ms_per_step"] > 0
    # attribution decomposes the full round: ablations can't be slower
    # than the full program by more than noise
    assert on_disk["unembed_ms_per_step"] > -1.0
    assert on_disk["window_stream_ms_per_step"] > -1.0


def test_committed_round_artifact_is_valid():
    """The committed PROFILE_rNN.json next to BENCH parses and carries
    the same contract (whatever round number is current)."""
    import glob
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    artifacts = sorted(glob.glob(os.path.join(root, "PROFILE_r*.json")))
    assert artifacts, "no committed PROFILE_rNN.json round artifact"
    with open(artifacts[-1]) as f:
        obj = json.load(f)
    assert set(obj) == REQUIRED_KEYS
