"""Fleet observability spine tests (tier-1, CPU) — ISSUE 12.

Unit: SLO-window attainment/aging semantics, router flight outcomes,
placement-decision evidence, heartbeat-failure accounting, fleet
snapshot assembly + element-wise schema validation (and that the
validators actually FAIL on doctored data). Live: end-to-end trace join
— one ``X-Request-ID`` appears in the router's ``/debug/requests``, the
replica's ``/debug/requests``, AND the engine round-record grant list —
and the chaos acceptance: two engine replicas behind the router with a
``FAULT_PLAN`` partitioning one; within one heartbeat ``/debug/fleet``
shows that replica breaker-open with its window attainment dropping
while fleet totals stay consistent, and after recovery a single
request's router timeline records the placement decision, the retry,
and a router-observed TTFT that reconciles with the replica flight
recorder's TTFT for the same request ID.
"""

import asyncio
import time

import pytest

import jax
import jax.numpy as jnp

import aiohttp  # noqa: F401 — skip cleanly where aiohttp is absent
from aiohttp.test_utils import TestClient, TestServer

from generativeaiexamples_tpu.obs import flight as obs_flight
from generativeaiexamples_tpu.obs import rounds as obs_rounds
from generativeaiexamples_tpu.router import fleet as router_fleet
from generativeaiexamples_tpu.router.flight import (ROUTER_SELF,
                                                    RouterFlightRecorder,
                                                    SloWindow)
from generativeaiexamples_tpu.router.server import ROUTER, create_router_app
from generativeaiexamples_tpu.router.table import ReplicaTable
from generativeaiexamples_tpu.utils import faults, resilience


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    resilience.reset_breakers()
    yield
    faults.clear()
    resilience.reset_breakers()


def _run(coro):
    loop = asyncio.get_event_loop_policy().new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ------------------------------------------------------------- SLO window


def test_slo_window_deadline_vs_ttft_semantics():
    win = SloWindow(window_s=60.0, slo_ttft_ms=100.0)
    # No deadline: TTFT under the default SLO attains.
    assert win.record(replica="r0", outcome="ok", ttft_ms=50.0,
                      duration_ms=200.0)
    assert not win.record(replica="r0", outcome="ok", ttft_ms=150.0,
                          duration_ms=200.0)
    # With a deadline, attainment is deadline-met — TTFT is irrelevant.
    assert win.record(replica="r0", outcome="ok", ttft_ms=900.0,
                      duration_ms=900.0, deadline_ms=1000.0)
    assert not win.record(replica="r0", outcome="ok", ttft_ms=10.0,
                          duration_ms=1500.0, deadline_ms=1000.0)
    # Non-ok outcomes never attain, whatever the numbers say.
    assert not win.record(replica="r0", outcome="midstream_loss",
                          ttft_ms=1.0, duration_ms=2.0, deadline_ms=1e6)
    snap = win.snapshot()["r0"]
    assert snap["requests"] == 5 and snap["attained"] == 2
    assert snap["attainment"] == 0.4
    assert snap["midstream_loss_rate"] == 0.2


def test_slo_window_rates_and_total_consistency():
    win = SloWindow(window_s=60.0)
    win.record(replica="r0", outcome="ok", ttft_ms=5.0, duration_ms=9.0)
    win.record(replica="r0", outcome="shed")
    win.record(replica="r1", outcome="connect_fail")
    win.record(replica="r1", outcome="error")
    win.record(replica="r1", outcome="disconnect")
    snap = win.snapshot(["r0", "r1", "r2"])
    assert snap["r0"]["shed_rate"] == 0.5
    # connect_fail + error count as errors; disconnect does NOT
    assert snap["r1"]["error_rate"] == round(2 / 3, 4)
    assert snap["r2"]["requests"] == 0 and snap["r2"]["attainment"] is None
    total = snap["_total"]
    assert total["requests"] == sum(
        snap[r]["requests"] for r in ("r0", "r1", "r2"))
    assert total["attained"] == sum(
        snap[r]["attained"] for r in ("r0", "r1", "r2"))


def test_slo_window_rows_age_out():
    win = SloWindow(window_s=0.05)
    win.record(replica="r0", outcome="error")
    assert win.snapshot()["r0"]["error_rate"] == 1.0
    time.sleep(0.08)
    win.record(replica="r0", outcome="ok", ttft_ms=1.0, duration_ms=2.0)
    snap = win.snapshot()["r0"]
    # the old incident aged out of the window; only the fresh row counts
    assert snap["requests"] == 1 and snap["error_rate"] == 0.0


# ----------------------------------------------------- router flight unit


def test_router_flight_outcome_and_timeline_contract():
    rec = RouterFlightRecorder(slo=SloWindow(window_s=60.0))
    tl = rec.begin_request({"X-Request-ID": "rf-1",
                            "X-Deadline-Ms": "5000"}, "/generate")
    assert tl.request_id == "rf-1"
    assert tl.meta["deadline_ms"] == 5000.0
    rec.placement(tl, replica="r0", affinity_blocks=3,
                  candidates=[{"replica": "r0", "score": 6.0,
                               "affinity_blocks": 3, "queue_depth": 0,
                               "in_flight": 1}],
                  t_start=tl.t_start, kv_donor="http://r1:8081")
    rec.attempt_failed(tl, replica="r0", reason="connect", retried=True)
    rec.first_byte(tl)
    rec.first_byte(tl)   # idempotent: only the first byte stamps TTFT
    rec.complete_request(tl, outcome="ok", replica="r1", status=200)
    rec.complete_request(tl, outcome="error")   # first outcome wins
    d = tl.to_dict()
    assert router_fleet.validate_router_timeline(d) == []
    names = [e["event"] for e in d["events"]]
    assert names.count("router_ttft") == 1
    for expected in ("router_place", "place", "kv_transfer_hint",
                     "retry", "finish"):
        assert expected in names, names
    place = next(e for e in d["events"] if e["event"] == "place")
    assert place["value"]["replica"] == "r0"
    assert place["value"]["candidates"][0]["score"] == 6.0
    assert d["meta"]["outcome"] == "ok" and d["meta"]["replica"] == "r1"
    # the connect failure landed one attempt row against r0; the final
    # ok (within its deadline) against r1
    snap = rec.slo.snapshot()
    assert snap["r0"]["outcomes"] == {"connect_fail": 1}
    assert snap["r1"]["attained"] == 1
    # and the recorder's completed ring serves /debug/requests
    assert rec.snapshot(limit=5)["completed"][0]["request_id"] == "rf-1"


def test_place_explained_matches_choice_evidence():
    table = ReplicaTable()
    table.add("r0", "http://a")
    table.add("r1", "http://b")
    blocks = table.affinity_blocks("shared system prompt " + "x" * 300)
    rep, dec = table.place_explained(blocks)
    table.record_placement(rep, blocks)
    rep2, dec2 = table.place_explained(blocks)
    # the sketch learned the prompt: the home replica wins with a
    # nonzero affinity match, and the evidence says so
    assert rep2.name == rep.name
    assert dec2["affinity_blocks"] > 0
    assert len(dec2["candidates"]) == 2
    by_name = {c["replica"]: c for c in dec2["candidates"]}
    assert by_name[rep.name]["score"] > by_name[
        "r1" if rep.name == "r0" else "r0"]["score"]
    assert dec["policy"] == "affinity"


# ------------------------------------------------- fleet snapshot (unit)


def _seeded_state():
    table = ReplicaTable()
    table.add("r0", "http://r0:1")
    table.add("r1", "http://r1:1")
    table.update_health("r0", ok=True, body={
        "load": {"in_flight": 2, "queue_depth": 3, "rejected_total": 0,
                 "prefix_hit_rate": 0.5},
        "rounds": {"rounds_completed": 4, "tokens_per_sec": 300.0,
                   "wall_tokens_per_sec": 40.0, "avg_device_ms": 5.0,
                   "avg_bw_util": 0.2, "avg_drift_ratio": 1.0,
                   "interleaved_share": 0.1},
        "capacity": {"slots": 4, "decode_step_ms": 2.0,
                     "model_source": "test",
                     "capacity_tokens_per_sec": 2000.0},
        "kv_tier": {"host_pages": 7, "offload_pages": 9,
                    "restore_pages": 3, "transfer_pages": 1},
    })
    table.update_health("r1", ok=False)
    win = SloWindow(window_s=600.0)
    win.record(replica="r0", outcome="ok", ttft_ms=10.0, duration_ms=20.0)
    win.record(replica="r1", outcome="connect_fail")
    win.record(replica=ROUTER_SELF, outcome="shed")
    return table, win


def test_fleet_snapshot_contract_and_headroom():
    table, win = _seeded_state()
    snap = router_fleet.build_fleet_snapshot(table, win, heartbeat_s=2.0)
    assert router_fleet.validate_fleet_snapshot(snap) == []
    rows = {r["name"]: r for r in snap["replicas"]}
    r0, r1 = rows["r0"], rows["r1"]
    # headroom = modeled capacity - observed wall token rate
    assert r0["capacity_tokens_per_sec"] == 2000.0
    assert r0["headroom_tokens_per_sec"] == 1960.0
    assert r0["kv_tier"]["host_pages"] == 7
    # the partitioned sibling: heartbeat failure counted, no telemetry
    assert r1["heartbeat_failures"] == 1 and not r1["reachable"]
    assert r1["rounds"] is None and r1["capacity"] is None
    assert r1["headroom_tokens_per_sec"] is None
    # fleet totals are sums of the rows (incl. the _router shed bucket
    # in window_requests — totals aggregate every outcome row)
    fl = snap["fleet"]
    assert fl["replicas_total"] == 2 and fl["replicas_placeable"] == 1
    assert fl["capacity_tokens_per_sec"] == 2000.0
    assert fl["headroom_tokens_per_sec"] == 1960.0
    assert fl["window_requests"] == 3
    assert fl["kv_tier_host_pages"] == 7
    # fleet attainment is REQUEST-level: the connect_fail attempt row
    # leaves the denominator (the request it belonged to has its own
    # terminal row); the shed and the ok remain -> 1 of 2
    assert fl["slo_attainment"] == 0.5


def test_fleet_capacity_counts_placeable_replicas_only():
    """A dead or draining replica's last-seen capacity block must not
    inflate the fleet headroom an autoscaler reads — lost capacity has
    to LOOK lost."""
    table, win = _seeded_state()
    before = router_fleet.build_fleet_snapshot(table, win, heartbeat_s=2.0)
    assert before["fleet"]["capacity_tokens_per_sec"] == 2000.0
    table.mark_draining("r0")
    snap = router_fleet.build_fleet_snapshot(table, win, heartbeat_s=2.0)
    rows = {r["name"]: r for r in snap["replicas"]}
    # the row keeps its numbers (state is visible right next to them)...
    assert rows["r0"]["capacity_tokens_per_sec"] == 2000.0
    assert rows["r0"]["draining"] and not rows["r0"]["placeable"]
    # ... but the fleet totals no longer count it
    assert snap["fleet"]["capacity_tokens_per_sec"] == 0.0
    assert snap["fleet"]["headroom_tokens_per_sec"] == 0.0


def test_fleet_validators_actually_fail():
    table, win = _seeded_state()
    snap = router_fleet.build_fleet_snapshot(table, win, heartbeat_s=2.0)
    import copy
    broken = copy.deepcopy(snap)
    broken["replicas"][0]["headroom_tps"] = \
        broken["replicas"][0].pop("headroom_tokens_per_sec")
    errs = router_fleet.validate_fleet_snapshot(broken)
    assert any("headroom_tokens_per_sec" in e for e in errs)
    assert any("unknown key" in e for e in errs)
    broken = copy.deepcopy(snap)
    broken["fleet"]["slo_attainment"] = "high"
    assert any("slo_attainment" in e
               for e in router_fleet.validate_fleet_snapshot(broken))
    # the timeline validator too (the preflight check leans on both)
    tl = {"request_id": "x", "started_unix_ms": 1, "age_ms": 1.0,
          "done": True, "meta": {}, "events": [{"t_ms": 0.1}],
          "events_dropped": 0}
    assert any("events[0]" in e
               for e in router_fleet.validate_router_timeline(tl))


def test_preflight_fleet_obs_check_green_and_can_fail(monkeypatch):
    from tools import preflight
    assert preflight.check_fleet_obs() == []
    # doctor the snapshot builder: the check must notice, not shrug
    orig = router_fleet.build_fleet_snapshot

    def broken(*a, **kw):
        snap = orig(*a, **kw)
        del snap["fleet"]["headroom_tokens_per_sec"]
        return snap

    monkeypatch.setattr(router_fleet, "build_fleet_snapshot", broken)
    errs = preflight.check_fleet_obs()
    assert any("headroom_tokens_per_sec" in e for e in errs)


# ----------------------------------------------- live (echo replicas)


def test_debug_endpoints_and_heartbeat_blind_spot_live():
    """Echo-replica e2e: the router serves /debug/requests and
    /debug/fleet; a request's timeline lands with placement evidence and
    TTFT; a dead replica's heartbeat failures become visible in both the
    snapshot and the counter (the blind spot this PR closes)."""
    from tests.test_router import EchoExample, _snapshot
    from generativeaiexamples_tpu.chains.server import create_app

    async def fn():
        replica = TestServer(create_app(EchoExample()))
        await replica.start_server()
        router_app = create_router_app(
            [("r0", f"http://127.0.0.1:{replica.port}"),
             ("dead", "http://127.0.0.1:1")],   # nothing listens there
            policy="affinity", heartbeat_s=30, run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        try:
            fails0 = _snapshot(
                'router_heartbeat_failures_total{replica="dead"}')
            resp = await client.post(
                "/generate", json={"question": "hello fleet",
                                   "use_knowledge_base": False},
                headers={"X-Request-ID": "obs-live-1",
                         "X-Deadline-Ms": "30000"})
            assert resp.status == 200
            await resp.read()
            snap = await (await client.get(
                "/debug/requests?limit=10")).json()
            tl = next(t for t in snap["completed"]
                      if t["request_id"] == "obs-live-1")
            assert router_fleet.validate_router_timeline(tl) == []
            names = [e["event"] for e in tl["events"]]
            assert "place" in names and "router_ttft" in names
            assert tl["meta"]["outcome"] == "ok"
            assert tl["meta"]["replica"] == "r0"   # dead can't serve
            assert tl["meta"]["ttft_ms"] > 0
            # one heartbeat: the dead replica's failure is COUNTED, not
            # just a silent breaker flip
            await client.post("/control/heartbeat")
            fleet = await (await client.get("/debug/fleet")).json()
            assert router_fleet.validate_fleet_snapshot(fleet) == []
            rows = {r["name"]: r for r in fleet["replicas"]}
            assert rows["dead"]["heartbeat_failures"] >= 1
            assert not rows["dead"]["reachable"]
            assert rows["r0"]["heartbeat_failures"] == 0
            assert _snapshot(
                'router_heartbeat_failures_total{replica="dead"}') \
                - fails0 >= 1
            # ages published for scrape (the /metrics refresh path)
            body = await (await client.get("/metrics")).text()
            assert 'router_heartbeat_age_seconds{replica="r0"}' in body
            assert "router_ttft_seconds_bucket" in body
        finally:
            await client.close()
            await replica.close()

    _run(fn())


def test_router_slo_window_sees_midstream_loss_live():
    """A replica that dies mid-stream lands a midstream_loss outcome in
    the window and the fleet snapshot's rates reflect it."""
    from tests.test_chaos import _stub_replica

    async def fn():
        dying = TestServer(_stub_replica(kill_mid_stream=True))
        await dying.start_server()
        router_app = create_router_app(
            [("r0", f"http://127.0.0.1:{dying.port}")],
            policy="affinity", heartbeat_s=30, run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        try:
            resp = await client.post("/generate", json={"question": "q"},
                                     headers={"X-Request-ID": "loss-1"})
            assert resp.status == 200
            body = (await resp.read()).decode()
            assert "replica_lost" in body
            snap = await (await client.get("/debug/requests")).json()
            tl = next(t for t in snap["completed"]
                      if t["request_id"] == "loss-1")
            assert tl["meta"]["outcome"] == "midstream_loss"
            assert "midstream_loss" in [e["event"] for e in tl["events"]]
            fleet = (await (await client.get("/debug/fleet")).json())
            row = next(r for r in fleet["replicas"] if r["name"] == "r0")
            assert row["slo"]["midstream_loss_rate"] == 1.0
            assert fleet["fleet"]["midstream_loss_rate"] == 1.0
        finally:
            await client.close()
            await dying.close()

    _run(fn())


# --------------------------------------------- live (engine replicas)


@pytest.fixture(scope="module")
def obs_engines():
    from generativeaiexamples_tpu.engine import Engine, EngineConfig
    from generativeaiexamples_tpu.models import llama
    from generativeaiexamples_tpu.models.configs import LlamaConfig
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    cfg = LlamaConfig(vocab_size=259 + 5, hidden_size=64,
                      intermediate_size=128, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16,
                      max_position_embeddings=1024)
    params = llama.init_params(cfg, jax.random.key(27), dtype=jnp.float32)
    # ONE prefill bucket so every chunk compiles the same program — a
    # warm turn must never pay a fresh XLA compile that drowns the
    # TTFT-reconciliation signal (same reasoning as test_router's
    # acceptance fixture).
    ecfg = EngineConfig(
        max_slots=2, max_input_length=1024, max_output_length=32,
        prefill_buckets=(64,), max_prefill_bucket=64,
        dtype="float32", page_size=16, kv_pool_tokens=4096, max_queue=16,
        steps_per_round=4)
    engines = [Engine(params, cfg, ByteTokenizer(), ecfg)
               for _ in range(2)]
    for e in engines:
        e.start()
    yield engines
    for e in engines:
        e.stop()


def _engine_apps(engines):
    from generativeaiexamples_tpu.chains.examples.developer_rag import (
        QAChatbot)
    from generativeaiexamples_tpu.chains.llm import EngineLLM
    from generativeaiexamples_tpu.chains.server import create_app
    from generativeaiexamples_tpu.embed.encoder import HashEmbedder
    from generativeaiexamples_tpu.utils.app_config import AppConfig
    from generativeaiexamples_tpu.utils.configuration import from_dict

    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "tpu-jax"},
        "embeddings": {"model_engine": "hash", "dimensions": 32},
    })
    return [create_app(QAChatbot(llm=EngineLLM(e),
                                 embedder=HashEmbedder(dim=32),
                                 config=cfg, fused_rag=False), config=cfg)
            for e in engines]


def _gen(question, context, rid=None, deadline_ms=None, num_tokens=6):
    headers = {}
    if rid:
        headers["X-Request-ID"] = rid
    if deadline_ms:
        headers["X-Deadline-Ms"] = str(deadline_ms)
    return ({"question": question, "context": context,
             "use_knowledge_base": False, "num_tokens": num_tokens},
            headers)


def test_acceptance_trace_join_and_partition_fleet_view(obs_engines,
                                                        monkeypatch):
    """ISSUE 12 acceptance. (a) Trace join: one X-Request-ID appears in
    the router's /debug/requests, the replica's /debug/requests, and the
    engine round-record grant list. (b) Chaos: FAULT_PLAN partitions the
    busier replica — within one heartbeat /debug/fleet shows it
    breaker-open with window attainment dropping while fleet totals stay
    consistent; after recovery, a request's router timeline records the
    placement decision, the retry, and a router-observed TTFT that
    reconciles with the replica recorder's TTFT for the same ID."""
    engines = obs_engines
    # The window must comfortably cover the whole CPU-paced scenario.
    monkeypatch.setenv("ROUTER_SLO_WINDOW_S", "600")

    async def fn():
        servers = [TestServer(app) for app in _engine_apps(engines)]
        for s in servers:
            await s.start_server()
        urls = [f"http://127.0.0.1:{s.port}" for s in servers]
        # Short breaker cooldown so recovery fits the test; everything
        # else production-default.
        table = ReplicaTable(breaker_failures=3, breaker_cooldown_s=2.0)
        router_app = create_router_app(
            [(f"r{i}", u) for i, u in enumerate(urls)], table=table,
            policy="affinity", heartbeat_s=30, run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        router = router_app[ROUTER]
        try:
            # Warm every geometry on BOTH replicas (compiles happen
            # here, not under measurement).
            async with aiohttp.ClientSession() as s:
                for url in urls:
                    for t in range(2):
                        body, _ = _gen(f"warm q{t} " + "w" * 30,
                                       "warm ctx " + "c" * 150)
                        async with s.post(f"{url}/generate",
                                          json=body) as resp:
                            assert resp.status == 200, await resp.text()
                            await resp.read()

            def session_ctx(i: int) -> str:
                return f"fleet-obs session {i} " + chr(97 + i) * 160

            # ---------------- (a) trace join
            body, headers = _gen("join question " + "q" * 30,
                                 session_ctx(0),
                                 rid="join-fleet-1", deadline_ms=60000)
            resp = await client.post("/generate", json=body,
                                     headers=headers)
            assert resp.status == 200
            join_rep = resp.headers["X-Routed-Replica"]
            join_i = int(join_rep[1])
            await resp.read()
            # router timeline, by the SAME id
            rsnap = await (await client.get(
                "/debug/requests?limit=20")).json()
            rtl = next(t for t in rsnap["completed"]
                       if t["request_id"] == "join-fleet-1")
            assert router_fleet.validate_router_timeline(rtl) == []
            assert rtl["meta"]["replica"] == join_rep
            # replica timeline, same id (the GLOBAL recorder serves the
            # in-process replicas' /debug/requests)
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{urls[join_i]}/debug/requests") as r:
                    repl = await r.json()
            repl_tl = next(t for t in repl["completed"]
                           if t["request_id"] == "join-fleet-1")
            assert repl_tl["meta"]["generated"] > 0
            # engine round grants, same id — the JOIN contract, not
            # just header forwarding
            grant_ids = {rid for rec in obs_rounds.RECORDER.records()
                         for rid, _ in rec.grants}
            assert "join-fleet-1" in grant_ids

            # Seed 6 DISTINCT sessions (one turn each): the placement
            # tie-break rotation spreads them, and by pigeonhole one
            # replica homes >= 3. That side is the partition target —
            # each of its sessions' NEXT turn insists on it (their
            # prefix lives only in its sketch), so the partition's
            # connect failures are deterministic even after a retried
            # turn teaches the sibling one session's blocks.
            homes: dict = {}
            for i in range(6):
                body, headers = _gen(f"seed q{i} " + "q" * 30,
                                     session_ctx(i), deadline_ms=60000)
                resp = await client.post("/generate", json=body,
                                         headers=headers)
                assert resp.status == 200
                homes.setdefault(resp.headers["X-Routed-Replica"],
                                 []).append(i)
                await resp.read()
            home = max(homes, key=lambda k: len(homes[k]))
            home_i = int(home[1])
            sibling = f"r{1 - home_i}"
            assert len(homes[home]) >= 3

            # ---------------- (b) partition the home replica
            att0 = router.flight.slo.snapshot([home])[home]
            assert att0["attainment"] == 1.0  # every turn so far attained
            faults.set_plan(f"router.forward[{home}]=fail:conn; "
                            f"replica.heartbeat[{home}]=fail:conn")
            for i in homes[home]:
                body, headers = _gen(f"part q{i} " + "q" * 30,
                                     session_ctx(i), deadline_ms=60000)
                resp = await client.post("/generate", json=body,
                                         headers=headers)
                # the partition is invisible to callers: connect-phase
                # failures retry onto the sibling
                assert resp.status == 200
                assert resp.headers["X-Routed-Replica"] == sibling
                await resp.read()
            # within ONE heartbeat the fleet view shows the truth
            await client.post("/control/heartbeat")
            fleet = await (await client.get("/debug/fleet")).json()
            assert router_fleet.validate_fleet_snapshot(fleet) == []
            rows = {r["name"]: r for r in fleet["replicas"]}
            dead = rows[home]
            assert dead["breaker"] == "open" and not dead["placeable"]
            assert not dead["reachable"]
            assert dead["heartbeat_failures"] >= 1
            # attainment DROPPED: the connect_fail attempt rows count
            # against the partitioned replica's window
            att1 = dead["slo"]
            assert att1["outcomes"].get("connect_fail", 0) >= 3
            assert att1["attainment"] < (att0["attainment"] or 1.0)
            assert rows[sibling]["slo"]["attainment"] == 1.0
            # fleet totals stay CONSISTENT: the totals row aggregates
            # exactly the per-replica rows (no outcome lost or double-
            # counted by the retries), and the fleet attainment is
            # request-level — every retried request completed ok within
            # its deadline on the sibling, so CALLERS saw a perfect SLO
            # even while the partitioned replica's own window dropped
            per_rep = [r["slo"] for r in fleet["replicas"]]
            assert fleet["fleet"]["window_requests"] == sum(
                s["requests"] for s in per_rep)
            attained_sum = sum(s["attained"] for s in per_rep)
            terminal = sum(
                s["requests"] - s["outcomes"].get("connect_fail", 0)
                - s["outcomes"].get("disconnect", 0) for s in per_rep)
            assert fleet["fleet"]["slo_attainment"] == round(
                attained_sum / terminal, 4)
            assert fleet["fleet"]["slo_attainment"] == 1.0
            # engine-backed rows carry the heartbeat telemetry blocks
            sib = rows[sibling]
            assert sib["capacity"] is not None \
                and sib["capacity"]["capacity_tokens_per_sec"] > 0
            assert sib["rounds"] is not None \
                and sib["rounds"]["rounds_completed"] > 0
            assert sib["headroom_tokens_per_sec"] is not None

            # ---------------- recovery + TTFT reconciliation
            faults.clear()
            await asyncio.sleep(2.1)   # breaker cooldown elapses
            await client.post("/control/heartbeat")
            fleet = await (await client.get("/debug/fleet")).json()
            rows = {r["name"]: r for r in fleet["replicas"]}
            assert rows[home]["reachable"]
            assert rows[home]["breaker"] != "open"
            # one-shot connect fault, untagged: whichever replica the
            # next request is placed on fails ONCE at connect, so the
            # timeline deterministically records a retry before success.
            faults.set_plan("router.forward=fail:conn*1")
            body, headers = _gen("recover question " + "q" * 30,
                                 session_ctx(9),
                                 rid="recover-fleet-1",
                                 deadline_ms=60000)
            resp = await client.post("/generate", json=body,
                                     headers=headers)
            faults.clear()
            assert resp.status == 200
            served = resp.headers["X-Routed-Replica"]
            served_i = int(served[1])
            await resp.read()
            rsnap = await (await client.get(
                "/debug/requests?limit=20")).json()
            rtl = next(t for t in rsnap["completed"]
                       if t["request_id"] == "recover-fleet-1")
            events = [e["event"] for e in rtl["events"]]
            # placement decision, the retry, and the router TTFT are
            # all on ONE record
            assert events.count("place") == 2, events
            assert "retry" in events
            retry = next(e for e in rtl["events"]
                         if e["event"] == "retry")
            assert retry["value"]["reason"] == "connect"
            assert rtl["meta"]["outcome"] == "ok"
            router_ttft = rtl["meta"]["ttft_ms"]
            assert router_ttft and router_ttft > 0
            # ... and it reconciles with the replica flight recorder's
            # TTFT for the SAME request id: the router observes the
            # replica's TTFT plus edge overhead (never less), and on a
            # warmed engine that overhead is bounded.
            async with aiohttp.ClientSession() as s:
                async with s.get(
                        f"{urls[served_i]}/debug/requests") as r:
                    repl = await r.json()
            repl_tl = next(t for t in repl["completed"]
                           if t["request_id"] == "recover-fleet-1")
            replica_ttft = repl_tl["meta"]["ttft_ms"]
            assert replica_ttft and replica_ttft > 0
            assert router_ttft >= replica_ttft - 5.0, \
                (router_ttft, replica_ttft)
            assert router_ttft - replica_ttft < 2000.0, \
                (router_ttft, replica_ttft)
        finally:
            await client.close()
            for s in servers:
                await s.close()

    _run(fn())
