"""LlamaIndex connector classes for the TPU serving stack.

The reference's canonical chain is LlamaIndex-first (reference:
examples/developer_rag/chains.py builds a LlamaIndex ServiceContext over
the Triton connector via common/utils.py:122-140). These classes let a
LlamaIndex application point at the TPU stack the same way: a
``CustomLLM`` for completions and a ``BaseEmbedding`` for the encoder.

Import-degrades like ``langchain_tpu``: real LlamaIndex base classes when
installed, structural stand-ins otherwise.
"""

from __future__ import annotations

from typing import Any, List, Optional

try:
    from llama_index.core.base.embeddings.base import BaseEmbedding as _LIEmb
    from llama_index.core.llms import (CompletionResponse,
                                       CompletionResponseGen, CustomLLM,
                                       LLMMetadata)
    from llama_index.core.llms.callbacks import (llm_completion_callback)
    HAVE_LLAMAINDEX = True
except ImportError:
    HAVE_LLAMAINDEX = False

    class CompletionResponse:  # type: ignore[no-redef]
        def __init__(self, text: str = "", delta: str = ""):
            self.text = text
            self.delta = delta

    CompletionResponseGen = Any  # type: ignore[assignment,misc]

    class LLMMetadata:  # type: ignore[no-redef]
        def __init__(self, **kw: Any):
            for k, v in kw.items():
                setattr(self, k, v)

    def llm_completion_callback():  # type: ignore[no-redef]
        def deco(fn):
            return fn
        return deco

    class CustomLLM:  # type: ignore[no-redef]
        def __init__(self, **kwargs: Any):
            for k, v in kwargs.items():
                setattr(self, k, v)

    class _LIEmb:  # type: ignore[no-redef]
        def __init__(self, **kwargs: Any):
            for k, v in kwargs.items():
                setattr(self, k, v)


class TpuLlamaIndexLLM(CustomLLM):
    """LlamaIndex CustomLLM over the TPU serving stack (gRPC or OpenAI
    HTTP), the role the Triton connector plays in the reference's
    ``set_service_context`` (common/utils.py:122-140)."""

    server_url: str = ""
    model_name: str = "ensemble"
    mode: str = "grpc"
    temperature: float = 1.0
    top_k: int = 1
    top_p: float = 0.0
    tokens: int = 100
    context_window: int = 3000       # reference max_input_length
    timeout: float = 120.0

    model_config = {"arbitrary_types_allowed": True, "extra": "allow"}

    @property
    def metadata(self) -> LLMMetadata:
        return LLMMetadata(context_window=self.context_window,
                           num_output=self.tokens,
                           model_name=self.model_name)

    def _delegate(self):
        llm = getattr(self, "_tpu_llm", None)
        if llm is None:
            from .langchain_tpu import TpuLLM
            llm = TpuLLM(server_url=self.server_url,
                         model_name=self.model_name, mode=self.mode,
                         temperature=self.temperature, top_k=self.top_k,
                         top_p=self.top_p, tokens=self.tokens,
                         timeout=self.timeout)
            object.__setattr__(self, "_tpu_llm", llm)
        return llm

    @llm_completion_callback()
    def complete(self, prompt: str, formatted: bool = False,
                 **kwargs: Any) -> CompletionResponse:
        text = self._delegate()._call(prompt, **kwargs)
        return CompletionResponse(text=text)

    @llm_completion_callback()
    def stream_complete(self, prompt: str, formatted: bool = False,
                        **kwargs: Any) -> "CompletionResponseGen":
        def gen():
            acc = ""
            for chunk in self._delegate()._stream(prompt, **kwargs):
                acc += chunk.text
                yield CompletionResponse(text=acc, delta=chunk.text)
        return gen()


class TpuLlamaIndexEmbedding(_LIEmb):
    """LlamaIndex embedding model over the stack's encoder (passage/query
    modes, reference: nemo_embed.py:96-102)."""

    server_url: str = ""
    mode: str = "grpc"
    model_name: str = "e5-large-v2"
    timeout: float = 60.0

    model_config = {"arbitrary_types_allowed": True, "extra": "allow"}

    def _delegate(self):
        emb = getattr(self, "_tpu_emb", None)
        if emb is None:
            from .langchain_tpu import TpuEmbeddings
            emb = TpuEmbeddings(server_url=self.server_url, mode=self.mode,
                                model_name=self.model_name,
                                timeout=self.timeout)
            object.__setattr__(self, "_tpu_emb", emb)
        return emb

    def _get_query_embedding(self, query: str) -> List[float]:
        return self._delegate().embed_query(query)

    def _get_text_embedding(self, text: str) -> List[float]:
        return self._delegate().embed_documents([text])[0]

    def _get_text_embeddings(self, texts: List[str]) -> List[List[float]]:
        return self._delegate().embed_documents(texts)

    async def _aget_query_embedding(self, query: str) -> List[float]:
        return self._get_query_embedding(query)

    async def _aget_text_embedding(self, text: str) -> List[float]:
        return self._get_text_embedding(text)

    # convenience aliases usable without LlamaIndex installed
    def get_query_embedding(self, query: str) -> List[float]:
        if HAVE_LLAMAINDEX:
            return super().get_query_embedding(query)
        return self._get_query_embedding(query)

    def get_text_embedding(self, text: str) -> List[float]:
        if HAVE_LLAMAINDEX:
            return super().get_text_embedding(text)
        return self._get_text_embedding(text)
