"""CLI: ``python -m generativeaiexamples_tpu.ingest`` — streaming ingest.

The script form of the reference's ``run.py`` CLI over its Morpheus
pipeline (reference: experimental/streaming_ingest_rag/run.py +
vdb_utils.py config merge). Sources: --files GLOB (optionally --watch),
--rss URL, --kafka TOPIC. The destination index persists with --save-dir
so a chain server can load it.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m generativeaiexamples_tpu.ingest")
    parser.add_argument("--files", action="append", default=[],
                        help="glob pattern (repeatable)")
    parser.add_argument("--rss", action="append", default=[],
                        help="feed URL (repeatable)")
    parser.add_argument("--kafka", default="",
                        help="topic (requires --kafka-servers)")
    parser.add_argument("--kafka-servers", default="localhost:9092")
    parser.add_argument("--watch", action="store_true",
                        help="keep polling sources for new content")
    parser.add_argument("--poll-interval", type=float, default=5.0)
    parser.add_argument("--embedder", default="hash",
                        choices=["hash", "tpu-jax"])
    parser.add_argument("--embedding-dim", type=int, default=256)
    parser.add_argument("--store", default="exact")
    parser.add_argument("--chunk-size", type=int, default=510)
    parser.add_argument("--chunk-overlap", type=int, default=200)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--max-items", type=int, default=None)
    parser.add_argument("--save-dir", default="")
    args = parser.parse_args(argv)

    from ..embed.encoder import get_embedder
    from ..retrieval.docstore import DocumentIndex
    from .pipeline import IngestPipeline
    from .sources import FilesystemSource, KafkaSource, RSSSource

    sources = []
    if args.files:
        sources.append(FilesystemSource(args.files, watch=args.watch,
                                        poll_interval=args.poll_interval))
    if args.rss:
        sources.append(RSSSource(args.rss, watch=args.watch,
                                 poll_interval=args.poll_interval))
    if args.kafka:
        sources.append(KafkaSource(args.kafka_servers, args.kafka))
    if not sources:
        parser.error("at least one of --files/--rss/--kafka is required")

    async def merged():
        # Pump sources concurrently: sequential chaining would let a
        # --watch source's infinite poll loop starve every later source.
        import asyncio
        q: asyncio.Queue = asyncio.Queue(maxsize=64)
        stop = object()

        async def pump(src):
            try:
                async for item in src:
                    await q.put(item)
            finally:
                await q.put(stop)

        tasks = [asyncio.ensure_future(pump(s)) for s in sources]
        done_sources = 0
        try:
            while done_sources < len(sources):
                item = await q.get()
                if item is stop:
                    done_sources += 1
                    continue
                yield item
        finally:
            for t in tasks:
                t.cancel()

    embedder = get_embedder(args.embedder, "e5-large-v2",
                            dim=args.embedding_dim)
    index = DocumentIndex(embedder, store_name=args.store)
    pipe = IngestPipeline(merged(), index, chunk_size=args.chunk_size,
                          chunk_overlap=args.chunk_overlap,
                          batch_size=args.batch_size,
                          max_items=args.max_items)
    stats = pipe.run_sync()
    if args.save_dir:
        index.save(args.save_dir)
    json.dump(stats.snapshot(), sys.stdout)
    print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
