"""Sparse MoE tests: routing, dense-parity, EP sharding, capacity drops.

The dense zero-gated formulation (models/llama.py ``_moe_mlp`` with
moe_impl="dense") is the oracle: with capacity high enough for zero drops,
the sparse path must match it numerically.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.parallel import MeshPlan, make_mesh
from generativeaiexamples_tpu.parallel.moe import (
    ep_sparse_moe_ffn, expert_capacity, route_topk, sparse_moe_ffn)

CFG = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=96,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  num_experts=4, num_experts_per_tok=2,
                  moe_capacity_factor=2.0)  # C = T: no drops possible


def _layer_params(key):
    params = llama.init_params(CFG, key, dtype=jnp.float32)
    lp = params["layers"]
    return {name: lp[name][0] for name in
            ("router", "w_gate", "w_up", "w_down")}


def test_route_topk_slots_unique_and_capped():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)
    C = 3
    expert, slot, weight, keep = route_topk(logits, 2, C)
    expert, slot, keep = (np.asarray(expert), np.asarray(slot),
                          np.asarray(keep))
    # kept (expert, slot) pairs are unique and within capacity
    pairs = {(int(e), int(s)) for e, s, k in zip(expert, slot, keep) if k}
    assert len(pairs) == int(keep.sum())
    assert slot[keep].max() < C
    # weights are a softmax over each token's k choices
    w = np.asarray(weight).reshape(16, 2)
    np.testing.assert_allclose(w.sum(-1), 1.0, atol=1e-6)


def test_sparse_matches_dense_when_no_drops():
    key = jax.random.key(0)
    lp = _layer_params(key)
    x = jax.random.normal(jax.random.key(1), (2, 8, 64), jnp.float32)

    sparse = sparse_moe_ffn(x, lp, CFG)
    from dataclasses import replace
    dense_cfg = replace(CFG, moe_impl="dense")
    dense = llama._moe_mlp(x, lp, dense_cfg)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


def test_full_model_sparse_matches_dense():
    """End-to-end forward parity: logits through the whole decoder."""
    from dataclasses import replace
    params = llama.init_params(CFG, jax.random.key(2), dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 128, (2, 6), np.int32))
    pos = jnp.broadcast_to(jnp.arange(6, dtype=jnp.int32), (2, 6))
    sparse_logits, _ = llama.apply(params, CFG, tokens, pos)
    dense_logits, _ = llama.apply(params, replace(CFG, moe_impl="dense"),
                                  tokens, pos)
    np.testing.assert_allclose(np.asarray(sparse_logits),
                               np.asarray(dense_logits),
                               rtol=2e-4, atol=2e-4)


def test_ep_shardmap_matches_single_device(cpu_devices):
    """Explicit shard_map EP path (experts over ep, FFN width over tp with
    psum) must match the unsharded sparse path exactly."""
    lp = _layer_params(jax.random.key(4))
    x = jax.random.normal(jax.random.key(5), (2, 8, 64), jnp.float32)
    ref = sparse_moe_ffn(x, lp, CFG)

    mesh = make_mesh(MeshPlan(ep=4, tp=2))
    out = jax.jit(lambda x, lp: ep_sparse_moe_ffn(mesh, x, lp, CFG))(x, lp)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_capacity_overflow_drops_tokens():
    """With capacity_factor << 1 some claims must be dropped (keep=False) —
    and the layer still produces finite output."""
    from dataclasses import replace
    tight = replace(CFG, moe_capacity_factor=0.25)
    lp = _layer_params(jax.random.key(6))
    x = jax.random.normal(jax.random.key(7), (2, 16, 64), jnp.float32)
    T, k, E = 32, 2, 4
    C = expert_capacity(T, E, k, 0.25)
    assert C * E < T * k  # capacity genuinely binds
    logits = x.reshape(T, 64) @ lp["router"]
    _, _, _, keep = route_topk(logits, k, C)
    assert int(np.asarray(keep).sum()) < T * k
    out = sparse_moe_ffn(x, lp, tight)
    assert bool(jnp.isfinite(out).all())


def test_mixtral_registry_uses_sparse():
    from generativeaiexamples_tpu.models.configs import MIXTRAL_8X7B
    assert MIXTRAL_8X7B.moe_impl == "sparse"
    assert MIXTRAL_8X7B.num_experts == 8


def test_sparse_moe_in_engine_generates():
    """The serving engine runs the sparse path end-to-end (prefill uses
    T=bucket tokens, decode T=slots — both capacity geometries)."""
    from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                                 SamplingParams)
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    cfg = LlamaConfig(vocab_size=259 + 5, hidden_size=64,
                      intermediate_size=96, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, num_experts=4,
                      num_experts_per_tok=2)
    params = llama.init_params(cfg, jax.random.key(8), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_input_length=32, max_output_length=16,
                        prefill_buckets=(32,), dtype="float32", page_size=16,
                        steps_per_round=4)
    with Engine(params, cfg, ByteTokenizer(), ecfg) as eng:
        s = eng.submit(eng.tokenizer.encode("moe"),
                       SamplingParams(max_tokens=6, top_k=1, ignore_eos=True))
        s.text()
        assert len(s.token_ids) == 6
