"""LLM client abstraction for the chains layer.

Parity with the reference's ``get_llm`` factory hub
(reference: common/utils.py:236-266 switches on ``model_engine``:
triton-trt-llm / nv-ai-foundation / nemo-infer / ...). Engines here:

- ``tpu-jax``       in-process continuous-batching Engine (zero-copy path).
- ``openai-compat`` HTTP client for any OpenAI-style ``/v1/completions``
                    server — including this framework's own ``serving`` API
                    (parity with the nemo-infer connector,
                    reference: integrations/langchain/llms/nemo_infer.py).
- ``echo``          deterministic test double (the 'fake engine' the
                    reference's enum invited but never shipped, SURVEY.md §4).
"""

from __future__ import annotations

import abc
import json
from typing import Iterator, Optional

from ..utils.errors import ConfigError


class LLM(abc.ABC):
    """Minimal streaming text-completion interface used by all chains."""

    @abc.abstractmethod
    def stream(self, prompt: str, max_tokens: int = 256,
               stop: Optional[list[str]] = None, temperature: float = 1.0,
               top_k: int = 1, top_p: float = 0.0,
               ) -> Iterator[str]:
        """Yield answer text chunks. Default sampling mirrors the
        reference's client defaults (trt_llm.py:68-74: temp 1.0, top_k 1)."""

    def complete(self, prompt: str, **kw) -> str:
        return "".join(self.stream(prompt, **kw))


class EchoLLM(LLM):
    """Deterministic test double: echoes a transform of the prompt tail."""

    def __init__(self, prefix: str = "ECHO: ", tail_chars: int = 160):
        self.prefix = prefix
        self.tail_chars = tail_chars
        self.calls: list[str] = []

    def stream(self, prompt: str, max_tokens: int = 256,
               stop: Optional[list[str]] = None, temperature: float = 1.0,
               top_k: int = 1, top_p: float = 0.0) -> Iterator[str]:
        self.calls.append(prompt)
        tail = prompt[-self.tail_chars:]
        # A real model never echoes its chat scaffold; scrub template
        # markers so caller-supplied stop words don't trip on the echo.
        for marker in ("<s>", "</s>", "[INST]", "[/INST]",
                       "<<SYS>>", "<</SYS>>"):
            tail = tail.replace(marker, "")
        text = (self.prefix + tail)[:max_tokens]
        for s in stop or []:
            idx = text.find(s)
            if idx >= 0:
                text = text[:idx]
        for i in range(0, len(text), 7):  # chunked like a real stream
            yield text[i:i + 7]


class EngineLLM(LLM):
    """In-process engine: the TPU-native equivalent of pointing LangChain's
    TritonClient at a local Triton (reference: trt_llm.py:124 ``_call``) —
    minus the gRPC hop, because the engine lives in this process."""

    def __init__(self, engine):
        self.engine = engine
        engine.start()

    def stream(self, prompt: str, max_tokens: int = 256,
               stop: Optional[list[str]] = None, temperature: float = 1.0,
               top_k: int = 1, top_p: float = 0.0) -> Iterator[str]:
        import time

        from ..engine.sampling_params import SamplingParams
        params = SamplingParams(max_tokens=max_tokens,
                                stop_words=list(stop or []),
                                temperature=temperature, top_k=top_k,
                                top_p=top_p)
        stream = self.engine.stream_text(prompt, params)
        yield from self._consume(stream, time.monotonic())

    def stream_rag(self, question: str, enc_ids: list,
                   max_tokens: int = 256,
                   stop: Optional[list[str]] = None,
                   temperature: float = 1.0, top_k: int = 1,
                   top_p: float = 0.0, on_sources=None,
                   q_ids: Optional[list] = None) -> Iterator[str]:
        """Fused-RAG generation: retrieval + prompt assembly + prefill run
        as one device program inside the engine (engine/rag_fusion.py).
        ``enc_ids``: the question's tokens in the ENCODER vocabulary,
        query prefix included. ``on_sources`` (optional callable) receives
        the retrieved corpus row ids once they are known — the on-device
        retrieval's answer to the host path's similarity_search result.
        ``q_ids``: the question pre-tokenized in the LLM vocab (callers
        that already encoded it for a bucket check pass it to keep one
        tokenization on the TTFT path)."""
        import time

        from ..engine.sampling_params import SamplingParams
        params = SamplingParams(max_tokens=max_tokens,
                                stop_words=list(stop or []),
                                temperature=temperature, top_k=top_k,
                                top_p=top_p)
        self.engine.start()
        if q_ids is None:
            q_ids = self.engine.tokenizer.encode(question, add_bos=False)
        stream = self.engine.submit_rag(q_ids, enc_ids, params)
        yield from self._consume(stream, time.monotonic(),
                                 on_sources=on_sources)

    def _consume(self, stream, t0: float, on_sources=None) -> Iterator[str]:
        import time

        from ..obs.tracing import record_stage
        first = True
        try:
            for chunk in stream:
                if first:
                    # stage-breakdown hook: time to the first visible
                    # chunk (includes tokenize+queue+prefill+detok).
                    # engine_ttft is NOT re-reported here — the engine
                    # records the authoritative one at first-token
                    # harvest (engine.py _emit_token).
                    record_stage("llm_first_chunk", time.monotonic() - t0)
                    if on_sources is not None and stream.source_ids:
                        on_sources(stream.source_ids)
                    first = False
                yield chunk
        finally:
            if stream.finish_reason is None:
                # consumer abandoned the generator mid-stream: release the
                # decode slot instead of generating to max_tokens
                stream.cancel()


class OpenAICompatLLM(LLM):
    """Streaming client for ``/v1/completions`` SSE servers.

    Unlike the reference's nemo-infer client — which must diff cumulative
    text to recover per-token deltas (reference: nemo_infer.py:141-156) —
    OpenAI-style servers send true deltas, so chunks pass through as-is.
    """

    def __init__(self, server_url: str, model_name: str = "default",
                 timeout: float = 120.0, send_top_k: bool = True):
        if not server_url:
            raise ConfigError("openai-compat engine requires llm.server_url")
        self.url = server_url.rstrip("/") + "/v1/completions"
        self.model_name = model_name
        self.timeout = timeout
        # top_k is this framework's extension; disable against servers that
        # reject unknown sampling arguments.
        self.send_top_k = send_top_k

    def stream(self, prompt: str, max_tokens: int = 256,
               stop: Optional[list[str]] = None, temperature: float = 1.0,
               top_k: int = 1, top_p: float = 0.0) -> Iterator[str]:
        import requests

        body = {"model": self.model_name, "prompt": prompt,
                "max_tokens": max_tokens, "stream": True,
                "temperature": temperature, "top_p": top_p,
                "stop": list(stop or [])}
        if top_k == 1:
            # top_k==1 means greedy regardless of temperature (EngineLLM /
            # ops.sampling semantics); express it as temperature=0, portable
            # to servers that reject non-standard arguments (the real OpenAI
            # API 400s on unknown fields).
            body["temperature"] = 0.0
        elif top_k > 1 and self.send_top_k:
            body["top_k"] = top_k
        with requests.post(self.url, json=body, stream=True,
                           timeout=self.timeout) as resp:
            resp.raise_for_status()
            for line in resp.iter_lines(decode_unicode=True):
                if not line or not line.startswith("data:"):
                    continue
                payload = line[len("data:"):].strip()
                if payload == "[DONE]":
                    return
                choice = json.loads(payload)["choices"][0]
                if choice.get("text"):
                    yield choice["text"]


def get_llm(config=None, engine=None) -> LLM:
    """Engine-switched factory (reference: common/utils.py:236-266)."""
    if config is None:
        from ..utils.app_config import get_config
        config = get_config()
    kind = config.llm.model_engine
    if kind == "echo":
        return EchoLLM()
    if kind == "tpu-jax":
        if engine is None:
            raise ConfigError(
                "model_engine=tpu-jax needs an in-process Engine instance "
                "(pass engine=); for a remote server use openai-compat")
        return EngineLLM(engine)
    if kind in ("openai-compat", "tpu-http"):
        return OpenAICompatLLM(config.llm.server_url, config.llm.model_name)
    raise ConfigError(f"unknown llm.model_engine {kind!r}")
