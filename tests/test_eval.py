"""Evaluation-suite tests (reference: tools/evaluation/*.ipynb behavior).

A ScriptedLLM plays the judge/synthesis model so every parse path is
exercised deterministically — the reference's notebooks have no tests at
all (SURVEY.md §4)."""

import json
import subprocess
import sys

import pytest

from generativeaiexamples_tpu.chains.llm import LLM, EchoLLM
from generativeaiexamples_tpu.tools.eval import (
    EvalConfig, context_precision, faithfulness, generate_qa_pairs,
    judge_answer, ndcg_at_k, retrieval_metrics, run_eval)
from generativeaiexamples_tpu.tools.eval.judge import (parse_rating,
                                                       summarize_ratings)
from generativeaiexamples_tpu.tools.eval.metrics import parse_verdict
from generativeaiexamples_tpu.tools.eval.synthesize import (extract_qa_json,
                                                            extractive_pair)


class ScriptedLLM(LLM):
    """Returns canned responses in order; repeats the last one."""

    def __init__(self, responses):
        self.responses = list(responses)
        self.prompts = []

    def stream(self, prompt, max_tokens=256, stop=None, temperature=1.0,
               top_k=1, top_p=0.0):
        self.prompts.append(prompt)
        idx = min(len(self.prompts) - 1, len(self.responses) - 1)
        yield self.responses[idx]


# ------------------------------------------------------------- synthesize

def test_extract_qa_json_bare_list():
    text = '[{"question": "What is the MXU size?", "answer": "It is 128x128."}]'
    assert extract_qa_json(text) == [("What is the MXU size?",
                                      "It is 128x128.")]


def test_extract_qa_json_fenced_and_prose():
    text = ('Here are the pairs:\n```json\n'
            '{"question": "What links chips together?", '
            '"answer": "ICI links."}\n```\nHope that helps!')
    assert extract_qa_json(text) == [("What links chips together?",
                                      "ICI links.")]


def test_extract_qa_json_numbered_keys():
    text = json.dumps({"question1": "How big is the page size here?",
                       "answer1": "128 tokens.",
                       "question2": "What is stored in pages?",
                       "answer2": "KV cache."})
    pairs = extract_qa_json(text)
    assert ("How big is the page size here?", "128 tokens.") in pairs
    assert ("What is stored in pages?", "KV cache.") in pairs


def test_extract_qa_json_rejects_placeholders():
    # a model (or the echo double) parroting the format example back
    assert extract_qa_json('[{"question": "...", "answer": "..."}]') == []


def test_extract_qa_json_garbage():
    assert extract_qa_json("no json here at all") == []


def test_generate_qa_pairs_retry_then_fallback():
    llm = ScriptedLLM(["garbage", "still garbage"])
    pairs = generate_qa_pairs(llm, [("The MXU is a systolic array. More.",
                                     {"doc_id": 7, "source": "a.txt"})],
                              max_retries=1)
    # deterministic ladder: harder keyword question first, quote-back second
    assert [p.synthetic_mode for p in pairs] == ["keyword", "extractive"]
    assert all(p.gt_doc_id == 7 for p in pairs)
    assert "MXU" in pairs[1].question
    # the keyword question must not quote the chunk's sentence verbatim
    assert "The MXU is a systolic array." not in pairs[0].question
    assert len(llm.prompts) == 2  # initial + one retry


def test_generate_qa_pairs_llm_mode():
    llm = ScriptedLLM(['[{"question": "What does the pool share?", '
                       '"answer": "Fixed-size pages."}]'])
    pairs = generate_qa_pairs(llm, [("text chunk", {"doc_id": 1})])
    assert pairs[0].synthetic_mode == "llm"
    assert pairs[0].gt_answer == "Fixed-size pages."


def test_extractive_pair_first_sentence():
    q, a = extractive_pair("Paged KV shares a pool. Second sentence here.")
    assert a == "Paged KV shares a pool."
    assert "Paged KV shares a pool." in q


# ---------------------------------------------------------------- metrics

def test_parse_verdict():
    assert parse_verdict("Yes, clearly.") is True
    assert parse_verdict("No.") is False
    assert parse_verdict("Yes and no") is True  # first wins
    assert parse_verdict("maybe?") is None


def test_faithfulness_counts_supported_statements():
    llm = ScriptedLLM([
        "The MXU is 128x128.\nThe MXU runs bfloat16.",  # statements
        "Yes",                                           # verdict 1
        "No",                                            # verdict 2
    ])
    score = faithfulness(llm, "q", "answer text here", ["ctx"])
    assert score == pytest.approx(0.5)


def test_faithfulness_unparsable_is_none():
    llm = ScriptedLLM(["Statement one is here.", "shrug"])
    assert faithfulness(llm, "q", "answer text", ["ctx"]) is None


def test_context_precision_rank_weighted():
    # contexts: [relevant, irrelevant, relevant] ->
    # (1/1 + 2/3) / 2 = 0.8333
    llm = ScriptedLLM(["Yes", "No", "Yes"])
    score = context_precision(llm, "q", "gt", ["c1", "c2", "c3"])
    assert score == pytest.approx((1.0 + 2 / 3) / 2)


def test_context_precision_none_relevant():
    llm = ScriptedLLM(["No"])
    assert context_precision(llm, "q", "gt", ["c1", "c2"]) == 0.0


def test_ndcg_and_retrieval_metrics():
    assert ndcg_at_k([5, 3, 9], 5, 4) == pytest.approx(1.0)
    assert ndcg_at_k([3, 5, 9], 5, 4) == pytest.approx(0.6309, abs=1e-3)
    assert ndcg_at_k([3, 9], 5, 4) == 0.0
    m = retrieval_metrics([3, 5], 5, 2)
    assert m["hit"] == 1.0 and m["mrr"] == 0.5
    assert retrieval_metrics([1], None, 4) is None


# ------------------------------------------------------------------ judge

def test_parse_rating_variants():
    assert parse_rating('"Rating": 4, "Explanation": "Good."')[0] == 4
    assert parse_rating("Rating: 5 Explanation: perfect")[0] == 5
    assert parse_rating("Rating: 0")[0] == 1    # clamp 0 -> 1 (ref notebook)
    assert parse_rating("Rating: 9")[0] == 5    # clamp hallucinated >5
    assert parse_rating("no rating at all")[0] is None


def test_judge_answer_retry():
    llm = ScriptedLLM(["unparsable", '"Rating": 3, "Explanation": "ok"'])
    rating, expl = judge_answer(llm, "q", "ctx", "gt", "ans", max_retries=1)
    assert rating == 3
    assert "ok" in expl


def test_summarize_ratings():
    s = summarize_ratings([5, 5, 3, None])
    assert s["mean_rating"] == pytest.approx(4.33, abs=0.01)
    assert s["histogram"]["5"] == 2
    assert s["rated"] == 3 and s["unparsed"] == 1


# ----------------------------------------------------------------- runner

def _dev_example():
    from generativeaiexamples_tpu.chains.examples.developer_rag import QAChatbot
    from generativeaiexamples_tpu.utils.app_config import AppConfig
    from generativeaiexamples_tpu.utils.configuration import from_dict

    cfg = from_dict(AppConfig, {
        "llm": {"model_engine": "echo"},
        "embeddings": {"model_engine": "hash", "dimensions": 128},
        "vector_store": {"name": "exact"},
        "text_splitter": {"chunk_size": 60, "chunk_overlap": 10}})
    return QAChatbot(config=cfg)


def test_run_eval_dev_stack(tmp_path):
    example = _dev_example()
    corpus = {
        "a.txt": "The MXU is a 128x128 systolic array for matrix multiplies.",
        "b.txt": "Paged KV caching shares a pool of fixed-size pages.",
        "c.txt": "Continuous batching admits requests between decode steps.",
    }
    for name, text in corpus.items():
        p = tmp_path / name
        p.write_text(text)
        example.ingest_docs(str(p), name)

    out = tmp_path / "report.json"
    report = run_eval(example, example.llm,
                      EvalConfig(output_path=str(out), max_questions=6))
    m = report.metrics
    # extractive fallback -> quote-back questions -> hash retrieval finds
    # the gold chunk: the nDCG-parity north star is actually measurable
    assert m["retrieval"]["ndcg"] > 0.8
    assert m["retrieval"]["hit"] > 0.8
    assert m["num_questions"] >= 3
    assert (sum(m["synthetic_modes"].values()) == m["num_questions"]
            and set(m["synthetic_modes"]) <= {"keyword", "extractive"})
    # per-mode breakdown accompanies the aggregate
    assert set(m["retrieval"]["by_mode"]) == set(m["synthetic_modes"])
    # echo LLM parses no verdicts/ratings: reported as unscored, not fake
    assert m["faithfulness"] is None
    assert m["judge"]["unparsed"] == m["num_questions"]
    saved = json.loads(out.read_text())
    assert saved["metrics"]["retrieval"]["ndcg"] == m["retrieval"]["ndcg"]
    assert len(saved["questions"]) == m["num_questions"]


def test_run_eval_scripted_full_scores():
    """With a parseable judge, every metric lands a value."""
    example = _dev_example()
    import tempfile, os
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "d.txt")
        with open(p, "w") as f:
            f.write("The interconnect carries collective operations.")
        example.ingest_docs(p, "d.txt")

    judge = ScriptedLLM([
        '[{"question": "What carries the collectives?", '
        '"answer": "The interconnect."}]',     # synthesis
        "The interconnect carries them.",       # statements
        "Yes",                                  # faithfulness verdict
        "Yes",                                  # ctx precision (1 context)
        '"Rating": 4, "Explanation": "Close to reference."',
    ])
    report = run_eval(example, judge, EvalConfig(max_questions=1))
    m = report.metrics
    assert m["synthetic_modes"] == {"llm": 1}
    assert m["faithfulness"] == 1.0
    assert m["context_precision"] == 1.0
    assert m["judge"]["mean_rating"] == 4.0


def test_eval_cli_runs_headless(tmp_path):
    out = tmp_path / "r.json"
    proc = subprocess.run(
        [sys.executable, "-m", "generativeaiexamples_tpu.tools.eval",
         "--output", str(out), "--max-questions", "4"],
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr
    metrics = json.loads(proc.stdout)
    # the 4-doc builtin corpus: keyword + quote-back questions both land
    assert metrics["retrieval"]["ndcg"] >= 0.8
    assert out.exists()


def test_repo_root_eval_artifact(tmp_path, repo_root):
    """The round artifact generator (eval.py) runs the LIVE-server eval
    end-to-end on the dev stack and writes a structurally complete
    EVAL_r{NN}.json."""
    corpus = tmp_path / "docs"
    corpus.mkdir()
    (corpus / "a.md").write_text(
        "The MXU is a 128x128 systolic array for matrix multiplies. "
        "Feeding it large batched bfloat16 matmuls keeps utilization high.")
    (corpus / "b.md").write_text(
        "Paged KV caching shares a pool of fixed-size pages between "
        "decode slots, sizing cache capacity to HBM instead of batch.")
    (corpus / "c.md").write_text(
        "Continuous batching admits new requests between decode steps "
        "without recompiling the executable.")
    out = tmp_path / "EVAL_r99.json"
    proc = subprocess.run(
        [sys.executable, str(repo_root / "eval.py"), "--round", "99",
         "--output", str(out), "--corpus", str(corpus),
         "--max-questions", "4", "--max-chunks", "4", "--num-tokens", "8",
         "--world-size", "1"],
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr
    artifact = json.loads(out.read_text())
    assert artifact["round"] == 99
    assert artifact["stack"]["weights"] == "random-init"
    assert "live chain-server" in artifact["stack"]["transport"]
    m = artifact["metrics"]
    assert 0.0 <= m["retrieval"]["ndcg"] <= 1.0 and m["retrieval"]["scored"]
    # every question produced a non-error answer through the live server
    assert artifact["generation"]["answers"] == m["num_questions"]
    assert len(artifact["questions"]) == m["num_questions"]
