"""Tier-1 guard for the bench's output contract.

bench.py's JSON line is the perf trajectory the driver diffs round over
round; a silently renamed field breaks that comparison without breaking
the bench. This suite assembles a fully-populated synthetic result
through the SAME ``bench.assemble_result`` the chip run uses and
validates it against tools/bench_schema.json — so a field rename in
either place fails here, on CPU, before any chip time is spent.
"""

import pytest

import bench
from tools.check_bench_schema import (BenchSchemaError, load_schema,
                                      validate_result)


def synthetic_result() -> dict:
    """A result with every branch populated (chat + e2e + pipeline),
    built through bench.assemble_result so the test pins the real
    emit path, not a hand-copied dict."""
    chat = {
        "turns": 3, "system_prompt_tokens": 512,
        "cold_ttft_ms": 180.0, "warm_p50_ttft_ms": 120.0,
        "warm_min_ttft_ms": 110.0, "warm_ttfts_ms": [120.0, 121.5],
        "prefix_cache_hit_tokens": 1024, "prefix_cache_hit_rate": 0.8,
        "prefix_cache_evicted_pages": 0,
        # built through the real emit path so the spec contract is
        # pinned exactly as bench.py produces it
        "spec": bench.spec_snapshot(
            {}, {"spec_verify_rounds": 10, "spec_draft_tokens": 40,
                 "spec_accepted_tokens": 28, "spec_verify_tokens": 52,
                 "spec_verify_slot_steps": 24}),
    }
    dist = {"p99": 190.0, "min": 170.0, "max": 190.0,
            "batch_p50s": [178.0, 180.0, 179.0], "samples": 24}
    breakdown = {"embedding": 4.0, "retrieve": 1.0, "templating": 0.2,
                 "llm": 460.0, "llm_first_chunk": 175.0,
                 "engine_ttft": 172.0, "engine_admit_pickup": 0.4,
                 "engine_admit_dispatch": 3.2,
                 "engine_prefill_chunk": 2.8,
                 "engine_first_readback": 130.0,
                 "engine_harvest_wait": 140.0,
                 "loop_admit": 3.5, "loop_dispatch": 2.7}
    pipeline = bench.pipeline_snapshot({
        "harvest_wait_ms": 420.0, "harvest_rounds": 3,
        "first_readback_ms": 260.0, "first_readbacks": 2,
        "dispatch_depth_peak": 2})
    capacity = {
        "slots_sweep": [8, 16], "prompt_len": 512, "output_len": 64,
        "requests_per_rung": 8, "kv_pool_tokens_per_slot": 768,
        "rungs": [
            {"slots": 8, "engine_p50_ttft_ms": 150.0,
             "engine_p99_ttft_ms": 160.0,
             "decode_tokens_per_sec": 494.0,
             "tokens_per_sec_per_slot": 61.8,
             "hbm_bw_achieved_gbps": 590.4, "hbm_bw_util": 0.72,
             "decode_window_steady": True,
             "sampler_rows_skipped_frac": 0.05},
            {"slots": 16, "engine_p50_ttft_ms": 170.0,
             "engine_p99_ttft_ms": 185.0,
             "decode_tokens_per_sec": 900.0,
             "tokens_per_sec_per_slot": 56.3,
             "hbm_bw_achieved_gbps": 610.0, "hbm_bw_util": 0.74,
             "decode_window_steady": True,
             "sampler_rows_skipped_frac": 0.02},
        ],
    }
    return bench.assemble_result(
        kind="e2e_chat", model="llama-2-7b-chat", headline=178.0,
        engine_p50=140.0, engine_p99=150.0, tput=500.0,
        achieved_bw=590.4e9, bw_util=0.72, bw_steady=True,
        chat=chat, e2e_p50=178.0, e2e_dist=dist, e2e_breakdown=breakdown,
        e2e_tps_p50=32.0, pipeline=pipeline, quant="int8", kv_quant=None,
        weights="random-init", prompt_len=512, out_len=64, slots=8,
        steps_per_round=16, kv_pool_pages=63, device="TPU v5 lite",
        rtt_ms=100.8, n_devices=1, bench_seconds=100.0,
        capacity=capacity)


def test_assembled_result_matches_schema():
    validate_result(synthetic_result())


def test_engine_only_degraded_result_matches_schema():
    """The BENCH_SKIP_E2E / embedder-failure rung: chat and e2e blocks
    null out but the contract still validates."""
    result = synthetic_result()
    result.update({"chat": None, "e2e_chat_ttft_ms": None,
                   "e2e_chat_p99_ttft_ms": None, "e2e_ttft_dist_ms": None,
                   "e2e_breakdown_ms": None})
    validate_result(result)


def test_pipeline_snapshot_keys_pinned_by_schema():
    """pipeline_snapshot's keys ARE the schema's engine_pipeline section:
    renaming either side alone fails."""
    schema = load_schema()
    snap = bench.pipeline_snapshot({})
    assert set(snap) == set(schema["engine_pipeline"])
    # zero-stats snapshot is well-typed (no div-by-zero artifacts)
    validate_result(dict(synthetic_result(), engine_pipeline=snap))


def test_breakdown_stage_rename_fails_fast():
    result = synthetic_result()
    # the r5 stage name: the loop no longer blocks on round harvests, so
    # the stage was renamed — the schema must reject the stale name
    result["e2e_breakdown_ms"]["loop_hround"] = 284.7
    with pytest.raises(BenchSchemaError, match="loop_hround"):
        validate_result(result)


def test_missing_required_field_fails_fast():
    result = synthetic_result()
    del result["engine_p50_ttft_ms"]
    with pytest.raises(BenchSchemaError, match="engine_p50_ttft_ms"):
        validate_result(result)


def test_unknown_toplevel_field_fails_fast():
    result = synthetic_result()
    result["ttft_p50_ms"] = 140.0  # a rename half-applied
    with pytest.raises(BenchSchemaError, match="ttft_p50_ms"):
        validate_result(result)


def test_wrong_type_fails_fast():
    result = synthetic_result()
    result["decode_tokens_per_sec"] = "494.1"
    with pytest.raises(BenchSchemaError, match="decode_tokens_per_sec"):
        validate_result(result)


def test_capacity_rung_rename_fails_fast():
    """Element-wise rung validation: a rename inside one slot rung's
    dict cannot hide behind the list type."""
    result = synthetic_result()
    rung = result["capacity"]["rungs"][1]
    rung["tput"] = rung.pop("decode_tokens_per_sec")
    with pytest.raises(BenchSchemaError, match=r"capacity.rungs\[1\]"):
        validate_result(result)


def test_nested_chat_contract_pinned():
    result = synthetic_result()
    result["chat"]["warm_ttft_ms"] = 1.0  # unknown chat key
    with pytest.raises(BenchSchemaError, match="warm_ttft_ms"):
        validate_result(result)


def test_spec_block_contract_pinned():
    """The nested speculative-decoding block is validated element-wise:
    spec_snapshot's keys ARE the schema's spec section, a rename inside
    chat.spec fails fast, and spec: null (spec off) stays valid."""
    schema = load_schema()
    snap = bench.spec_snapshot({}, {"spec_verify_rounds": 1})
    assert set(snap) == set(schema["spec"])
    result = synthetic_result()
    validate_result(dict(result, chat=dict(result["chat"], spec=None)))
    result["chat"]["spec"]["accept_rate"] = \
        result["chat"]["spec"].pop("acceptance_rate")
    with pytest.raises(BenchSchemaError, match=r"chat.spec"):
        validate_result(result)


def test_spec_snapshot_none_without_verify_rounds():
    """A window with no verify round (spec off) publishes null, not a
    block of zeros pretending speculation ran."""
    assert bench.spec_snapshot({}, {}) is None
    assert bench.spec_snapshot({"spec_verify_rounds": 4},
                               {"spec_verify_rounds": 4}) is None


def test_disagg_section_contract_pinned():
    """The disagg section (docs/disaggregation.md) is validated
    element-wise per arm: the synthetic section's keys ARE the schema's
    disagg/disagg_arm sections, a rename inside an arm fails fast with
    the arm's index, and disagg: null (scenario off) stays valid."""
    from tools.preflight import synthetic_disagg

    schema = load_schema()
    section = synthetic_disagg()
    assert set(section) == set(schema["disagg"])
    for arm in section["arms"]:
        assert set(arm) == set(schema["disagg_arm"])
    result = synthetic_result()
    validate_result(dict(result, disagg=section))
    validate_result(dict(result, disagg=None))
    broken = synthetic_disagg()
    broken["arms"][1]["goodput"] = broken["arms"][1].pop("decode_goodput")
    with pytest.raises(BenchSchemaError, match=r"disagg\.arms\[1\]"):
        validate_result(dict(result, disagg=broken))


def test_failover_section_contract_pinned():
    """The failover section (docs/robustness.md) is validated
    element-wise per arm: the synthetic section's keys ARE the schema's
    failover/failover_arm sections, a rename inside an arm fails fast
    with the arm's index, and failover: null (scenario off) stays
    valid."""
    from tools.preflight import synthetic_failover

    schema = load_schema()
    section = synthetic_failover()
    assert set(section) == set(schema["failover"])
    for arm in section["arms"]:
        assert set(arm) == set(schema["failover_arm"])
    result = synthetic_result()
    validate_result(dict(result, failover=section))
    validate_result(dict(result, failover=None))
    broken = synthetic_failover()
    broken["arms"][1]["no_error_rate"] = \
        broken["arms"][1].pop("completed_no_error_rate")
    with pytest.raises(BenchSchemaError, match=r"failover\.arms\[1\]"):
        validate_result(dict(result, failover=broken))
