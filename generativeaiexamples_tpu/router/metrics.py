"""The fleet router's metric surface — one canonical table.

Every metric the router publishes is declared here, name -> (kind,
labelnames, help). ``docs/observability.md`` documents the same set in a
table fenced by ``<!-- router-metrics:begin/end -->`` and
``tools/check_metrics_docs.py`` enforces the two directions (a rename
here orphans the docs loudly; a new gauge can't ship undocumented) —
the same contract the engine gauge table has.

The registry is the process-wide one from ``obs/metrics.py``: when the
router runs in its own process these are simply its ``/metrics``; when
tests or the fleet bench run router + N replicas in ONE process, the
``router_*`` prefix keeps them distinct from the replicas' chain/engine
metrics, and the replica-labeled children tell the replicas apart.
"""

from __future__ import annotations

from ..obs import metrics as obs_metrics

#: name -> (kind, labelnames, help). The checker keys off the names; the
#: accessors below key off the whole row, so the two can never drift.
ROUTER_METRICS: dict[str, tuple[str, tuple[str, ...], str]] = {
    "router_replicas_healthy": (
        "gauge", (),
        "replicas currently placeable: reachable, not draining, breaker "
        "not open"),
    "router_replicas_total": (
        "gauge", (), "replicas in the table, placeable or not"),
    "router_placed_total": (
        "counter", ("replica",),
        "requests placed on each replica (post-retry final placement)"),
    "router_affinity_hits": (
        "counter", (),
        "placements whose chosen replica matched >= 1 prefix block in "
        "its affinity sketch"),
    "router_retries_total": (
        "counter", ("reason",),
        "forward attempts abandoned and retried on another replica, by "
        "reason: connect (connect-phase failure), draining (replica "
        "429'd as draining), breaker_open (placement raced a breaker "
        "trip)"),
    "router_drain_in_flight": (
        "gauge", (),
        "in-flight streams still running on DRAINING replicas, summed "
        "from heartbeats — a rollout waits for this to reach 0"),
    "router_kv_transfer_hints_total": (
        "counter", (),
        "placements forwarded with an X-KV-Transfer-From donor hint: "
        "the chosen replica missed the prompt's prefix but a sibling's "
        "affinity sketch covers it, so the replica fetches the prefix "
        "pages from the sibling instead of re-prefilling "
        "(docs/kv-tiering.md)"),
    "router_replica_queue_depth": (
        "gauge", ("replica",),
        "per-replica queued engine work from the last heartbeat: "
        "admission intake + scheduler backlog + in-flight device "
        "rounds — the congestion signal placement penalizes and the "
        "autoscaler's queue trigger reads"),
    "router_replica_in_flight": (
        "gauge", ("replica",),
        "per-replica in-flight /generate streams from the last "
        "heartbeat"),
    "router_replica_rejected_total": (
        "gauge", ("replica",),
        "per-replica cumulative engine admission rejections "
        "(queue-full + deadline queue drops) from the last heartbeat — "
        "the router diffs consecutive heartbeats into a recent shed "
        "rate for the load score"),
    "router_replica_prefix_hit_rate": (
        "gauge", ("replica",),
        "per-replica engine prefix-cache hit rate from the last "
        "heartbeat — fleet-wide cache health at a glance"),
    "router_heartbeat_failures_total": (
        "counter", ("replica",),
        "heartbeat probes that failed to get any HTTP answer from each "
        "replica — a climbing counter is the poller seeing a partition "
        "or a dead pod BEFORE placements go wrong"),
    "router_heartbeat_age_seconds": (
        "gauge", ("replica",),
        "seconds since each replica's last heartbeat observation, "
        "refreshed at scrape time and by the fleet refresh — a value "
        "far above ROUTER_HEARTBEAT_S means the poller itself has "
        "stalled, which silent breaker flips would otherwise hide"),
    "router_requests_total": (
        "counter", ("outcome",),
        "router-observed request outcomes: ok (stream completed), shed "
        "(backpressure relayed/originated), error (5xx/post-connect), "
        "connect_fail (one connect attempt failed; per attempt), "
        "midstream_loss (replica died on a 200), disconnect (caller "
        "hung up)"),
    "router_ttft_seconds": (
        "histogram", (),
        "router-observed time to first upstream body byte per routed "
        "request — the fleet-edge TTFT distribution, measured at the "
        "router, not replica self-reports"),
    "router_slo_attainment": (
        "gauge", ("replica",),
        "per-replica SLO attainment over the rolling ROUTER_SLO_WINDOW_S "
        "outcome window: requests that completed ok within their "
        "X-Deadline-Ms (or beat ROUTER_SLO_TTFT_MS when no deadline) "
        "over all router-observed outcomes placed there"),
    "router_window_shed_rate": (
        "gauge", ("replica",),
        "windowed fraction of each replica's router-observed outcomes "
        "that were backpressure sheds (429/503 relays)"),
    "router_window_error_rate": (
        "gauge", ("replica",),
        "windowed fraction of each replica's router-observed outcomes "
        "that were errors or failed connect attempts (caller "
        "disconnects excluded — they say nothing about the replica)"),
    "router_window_midstream_loss_rate": (
        "gauge", ("replica",),
        "windowed fraction of each replica's router-observed outcomes "
        "that were mid-stream losses (error frame appended to a 200)"),
    "router_fleet_headroom_tokens_per_sec": (
        "gauge", (),
        "fleet capacity-headroom estimate from the last fleet refresh: "
        "summed modeled decode capacity (per-replica step-cost model "
        "from the heartbeat) minus observed round-telemetry throughput "
        "— the number an SLO-driven autoscaler scales on "
        "(GET /debug/fleet carries the per-replica breakdown)"),
    "router_autoscale_target_replicas": (
        "gauge", (),
        "the autoscale controller's current replica target — what the "
        "last control cycle decided the fleet should be, whether or not "
        "an executor has finished converging it (GET /debug/autoscale "
        "carries the decision ring with full evidence)"),
    "router_autoscale_decisions_total": (
        "counter", ("action",),
        "autoscale control cycles by decided action: scale_up, "
        "scale_down, hold, surge_on, surge_off, blocked (cooldown / "
        "not leader / no executor / no drain candidate) — "
        "docs/autoscaling.md has the control law"),
    "router_surge_queue_depth": (
        "gauge", (),
        "requests currently waiting in the router's surge-admission "
        "queue — nonzero only while the fleet is at max_replicas and "
        "overloaded; sustained depth near ROUTER_SURGE_QUEUE_CAP means "
        "the fleet ceiling itself is too low"),
    "router_replicas_role": (
        "gauge", ("role",),
        "replicas in the table by disaggregation role (unified / "
        "prefill / decode) as last heartbeat-advertised — a role-less "
        "fleet reads all-unified (docs/disaggregation.md)"),
    "router_disagg_handoffs_total": (
        "counter", (),
        "long prompts served through the two-leg disaggregated "
        "prefill/decode handoff: prefill-role replica ran the prompt "
        "and pushed its finished prefix pages to the chosen decode "
        "replica, which then admitted the request as a near-full "
        "prefix-cache hit (docs/disaggregation.md)"),
    "router_disagg_fallbacks_total": (
        "counter", ("reason",),
        "disaggregation handoffs abandoned in favor of normal in-place "
        "placement, by reason: prefill_error (leg-1 POST failed or "
        "non-200), prefill_timeout (leg-1 exceeded "
        "ROUTER_DISAGG_PREFILL_TIMEOUT_S), no_pages (prefill replica "
        "exported nothing) — each one served correctly via recompute, "
        "just without the TTFT win"),
    "router_resume_total": (
        "counter", ("outcome",),
        "mid-stream failover resumes attempted after a replica died on "
        "a 200, by outcome: ok (sibling continued the stream; the "
        "caller never saw an error frame), no_replica (no placeable "
        "sibling), rejected (sibling answered non-200), connect_fail "
        "(sibling unreachable), overflow (transcript exceeded "
        "ROUTER_TRANSCRIPT_MAX_BYTES so replay was off), "
        "budget_exhausted (ROUTER_RESUME_ATTEMPTS already spent) — "
        "every non-ok outcome falls back to the classic replica_lost "
        "error frame (docs/robustness.md)"),
    "router_resume_replay_tokens": (
        "gauge", (),
        "replayed generated-so-far tokens admitted by the sibling on "
        "the most recent successful resume (from its X-Resume-Replayed "
        "header) — how much completed work the failover preserved "
        "instead of re-billing the client for"),
}


def _get(name: str):
    kind, labelnames, help_txt = ROUTER_METRICS[name]
    reg = obs_metrics.REGISTRY
    if kind == "histogram":
        return reg.histogram(name, help_txt,
                             buckets=obs_metrics.STAGE_BUCKETS,
                             labelnames=labelnames)
    factory = reg.counter if kind == "counter" else reg.gauge
    return factory(name, help_txt, labelnames=labelnames)


def counter(name: str, *labels: str):
    m = _get(name)
    return m.labels(*labels) if labels else m


def gauge(name: str, *labels: str):
    m = _get(name)
    return m.labels(*labels) if labels else m


def histogram(name: str, *labels: str):
    m = _get(name)
    return m.labels(*labels) if labels else m


def record_replica_load(name: str, load: dict) -> None:
    """Mirror one replica's heartbeat ``load`` block into the
    replica-labeled gauges (obs/metrics stays scrape-shaped: the router
    polls, the gauges hold the last observation)."""
    if "queue_depth" in load:
        gauge("router_replica_queue_depth", name).set(
            float(load["queue_depth"]))
    if "in_flight" in load:
        gauge("router_replica_in_flight", name).set(
            float(load["in_flight"]))
    if "rejected_total" in load:
        gauge("router_replica_rejected_total", name).set(
            float(load["rejected_total"]))
    if "prefix_hit_rate" in load:
        gauge("router_replica_prefix_hit_rate", name).set(
            float(load["prefix_hit_rate"]))
