"""Streaming ingest: continuous sources -> chunks -> embeddings -> store.

The TPU-native replacement for the reference's Morpheus VDB-upload
pipeline (reference: experimental/streaming_ingest_rag/pipeline.py:60-102
— RSS/filesystem/Kafka source pipes -> content extraction -> tokenize ->
Triton embedding -> WriteToVectorDBStage, with MonitorStage throughput
counters between stages). Morpheus is a GPU SIMD pipeline framework;
here the same shape is an asyncio pipeline — stages connected by bounded
queues (natural backpressure), the embed stage batching documents into
the jit-compiled encoder, per-stage counters in the metrics registry.

  sources.py   FilesystemSource (glob + poll watch), RSSSource
               (stdlib XML parsing), KafkaSource (gated on a client lib)
  pipeline.py  stage runner + batching + stats
  __main__.py  CLI: python -m generativeaiexamples_tpu.ingest ...
"""

from .pipeline import IngestPipeline, PipelineStats
from .sources import FilesystemSource, KafkaSource, RSSSource, SourceItem

__all__ = ["IngestPipeline", "PipelineStats", "FilesystemSource",
           "RSSSource", "KafkaSource", "SourceItem"]
