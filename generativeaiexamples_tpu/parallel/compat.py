"""Version portability for the sharding APIs the parallel layer uses.

Same pattern as the engine's ``_layout_api`` shim (engine/engine.py):
jax moved ``shard_map`` out of ``jax.experimental`` into the top-level
namespace around 0.5/0.6 and grew ``jax.lax.pcast`` and
``jax.tree.leaves_with_path`` in the same window. On 0.4.x those
spellings raise AttributeError at trace time — which is exactly how the
seed-failing ``test_moe``/``test_pipeline``/``test_weight_cache`` runs
died. Callers import the portable spellings from here instead of
version-gating at every site.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_04x

    def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
        """0.4.x spelling. ``check_rep`` is disabled because the callers
        were written against the new API's explicit replication casts
        (``pcast``), which 0.4.x cannot express — the old rep checker
        would reject values the new API marks varying. Semantics are
        unchanged; only the static replication audit is skipped."""
        kwargs.setdefault("check_rep", False)
        return _shard_map_04x(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)


if hasattr(jax.lax, "pcast"):
    pcast = jax.lax.pcast
else:
    def pcast(x, axes, to):  # noqa: ARG001 — mirror the new signature
        """Replication-cast is purely a static annotation for the new
        API's varying-manual-axes checker; on 0.4.x (where the checker
        is disabled above) the value itself is already correct, so the
        cast is the identity."""
        return x


if hasattr(jax.tree, "leaves_with_path"):
    tree_leaves_with_path = jax.tree.leaves_with_path
else:
    from jax.tree_util import tree_leaves_with_path  # noqa: F401  0.4.x home
