"""Exact top-k vector store with numpy / native C++ / TPU backends.

The FAISS-flat equivalent (reference: common/utils.py:197-198 uses
``langchain.vectorstores.FAISS``). One store, three engines:
  - "auto":   native C++ (OpenMP) when the toolchain is up, else numpy.
  - "numpy":  blocked BLAS matmul + argpartition.
  - "tpu":    jit matmul + lax.top_k on the accelerator — the stand-in for
              the reference's GPU-resident Milvus search
              (reference: common/utils.py:181-186 GPU_IVF_FLAT).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import numpy as np

from .store import SearchHit, VectorStore, _as_2d, score_matrix


class ExactStore(VectorStore):
    def __init__(self, dim: int, metric: str = "ip", backend: str = "auto",
                 capacity: int = 1024):
        if metric not in ("ip", "l2"):
            raise ValueError(f"metric must be ip|l2, got {metric!r}")
        self._dim = dim
        self.metric = metric
        self.backend = backend
        self._data = np.zeros((capacity, dim), np.float32)
        self._sq = np.zeros((capacity,), np.float32)
        self._live = np.zeros((capacity,), np.uint8)
        self._n = 0
        self._deleted = 0
        self._tpu: Optional["_TpuBackend"] = None

    # ------------------------------------------------------------- plumbing

    @property
    def dim(self) -> int:
        return self._dim

    def __len__(self) -> int:
        return self._n - self._deleted

    def _grow(self, need: int) -> None:
        cap = self._data.shape[0]
        if need <= cap:
            return
        new_cap = max(need, cap * 2)
        for name in ("_data", "_sq", "_live"):
            old = getattr(self, name)
            new = np.zeros((new_cap,) + old.shape[1:], old.dtype)
            new[:self._n] = old[:self._n]
            setattr(self, name, new)

    # ------------------------------------------------------------------ API

    def add(self, embeddings: np.ndarray) -> list[int]:
        emb = _as_2d(embeddings)
        if emb.shape[1] != self._dim:
            raise ValueError(f"dim mismatch: store {self._dim}, got {emb.shape[1]}")
        n_new = emb.shape[0]
        self._grow(self._n + n_new)
        ids = list(range(self._n, self._n + n_new))
        self._data[self._n:self._n + n_new] = emb
        self._sq[self._n:self._n + n_new] = np.einsum("nd,nd->n", emb, emb)
        self._live[self._n:self._n + n_new] = 1
        self._n += n_new
        self._tpu = None  # device copy invalidated
        return ids

    def delete(self, ids: Sequence[int]) -> None:
        for i in ids:
            if 0 <= i < self._n and self._live[i]:
                self._live[i] = 0
                self._deleted += 1
        self._tpu = None

    def export_vectors(self) -> tuple[list[int], np.ndarray]:
        """(ids, rows) of every live vector — feeds the engine's
        device-resident fused-RAG corpus."""
        live = [i for i in range(self._n) if self._live[i]]
        return live, self._data[live].copy()

    def search(self, queries: np.ndarray, k: int = 4) -> list[list[SearchHit]]:
        q = _as_2d(queries)
        if self._n == 0:
            return [[] for _ in range(q.shape[0])]
        k_eff = min(k, len(self))
        if k_eff == 0:
            return [[] for _ in range(q.shape[0])]
        idx, score = self._dispatch(q, k_eff)
        return [
            [SearchHit(int(i), float(s)) for i, s in zip(ri, rs) if i >= 0]
            for ri, rs in zip(idx, score)
        ]

    def _dispatch(self, q: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
        base = self._data[:self._n]
        live = self._live[:self._n]
        any_dead = self._deleted > 0
        if self.backend in ("auto", "native"):
            from . import native
            out = native.brute_topk(
                base, np.ascontiguousarray(q), k,
                0 if self.metric == "ip" else 1,
                base_sq=self._sq[:self._n] if self.metric == "l2" else None,
                live=live if any_dead else None)
            if out is not None:
                return out
            if self.backend == "native":
                raise RuntimeError("native topk backend unavailable")
        if self.backend == "tpu":
            if self._tpu is None:
                from .tpu_search import _TpuBackend
                self._tpu = _TpuBackend(base, live if any_dead else None,
                                        self.metric)
            return self._tpu.search(q, k)
        scores = score_matrix(base, q, self.metric,
                              base_sqnorm=self._sq[:self._n])
        if any_dead:
            scores = np.where(live[None, :] == 1, scores, -np.inf)
        part = np.argpartition(-scores, k - 1, axis=1)[:, :k]
        part_scores = np.take_along_axis(scores, part, axis=1)
        order = np.argsort(-part_scores, axis=1)
        idx = np.take_along_axis(part, order, axis=1)
        top = np.take_along_axis(part_scores, order, axis=1)
        idx = np.where(np.isfinite(top), idx, -1)
        return idx.astype(np.int64), top.astype(np.float32)

    # ---------------------------------------------------------- persistence

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        np.savez_compressed(os.path.join(path, "vectors.npz"),
                            data=self._data[:self._n],
                            live=self._live[:self._n])
        with open(os.path.join(path, "store.json"), "w") as f:
            json.dump({"kind": "exact", "dim": self._dim,
                       "metric": self.metric, "backend": self.backend}, f)

    @classmethod
    def load(cls, path: str) -> "ExactStore":
        with open(os.path.join(path, "store.json")) as f:
            meta = json.load(f)
        z = np.load(os.path.join(path, "vectors.npz"))
        store = cls(dim=meta["dim"], metric=meta["metric"],
                    backend=meta.get("backend", "auto"),
                    capacity=max(1, z["data"].shape[0]))
        n = z["data"].shape[0]
        store._data[:n] = z["data"]
        store._sq[:n] = np.einsum("nd,nd->n", z["data"], z["data"])
        store._live[:n] = z["live"]
        store._n = n
        store._deleted = int(n - z["live"].sum())
        return store
