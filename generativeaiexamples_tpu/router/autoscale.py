"""SLO-driven fleet autoscaler + surge admission: the loop that CLOSES
the control loop PR 12 instrumented.

The fleet snapshot (``GET /debug/fleet``, router/fleet.py) already
carries everything a conductor needs — per-replica queue depth, the
rolling SLO window, calibrated ``capacity_tokens_per_sec`` from the same
step-cost model the open-loop goodput bench fits, and the derived
capacity headroom. Until now nothing ACTED on it: the fleet could see an
overload coming and could only shed. This module is the actor
(Mooncake's overload-oriented conductor, DistServe's pool sizing,
adapted to this stack):

- :class:`AutoscaleController` — a periodic control cycle over the
  fleet snapshot. Scale **up** on LEADING indicators (headroom
  consumption, queue depth per replica and its trend across the rolling
  window, SLO-slack exhaustion) *before* ``shed_total`` starts climbing;
  sheds themselves are kept only as the lagging backstop. Scale **down**
  only through the PR-7 drain protocol — a streaming replica is never
  killed. Every cycle appends a :data:`decision record <DECISION_SCHEMA>`
  with its full evidence to a bounded ring (``GET /debug/autoscale``),
  so "why did the fleet scale at 14:03" is a join against
  ``/debug/fleet``, not archaeology.
- :class:`SurgeGate` — router-level surge admission for the at-max
  fleet: a bounded wait queue in front of placement whose rejections are
  honest backpressure (429 + ``Retry-After`` derived from the MEASURED
  service-time estimate, fast 429 ``deadline_unmeetable`` when the
  caller's budget cannot survive the queue) instead of cascading
  timeouts.
- Executors — :class:`LocalExecutor` activates/parks in-process
  replicas through the router's own membership API (the bench and the
  chaos tests drive this one), :class:`KubeOperatorExecutor` patches the
  HelmPipeline CR's chart values through the operator's reconcile path
  (deploy/operator.py ``set_scale_target``) with optimistic-concurrency
  single-writer semantics; the controller additionally gates every
  execution behind a ``leader`` callable so an active/standby router
  pair (deploy/leader.py) has exactly one writer.

The decision-record and ``/debug/autoscale`` contracts are pinned by
:data:`AUTOSCALE_SCHEMA` / :data:`DECISION_SCHEMA` /
:data:`EVIDENCE_SCHEMA` and enforced element-wise by
:func:`validate_autoscale_snapshot` — ``tools/preflight.py`` runs it
over a synthetic-but-real controller (proven able to fail in tier 1),
the same way the fleet snapshot contract is pinned.
"""

from __future__ import annotations

import asyncio
import math
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import aiohttp

from ..utils import faults
from ..utils.logging import get_logger
from . import metrics as router_metrics
from .fleet import _TYPES, _check
from .flight import _env_float

logger = get_logger(__name__)

#: Everything a decision's ``action`` field may say. ``hold`` is the
#: no-op cycle (evidence still recorded); ``surge_on``/``surge_off`` are
#: the at-max admission-mode transitions; ``blocked`` is a wanted scale
#: action that could not run (cooldown, not leader, no executor).
ACTIONS = ("scale_up", "scale_down", "hold", "surge_on", "surge_off",
           "blocked")


# --------------------------------------------------------------- policy


@dataclass
class AutoscalePolicy:
    """The control law's knobs (docs/autoscaling.md has the full table).

    Scale-up triggers are LEADING indicators; any one suffices:
    utilization ≥ ``up_util``, queue depth per placeable replica ≥
    ``queue_high`` (or ≥ half of it while the trend is rising), windowed
    TTFT p50 past ``slack_frac`` of the SLO, or — the lagging backstop —
    a nonzero shed rate. Scale-down needs ``down_stable_ticks``
    consecutive quiet cycles (utilization ≤ ``down_util``, empty queue,
    zero sheds) and proceeds one replica at a time via drain.
    """

    min_replicas: int = 1
    max_replicas: int = 1
    target_util: float = 0.75     # sizing target for the demand model
    up_util: float = 0.85         # headroom-consumption trigger
    queue_high: float = 4.0       # queued requests per placeable replica
    slack_frac: float = 0.8       # windowed ttft_p50 / SLO trigger
    down_util: float = 0.30
    down_stable_ticks: int = 3
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 30.0
    interval_s: float = 2.0       # control-cycle period
    trend_window: int = 5         # cycles kept for the queue trend
    drain_wait_s: float = 60.0    # scale-down drain budget

    @classmethod
    def from_env(cls, *, min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None) -> "AutoscalePolicy":
        """``ROUTER_AUTOSCALE_*`` env knobs over the defaults above."""
        e = _env_float
        return cls(
            min_replicas=int(min_replicas if min_replicas is not None
                             else e("ROUTER_AUTOSCALE_MIN", 1)),
            max_replicas=int(max_replicas if max_replicas is not None
                             else e("ROUTER_AUTOSCALE_MAX", 1)),
            target_util=e("ROUTER_AUTOSCALE_TARGET_UTIL", 0.75),
            up_util=e("ROUTER_AUTOSCALE_UP_UTIL", 0.85),
            queue_high=e("ROUTER_AUTOSCALE_QUEUE_HIGH", 4.0),
            slack_frac=e("ROUTER_AUTOSCALE_SLACK_FRAC", 0.8),
            down_util=e("ROUTER_AUTOSCALE_DOWN_UTIL", 0.30),
            down_stable_ticks=int(
                e("ROUTER_AUTOSCALE_DOWN_STABLE_TICKS", 3)),
            up_cooldown_s=e("ROUTER_AUTOSCALE_UP_COOLDOWN_S", 5.0),
            down_cooldown_s=e("ROUTER_AUTOSCALE_DOWN_COOLDOWN_S", 30.0),
            interval_s=e("ROUTER_AUTOSCALE_INTERVAL_S", 2.0),
            trend_window=int(e("ROUTER_AUTOSCALE_TREND_WINDOW", 5)),
            drain_wait_s=e("ROUTER_AUTOSCALE_DRAIN_WAIT_S", 60.0))

    def snapshot(self) -> dict:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "target_util": self.target_util,
            "up_util": self.up_util,
            "queue_high": self.queue_high,
            "slack_frac": self.slack_frac,
            "down_util": self.down_util,
            "down_stable_ticks": self.down_stable_ticks,
            "up_cooldown_s": self.up_cooldown_s,
            "down_cooldown_s": self.down_cooldown_s,
            "interval_s": self.interval_s,
            "trend_window": self.trend_window,
            "drain_wait_s": self.drain_wait_s,
        }


# ----------------------------------------------------------- surge gate


class SurgeGate:
    """Bounded-queue admission at the router's front door.

    In-flight forwards and their hold times are counted ALWAYS (two
    integer ops per request), so the moment the controller flips the
    gate ``active`` — fleet at max and still overloaded — the
    concurrency accounting and the service-time EWMA are already warm.
    While active, a request beyond the concurrency bound waits in a
    bounded FIFO; the three rejection paths are all honest backpressure:

    - ``deadline_unmeetable`` — the caller's ``X-Deadline-Ms`` is below
      the estimated queue wait: fast 429 before any queueing.
    - ``surge_queue_full`` — the wait queue is at ``queue_cap``.
    - ``surge_timeout`` — the request waited ``max_wait_s`` without a
      slot freeing.

    Every rejection's ``Retry-After`` derives from the MEASURED estimate
    ``(position + 1) × service_ewma_ms / concurrency`` — the queue-wait
    a retry would actually face, not a constant. Single-event-loop only
    (the router's); no locks by construction.
    """

    def __init__(self, *, queue_cap: Optional[int] = None,
                 max_wait_s: Optional[float] = None,
                 concurrency: Optional[int] = None,
                 service_prior_ms: float = 500.0):
        self.queue_cap = int(queue_cap if queue_cap is not None
                             else _env_float("ROUTER_SURGE_QUEUE_CAP", 64))
        self.max_wait_s = float(
            max_wait_s if max_wait_s is not None
            else _env_float("ROUTER_SURGE_MAX_WAIT_S", 5.0))
        self.concurrency = max(1, int(
            concurrency if concurrency is not None
            else _env_float("ROUTER_SURGE_CONCURRENCY", 16)))
        # An EXPLICIT bound (constructor arg or env) is an operator
        # decision: the controller's per-replica tracking must not
        # overwrite it (AutoscaleController.tick consults this).
        self.concurrency_pinned = (
            concurrency is not None
            or bool(os.environ.get("ROUTER_SURGE_CONCURRENCY")))
        self.active = False
        self._in_flight = 0
        self._waiters: deque = deque()
        self._service_ewma_ms = float(service_prior_ms)
        self.admitted_total = 0
        self.rejected: dict[str, int] = {}

    # ------------------------------------------------------------ control

    def set_active(self, value: bool) -> None:
        self.active = bool(value)
        if not self.active:
            # Draining the wait queue on deactivation: the overload is
            # over, everyone queued gets through.
            while self._waiters:
                fut = self._waiters.popleft()
                if not fut.done():
                    self._in_flight += 1
                    fut.set_result(True)
            self._publish_depth()

    def set_concurrency(self, value: int) -> None:
        self.concurrency = max(1, int(value))
        # A RAISED bound frees slots NOW: grant queued waiters up to it
        # (otherwise they sit out max_wait_s against free capacity,
        # since grants otherwise only happen on exit()).
        self._grant_waiters()

    # ------------------------------------------------------------- admit

    def estimate_wait_ms(self, position: Optional[int] = None) -> float:
        """Measured queue-wait estimate for a request entering at
        ``position`` (default: the back of the current queue)."""
        pos = len(self._waiters) if position is None else position
        return (pos + 1) * self._service_ewma_ms / self.concurrency

    async def enter(self, deadline_ms: Optional[float] = None
                    ) -> tuple[Optional[float],
                               Optional[tuple[str, float]]]:
        """Admit one forward. Returns ``(ticket, None)`` on admission
        (pass the ticket to :meth:`exit` in a finally) or
        ``(None, (err_type, est_wait_ms))`` on rejection."""
        if not self.active:
            self._in_flight += 1
            return time.monotonic(), None
        if self._in_flight < self.concurrency and not self._waiters:
            self._in_flight += 1
            self.admitted_total += 1
            return time.monotonic(), None
        est = self.estimate_wait_ms()
        if deadline_ms is not None and est > float(deadline_ms):
            self._reject("deadline_unmeetable")
            return None, ("deadline_unmeetable", est)
        if len(self._waiters) >= self.queue_cap:
            self._reject("surge_queue_full")
            return None, ("surge_queue_full", est)
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        self._publish_depth()
        try:
            await asyncio.wait_for(fut, timeout=self.max_wait_s)
        except asyncio.TimeoutError:
            try:
                self._waiters.remove(fut)
            except ValueError:
                # Already popped by a grantor. On 3.12+ wait_for can
                # surface TimeoutError even though the grant landed
                # first (the cancel races set_result) — the slot is
                # OURS; admitting is both correct and the only path
                # that doesn't leak the _in_flight increment.
                if fut.done() and not fut.cancelled():
                    self._publish_depth()
                    self.admitted_total += 1
                    return time.monotonic(), None
            self._publish_depth()
            self._reject("surge_timeout")
            return None, ("surge_timeout", self.estimate_wait_ms())
        except BaseException:
            # Caller cancelled while queued: leave honestly.
            try:
                self._waiters.remove(fut)
            except ValueError:
                # Already granted (raced a grant): give the slot back.
                if fut.done() and not fut.cancelled():
                    self._release_slot()
            self._publish_depth()
            raise
        self._publish_depth()
        self.admitted_total += 1
        return time.monotonic(), None

    def exit(self, ticket: Optional[float]) -> None:
        """Release one forward's slot; feeds the service-time EWMA."""
        if ticket is None:
            return
        held_ms = (time.monotonic() - ticket) * 1e3
        self._service_ewma_ms = (0.8 * self._service_ewma_ms
                                 + 0.2 * held_ms)
        self._release_slot()

    def _release_slot(self) -> None:
        self._in_flight = max(0, self._in_flight - 1)
        self._grant_waiters()

    def _grant_waiters(self) -> None:
        while self._waiters and self._in_flight < self.concurrency:
            fut = self._waiters.popleft()
            if not fut.done():
                self._in_flight += 1
                fut.set_result(True)
        self._publish_depth()

    def _reject(self, reason: str) -> None:
        self.rejected[reason] = self.rejected.get(reason, 0) + 1

    def _publish_depth(self) -> None:
        router_metrics.gauge("router_surge_queue_depth").set(
            len(self._waiters))

    def snapshot(self) -> dict:
        return {
            "active": self.active,
            "queue_depth": len(self._waiters),
            "queue_cap": self.queue_cap,
            "concurrency": self.concurrency,
            "in_flight": self._in_flight,
            "max_wait_s": self.max_wait_s,
            "est_wait_ms": round(self.estimate_wait_ms(), 1),
            "service_ewma_ms": round(self._service_ewma_ms, 1),
            "admitted_total": self.admitted_total,
            "rejected": dict(self.rejected),
        }


# ------------------------------------------------------------ executors


class LocalExecutor:
    """Activate/park pre-built in-process replicas through the router's
    own membership path — the executor the bench and the chaos tests
    drive. ``pool`` is the PARKED (name, url) pairs; scale-up activates
    from it (``table.add`` + an immediate probe so the replica takes
    traffic without waiting a heartbeat), scale-down drains via
    :meth:`FleetRouter.remove_replica` and parks the pair again."""

    def __init__(self, router, pool: Sequence[tuple[str, str]] = (),
                 drain_wait_s: float = 30.0):
        self.router = router
        self._parked: deque = deque(pool)
        self.drain_wait_s = float(drain_wait_s)

    @property
    def parked(self) -> list[tuple[str, str]]:
        return list(self._parked)

    async def scale_to(self, target: int, *, current: int, action: str,
                       victim: Optional[str] = None) -> dict:
        added: list[str] = []
        removed: list[str] = []
        while current + len(added) < target and self._parked:
            name, url = self._parked.popleft()
            # A parked replica was DRAINED on its way out (scale-down);
            # re-activation must reopen its admission or it answers 429
            # draining forever. Bounded like every other control call —
            # a wedged parked replica must not stall the control loop.
            try:
                assert self.router._session is not None
                async with self.router._session.post(
                        url + "/control/undrain",
                        timeout=aiohttp.ClientTimeout(
                            total=self.router.heartbeat_timeout_s)) \
                        as resp:
                    await resp.read()
            except Exception:  # noqa: BLE001 — fresh replicas have no drain
                pass
            rep = self.router.table.add(name, url)
            # Probe now: the new replica serves the burst that caused
            # the scale-up, not the one after next heartbeat.
            await self.router._probe(rep)
            added.append(name)
        while current - len(removed) > target:
            name = victim or self.router.table.scale_down_candidate(
                exclude_roles=("prefill",))
            victim = None
            if name is None:
                break
            rep = self.router.table.get(name)
            url = rep.url if rep is not None else None
            ok = await self.router.remove_replica(
                name, drain=True, wait_s=self.drain_wait_s)
            if not ok:
                break
            removed.append(name)
            if url is not None:
                self._parked.append((name, url))
        detail = f"local: parked={len(self._parked)}"
        return {"ok": True, "added": added, "removed": removed,
                "error": None, "detail": detail}


class KubeOperatorExecutor:
    """Scale through the operator's reconcile path: patch the
    HelmPipeline CR's chart values (``deploy.operator.set_scale_target``)
    so the operator's watch re-renders the chart and k8s rolls the
    Deployment — scale-down pods drain through the existing preStop
    hook, so the drain protocol holds without the router killing
    anything. Single-writer: the PUT carries the resourceVersion the
    read observed, so a concurrent writer (a second, split-brain router)
    surfaces as ``ConflictError`` and the decision records ``ok=False``
    instead of silently clobbering."""

    def __init__(self, kube, *, namespace: str, pipeline: str,
                 release: str, values_path: Sequence[str] = ()):
        self.kube = kube
        self.namespace = namespace
        self.pipeline = pipeline
        self.release = release
        self.values_path = tuple(values_path) or ("replicas",)

    async def scale_to(self, target: int, *, current: int, action: str,
                       victim: Optional[str] = None) -> dict:
        from ..deploy.operator import set_scale_target
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, lambda: set_scale_target(
                self.kube, namespace=self.namespace,
                pipeline=self.pipeline, release=self.release,
                replicas=int(target), values_path=self.values_path))
        return {"ok": True, "added": [], "removed": [], "error": None,
                "detail": (f"kube: {self.namespace}/{self.pipeline} "
                           f"{self.release}.{'.'.join(self.values_path)}"
                           f"={int(target)}")}


# ----------------------------------------------------------- controller


class AutoscaleController:
    """The periodic control cycle (see module docstring). ``router`` is
    a :class:`~.server.FleetRouter` (or anything with ``refresh_fleet``
    + ``table``); ``executor`` may be None (decisions are still
    recorded — a dry-run conductor); ``leader`` gates every execution
    (active/standby single-writer)."""

    def __init__(self, router, *, policy: Optional[AutoscalePolicy] = None,
                 executor=None, surge: Optional[SurgeGate] = None,
                 leader: Optional[Callable[[], bool]] = None,
                 slo_ttft_ms: Optional[float] = None,
                 ring_cap: int = 256):
        self.router = router
        self.policy = policy or AutoscalePolicy.from_env()
        self.executor = executor
        self.surge = surge or SurgeGate()
        self.leader = leader or (lambda: True)
        # The slack-exhaustion trigger compares the windowed TTFT p50
        # against the SAME SLO the window scores attainment with.
        if slo_ttft_ms is None:
            window = getattr(getattr(router, "flight", None), "slo", None)
            slo_ttft_ms = getattr(window, "slo_ttft_ms", None) \
                or _env_float("ROUTER_SLO_TTFT_MS", 2000.0)
        self.slo_ttft_ms = float(slo_ttft_ms)
        self._decisions: deque = deque(maxlen=ring_cap)
        self._decisions_total: dict[str, int] = {}
        self._queue_history: deque = deque(
            maxlen=max(2, self.policy.trend_window))
        self._seq = 0
        self._last_up_t = 0.0
        self._last_down_t = 0.0
        self._quiet_ticks = 0
        self.target_replicas: Optional[int] = None
        self._now = time.monotonic   # tests pin the clock here

    # ----------------------------------------------------------- evidence

    def _evidence(self, snap: dict) -> dict:
        fleet = snap.get("fleet") or {}
        placeable = int(fleet.get("replicas_placeable", 0))
        queue_depth = int(fleet.get("queue_depth", 0))
        tps = float(fleet.get("tokens_per_sec", 0.0) or 0.0)
        cap = float(fleet.get("capacity_tokens_per_sec", 0.0) or 0.0)
        util = round(tps / cap, 4) if cap > 0 else None
        self._queue_history.append(queue_depth)
        hist = list(self._queue_history)
        trend = ((hist[-1] - hist[0]) / max(1, len(hist) - 1)
                 if len(hist) >= 2 else 0.0)
        return {
            "snapshot_unix_ms": int(snap.get("generated_unix_ms", 0)),
            "replicas_total": int(fleet.get("replicas_total", 0)),
            "replicas_placeable": placeable,
            "in_flight": int(fleet.get("in_flight", 0)),
            "queue_depth": queue_depth,
            "queue_per_replica": round(
                queue_depth / max(1, placeable), 3),
            "queue_trend": round(trend, 3),
            "utilization": util,
            "tokens_per_sec": tps,
            "capacity_tokens_per_sec": cap,
            "headroom_tokens_per_sec": float(
                fleet.get("headroom_tokens_per_sec", 0.0) or 0.0),
            "shed_rate": float(fleet.get("shed_rate", 0.0) or 0.0),
            "slo_attainment": fleet.get("slo_attainment"),
            "ttft_p50_ms": fleet.get("ttft_p50_ms"),
            "surge_queue_depth": len(self.surge._waiters),
            # Disaggregation role census (docs/disaggregation.md): a
            # role-ful fleet's capacity is per-pool, and the decision
            # record must show WHICH pool the evidence describes — a
            # role-less fleet reads {"unified": N}.
            "roles": dict(fleet.get("roles") or {}),
        }

    def _up_reasons(self, ev: dict) -> list[str]:
        p = self.policy
        reasons = []
        util = ev["utilization"]
        if util is not None and util >= p.up_util:
            reasons.append(f"utilization {util:.2f} >= {p.up_util:g}")
        qpr = ev["queue_per_replica"]
        if qpr >= p.queue_high:
            reasons.append(f"queue/replica {qpr:g} >= {p.queue_high:g}")
        elif ev["queue_trend"] > 0 and qpr >= p.queue_high / 2:
            reasons.append(
                f"queue rising ({ev['queue_trend']:+g}/tick) at "
                f"{qpr:g}/replica")
        ttft = ev["ttft_p50_ms"]
        if ttft is not None and self.slo_ttft_ms \
                and ttft >= p.slack_frac * self.slo_ttft_ms:
            reasons.append(
                f"slack exhaustion: ttft_p50 {ttft:.0f} ms >= "
                f"{p.slack_frac:g} x SLO {self.slo_ttft_ms:g} ms")
        if ev["shed_rate"] > 0:
            # The LAGGING backstop: if this fires first, the leading
            # indicators were mistuned — the decision record says so.
            reasons.append(f"sheds observed (rate "
                           f"{ev['shed_rate']:g}) — late")
        return reasons

    def _desired_up(self, ev: dict) -> int:
        """Demand model: size the fleet so observed load would sit at
        ``target_util`` of the calibrated capacity. The open-loop
        goodput curves are monotone in offered load up to the knee, and
        ``capacity_tokens_per_sec`` IS the knee's capacity estimate —
        so load / (per-replica capacity × target) is the replica count
        that keeps the fleet left of it."""
        p = self.policy
        placeable = max(1, ev["replicas_placeable"])
        cap_per = ev["capacity_tokens_per_sec"] / placeable \
            if ev["capacity_tokens_per_sec"] > 0 else 0.0
        if cap_per > 0 and ev["tokens_per_sec"] > 0:
            desired = math.ceil(
                ev["tokens_per_sec"] / (cap_per * p.target_util))
        else:
            desired = ev["replicas_total"] + 1
        return max(desired, ev["replicas_total"] + 1)

    # ------------------------------------------------------------- decide

    def _decide(self, ev: dict) -> tuple[str, str, int]:
        """Pure control law: ``(action, reason, target_replicas)``."""
        p = self.policy
        total = ev["replicas_total"]
        now = self._now()
        if total < p.min_replicas:
            self._quiet_ticks = 0
            return ("scale_up", f"below min_replicas {p.min_replicas}",
                    p.min_replicas)
        up_reasons = self._up_reasons(ev)
        if up_reasons:
            self._quiet_ticks = 0
            reason = "; ".join(up_reasons)
            if total >= p.max_replicas:
                if not self.surge.active:
                    return ("surge_on",
                            f"at max_replicas {p.max_replicas}: {reason}",
                            total)
                return ("hold", f"at max (surge active): {reason}", total)
            if now - self._last_up_t < p.up_cooldown_s:
                return ("blocked", f"scale-up cooldown: {reason}", total)
            target = min(p.max_replicas, self._desired_up(ev))
            return ("scale_up", reason, target)
        if self.surge.active:
            return ("surge_off", "overload cleared", total)
        util = ev["utilization"]
        quiet = ((util is None or util <= p.down_util)
                 and ev["queue_depth"] == 0 and ev["shed_rate"] == 0
                 and ev["surge_queue_depth"] == 0)
        if quiet:
            self._quiet_ticks += 1
        else:
            self._quiet_ticks = 0
        if quiet and total > p.min_replicas \
                and self._quiet_ticks >= p.down_stable_ticks:
            if now - self._last_down_t < p.down_cooldown_s:
                return ("blocked", "scale-down cooldown", total)
            return ("scale_down",
                    f"{self._quiet_ticks} quiet ticks "
                    f"(util {util if util is not None else 'n/a'} <= "
                    f"{p.down_util:g}, empty queue, no sheds)",
                    total - 1)
        return ("hold", "within bounds", total)

    # --------------------------------------------------------------- tick

    async def tick(self) -> dict:
        """One control cycle: observe → decide → (maybe) act → record.
        Never raises: executor failures land in the record's
        ``executor.error`` and retry naturally next cycle."""
        snap = self.router.refresh_fleet()
        ev = self._evidence(snap)
        action, reason, target = self._decide(ev)
        leader = bool(self.leader())
        executed = False
        executor_result: Optional[dict] = None
        if action in ("scale_up", "scale_down"):
            victim = None
            if action == "scale_down":
                # Never drain the prefill pool on a quiet-fleet signal:
                # the quiet evidence is DECODE-side, and losing the only
                # prefill replica kills every in-flight handoff leg
                # (docs/disaggregation.md).
                victim = self.router.table.scale_down_candidate(
                    exclude_roles=("prefill",))
                if victim is None:
                    action, reason = "blocked", ("no drainable scale-down "
                                                 f"candidate ({reason})")
            if action != "blocked" and not leader:
                action, reason = "blocked", f"not leader ({reason})"
            if action != "blocked" and self.executor is None:
                action, reason = "blocked", f"no executor ({reason})"
            if action in ("scale_up", "scale_down"):
                try:
                    faults.inject("autoscale.execute")
                    executor_result = await self.executor.scale_to(
                        target, current=ev["replicas_total"],
                        action=action, victim=victim)
                    executed = bool(executor_result.get("ok", True))
                except Exception as exc:  # noqa: BLE001 — recorded, retried
                    logger.warning("autoscale executor failed: %s", exc)
                    executor_result = {"ok": False, "added": [],
                                       "removed": [], "error": str(exc),
                                       "detail": ""}
                if executed:
                    if action == "scale_up":
                        self._last_up_t = self._now()
                    else:
                        self._last_down_t = self._now()
        if action == "surge_on":
            self.surge.set_active(True)
        elif action == "surge_off":
            self.surge.set_active(False)
        # Concurrency tracks the live fleet so the gate's bound means
        # "what the placeable replicas can hold", not a stale constant —
        # unless the operator PINNED it (an explicit constructor bound
        # or ROUTER_SURGE_CONCURRENCY is an incident-control override
        # the controller must not fight).
        if ev["replicas_placeable"] > 0 \
                and not self.surge.concurrency_pinned:
            self.surge.set_concurrency(
                ev["replicas_placeable"]
                * int(_env_float("ROUTER_SURGE_CONCURRENCY_PER_REPLICA",
                                 8)))
        self.target_replicas = target
        record = {
            "seq": self._seq,
            "unix_ms": int(time.time() * 1e3),
            "action": action,
            "reason": reason,
            "current_replicas": ev["replicas_total"],
            "target_replicas": target,
            "surge_active": self.surge.active,
            "leader": leader,
            "executed": executed,
            "executor": executor_result,
            "evidence": ev,
        }
        self._seq += 1
        self._decisions.append(record)
        self._decisions_total[action] = \
            self._decisions_total.get(action, 0) + 1
        router_metrics.gauge("router_autoscale_target_replicas").set(
            target)
        router_metrics.counter(
            "router_autoscale_decisions_total", action).inc()
        if action not in ("hold",):
            logger.info("autoscale: %s -> %d replicas (%s)", action,
                        target, reason)
        return record

    async def run(self) -> None:
        """The background loop ``create_router_app`` starts. Survives
        everything except cancellation."""
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("autoscale cycle failed")
            await asyncio.sleep(self.policy.interval_s)

    # ----------------------------------------------------------- snapshot

    def snapshot(self, limit: int = 50) -> dict:
        """The ``GET /debug/autoscale`` payload (schema-pinned)."""
        decisions = list(self._decisions)
        if limit:
            decisions = decisions[-int(limit):]
        return {
            "enabled": True,
            "leader": bool(self.leader()),
            "executor": (type(self.executor).__name__
                         if self.executor is not None else None),
            "slo_ttft_ms": float(self.slo_ttft_ms),
            "policy": self.policy.snapshot(),
            "target_replicas": self.target_replicas,
            "surge": self.surge.snapshot(),
            "decisions_total": dict(self._decisions_total),
            "decisions": decisions,
        }


# -------------------------------------------------------------- schemas

#: Top-level ``GET /debug/autoscale`` contract.
AUTOSCALE_SCHEMA: dict[str, list[str]] = {
    "enabled": ["bool"],
    "leader": ["bool"],
    "executor": ["str", "null"],
    "slo_ttft_ms": ["num"],
    "policy": ["obj"],
    "target_replicas": ["int", "null"],
    "surge": ["obj"],
    "decisions_total": ["obj"],
    "decisions": ["list"],
}

#: One decision record in the ring.
DECISION_SCHEMA: dict[str, list[str]] = {
    "seq": ["int"],
    "unix_ms": ["int"],
    "action": ["str"],
    "reason": ["str"],
    "current_replicas": ["int"],
    "target_replicas": ["int"],
    "surge_active": ["bool"],
    "leader": ["bool"],
    "executed": ["bool"],
    "executor": ["obj", "null"],
    "evidence": ["obj"],
}

#: The per-decision evidence block — the join against ``/debug/fleet``.
EVIDENCE_SCHEMA: dict[str, list[str]] = {
    "snapshot_unix_ms": ["int"],
    "replicas_total": ["int"],
    "replicas_placeable": ["int"],
    "in_flight": ["int"],
    "queue_depth": ["int"],
    "queue_per_replica": ["num"],
    "queue_trend": ["num"],
    "utilization": ["num", "null"],
    "tokens_per_sec": ["num"],
    "capacity_tokens_per_sec": ["num"],
    "headroom_tokens_per_sec": ["num"],
    "shed_rate": ["num"],
    "slo_attainment": ["num", "null"],
    "ttft_p50_ms": ["num", "null"],
    "surge_queue_depth": ["int"],
    "roles": ["obj"],
}

#: The ``surge`` sub-block.
SURGE_SCHEMA: dict[str, list[str]] = {
    "active": ["bool"],
    "queue_depth": ["int"],
    "queue_cap": ["int"],
    "concurrency": ["int"],
    "in_flight": ["int"],
    "max_wait_s": ["num"],
    "est_wait_ms": ["num"],
    "service_ewma_ms": ["num"],
    "admitted_total": ["int"],
    "rejected": ["obj"],
}


def validate_autoscale_snapshot(snap: dict) -> list[str]:
    """Every mismatch between ``snap`` and the ``/debug/autoscale``
    contract; empty on a clean snapshot. Element-wise: each decision
    record and its evidence block are checked individually, and actions
    must come from :data:`ACTIONS`."""
    errors: list[str] = []
    _check("autoscale", snap, AUTOSCALE_SCHEMA, errors)
    if isinstance(snap.get("surge"), dict):
        _check("autoscale.surge", snap["surge"], SURGE_SCHEMA, errors)
    for i, rec in enumerate(snap.get("decisions") or []):
        section = f"autoscale.decisions[{i}]"
        _check(section, rec, DECISION_SCHEMA, errors)
        if not isinstance(rec, dict):
            continue
        if rec.get("action") not in ACTIONS:
            errors.append(f"{section}.action: {rec.get('action')!r} not "
                          f"in {ACTIONS}")
        if isinstance(rec.get("evidence"), dict):
            _check(f"{section}.evidence", rec["evidence"],
                   EVIDENCE_SCHEMA, errors)
    if isinstance(snap.get("decisions_total"), dict):
        for action, count in snap["decisions_total"].items():
            if action not in ACTIONS or not _TYPES["int"](count):
                errors.append(f"autoscale.decisions_total: bad entry "
                              f"{action!r}={count!r}")
    return errors


__all__ = [
    "ACTIONS", "AUTOSCALE_SCHEMA", "DECISION_SCHEMA", "EVIDENCE_SCHEMA",
    "SURGE_SCHEMA", "AutoscaleController", "AutoscalePolicy",
    "KubeOperatorExecutor", "LocalExecutor", "SurgeGate",
    "validate_autoscale_snapshot",
]
