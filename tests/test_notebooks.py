"""Every shipped notebook executes headlessly, start to finish.

The reference's notebooks have no execution checks at all (SURVEY.md §4);
here they are CI surface: nbclient runs each one in a fresh kernel with
the repo root on sys.path (the notebooks' own `sys.path.insert` handles
it, since they run with notebooks/ as cwd).
"""

import glob
import os

import pytest

nbformat = pytest.importorskip("nbformat")
nbclient = pytest.importorskip("nbclient")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NOTEBOOKS = sorted(glob.glob(os.path.join(REPO, "notebooks", "*.ipynb")))
# Serving notebooks talk to a live server / real chip and guard themselves
# with availability checks; everything else must run anywhere.
OFFLINE = [p for p in NOTEBOOKS
           if os.path.basename(p) not in ("00_serving_quickstart.ipynb",
                                          "07_local_checkpoint_rag.ipynb")]


@pytest.mark.parametrize("path", OFFLINE,
                         ids=[os.path.basename(p) for p in OFFLINE])
def test_notebook_executes(path):
    nb = nbformat.read(path, as_version=4)
    client = nbclient.NotebookClient(
        nb, timeout=600, kernel_name="python3",
        resources={"metadata": {"path": os.path.dirname(path)}})
    client.execute()  # raises CellExecutionError on any failing cell
