"""The pluggable-example contract.

Exact parity with the reference's ABC (reference: common/base.py:21-33):
``llm_chain`` / ``rag_chain`` stream answer text, ``ingest_docs`` loads a
file into the knowledge base; ``document_search`` is optional and duck-typed
by the server (reference: common/server.py:152).

Request identity: examples do NOT thread a request ID through these
signatures. The chain server binds the inbound request's flight-recorder
timeline (adopted ``X-Request-ID``/traceparent, ``obs/flight.py``) on the
context the chain generator runs under, so anything an example calls —
``event_span`` stages, the embedder, ``Engine.submit`` via EngineLLM —
lands on the right per-request timeline automatically. An example that
wants the ID (e.g. to tag its own logs) reads
``current_request_id()`` below.
"""

from __future__ import annotations

import abc
from typing import Any, Generator

from ..obs.flight import current_request_id  # noqa: F401  (re-export)


class BaseExample(abc.ABC):
    """Base class for all chain-server examples."""

    @abc.abstractmethod
    def llm_chain(self, context: str, question: str, num_tokens: int,
                  ) -> Generator[str, None, None]:
        """Answer ``question`` with the LLM alone (no knowledge base);
        ``context`` is caller-supplied free text."""

    @abc.abstractmethod
    def rag_chain(self, prompt: str, num_tokens: int,
                  ) -> Generator[str, None, None]:
        """Answer using retrieval over the ingested knowledge base."""

    @abc.abstractmethod
    def ingest_docs(self, data_dir: str, filename: str) -> None:
        """Load a document file into the knowledge base."""

    # Optional (duck-typed by the server, like the reference):
    # def document_search(self, content: str, num_docs: int) -> list[dict]
