"""gRPC serving endpoint (tpu.serving.LLMService).

The reference serves gRPC on :8001 via Triton and its connectors default
to it (reference: model_server_client/trt_llm.py:370 ``GrpcTritonClient``,
server URL ``localhost:8001``). Here the gRPC surface is first-party:
unary + server-streaming Generate with the ensemble tensor semantics
(decoupled deltas, final-response flag, stop signal via RPC cancellation)
and an Embed RPC for the encoder.

Service stubs are registered with ``grpc.method_handlers_generic_handler``
— the image ships protoc without the grpcio-tools plugin, so messages are
protoc-generated (serving/protos) and handlers are wired by hand; the
wire format is identical to what generated stubs would produce.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Iterator, Optional

import grpc

from ..obs import metrics as obs_metrics
from ..utils.errors import EngineError
from ..utils.logging import get_logger
from .protos import llm_service_pb2 as pb

logger = get_logger(__name__)

SERVICE = "tpu.serving.LLMService"


def _params_from_request(req, max_output: int):
    from ..engine.sampling_params import SamplingParams
    if req.beam_width not in (0, 1):
        raise ValueError("beam_width != 1 is not supported")
    return SamplingParams(
        max_tokens=min(req.max_tokens or 100, max_output),
        temperature=req.temperature if req.temperature else 1.0,
        top_k=req.top_k if req.top_k else 1,
        top_p=req.top_p,
        repetition_penalty=(req.repetition_penalty
                            if req.repetition_penalty else 1.0),
        length_penalty=req.length_penalty if req.length_penalty else 1.0,
        random_seed=req.random_seed,
        stop_words=list(req.stop_words),
        bad_words=list(req.bad_words),
        ignore_eos=req.ignore_eos,
    )


class LLMServicer:
    """Handler implementations (the servicer generated stubs would wrap)."""

    def __init__(self, engine, model_name: str = "model",
                 embed_service=None, max_output: int = 512):
        self.engine = engine
        self.model_name = model_name
        self.embed_service = embed_service
        self.max_output = max_output

    def Health(self, request, context) -> pb.HealthResponse:
        return pb.HealthResponse(ready=True, model_name=self.model_name)

    def _submit(self, request, context):
        self.engine.start()
        try:
            params = _params_from_request(request, self.max_output)
            return self.engine.stream_text(request.text_input, params)
        except (ValueError, EngineError) as exc:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))

    def Generate(self, request, context) -> pb.GenerateResponse:
        timer = obs_metrics.RequestTimer("grpc_generate")
        stream = self._submit(request, context)
        # deadline/disconnect must release the decode slot, not keep
        # generating to max_tokens
        context.add_callback(stream.cancel)
        try:
            chunks = []
            for chunk in stream:
                timer.token(1)
                chunks.append(chunk)
            return pb.GenerateResponse(
                model_name=self.model_name, text_output="".join(chunks),
                final=True, finish_reason=stream.finish_reason or "")
        finally:
            timer.finish()

    def GenerateStream(self, request, context
                       ) -> Iterator[pb.GenerateResponse]:
        """Decoupled-mode deltas + a final-response marker; client-side RPC
        cancellation doubles as the mid-stream stop signal
        (reference: trt_llm.py:392-400 ``_send_stop_signals``)."""
        timer = obs_metrics.RequestTimer("grpc_generate")
        stream = self._submit(request, context)
        context.add_callback(stream.cancel)   # client hung up -> free slot
        try:
            for chunk in stream:
                timer.token(1)
                yield pb.GenerateResponse(model_name=self.model_name,
                                          text_output=chunk, final=False)
            yield pb.GenerateResponse(
                model_name=self.model_name, text_output="", final=True,
                finish_reason=stream.finish_reason or "")
        finally:
            timer.finish()

    def Embed(self, request, context) -> pb.EmbedResponse:
        if self.embed_service is None:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "no embedder configured")
        texts = list(request.texts)
        if not texts:
            return pb.EmbedResponse(dim=self.embed_service.dim)
        if request.input_type == "query":
            rows = list(self.embed_service.embed_queries(texts))
        else:
            rows = list(self.embed_service.embed_documents(texts))
        flat = [float(x) for row in rows for x in row]
        return pb.EmbedResponse(dim=len(flat) // len(texts), values=flat)


def _handlers(servicer: LLMServicer):
    rpcs = {
        "Health": grpc.unary_unary_rpc_method_handler(
            servicer.Health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString),
        "Generate": grpc.unary_unary_rpc_method_handler(
            servicer.Generate,
            request_deserializer=pb.GenerateRequest.FromString,
            response_serializer=pb.GenerateResponse.SerializeToString),
        "GenerateStream": grpc.unary_stream_rpc_method_handler(
            servicer.GenerateStream,
            request_deserializer=pb.GenerateRequest.FromString,
            response_serializer=pb.GenerateResponse.SerializeToString),
        "Embed": grpc.unary_unary_rpc_method_handler(
            servicer.Embed,
            request_deserializer=pb.EmbedRequest.FromString,
            response_serializer=pb.EmbedResponse.SerializeToString),
    }
    return grpc.method_handlers_generic_handler(SERVICE, rpcs)


def serve_grpc(engine, model_name: str = "model", embed_service=None,
               max_output: int = 512, host: str = "0.0.0.0",
               port: int = 8001, max_workers: int = 16) -> grpc.Server:
    """Start the gRPC server (non-blocking); returns the grpc.Server."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers,
                                   thread_name_prefix="grpc"))
    server.add_generic_rpc_handlers((_handlers(LLMServicer(
        engine, model_name, embed_service, max_output)),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:  # grpc reports bind failure via a 0 port, not an error
        raise OSError(f"gRPC failed to bind {host}:{port} (port in use?)")
    server.start()
    logger.info("gRPC serving %s on %s:%d", model_name, host, bound)
    server._bound_port = bound  # convenience for tests/port-0 binds
    return server


class GrpcLLMClient:
    """Minimal client over the same hand-wired stubs (streaming generate,
    embed, readiness polling — the roles of the reference's
    GrpcTritonClient, trt_llm.py:370-499)."""

    def __init__(self, target: str, timeout: float = 120.0):
        self.channel = grpc.insecure_channel(target)
        self.timeout = timeout
        self._generate = self.channel.unary_unary(
            f"/{SERVICE}/Generate",
            request_serializer=pb.GenerateRequest.SerializeToString,
            response_deserializer=pb.GenerateResponse.FromString)
        self._generate_stream = self.channel.unary_stream(
            f"/{SERVICE}/GenerateStream",
            request_serializer=pb.GenerateRequest.SerializeToString,
            response_deserializer=pb.GenerateResponse.FromString)
        self._embed = self.channel.unary_unary(
            f"/{SERVICE}/Embed",
            request_serializer=pb.EmbedRequest.SerializeToString,
            response_deserializer=pb.EmbedResponse.FromString)
        self._health = self.channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString)

    def wait_ready(self, timeout: float = 30.0) -> pb.HealthResponse:
        import time
        deadline = time.monotonic() + timeout
        while True:
            try:
                return self._health(pb.HealthRequest(), timeout=2.0)
            except grpc.RpcError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)

    def generate(self, text: str, **kw) -> str:
        resp = self._generate(pb.GenerateRequest(text_input=text, **kw),
                              timeout=self.timeout)
        return resp.text_output

    def generate_stream(self, text: str, **kw) -> Iterator[str]:
        for resp in self._generate_stream(
                pb.GenerateRequest(text_input=text, **kw),
                timeout=self.timeout):
            if resp.final:
                return
            yield resp.text_output

    def embed(self, texts: list[str], input_type: str = "passage"):
        resp = self._embed(pb.EmbedRequest(texts=texts,
                                           input_type=input_type),
                           timeout=self.timeout)
        import numpy as np
        return np.asarray(resp.values, np.float32).reshape(
            len(texts), resp.dim)

    def close(self) -> None:
        self.channel.close()
