"""Tier-1 CPU smoke of the slots-ladder capacity sweep
(``BENCH_SLOTS_SWEEP``): two tiny rungs end-to-end through real engines,
plus the section/rung key contract against tools/bench_schema.json —
the BENCH_SWEEP_rNN capacity table as one automated, schema-validated
scenario instead of hand-rolled single-rung runs."""

import jax
import jax.numpy as jnp
import pytest

import bench
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from tools.check_bench_schema import load_schema

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=256)


@pytest.fixture(scope="module")
def capacity():
    params = llama.init_params(CFG, jax.random.key(11), dtype=jnp.float32)
    return bench.run_capacity_sweep(
        params, CFG, ByteTokenizer(), [1, 2],
        prompt_len=16, out_len=4, n_requests=2,
        steps_per_round=4,
        # tiny-geometry overrides (production defaults target the chip);
        # pool sizing stays the sweep's own default so steadiness-by-
        # construction is pinned on the jnp fallback path below
        max_input_length=64, max_output_length=16,
        prefill_buckets=(16, 32, 64), dtype="float32", page_size=16,
        max_queue=64)


def test_capacity_sweep_runs_every_rung(capacity):
    assert capacity["slots_sweep"] == [1, 2]
    assert [r["slots"] for r in capacity["rungs"]] == [1, 2]
    for rung in capacity["rungs"]:
        assert rung["decode_tokens_per_sec"] > 0
        assert rung["engine_p50_ttft_ms"] > 0
        assert rung["engine_p99_ttft_ms"] >= rung["engine_p50_ttft_ms"]
        assert rung["tokens_per_sec_per_slot"] == pytest.approx(
            rung["decode_tokens_per_sec"] / rung["slots"], rel=0.02)
        assert rung["hbm_bw_achieved_gbps"] >= 0
        assert 0.0 <= rung["sampler_rows_skipped_frac"] <= 1.0
        # default pool sizing covers the bucketed (pow-2) window, so the
        # roofline number is steady by construction — on the jnp
        # fallback path this test runs on, not just the kernel path
        assert rung["decode_window_steady"] is True


def test_capacity_section_keys_pinned_by_schema(capacity):
    """The emitted section IS the schema's capacity/capacity_rung
    contract — renaming either side alone fails (same enforcement as
    openloop_rate / fleet_policy)."""
    schema = load_schema()
    assert set(capacity) == set(schema["capacity"])
    for rung in capacity["rungs"]:
        assert set(rung) == set(schema["capacity_rung"])
