"""Fleet router: prefix-affinity placement over N engine replicas.

The subsystem that turns one fast engine into a fleet (docs/router.md):

- :mod:`.table` — replica table, byte-block affinity sketches, the
  placement score (affinity + load + health);
- :mod:`.server` — the asyncio HTTP front: forwarding, connect-only
  retry, heartbeats, drain observation, dynamic membership;
- :mod:`.autoscale` — the SLO-driven autoscale controller + surge
  admission (docs/autoscaling.md) that closes the control loop over
  the :mod:`.fleet` snapshot;
- :mod:`.metrics` — the ``router_*`` metric surface (doc-enforced);
- ``python -m generativeaiexamples_tpu.router`` — serve the router, or
  ``drain`` a replica for a rollout (the k8s preStop hook).
"""

from .autoscale import (AutoscaleController, AutoscalePolicy,  # noqa: F401
                        SurgeGate)
from .metrics import ROUTER_METRICS  # noqa: F401
from .server import FleetRouter, create_router_app  # noqa: F401
from .table import ReplicaTable, affinity_blocks  # noqa: F401
