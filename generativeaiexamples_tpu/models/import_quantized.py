"""Pre-quantized checkpoint importers: GPTQ and AWQ.

The reference loads both formats into per-rank TRT engines
(reference: conversion_scripts/llama/weight.py:979 ``load_from_gptq_llama``
— int32-packed ``qweight``/``qzeros``/fp16 ``scales`` triples;
weight.py:1194 ``load_from_awq_llama`` — AMMO-style fp16 weights with
per-group ``weight_quantizer._amax`` and activation
``input_quantizer._pre_quant_scale``). Here they land in the group-wise
int4 leaf format of ops/quant.py:

  GPTQ: w[k,n] = (u[k,n] - 1 - uz[g,n]) * s[g,n]
        -> {"q4": u-8 packed, "gscale": s, "gbias": (7 - uz) * s}
  AWQ:  y = (x * pre_s) @ W,  W quantized per-group with scale amax/8
        -> {"q4", "gscale", "pre_scale"}

GPTQ checkpoints with a non-trivial ``g_idx`` (act-order reordering) are
rejected loudly — honoring them needs a per-column group gather the
runtime doesn't implement.
"""

from __future__ import annotations

import os
import re
from typing import Any, Iterator

import jax.numpy as jnp
import numpy as np

from ..utils.errors import ModelLoadError
from .configs import LlamaConfig

Params = dict[str, Any]

# HF projection name -> (our stacked key, weight axes note)
_PROJ_KEYS = {
    "self_attn.q_proj": "wq",
    "self_attn.k_proj": "wk",
    "self_attn.v_proj": "wv",
    "self_attn.o_proj": "wo",
    "mlp.gate_proj": "w_gate",
    "mlp.up_proj": "w_up",
    "mlp.down_proj": "w_down",
}
_LAYER_RE = re.compile(r"layers\.(\d+)\.(.+)$")


def _iter_tensors(path: str) -> Iterator[tuple[str, np.ndarray]]:
    """Yield (name, array) from a .safetensors / .pt file or a dir of
    them (same formats the reference accepts, weight.py:986-996)."""
    files = []
    if os.path.isdir(path):
        for n in sorted(os.listdir(path)):
            if n.endswith((".safetensors", ".pt", ".bin")):
                files.append(os.path.join(path, n))
    else:
        files = [path]
    if not files:
        raise ModelLoadError(f"no checkpoint tensors under {path}")
    for f in files:
        if f.endswith(".safetensors"):
            from safetensors.numpy import safe_open
            with safe_open(f, framework="numpy") as sf:
                for key in sf.keys():
                    yield key, sf.get_tensor(key)
        else:
            import torch
            state = torch.load(f, map_location="cpu", weights_only=True)
            for key, t in state.items():
                yield key, t.to(torch.float32).numpy() \
                    if t.dtype in (torch.float16, torch.bfloat16) \
                    else t.numpy()


def sniff_quantized_format(path: str) -> str:
    """'gptq' | 'awq' | '' by tensor NAMES only — no tensor reads.

    safetensors names come from the file header; torch .pt/.bin archives
    are zipfiles whose embedded pickle carries the state-dict keys as raw
    strings, so a substring scan of ``data.pkl`` identifies the format
    without deserializing multi-GB weights (or choking on
    non-state-dict binaries like training_args.bin)."""
    files = []
    if os.path.isdir(path):
        for n in sorted(os.listdir(path)):
            if n.endswith((".safetensors", ".pt", ".bin")):
                files.append(os.path.join(path, n))
    elif os.path.isfile(path):
        files = [path]
    for f in files:
        try:
            if f.endswith(".safetensors"):
                from safetensors.numpy import safe_open
                with safe_open(f, framework="numpy") as sf:
                    for key in sf.keys():
                        if key.endswith(".qweight"):
                            return "gptq"
                        if key.endswith("weight_quantizer._amax"):
                            return "awq"
            else:
                import zipfile
                with zipfile.ZipFile(f) as z:
                    pkl = next((n for n in z.namelist()
                                if n.endswith("data.pkl")), None)
                    if pkl is None:
                        continue
                    blob = z.read(pkl)
                    if b".qweight" in blob:
                        return "gptq"
                    if b"weight_quantizer._amax" in blob:
                        return "awq"
        except Exception:  # noqa: BLE001 — unreadable: not ours to claim
            continue
    return ""


def _unpack_nibbles(packed: np.ndarray, axis: int) -> np.ndarray:
    """int32-packed uint4 -> uint8 (0..15), expanding ``axis`` by 8
    (little-endian nibble order: value j at bits 4j — the same order the
    reference's unpack_int32_into_int8 produces, weight.py:999-1006)."""
    p = packed.astype(np.uint32)
    parts = [((p >> (4 * j)) & 0xF).astype(np.uint8) for j in range(8)]
    return np.stack(parts, axis=axis + 1).reshape(
        *p.shape[:axis], p.shape[axis] * 8, *p.shape[axis + 1:])


def _pack_q4(q: np.ndarray) -> np.ndarray:
    """Signed int4 (K, N) -> packed int8 (K/2, N), low nibble = even k
    (ops/quant.py layout)."""
    return ((q[0::2, :] & 0x0F) | (q[1::2, :] << 4)).astype(np.int8)


def _gptq_leaf(qweight: np.ndarray, qzeros: np.ndarray,
               scales: np.ndarray) -> dict[str, np.ndarray]:
    u = _unpack_nibbles(qweight, axis=0)            # (K, N) uint8
    q = u.astype(np.int8) - 8                       # signed int4
    uz = _unpack_nibbles(qzeros, axis=1)            # (G, N) uint8
    s = scales.astype(np.float32)
    gbias = (7.0 - uz.astype(np.float32)) * s       # w = q*s + (7-uz)*s
    return {"q4": _pack_q4(q), "gscale": s, "gbias": gbias}


def _awq_leaf(weight: np.ndarray, amax: np.ndarray,
              pre_scale: np.ndarray) -> dict[str, np.ndarray]:
    # AMMO stores weight (N_out, K); we use (K, N).
    wT = weight.astype(np.float32).T                # (K, N)
    K, N = wT.shape
    G = amax.size // N
    s = (amax.astype(np.float32).reshape(N, G).T / 8.0)  # (G, N)
    s = np.maximum(s, 1e-12)
    q = np.clip(np.round(wT / np.repeat(s, K // G, axis=0)),
                -8, 7).astype(np.int8)
    return {"q4": _pack_q4(q), "gscale": s,
            "pre_scale": pre_scale.astype(np.float32).reshape(-1)}


def _stack_leaves(per_layer: list[dict[str, np.ndarray]],
                  dtype=jnp.float32) -> dict[str, jnp.ndarray]:
    keys = per_layer[0].keys()
    return {k: jnp.asarray(np.stack([d[k] for d in per_layer], axis=0))
            for k in keys}


def load_quantized_checkpoint(path: str, cfg: LlamaConfig,
                              dtype: jnp.dtype = jnp.bfloat16,
                              fmt: str = "") -> Params:
    """Load a GPTQ or AWQ checkpoint into a stacked llama param tree with
    group-wise int4 leaves. Plain tensors (embeddings, norms, lm_head)
    load at ``dtype``. ``fmt`` skips re-sniffing when the caller already
    detected it."""
    fmt = fmt or sniff_quantized_format(path)
    if not fmt:
        raise ModelLoadError(f"{path}: neither GPTQ (.qweight) nor AWQ "
                             "(weight_quantizer._amax) tensors found")
    L = cfg.num_layers
    raw: dict[str, np.ndarray] = {}
    for key, arr in _iter_tensors(path):
        raw[key.removeprefix("model.")] = arr

    if any(k.endswith(".g_idx") for k in raw):
        for k in (k for k in raw if k.endswith(".g_idx")):
            g = raw[k]
            group = g.size // (g.max() + 1) if g.size else 1
            if not np.array_equal(g, np.arange(g.size) // max(group, 1)):
                raise ModelLoadError(
                    "GPTQ checkpoint uses act-order (non-trivial g_idx); "
                    "reorder it with sequential groups before importing")

    layer_acc: dict[str, list] = {name: [None] * L
                                  for name in _PROJ_KEYS.values()}
    norms: dict[str, list] = {"attn_norm": [None] * L,
                              "mlp_norm": [None] * L}
    top: dict[str, np.ndarray] = {}

    for key, arr in raw.items():
        if key == "embed_tokens.weight":
            top["embed"] = arr
        elif key == "norm.weight":
            top["final_norm"] = arr
        elif key == "lm_head.weight":
            top["lm_head"] = arr.T
        m = _LAYER_RE.match(key)
        if not m:
            continue
        idx, rest = int(m.group(1)), m.group(2)
        if rest == "input_layernorm.weight":
            norms["attn_norm"][idx] = arr
            continue
        if rest == "post_attention_layernorm.weight":
            norms["mlp_norm"][idx] = arr
            continue
        for proj, ours in _PROJ_KEYS.items():
            if not rest.startswith(proj + "."):
                continue
            if fmt == "gptq" and rest == f"{proj}.qweight":
                layer_acc[ours][idx] = _gptq_leaf(
                    arr, raw[f"layers.{idx}.{proj}.qzeros"],
                    raw[f"layers.{idx}.{proj}.scales"])
            elif fmt == "awq" and rest == f"{proj}.weight":
                layer_acc[ours][idx] = _awq_leaf(
                    arr, raw[f"layers.{idx}.{proj}."
                             "weight_quantizer._amax"],
                    raw[f"layers.{idx}.{proj}."
                        "input_quantizer._pre_quant_scale"])
            break

    missing = [f"{k}[{i}]" for k, v in {**layer_acc, **norms}.items()
               for i, x in enumerate(v) if x is None]
    if missing or "embed" not in top or "final_norm" not in top:
        raise ModelLoadError(
            f"incomplete quantized checkpoint ({sorted(missing)[:5]}...)")

    layers: dict[str, Any] = {
        name: _stack_leaves(acc) for name, acc in layer_acc.items()}
    layers["attn_norm"] = jnp.asarray(np.stack(norms["attn_norm"]), dtype)
    layers["mlp_norm"] = jnp.asarray(np.stack(norms["mlp_norm"]), dtype)

    params: Params = {
        "embed": jnp.asarray(top["embed"], dtype),
        "layers": layers,
        "final_norm": jnp.asarray(top["final_norm"], dtype),
    }
    if "lm_head" in top:
        params["lm_head"] = jnp.asarray(top["lm_head"], dtype)
    elif not cfg.tie_word_embeddings:
        raise ModelLoadError("quantized checkpoint has no lm_head and "
                             "config does not tie embeddings")
    return params
