"""Config-system tests (schema parity: reference common/configuration.py)."""

import io
import json

import pytest

from generativeaiexamples_tpu.utils.app_config import AppConfig, get_config
from generativeaiexamples_tpu.utils.configuration import (
    asdict, from_dict, from_file, print_help, update_dict)
from generativeaiexamples_tpu.utils.errors import ConfigError


def test_defaults():
    cfg = from_dict(AppConfig, {})
    assert cfg.text_splitter.chunk_size == 510
    assert cfg.text_splitter.chunk_overlap == 200
    assert cfg.embeddings.dimensions == 1024
    assert cfg.embeddings.model_name == "intfloat/e5-large-v2"
    assert cfg.retriever.top_k == 4
    assert cfg.retriever.max_context_tokens == 1500
    assert cfg.engine.max_input_length == 3000
    assert cfg.engine.max_output_length == 512
    assert cfg.engine.max_batch_size == 128
    assert cfg.vector_store.nlist == 64 and cfg.vector_store.nprobe == 16
    assert "[INST]" in cfg.prompts.rag_template


def test_file_overlay(tmp_path):
    p = tmp_path / "config.json"
    p.write_text(json.dumps({
        "vector_store": {"name": "ivf", "nlist": 128},
        "llm": {"model_name": "llama-2-13b-chat"},
    }))
    cfg = from_file(AppConfig, str(p))
    assert cfg.vector_store.name == "ivf"
    assert cfg.vector_store.nlist == 128
    assert cfg.vector_store.nprobe == 16  # untouched default
    assert cfg.llm.model_name == "llama-2-13b-chat"


def test_yaml_file(tmp_path):
    p = tmp_path / "config.yaml"
    p.write_text("llm:\n  model_engine: echo\nengine:\n  page_size: 64\n")
    cfg = from_file(AppConfig, str(p))
    assert cfg.llm.model_engine == "echo"
    assert cfg.engine.page_size == 64


def test_env_overlay_wins_over_file(tmp_path, monkeypatch):
    p = tmp_path / "config.json"
    p.write_text(json.dumps({"llm": {"model_name": "from-file"}}))
    monkeypatch.setenv("APP_LLM_MODELNAME", "from-env")
    cfg = from_file(AppConfig, str(p))
    assert cfg.llm.model_name == "from-env"


def test_env_coercion(monkeypatch):
    monkeypatch.setenv("APP_ENGINE_MAXBATCHSIZE", "32")
    monkeypatch.setenv("APP_TRACING_ENABLED", "true")
    cfg = from_dict(AppConfig, {})
    assert cfg.engine.max_batch_size == 32
    assert cfg.tracing.enabled is True


def test_missing_file_is_defaults():
    cfg = from_file(AppConfig, "/nonexistent/config.yaml")
    assert cfg.llm.model_engine == "tpu-jax"


def test_asdict_roundtrip():
    cfg = from_dict(AppConfig, {})
    d = asdict(cfg)
    assert d["text_splitter"]["chunk_size"] == 510
    cfg2 = from_dict(AppConfig, d)
    assert cfg2 == cfg


def test_print_help_lists_every_section():
    buf = io.StringIO()
    print_help(AppConfig, stream=buf)
    text = buf.getvalue()
    for section in ("vector_store", "llm", "text_splitter", "embeddings",
                    "prompts", "retriever", "mesh", "engine", "tracing"):
        assert section in text
    assert "APP_LLM_MODELNAME" in text


def test_update_dict_deep_merge():
    base = {"a": {"b": 1, "c": 2}, "d": 3}
    out = update_dict(base, {"a": {"b": 9}, "e": 4})
    assert out == {"a": {"b": 9, "c": 2}, "d": 3, "e": 4}
    assert base["a"]["b"] == 1  # no mutation


def test_get_config_singleton(tmp_path, monkeypatch):
    p = tmp_path / "c.yaml"
    p.write_text("llm:\n  model_name: singleton-test\n")
    monkeypatch.setenv("APP_CONFIG_FILE", str(p))
    cfg = get_config(reload=True)
    assert cfg.llm.model_name == "singleton-test"
    assert get_config() is cfg


def test_bad_coercion_raises():
    with pytest.raises((ConfigError, ValueError)):
        from_dict(AppConfig, {"engine": {"max_batch_size": "not-a-number"}})
