"""Disaggregated prefill/decode chip pools (docs/disaggregation.md).

Role model and placement (tier-1, CPU): a prefill-role engine refuses
decode-bound work with ``RoleMismatchError`` (retryable 429 at the
edge, never a breaker trip), the router's placement filter keeps short
decode-bound requests off prefill-role replicas, a role-less fleet
places byte-for-byte as before the subsystem existed, and the
``handoff_beats_prefill`` / ``StepCostModel.handoff_cheaper`` pricing
rules answer the documented way at every unmeasured edge.

The acceptance pin: two in-process replicas (1 prefill + 1 decode)
behind the real router over real HTTP — a long ``/generate`` prompt is
served through the two-leg handoff (prefill leg, KV-page push, decode
admission as a near-full prefix hit) and the answer is TOKEN-IDENTICAL
to the same request served by a unified replica."""

import asyncio

import pytest

import jax
import jax.numpy as jnp

import aiohttp  # noqa: F401 — skip cleanly where aiohttp is absent
from aiohttp.test_utils import TestClient, TestServer

from generativeaiexamples_tpu.chains.examples.developer_rag import QAChatbot
from generativeaiexamples_tpu.chains.llm import EngineLLM
from generativeaiexamples_tpu.chains.server import create_app
from generativeaiexamples_tpu.embed.encoder import HashEmbedder
from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                             SamplingParams)
from generativeaiexamples_tpu.engine.scheduler import StepCostModel
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.obs import metrics as obs_metrics
from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer
from generativeaiexamples_tpu.router.table import (ReplicaTable,
                                                   handoff_beats_prefill)
from generativeaiexamples_tpu.utils import faults, resilience
from generativeaiexamples_tpu.utils.app_config import AppConfig
from generativeaiexamples_tpu.utils.configuration import from_dict
from generativeaiexamples_tpu.utils.errors import (ConfigError,
                                                   RoleMismatchError)

PAGE = 16

CFG = LlamaConfig(vocab_size=259 + 5, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                  max_position_embeddings=1024)

APP_CFG = from_dict(AppConfig, {
    "llm": {"model_engine": "tpu-jax"},
    "embeddings": {"model_engine": "hash", "dimensions": 32},
})


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.key(29), dtype=jnp.float32)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    # Roles and tiering are under TEST control, not ambient env.
    for var in ("ENGINE_ROLE", "KV_HOST_POOL_TOKENS",
                "ROLE_PREFILL_MAX_TOKENS", "KV_EXPORT_CONCURRENCY"):
        monkeypatch.delenv(var, raising=False)
    faults.clear()
    resilience.reset_breakers()
    yield
    faults.clear()
    resilience.reset_breakers()


def build_engine(params, role="unified", host_tokens=16384):
    """A tiny tier-enabled engine, chunked prefill, shared by the
    role/handoff tests here and the disagg chaos tests in
    tests/test_chaos.py."""
    cfg = EngineConfig(
        max_slots=2, max_input_length=1024, max_output_length=32,
        prefill_buckets=(64,), max_prefill_bucket=64, page_size=PAGE,
        dtype="float32", kv_pool_tokens=4096, max_queue=32,
        steps_per_round=4, kv_host_pool_tokens=host_tokens, role=role)
    return Engine(params, CFG, ByteTokenizer(), cfg)


def replica_app(eng):
    return create_app(QAChatbot(llm=EngineLLM(eng),
                                embedder=HashEmbedder(dim=32),
                                config=APP_CFG, fused_rag=False),
                      config=APP_CFG)


def _words(tag: str, n_chars: int) -> str:
    """Deterministic filler prose (seeded by tag, same scheme as the
    bench's prompt generator)."""
    import hashlib

    import numpy as np
    h = int.from_bytes(hashlib.blake2b(
        tag.encode(), digest_size=4).digest(), "little")
    rng = np.random.RandomState(h)
    toks = []
    total = 0
    while total < n_chars:
        w = "".join(chr(97 + c) for c in rng.randint(0, 26, size=5))
        toks.append(w)
        total += 6
    return " ".join(toks)[:n_chars]


def long_body(tag: str, n_chars: int = 550, num_tokens: int = 12) -> dict:
    return {"question": "What does the passage describe? " + tag,
            "context": _words(tag, n_chars),
            "use_knowledge_base": False, "num_tokens": num_tokens}


def _snap(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot().get(name, 0.0)


def _run(coro):
    loop = asyncio.get_event_loop_policy().new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


# ------------------------------------------------------------ role model

def test_engine_config_rejects_unknown_role():
    with pytest.raises(ConfigError, match="role"):
        EngineConfig(role="prefix")


def test_engine_role_env_beats_config(params, monkeypatch):
    monkeypatch.setenv("ENGINE_ROLE", "prefill")
    eng = build_engine(params, role="unified")
    assert eng.role == "prefill"
    monkeypatch.setenv("ENGINE_ROLE", "bogus")
    with pytest.raises(ConfigError, match="ENGINE_ROLE"):
        build_engine(params)


def test_prefill_role_engine_rejects_decode_bound_work(params,
                                                       monkeypatch):
    """A prefill-role engine admits prefill-shaped requests (tiny
    max_tokens) and refuses decode-bound ones at submit() — before any
    queue or slot state changes — with the typed routing error."""
    monkeypatch.setenv("ROLE_PREFILL_MAX_TOKENS", "2")
    eng = build_engine(params, role="prefill")
    prompt = [7] * (2 * PAGE)
    with eng:
        ok = eng.submit(prompt, SamplingParams(max_tokens=1, top_k=1,
                                               ignore_eos=True))
        ok.text()
        assert ok.finish_reason == "length"
        with pytest.raises(RoleMismatchError, match="prefill-role"):
            eng.submit(prompt, SamplingParams(max_tokens=8, top_k=1))
    # unified engines never hit the cap, whatever the env says
    uni = build_engine(params, role="unified")
    with uni:
        stream = uni.submit(prompt, SamplingParams(max_tokens=4, top_k=1,
                                                   ignore_eos=True))
        stream.text()
        assert stream.finish_reason == "length"


# ----------------------------------------------------- role-aware table

def _table_with_roles():
    table = ReplicaTable()
    table.add("p0", "http://p0")
    table.add("d0", "http://d0")
    table.add("d1", "http://d1")
    table.update_health("p0", ok=True, ready=True,
                        body={"role": "prefill"})
    table.update_health("d0", ok=True, ready=True,
                        body={"role": "decode"})
    table.update_health("d1", ok=True, ready=True,
                        body={"role": "decode"})
    return table


def test_prefill_replicas_never_take_normal_placements():
    """Satellite: short decode-bound requests NEVER land on a
    prefill-role replica, even with the decode pool loaded and the
    prefill replica idle."""
    table = _table_with_roles()
    for rep in ("d0", "d1"):
        table.update_health(rep, ok=True, ready=True, body={
            "role": "decode",
            "load": {"in_flight": 5, "queue_depth": 9}})
    for _ in range(16):
        rep, decision = table.place_explained(())
        assert rep is not None and rep.name != "p0"
        assert all(c["replica"] != "p0"
                   for c in decision["candidates"])
    # ... and the retry loop cannot reach it either
    rep = table.place((), exclude=("d0", "d1"))
    assert rep is None


def test_prefill_candidate_selection_and_rotation():
    table = _table_with_roles()
    assert table.prefill_candidate().name == "p0"
    table.add("p1", "http://p1")
    table.update_health("p1", ok=True, ready=True,
                        body={"role": "prefill"})
    picks = {table.prefill_candidate().name for _ in range(4)}
    assert picks == {"p0", "p1"}          # equal-load rotation
    table.update_health("p0", ok=True, ready=True, body={
        "role": "prefill", "load": {"queue_depth": 7, "in_flight": 2}})
    assert table.prefill_candidate().name == "p1"  # least-loaded wins
    table.mark_unreachable("p1")
    table.mark_draining("p0")
    assert table.prefill_candidate() is None
    # heartbeats that stop carrying a role demote to unified; bogus
    # roles are rejected at the parse, not trusted into placement
    table.update_health("d0", ok=True, ready=True, body={})
    table.update_health("d1", ok=True, ready=True, body={"role": "wat"})
    snap = {r["name"]: r["role"] for r in table.snapshot()}
    assert snap["d0"] == "unified" and snap["d1"] == "unified"


def test_scale_down_candidate_protects_roles():
    table = _table_with_roles()
    # p0 is the least-loaded replica — the naive victim
    table.update_health("d0", ok=True, ready=True, body={
        "role": "decode", "load": {"in_flight": 1, "queue_depth": 0}})
    table.update_health("d1", ok=True, ready=True, body={
        "role": "decode", "load": {"in_flight": 3, "queue_depth": 2}})
    assert table.scale_down_candidate() == "p0"
    assert table.scale_down_candidate(
        exclude_roles=("prefill",)) == "d0"
    assert table.scale_down_candidate(
        exclude=("d0",), exclude_roles=("prefill",)) == "d1"
    assert table.scale_down_candidate(
        exclude=("d0", "d1"), exclude_roles=("prefill",)) is None


def test_roleless_fleet_places_byte_for_byte():
    """Satellite: a fleet that never advertises a role must place
    exactly like one advertising ``unified`` everywhere — same chosen
    replicas, same decision evidence, request for request."""
    bare = ReplicaTable()
    tagged = ReplicaTable()
    for t in (bare, tagged):
        t.add("r0", "http://r0")
        t.add("r1", "http://r1")
    tagged.update_health("r0", ok=True, ready=True,
                         body={"role": "unified"})
    tagged.update_health("r1", ok=True, ready=True,
                         body={"role": "unified"})
    blocks = bare.affinity_blocks("x" * 400)
    bare.record_placement(bare._replicas["r1"], blocks)
    tagged.record_placement(tagged._replicas["r1"], blocks)
    for probe in (blocks, (), blocks[:1]):
        (rep_a, dec_a) = bare.place_explained(probe)
        (rep_b, dec_b) = tagged.place_explained(probe)
        assert rep_a.name == rep_b.name
        assert dec_a == dec_b
    assert bare.prefill_candidate() is None
    assert tagged.prefill_candidate() is None


# ----------------------------------------------------------- pricing

def test_handoff_beats_prefill_pricing():
    # unmeasured transfer legs: the handoff is assumed to win (the
    # first one IS the measurement), including the no-capacity case
    assert handoff_beats_prefill(None, 8192)
    assert handoff_beats_prefill({}, 8192)
    # measured transfer but unmeasured prefill: nothing to beat
    assert not handoff_beats_prefill(
        {"d2h_ms_per_page": 0.5, "h2d_ms_per_page": 0.5}, 8192)
    cap = {"page_size": 128, "d2h_ms_per_page": 0.5,
           "h2d_ms_per_page": 0.5, "prefill_ms_per_token": 1.0}
    # 8192 B ≈ 2048 tok = 16 pages: 16 ms transfer vs 2048 ms recompute
    assert handoff_beats_prefill(cap, 8192)
    # same prompt against a fast-prefill replica: recompute wins
    assert not handoff_beats_prefill(
        dict(cap, prefill_ms_per_token=0.001), 8192)


def test_step_cost_handoff_cheaper():
    model = StepCostModel(prefill_ms_per_token=0.125,
                          h2d_ms_per_page=0.0, d2h_ms_per_page=0.0)
    assert not model.handoff_cheaper(0, PAGE)       # nothing to ship
    assert model.handoff_cheaper(4, PAGE)           # unmeasured: True
    model = StepCostModel(prefill_ms_per_token=1.0,
                          h2d_ms_per_page=0.5, d2h_ms_per_page=0.5)
    assert model.handoff_cheaper(4, PAGE)           # 4 ms < 64 ms
    model = StepCostModel(prefill_ms_per_token=0.01,
                          h2d_ms_per_page=0.5, d2h_ms_per_page=0.5)
    assert not model.handoff_cheaper(4, PAGE)       # 4 ms > 0.64 ms


# ------------------------------------------------- donor export bound

def test_kv_export_concurrency_bound_sheds_429(params, monkeypatch):
    """Satellite: past KV_EXPORT_CONCURRENCY simultaneous exports the
    donor answers a retryable 429 (kv_export_busy, Retry-After) and
    counts kv_export_shed — it never queues a third device page-gather
    behind live decode rounds."""
    monkeypatch.setenv("KV_EXPORT_CONCURRENCY", "1")
    eng = build_engine(params)
    gate = asyncio.Event()

    def slow_export(hashes):
        import time
        time.sleep(0.4)
        return b"", 0

    async def fn():
        eng.export_blob = slow_export
        app = replica_app(eng)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            hashes = "ab" * 16

            async def first():
                gate.set()
                return await client.get(
                    f"/control/kv_pages?hashes={hashes}")

            t1 = asyncio.ensure_future(first())
            await gate.wait()
            await asyncio.sleep(0.05)   # let t1 occupy the export slot
            resp = await client.get(f"/control/kv_pages?hashes={hashes}")
            assert resp.status == 429
            body = await resp.json()
            assert body["error"]["type"] == "kv_export_busy"
            assert "Retry-After" in resp.headers
            assert (await t1).status == 200
            # the slot freed: the retry the 429 asked for now succeeds
            resp = await client.get(f"/control/kv_pages?hashes={hashes}")
            assert resp.status == 200
        finally:
            await client.close()

    with eng:
        _run(fn())
    assert eng.stats["kv_export_shed"] == 1


# ------------------------------------- the acceptance pin: full handoff

def test_disagg_handoff_token_identical_over_real_http(params,
                                                       monkeypatch):
    """1 prefill + 1 decode replica behind the real router: a long
    prompt is served through the two-leg handoff — prefill leg on p0,
    KV pages pushed over a real HTTP ``/control/kv_resume`` leg,
    decode admission on d0 as a near-full prefix hit — and the bytes
    out are IDENTICAL to the same request on a unified replica. Short
    requests never touch the prefill replica."""
    from generativeaiexamples_tpu.router.server import create_router_app

    monkeypatch.setenv("ROUTER_DISAGG_MIN_PROMPT_BYTES", "400")
    prefill_eng = build_engine(params, role="prefill")
    decode_eng = build_engine(params, role="decode")
    unified_eng = build_engine(params, role="unified")
    body = long_body("parity")

    async def fn():
        ref_server = TestServer(replica_app(unified_eng))
        p_server = TestServer(replica_app(prefill_eng))
        d_server = TestServer(replica_app(decode_eng))
        for s in (ref_server, p_server, d_server):
            await s.start_server()
        router_app = create_router_app(
            [("p0", f"http://127.0.0.1:{p_server.port}"),
             ("d0", f"http://127.0.0.1:{d_server.port}")],
            policy="affinity", heartbeat_s=30, kv_transfer=True,
            run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        ref_client = TestClient(ref_server)
        try:
            # the UNIFIED reference answer, same body, no router
            resp = await ref_client.post("/generate", json=body)
            assert resp.status == 200
            reference = (await resp.read()).decode()
            assert reference and "[error]" not in reference

            # one heartbeat sweep teaches the router the roles
            await client.post("/control/heartbeat")
            snap = await (await client.get("/router/replicas")).json()
            roles = {r["name"]: r["role"] for r in snap["replicas"]}
            assert roles == {"p0": "prefill", "d0": "decode"}
            fleet = await (await client.get("/debug/fleet")).json()
            assert fleet["fleet"]["roles"] == {"prefill": 1, "decode": 1}

            handoffs0 = _snap("router_disagg_handoffs_total")
            resp = await client.post("/generate", json=body,
                                     headers={"X-Request-ID": "dis-1"})
            assert resp.status == 200
            # the decode replica served it; the prefill replica is
            # reached only through the handoff leg
            assert resp.headers["X-Routed-Replica"] == "d0"
            answer = (await resp.read()).decode()
            assert answer == reference
            assert _snap("router_disagg_handoffs_total") == handoffs0 + 1

            # the handoff was REAL: pages were exported on p0, pushed
            # over HTTP into d0's host tier, and restored at admission
            assert prefill_eng.stats["kv_tier_export_pages"] > 0
            assert prefill_eng.stats["prefills"] >= 1
            assert decode_eng.stats["kv_tier_resumed_blocks"] > 0
            assert decode_eng.stats["kv_tier_restore_pages"] >= 1

            # both legs share one timeline under the caller's rid
            dbg = await (await client.get(
                "/debug/requests?limit=10")).json()
            tl = next(t for t in dbg["completed"]
                      if t["request_id"] == "dis-1")
            names = [e["event"] for e in tl["events"]]
            assert "router_disagg_prefill" in names
            assert "disagg_handoff" in names

            # SHORT decode-bound request: under the byte floor, no
            # handoff, and the prefill replica never sees it
            prefills_before = prefill_eng.stats["prefills"]
            resp = await client.post("/generate", json={
                "question": "short one?", "use_knowledge_base": False,
                "num_tokens": 4})
            assert resp.status == 200
            assert resp.headers["X-Routed-Replica"] == "d0"
            assert "[error]" not in (await resp.read()).decode()
            assert _snap("router_disagg_handoffs_total") == handoffs0 + 1
            assert prefill_eng.stats["prefills"] == prefills_before
        finally:
            await client.close()
            await ref_client.close()
            for s in (p_server, d_server):
                await s.close()

    with prefill_eng, decode_eng, unified_eng:
        _run(fn())


def test_roleless_fleet_never_enters_disagg_path(params, monkeypatch):
    """The enable gate is the fleet: with no prefill-role replica the
    same long prompt takes the plain placement path — no handoff, no
    fallback, no prefill-leg stage on the timeline."""
    from generativeaiexamples_tpu.router.server import create_router_app

    monkeypatch.setenv("ROUTER_DISAGG_MIN_PROMPT_BYTES", "400")
    eng = build_engine(params)
    body = long_body("roleless")

    async def fn():
        server = TestServer(replica_app(eng))
        await server.start_server()
        router_app = create_router_app(
            [("r0", f"http://127.0.0.1:{server.port}")],
            policy="affinity", heartbeat_s=30, run_heartbeat=False)
        client = TestClient(TestServer(router_app))
        await client.start_server()
        try:
            await client.post("/control/heartbeat")
            h0 = _snap("router_disagg_handoffs_total")
            f0 = sum(_snap(
                f'router_disagg_fallbacks_total{{reason="{r}"}}')
                for r in ("prefill_error", "prefill_timeout",
                          "no_pages"))
            resp = await client.post("/generate", json=body,
                                     headers={"X-Request-ID": "nr-1"})
            assert resp.status == 200
            assert "[error]" not in (await resp.read()).decode()
            assert _snap("router_disagg_handoffs_total") == h0
            assert sum(_snap(
                f'router_disagg_fallbacks_total{{reason="{r}"}}')
                for r in ("prefill_error", "prefill_timeout",
                          "no_pages")) == f0
            dbg = await (await client.get(
                "/debug/requests?limit=10")).json()
            tl = next(t for t in dbg["completed"]
                      if t["request_id"] == "nr-1")
            assert "router_disagg_prefill" \
                not in [e["event"] for e in tl["events"]]
        finally:
            await client.close()
            await server.close()

    with eng:
        _run(fn())
