"""Validate a bench.py result (or a committed BENCH_rNN.json round
artifact) against the checked-in key schema.

The bench JSON line IS the perf trajectory: the driver diffs one round's
fields against the last, so a silent rename (``loop_hround`` →
``engine_harvest_wait``, ``e2e_chat_p99_ttft_ms`` → anything) breaks the
comparison without breaking the bench. Two enforcement points share this
module:

- ``bench.py`` validates its own result before printing — a drifting
  field aborts the bench run on the chip with a precise message;
- ``tests/test_bench_schema.py`` validates a fully-populated synthetic
  result assembled by ``bench.assemble_result`` in the tier-1 suite —
  renames fail fast on CPU, before any chip time is spent.

CLI: ``python tools/check_bench_schema.py BENCH_r06.json [...]``
(accepts either the raw result object or the driver's artifact wrapper
with a ``parsed`` sub-object).
"""

from __future__ import annotations

import json
import os
import sys

SCHEMA_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "bench_schema.json")

_TYPES = {
    "str": lambda v: isinstance(v, str),
    # bool is an int subclass: exclude it from the numeric kinds so a
    # True never masquerades as a measurement
    "num": lambda v: isinstance(v, (int, float))
    and not isinstance(v, bool),
    "int": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "bool": lambda v: isinstance(v, bool),
    "obj": lambda v: isinstance(v, dict),
    "list": lambda v: isinstance(v, list),
    "null": lambda v: v is None,
}


class BenchSchemaError(ValueError):
    """A bench result does not match the checked-in key schema."""


def load_schema(path: str = SCHEMA_PATH) -> dict:
    with open(path) as f:
        return json.load(f)


def _check_types(section: str, obj: dict, spec: dict,
                 errors: list) -> None:
    for key, kinds in spec.items():
        if key not in obj:
            errors.append(f"{section}: missing required key {key!r}")
            continue
        value = obj[key]
        if not any(_TYPES[kind](value) for kind in kinds):
            errors.append(
                f"{section}.{key}: value {value!r} is not any of "
                f"{'/'.join(kinds)}")
    unknown = sorted(set(obj) - set(spec))
    if unknown:
        errors.append(
            f"{section}: unknown key(s) {unknown} — new fields must be "
            f"added to tools/bench_schema.json (renames break the "
            f"round-over-round perf comparison)")


def validate_result(result: dict, schema: dict | None = None) -> None:
    """Raise BenchSchemaError listing every mismatch between ``result``
    and the schema; returns silently on a clean result."""
    schema = schema or load_schema()
    errors: list[str] = []
    _check_types("result", result, schema["top_level"], errors)
    for section in ("engine_pipeline", "engine_rounds", "e2e_ttft_dist_ms",
                    "chat", "openloop", "fleet", "capacity", "multichip",
                    "kv_pressure", "autoscale", "disagg", "failover",
                    "obs_overhead"):
        sub = result.get(section)
        if isinstance(sub, dict):
            _check_types(section, sub, schema[section], errors)
    # Speculative-decoding blocks: chat and openloop carry a nested
    # ``spec`` object (null when spec is off) — validated element-wise
    # against the shared ``spec`` section so an acceptance-rate /
    # tokens-per-step rename can't hide behind the obj type.
    for section in ("chat", "openloop"):
        sub = result.get(section)
        if isinstance(sub, dict) and isinstance(sub.get("spec"), dict):
            _check_types(f"{section}.spec", sub["spec"], schema["spec"],
                         errors)
    # Open-loop sweep: each per-rate entry carries the SLO-attainment /
    # goodput headline fields — validated element-wise so a rename in
    # one rate's dict can't hide behind the list type.
    openloop = result.get("openloop")
    if isinstance(openloop, dict):
        rates = openloop.get("rates")
        if isinstance(rates, list):
            for i, entry in enumerate(rates):
                if isinstance(entry, dict):
                    _check_types(f"openloop.rates[{i}]", entry,
                                 schema["openloop_rate"], errors)
                else:
                    errors.append(
                        f"openloop.rates[{i}]: {entry!r} is not an object")
    # Fleet sweep: each per-policy entry carries the cross-replica
    # prefix-hit / SLO headline fields — validated element-wise so a
    # rename in one policy's dict can't hide behind the list type.
    fleet = result.get("fleet")
    if isinstance(fleet, dict):
        policies = fleet.get("policies")
        if isinstance(policies, list):
            for i, entry in enumerate(policies):
                if isinstance(entry, dict):
                    _check_types(f"fleet.policies[{i}]", entry,
                                 schema["fleet_policy"], errors)
                else:
                    errors.append(
                        f"fleet.policies[{i}]: {entry!r} is not an object")
        # Fleet-observability block sourced from the router's
        # /debug/fleet (per-replica SLO attainment + capacity headroom)
        # — element-wise like every other nested headline block.
        obs = fleet.get("fleet_obs")
        if isinstance(obs, dict):
            _check_types("fleet.fleet_obs", obs, schema["fleet_obs"],
                         errors)
            reps = obs.get("replicas")
            if isinstance(reps, list):
                for i, entry in enumerate(reps):
                    if isinstance(entry, dict):
                        _check_types(f"fleet.fleet_obs.replicas[{i}]",
                                     entry, schema["fleet_obs_replica"],
                                     errors)
                    else:
                        errors.append(
                            f"fleet.fleet_obs.replicas[{i}]: {entry!r} "
                            f"is not an object")
    # Capacity sweep: each slot rung carries the TTFT/throughput/HBM-
    # roofline headline fields — validated element-wise so a rename in
    # one rung's dict can't hide behind the list type.
    capacity = result.get("capacity")
    if isinstance(capacity, dict):
        rungs = capacity.get("rungs")
        if isinstance(rungs, list):
            for i, entry in enumerate(rungs):
                if isinstance(entry, dict):
                    _check_types(f"capacity.rungs[{i}]", entry,
                                 schema["capacity_rung"], errors)
                else:
                    errors.append(
                        f"capacity.rungs[{i}]: {entry!r} is not an object")
    # Multi-chip sweep: each mesh rung carries the tok/s + TTFT vs
    # chips headline fields and the topology-matched budget evidence —
    # validated element-wise (incl. each rung's nested ``spec`` block)
    # so a rename in one rung's dict can't hide behind the list type.
    multichip = result.get("multichip")
    if isinstance(multichip, dict):
        rungs = multichip.get("rungs")
        if isinstance(rungs, list):
            for i, entry in enumerate(rungs):
                if isinstance(entry, dict):
                    _check_types(f"multichip.rungs[{i}]", entry,
                                 schema["multichip_rung"], errors)
                    if isinstance(entry.get("spec"), dict):
                        _check_types(f"multichip.rungs[{i}].spec",
                                     entry["spec"], schema["spec"],
                                     errors)
                else:
                    errors.append(
                        f"multichip.rungs[{i}]: {entry!r} is not an "
                        f"object")
    # KV-pressure scenario: each tiering-on/off arm carries the warm-TTFT
    # / restore-hit headline fields — validated element-wise so a rename
    # in one arm's dict can't hide behind the list type.
    kvp = result.get("kv_pressure")
    if isinstance(kvp, dict):
        arms = kvp.get("arms")
        if isinstance(arms, list):
            for i, entry in enumerate(arms):
                if isinstance(entry, dict):
                    _check_types(f"kv_pressure.arms[{i}]", entry,
                                 schema["kv_pressure_arm"], errors)
                else:
                    errors.append(
                        f"kv_pressure.arms[{i}]: {entry!r} is not an "
                        f"object")
    # Autoscale scenario: each policy arm (autoscaled / static) carries
    # the slo_attainment / replica_minutes headline fields — validated
    # element-wise so a rename in one arm's dict can't hide behind the
    # list type.
    autoscale = result.get("autoscale")
    if isinstance(autoscale, dict):
        arms = autoscale.get("policies")
        if isinstance(arms, list):
            for i, entry in enumerate(arms):
                if isinstance(entry, dict):
                    _check_types(f"autoscale.policies[{i}]", entry,
                                 schema["autoscale_policy"], errors)
                else:
                    errors.append(
                        f"autoscale.policies[{i}]: {entry!r} is not an "
                        f"object")
    # Disaggregation scenario: each arm (unified / disagg at equal
    # chips) carries the TTFT + decode-goodput headline fields and the
    # handoff accounting — validated element-wise so a rename in one
    # arm's dict can't hide behind the list type.
    disagg = result.get("disagg")
    if isinstance(disagg, dict):
        arms = disagg.get("arms")
        if isinstance(arms, list):
            for i, entry in enumerate(arms):
                if isinstance(entry, dict):
                    _check_types(f"disagg.arms[{i}]", entry,
                                 schema["disagg_arm"], errors)
                else:
                    errors.append(
                        f"disagg.arms[{i}]: {entry!r} is not an object")
    # Failover scenario: each arm (resume on / resume off around the
    # same scripted mid-stream kill) carries the error-free completion
    # rate and the resume accounting — validated element-wise so a
    # rename in one arm's dict can't hide behind the list type.
    failover = result.get("failover")
    if isinstance(failover, dict):
        arms = failover.get("arms")
        if isinstance(arms, list):
            for i, entry in enumerate(arms):
                if isinstance(entry, dict):
                    _check_types(f"failover.arms[{i}]", entry,
                                 schema["failover_arm"], errors)
                else:
                    errors.append(
                        f"failover.arms[{i}]: {entry!r} is not an object")
    breakdown = result.get("e2e_breakdown_ms")
    if isinstance(breakdown, dict):
        allowed = set(schema["breakdown_stages"])
        unknown = sorted(set(breakdown) - allowed)
        if unknown:
            errors.append(
                f"e2e_breakdown_ms: unknown stage(s) {unknown} — stage "
                f"renames must update breakdown_stages in "
                f"tools/bench_schema.json")
        for key, value in breakdown.items():
            if not _TYPES["num"](value):
                errors.append(
                    f"e2e_breakdown_ms.{key}: {value!r} is not numeric")
    if errors:
        raise BenchSchemaError("; ".join(errors))


def main(argv: list[str]) -> int:
    if not argv:
        print(__doc__)
        return 2
    schema = load_schema()
    rc = 0
    for path in argv:
        with open(path) as f:
            obj = json.load(f)
        result = obj.get("parsed", obj)  # driver artifact wrapper or raw
        try:
            validate_result(result, schema)
            print(f"{path}: ok")
        except BenchSchemaError as exc:
            print(f"{path}: FAIL — {exc}")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
