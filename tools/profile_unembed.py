"""Isolate the lm_head projection cost: which dot formulation streams the
int8 vocab matrix at HBM speed? Run on TPU."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

B, D, V = 8, 4096, 32000
h = jnp.ones((B, D), jnp.bfloat16)
wq = jnp.ones((D, V), jnp.int8)
scale = jnp.ones((V,), jnp.float32)
wb = jnp.ones((D, V), jnp.bfloat16)


def timeit(f, *a, n=30):
    g = jax.jit(f)
    for _ in range(3):
        out = g(*a)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    outs = [g(*a) for _ in range(n)]
    jax.block_until_ready(outs)
    return (time.perf_counter() - t0) / n * 1e3


dims = (((1,), (0,)), ((), ()))

ms = timeit(lambda h, w: jax.lax.dot_general(
    h, w, dims, preferred_element_type=jnp.float32) * scale, h, wq)
print(f"bf16 x s8 (pref f32): {ms:.3f} ms ({wq.nbytes/ms*1e3/1e9:.0f} GB/s)")

ms = timeit(lambda h, w: jax.lax.dot_general(
    h, w.astype(jnp.bfloat16), dims,
    preferred_element_type=jnp.float32) * scale, h, wq)
print(f"s8->bf16 cast dot:    {ms:.3f} ms ({wq.nbytes/ms*1e3/1e9:.0f} GB/s)")

ms = timeit(lambda h, w: jax.lax.dot_general(
    h.astype(jnp.float32), w.astype(jnp.float32), dims) * scale, h, wq)
print(f"f32 cast dot:         {ms:.3f} ms ({wq.nbytes/ms*1e3/1e9:.0f} GB/s)")

ms = timeit(lambda h, w: jax.lax.dot_general(
    h, w, dims, preferred_element_type=jnp.float32), h, wb)
print(f"bf16 x bf16 pref f32: {ms:.3f} ms ({wb.nbytes/ms*1e3/1e9:.0f} GB/s)")

ms = timeit(lambda h, w: (
    jax.lax.dot_general(h, w, dims,
                        preferred_element_type=jnp.bfloat16)
    .astype(jnp.float32) * scale), h, wq)
print(f"bf16 x s8 (pref bf16): {ms:.3f} ms ({wq.nbytes/ms*1e3/1e9:.0f} GB/s)")

# layer-matmul shape for comparison: (8,4096) @ (4096,11008) int8
wl = jnp.ones((4096, 11008), jnp.int8)
ms = timeit(lambda h, w: jax.lax.dot_general(
    h, w, dims, preferred_element_type=jnp.float32), h, wl)
print(f"layer-shape bf16 x s8: {ms:.3f} ms ({wl.nbytes/ms*1e3/1e9:.0f} GB/s)")
