"""Embedding service: jit batch encoder with passage/query modes.

Replaces the reference's embedding stack — HuggingFaceEmbeddings on cuda:0
(reference: common/utils.py:270-297) and the NeMo retriever's
``input_type`` passage/query switch
(reference: integrations/langchain/embeddings/nemo_embed.py:96-102) — with
a single jit-compiled encoder on TPU. Batches are padded to fixed buckets so
XLA compiles once per bucket.

The e5 convention: texts are prefixed "query: " / "passage: " before
encoding, then mean-pooled and L2-normalized.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

import numpy as np

from ..models.configs import ENCODER_REGISTRY, EncoderConfig
from ..models.tokenizer import Tokenizer, get_tokenizer


class EmbeddingService:
    """Batched on-device text embedding."""

    def __init__(self, params, cfg: EncoderConfig, tokenizer: Tokenizer,
                 max_length: int = 512, batch_buckets: Sequence[int] = (1, 8, 32),
                 seq_buckets: Sequence[int] = (128, 512),
                 normalize: bool = True):
        import jax
        import jax.numpy as jnp

        from ..models import encoder as enc

        self.cfg = cfg
        self.tokenizer = tokenizer
        self.max_length = min(max_length, cfg.max_position_embeddings)
        self.batch_buckets = tuple(sorted(batch_buckets))
        # Sequence buckets: a chat query is ~20 tokens — padding it to the
        # passage length (512) made every query pay a full-length encoder
        # pass on the TTFT-critical retrieve.
        self.seq_buckets = tuple(sorted(
            {min(s, self.max_length) for s in seq_buckets}
            | {self.max_length}))
        self.normalize = normalize
        self.params = params

        def encode_fn(params, packed):
            # tokens and mask ride ONE transfer: packed (2, B, S) int32 —
            # each host->device hop on a tunneled device costs real ms.
            tokens, mask = packed[0], packed[1]
            hidden = enc.apply(params, cfg, tokens, mask)
            return enc.mean_pool(hidden, mask, normalize=normalize)

        self._encode = jax.jit(encode_fn)
        self._jnp = jnp

    # The e5 prefix convention (also what the reference's NeMo embedder maps
    # its passage/query input_type onto).
    def embed_documents(self, texts: Sequence[str]) -> np.ndarray:
        return self._embed([f"passage: {t}" for t in texts])

    def embed_query(self, text: str) -> np.ndarray:
        return self._embed([f"query: {text}"])[0]

    def embed_queries(self, texts: Sequence[str]) -> np.ndarray:
        """Batched query-mode embedding (one bucketed dispatch, not one
        device round-trip per text)."""
        return self._embed([f"query: {t}" for t in texts])

    @property
    def dim(self) -> int:
        return self.cfg.hidden_size

    def _bucket(self, n: int) -> int:
        for b in self.batch_buckets:
            if n <= b:
                return b
        return self.batch_buckets[-1]

    def _embed(self, texts: Sequence[str]) -> np.ndarray:
        out = np.zeros((len(texts), self.cfg.hidden_size), np.float32)
        maxb = self.batch_buckets[-1]
        for start in range(0, len(texts), maxb):
            chunk = texts[start:start + maxb]
            out[start:start + len(chunk)] = self._embed_chunk(chunk)
        return out

    def _embed_chunk(self, texts: Sequence[str]) -> np.ndarray:
        import time

        from ..obs.tracing import record_stage
        jnp = self._jnp
        B = self._bucket(len(texts))
        encoded = [self.tokenizer.encode(t)[:self.max_length] for t in texts]
        longest = max((len(ids) for ids in encoded), default=1)
        S = next(s for s in self.seq_buckets if longest <= s)
        packed = np.zeros((2, B, S), np.int32)
        for i, ids in enumerate(encoded):
            packed[0, i, :len(ids)] = ids
            packed[1, i, :len(ids)] = 1
        t0 = time.monotonic()
        emb = self._encode(self.params, jnp.asarray(packed))
        t1 = time.monotonic()
        out = np.asarray(emb)[:len(texts)]
        record_stage("embed_dispatch", t1 - t0)
        record_stage("embed_readback", time.monotonic() - t1)
        return out


class HashEmbedder:
    """Deterministic no-model embedder for tests and air-gapped dev.

    The 'fake engine' the reference made trivial but never shipped
    (SURVEY.md §4: the model_engine enum invites a fake). Embeds by hashing
    character n-grams, so similar texts get similar vectors.
    """

    def __init__(self, dim: int = 64):
        self._dim = dim

    @property
    def dim(self) -> int:
        return self._dim

    def _vec(self, text: str) -> np.ndarray:
        v = np.zeros(self._dim, np.float32)
        t = text.lower()
        for n in (3, 4):
            for i in range(max(0, len(t) - n + 1)):
                gram = t[i:i + n]
                h = int.from_bytes(
                    hashlib.md5(gram.encode()).digest()[:8], "little")
                v[h % self._dim] += 1.0
        norm = np.linalg.norm(v)
        return v / norm if norm > 0 else v

    def embed_documents(self, texts: Sequence[str]) -> np.ndarray:
        return np.stack([self._vec(f"passage: {t}") for t in texts])

    def embed_query(self, text: str) -> np.ndarray:
        return self._vec(f"passage: {text}")

    def embed_queries(self, texts: Sequence[str]) -> np.ndarray:
        return np.stack([self.embed_query(t) for t in texts])


def get_embedder(model_engine: str = "tpu-jax",
                 model_name: str = "intfloat/e5-large-v2",
                 checkpoint_path: Optional[str] = None,
                 dim: int = 64):
    """Factory, parity with ``get_embedding_model``
    (reference: common/utils.py:270-297). Engines: 'tpu-jax' (on-device
    encoder; random weights unless checkpoint_path), 'hash' (test double).
    """
    if model_engine == "hash":
        return HashEmbedder(dim=dim)
    if model_engine == "tpu-jax":
        import os

        import jax

        from ..models import encoder as enc
        from ..utils.errors import ConfigError

        if model_name not in ENCODER_REGISTRY:
            raise ConfigError(
                f"unknown encoder model {model_name!r}; known: "
                f"{sorted(ENCODER_REGISTRY)}")
        cfg = ENCODER_REGISTRY[model_name]
        if checkpoint_path:
            if not os.path.isdir(checkpoint_path):
                raise ConfigError("checkpoint_path must be a directory")
            from ..models.import_hf import _iter_safetensors
            params = enc.params_from_named_tensors(
                _iter_safetensors(checkpoint_path), cfg)
            tok = get_tokenizer(checkpoint_path)
        else:
            params = enc.init_params(cfg, jax.random.key(0))
            tok = get_tokenizer("byte")
        return EmbeddingService(params, cfg, tok)
    raise ValueError(f"unknown embedding engine {model_engine!r}")
