"""Frontend app server: static UI + thin API proxy to the chain server.

Parity with the reference's frontend service (reference:
frontend/frontend/__main__.py parse_args, api.py APIServer.configure_routes
— pages mounted at /content/converse and /content/kb). The browser talks
only to this server; this server talks to the chain server through
``ChatClient`` (same topology as the reference, where Gradio callbacks call
chat_client server-side)."""

from __future__ import annotations

import argparse
import asyncio
import json
import os
from typing import Optional

from aiohttp import web

from ..obs import metrics as obs_metrics
from ..serving.streaming import iterate_in_thread
from ..utils.logging import get_logger
from .chat_client import ChatClient

logger = get_logger(__name__)

_STATIC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "static")


def _speech_from_env():
    """Build ASR/TTS clients when Riva is configured (RIVA_API_URI), else
    (None, None) — the converse page hides its mic/speaker controls."""
    server = os.environ.get("RIVA_API_URI", "")
    if not server:
        return None, None
    try:
        from .speech import ASRClient, TTSClient
        return ASRClient(server), TTSClient(server)
    except Exception as exc:  # noqa: BLE001 — degrade, don't crash the UI
        logger.warning("speech disabled: %s", exc)
        return None, None


def create_app(client: ChatClient, asr=None, tts=None) -> web.Application:
    app = web.Application(client_max_size=100 * 1024 ** 2)
    uploads: list[dict] = []  # kb page file table (reference: kb.py)
    if asr is None and tts is None:
        asr, tts = _speech_from_env()

    async def index(request: web.Request) -> web.Response:
        raise web.HTTPFound("/content/converse")

    async def converse(request: web.Request) -> web.FileResponse:
        return web.FileResponse(os.path.join(_STATIC, "converse.html"))

    async def kb(request: web.Request) -> web.FileResponse:
        return web.FileResponse(os.path.join(_STATIC, "kb.html"))

    async def api_generate(request: web.Request) -> web.StreamResponse:
        body = await request.json()
        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"})
        await resp.prepare(request)

        def chunks():
            # Per-request error capture via callback: the shared
            # ChatClient's last_error attribute can be overwritten by a
            # concurrent request's predict() before we'd read it.
            errs: list = []
            for chunk in client.predict(
                    body.get("question", ""),
                    use_knowledge_base=bool(body.get("use_knowledge_base", True)),
                    num_tokens=int(body.get("num_tokens", 256)),
                    context=body.get("context", ""),
                    on_error=errs.append):
                if chunk is None:
                    # predict() filtered any mid-stream error frames out
                    # of the answer text; hand the parsed failure (if
                    # any) to the async side as a typed item.
                    if errs:
                        yield ("__error__", dict(errs[-1]))
                    return
                yield chunk

        try:
            async for chunk in iterate_in_thread(chunks()):
                if isinstance(chunk, tuple):
                    # Partial answer + failure: forward the failure as a
                    # machine-readable event frame, NOT as answer text.
                    _, err = chunk
                    await resp.write(
                        ("\n\nevent: error\ndata: "
                         + json.dumps(err) + "\n\n").encode())
                    continue
                await resp.write(chunk.encode("utf-8"))
        except (ConnectionResetError, ConnectionError):
            pass
        except Exception as exc:  # noqa: BLE001 — surface to the UI
            logger.exception("proxy generate failed")
            await resp.write(
                ("\n\nevent: error\ndata: "
                 + json.dumps({"message": str(exc)}) + "\n\n").encode())
        await resp.write_eof()
        return resp

    async def api_search(request: web.Request) -> web.Response:
        body = await request.json()
        loop = asyncio.get_running_loop()
        try:
            docs = await loop.run_in_executor(
                None, lambda: client.search(body.get("content", ""),
                                            int(body.get("num_docs", 4))))
        except Exception:  # noqa: BLE001 — context pane is best-effort
            docs = []
        return web.json_response(docs)

    async def api_upload(request: web.Request) -> web.Response:
        reader = await request.multipart()
        field = await reader.next()
        while field is not None and field.name != "file":
            field = await reader.next()
        if field is None:
            raise web.HTTPUnprocessableEntity(text="no 'file' field")
        filename = os.path.basename(field.filename or "upload.bin")
        import shutil
        import tempfile
        # Per-upload temp dir: preserves the basename (ChatClient names the
        # upload after it) with no collision between concurrent uploads of
        # the same filename.
        tmp_dir = tempfile.mkdtemp(prefix="gaie-upload-")
        path = os.path.join(tmp_dir, filename)
        with open(path, "wb") as f:
            while True:
                chunk = await field.read_chunk()
                if not chunk:
                    break
                f.write(chunk)
        entry = {"filename": filename, "status": "uploading"}
        uploads.append(entry)
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(
                None, lambda: client.upload_documents([path]))
            entry["status"] = "ingested"
        except Exception as exc:  # noqa: BLE001
            entry["status"] = f"failed: {exc}"
            raise web.HTTPInternalServerError(text=str(exc)) from exc
        finally:
            shutil.rmtree(tmp_dir, ignore_errors=True)
        obs_metrics.REGISTRY.counter(
            "frontend_uploads_total",
            "documents uploaded through the frontend").inc()
        return web.json_response(entry)

    async def api_kb(request: web.Request) -> web.Response:
        return web.json_response(uploads)

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    # Speech: mic transcription + TTS of answers, the converse-page
    # wiring of the reference (reference: frontend/frontend/pages/
    # converse.py:65 builds mic + audio output into the chat page).
    async def api_speech_config(request: web.Request) -> web.Response:
        return web.json_response({"asr": asr is not None,
                                  "tts": tts is not None})

    async def api_transcribe(request: web.Request) -> web.Response:
        if asr is None:
            raise web.HTTPNotImplemented(text="speech not configured "
                                              "(set RIVA_API_URI)")
        audio = await request.read()   # 16 kHz mono 16-bit PCM WAV
        loop = asyncio.get_running_loop()
        try:
            text = await loop.run_in_executor(
                None, lambda: asr.transcribe(audio))
        except Exception as exc:  # noqa: BLE001 — surface to the UI
            raise web.HTTPBadGateway(text=f"asr failed: {exc}") from exc
        return web.json_response({"text": text})

    async def api_tts(request: web.Request) -> web.Response:
        if tts is None:
            raise web.HTTPNotImplemented(text="speech not configured "
                                              "(set RIVA_API_URI)")
        body = await request.json()
        text = str(body.get("text", ""))[:4000]
        loop = asyncio.get_running_loop()
        try:
            audio = await loop.run_in_executor(
                None, lambda: tts.synthesize(text))
        except Exception as exc:  # noqa: BLE001
            raise web.HTTPBadGateway(text=f"tts failed: {exc}") from exc
        return web.Response(body=audio, content_type="audio/wav")

    app.router.add_get("/", index)
    app.router.add_get("/content/converse", converse)
    app.router.add_get("/content/kb", kb)
    app.router.add_static("/static/", _STATIC)
    app.router.add_post("/api/generate", api_generate)
    app.router.add_post("/api/search", api_search)
    app.router.add_post("/api/upload", api_upload)
    app.router.add_get("/api/kb", api_kb)
    app.router.add_get("/api/speech/config", api_speech_config)
    app.router.add_post("/api/speech/transcribe", api_transcribe)
    app.router.add_post("/api/speech/tts", api_tts)
    app.router.add_get("/health", health)
    return app


def main(argv: Optional[list[str]] = None) -> None:
    """CLI parity with the reference frontend
    (reference: frontend/frontend/__main__.py:28-107)."""
    parser = argparse.ArgumentParser(description="TPU RAG frontend")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8090)
    parser.add_argument("--chain-server-url",
                        default=os.environ.get("APP_SERVERURL",
                                               "http://localhost:8081"))
    args = parser.parse_args(argv)
    client = ChatClient(args.chain_server_url)
    logger.info("frontend on %s:%d -> chain server %s",
                args.host, args.port, args.chain_server_url)
    web.run_app(create_app(client), host=args.host, port=args.port)


if __name__ == "__main__":
    main()
