"""End-to-end serving benchmark (run on real TPU hardware by the driver).

Measures the canonical QA-chatbot serving path through the real engine
(continuous batching, streaming): p50 time-to-first-token and aggregate
decode throughput. Baseline: the north-star <200 ms p50 TTFT for the
llama-2-7b chatbot (BASELINE.json; the reference publishes no numbers of
its own — BASELINE.md).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ms", "vs_baseline": N, ...}
``vs_baseline`` = baseline_ms / measured_ms (>1 ⇒ beating the target).

Env knobs: BENCH_MODEL (default llama-2-7b-chat; falls back to llama-1b on
OOM), BENCH_PROMPT_LEN, BENCH_OUTPUT_LEN, BENCH_REQUESTS, BENCH_SLOTS.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TTFT_BASELINE_MS = 200.0


def build_engine(model_name: str, slots: int, prompt_len: int, out_len: int):
    import jax
    import jax.numpy as jnp

    from generativeaiexamples_tpu.engine import Engine, EngineConfig
    from generativeaiexamples_tpu.models import llama
    from generativeaiexamples_tpu.models.configs import get_model_config
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    cfg = get_model_config(model_name)
    params = jax.jit(
        lambda key: llama.init_params(cfg, key, dtype=jnp.bfloat16)
    )(jax.random.key(0))
    jax.block_until_ready(params)

    bucket = max(64, prompt_len)
    ecfg = EngineConfig(max_slots=slots, max_input_length=bucket,
                        max_output_length=out_len,
                        prefill_buckets=(bucket,), dtype="bfloat16")
    return Engine(params, cfg, ByteTokenizer(), ecfg)


def run_bench(engine, prompt_len: int, out_len: int, n_requests: int,
              slots: int):
    from generativeaiexamples_tpu.engine import SamplingParams

    prompt_ids = list(range(3, 3 + 250)) * (prompt_len // 250 + 1)
    prompt_ids = prompt_ids[:prompt_len]
    sp = SamplingParams(max_tokens=out_len, top_k=1, ignore_eos=True)

    # Warmup: compile prefill/insert/decode.
    engine.start()
    engine.submit(prompt_ids, SamplingParams(max_tokens=4, top_k=1,
                                             ignore_eos=True)).text()

    # TTFT: sequential requests against an idle engine (the reference's
    # single-user chat scenario).
    ttfts = []
    for _ in range(n_requests):
        stream = engine.submit(prompt_ids, SamplingParams(
            max_tokens=2, top_k=1, ignore_eos=True))
        stream.text()
        ttfts.append(stream.ttft_ms)
    ttfts.sort()
    p50 = ttfts[len(ttfts) // 2]
    p99 = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))]

    # Throughput: saturate the decode batch.
    t0 = time.monotonic()
    streams = [engine.submit(prompt_ids, sp) for _ in range(slots)]
    total_tokens = 0
    for s in streams:
        s.text()
        total_tokens += len(s.token_ids)
    dt = time.monotonic() - t0
    tput = total_tokens / dt
    return p50, p99, tput


def main() -> None:
    model = os.environ.get("BENCH_MODEL", "llama-2-7b-chat")
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN", "512"))
    out_len = int(os.environ.get("BENCH_OUTPUT_LEN", "64"))
    n_requests = int(os.environ.get("BENCH_REQUESTS", "8"))
    slots = int(os.environ.get("BENCH_SLOTS", "4"))

    t_start = time.monotonic()
    try:
        engine = build_engine(model, slots, prompt_len, out_len)
    except Exception as exc:  # OOM on small chips: degrade, keep the signal
        sys.stderr.write(f"bench: {model} failed ({type(exc).__name__}: "
                         f"{exc}); falling back to llama-1b\n")
        model = "llama-1b"
        engine = build_engine(model, slots, prompt_len, out_len)

    try:
        p50, p99, tput = run_bench(engine, prompt_len, out_len, n_requests,
                                   slots)
    finally:
        engine.stop()

    import jax
    result = {
        "metric": f"p50_ttft_ms_{model.replace('-', '_')}",
        "value": round(p50, 2),
        "unit": "ms",
        "vs_baseline": round(TTFT_BASELINE_MS / p50, 3),
        "p99_ttft_ms": round(p99, 2),
        "decode_tokens_per_sec": round(tput, 1),
        "prompt_len": prompt_len,
        "output_len": out_len,
        "slots": slots,
        "device": jax.devices()[0].device_kind,
        "n_devices": jax.device_count(),
        "wall_s": round(time.monotonic() - t_start, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
