"""Train a SentencePiece-format BPE tokenizer from in-repo text.

Produces a real ``tokenizer.model`` (serialized ``ModelProto``) that
``models/sentencepiece.py`` loads — llama-2 vocab geometry (32000 pieces,
ids 0/1/2 = unk/bos/eos, byte-fallback pieces) and llama-2-like
compression on English tech prose (~4 chars/token), so benchmarks that
can't ship Meta's tokenizer still measure realistic prompt lengths
instead of byte-level ones (VERDICT r3 weak #4: the ByteTokenizer
inflated the e2e chatbot prompt to ~1k tokens).

The trainer is classic BPE over whitespace-split word types with the
SentencePiece metaspace convention; piece scores encode merge rank
(score = -rank), which is exactly what the encoder's best-score-first
merge loop expects.

Usage: python tools/train_tokenizer.py [out.model]
"""

from __future__ import annotations

import collections
import glob
import os
import struct
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

VOCAB_SIZE = 32000
_METASPACE = "▁"

# piece types (sentencepiece_model.proto)
_NORMAL, _UNKNOWN, _CONTROL, _BYTE = 1, 2, 3, 6


def corpus_text() -> str:
    """English-ish training text from the repo's own docs and sources."""
    parts = []
    patterns = ["*.md", "docs/**/*.md", "generativeaiexamples_tpu/**/*.py",
                "tests/**/*.py", "examples/**/*.py", "tools/**/*.py"]
    for pat in patterns:
        for path in sorted(glob.glob(os.path.join(REPO, pat),
                                     recursive=True)):
            try:
                with open(path, encoding="utf-8") as f:
                    parts.append(f.read())
            except OSError:
                continue
    return "\n".join(parts)


def train_bpe(text: str, n_merges: int) -> list[str]:
    """Learn ``n_merges`` BPE merges over whitespace-split word types.
    Returns merged pieces in rank order."""
    words: collections.Counter[tuple[str, ...]] = collections.Counter()
    for word in text.split():
        words[tuple(_METASPACE + word)] += 1

    # pair -> count, and pair -> set of word ids containing it
    vocab = list(words.items())
    pair_counts: collections.Counter = collections.Counter()
    pair_words: dict[tuple[str, str], set[int]] = collections.defaultdict(set)
    for wi, (sym, freq) in enumerate(vocab):
        for a, b in zip(sym, sym[1:]):
            pair_counts[(a, b)] += freq
            pair_words[(a, b)].add(wi)

    merges: list[str] = []
    seen_pieces: set[str] = set()
    while len(merges) < n_merges and pair_counts:
        (a, b), cnt = max(pair_counts.items(), key=lambda kv:
                          (kv[1], kv[0]))  # deterministic tie-break
        if cnt < 2:
            break
        merged = a + b
        del pair_counts[(a, b)]
        affected = pair_words.pop((a, b), set())
        for wi in affected:
            sym, freq = vocab[wi]
            out = []
            i = 0
            changed = False
            while i < len(sym):
                if i + 1 < len(sym) and sym[i] == a and sym[i + 1] == b:
                    out.append(merged)
                    i += 2
                    changed = True
                else:
                    out.append(sym[i])
                    i += 1
            if not changed:
                continue
            new = tuple(out)
            # decrement old pairs, increment new ones
            for p in zip(sym, sym[1:]):
                pair_counts[p] -= freq
                if pair_counts[p] <= 0:
                    del pair_counts[p]
                pair_words.get(p, set()).discard(wi)
            for p in zip(new, new[1:]):
                pair_counts[p] += freq
                pair_words[p].add(wi)
            vocab[wi] = (new, freq)
        if merged not in seen_pieces:
            seen_pieces.add(merged)
            merges.append(merged)
    return merges


# ------------------------------------------------------- proto writing

def _tag(field: int, wire: int) -> bytes:
    return _varint_bytes((field << 3) | wire)


def _varint_bytes(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _piece_msg(piece: str, score: float, ptype: int) -> bytes:
    body = (_tag(1, 2) + _varint_bytes(len(piece.encode()))
            + piece.encode()
            + _tag(2, 5) + struct.pack("<f", score)
            + _tag(3, 0) + _varint_bytes(ptype))
    return _tag(1, 2) + _varint_bytes(len(body)) + body


def write_model(pieces: list[tuple[str, float, int]], path: str) -> None:
    blob = bytearray()
    for piece, score, ptype in pieces:
        blob += _piece_msg(piece, score, ptype)
    trainer = (_tag(40, 0) + _varint_bytes(0)      # unk_id
               + _tag(41, 0) + _varint_bytes(1)    # bos_id
               + _tag(42, 0) + _varint_bytes(2))   # eos_id
    blob += _tag(2, 2) + _varint_bytes(len(trainer)) + trainer
    with open(path, "wb") as f:
        f.write(bytes(blob))


def main() -> None:
    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        REPO, "generativeaiexamples_tpu", "assets", "tokenizer_32k.model")
    text = corpus_text()
    print(f"corpus: {len(text)/1e6:.1f} MB")

    # vocab layout (llama-2 order): unk, bos, eos, 256 byte pieces,
    # single chars, then merges by rank. Scores: merges get -rank (the
    # encoder merges best-score-first); chars/bytes get a floor score.
    chars = sorted({c for c in _METASPACE + "".join(text.split())
                    if len(c) == 1})
    budget = VOCAB_SIZE - 3 - 256 - len(chars)
    merges = train_bpe(text, budget)
    print(f"learned {len(merges)} merges, {len(chars)} chars")

    pieces: list[tuple[str, float, int]] = [
        ("<unk>", 0.0, _UNKNOWN), ("<s>", 0.0, _CONTROL),
        ("</s>", 0.0, _CONTROL)]
    pieces += [(f"<0x{i:02X}>", -1e6, _BYTE) for i in range(256)]
    floor = -float(len(merges) + 1)
    pieces += [(c, floor, _NORMAL) for c in chars]
    pieces += [(m, -float(r), _NORMAL) for r, m in enumerate(merges, 1)]
    # pad to exactly VOCAB_SIZE so llama-2 configs (vocab 32000) line up
    for i in range(VOCAB_SIZE - len(pieces)):
        pieces.append((f"<extra_{i}>", -1e6, _NORMAL))
    pieces = pieces[:VOCAB_SIZE]

    os.makedirs(os.path.dirname(out), exist_ok=True)
    write_model(pieces, out)
    print(f"wrote {out} ({os.path.getsize(out)/1e3:.0f} kB, "
          f"{len(pieces)} pieces)")

    # sanity: round-trip + compression through the real loader
    from generativeaiexamples_tpu.models.sentencepiece import (
        SentencePieceTokenizer)
    tok = SentencePieceTokenizer(out)
    sample = ("The continuous batching engine admits new requests into "
              "the decode batch between steps without recompiling.")
    ids = tok.encode(sample)
    print(f"sample: {len(sample)} chars -> {len(ids)} tokens "
          f"({len(sample)/len(ids):.2f} chars/tok)")
    assert tok.decode(ids) == sample, tok.decode(ids)
    print("round-trip OK")


if __name__ == "__main__":
    main()
