"""generativeaiexamples_tpu — a TPU-native RAG serving framework.

A brand-new framework with the capabilities of NVIDIA's GenerativeAIExamples
RAG stack (reference: /root/reference, v0.4.0), built from scratch in
idiomatic JAX/XLA/Pallas/pjit:

- ``models/``    JAX model definitions (Llama-2/CodeLlama, BERT-style e5
                 embedder, Mixtral MoE) with HF checkpoint importers.
- ``ops/``       TPU compute primitives: RoPE, RMSNorm, attention (incl. a
                 Pallas paged-attention decode kernel with a jnp fallback),
                 sampling, quantized matmul, on-device top-k retrieval.
- ``parallel/``  Device-mesh construction and sharding rules (dp/tp/pp/ep/sp
                 axes over ICI; ``jax.distributed`` bootstrap for multi-host
                 DCN) — the XLA-collectives answer to the reference's
                 NCCL/mpirun stack
                 (reference: llm-inference-server/model_server/server.py:78-101).
- ``engine/``    The TensorRT-LLM/Triton replacement: continuous-batching
                 scheduler, slotted/paged KV cache, streaming detokenizer,
                 AOT compile cache.
- ``serving/``   OpenAI-style HTTP API + Triton-compatible tensor shim
                 (reference: ensemble_models/llama/ensemble/config.pbtxt:27-117).
- ``embed/``     jax.jit batch encoder for e5-large-v2-class embedding models
                 (reference: common/utils.py:270-297).
- ``retrieval/`` Vector stores: first-party brute/IVF (numpy, on-TPU matmul
                 top-k, native C++), gated Milvus/pgvector connectors
                 (reference: common/utils.py:143-225).
- ``chains/``    The chain server: 3-endpoint HTTP API with pluggable RAG
                 examples (reference: RetrievalAugmentedGeneration/common/server.py).
- ``frontend/``  Web chat + knowledge-base UI (reference: frontend/).
- ``obs/``       OpenTelemetry tracing + first-party TTFT/TPS metrics
                 (reference: common/tracing.py, tools/observability/).
- ``tools/``     Evaluation: synthetic QA, RAGAS-style metrics, retrieval
                 nDCG, LLM judge (reference: tools/evaluation/).
- ``ingest/``    Streaming ingest: fs/RSS/Kafka sources -> chunk ->
                 batched embed -> vector store
                 (reference: experimental/streaming_ingest_rag/).
- ``integrations/`` LangChain + LlamaIndex connector classes
                 (reference: integrations/langchain/).
- ``assistant/`` Multimodal assistant: PPTX/DOCX parsing, conversation
                 memory, fact-check guardrail, feedback capture
                 (reference: experimental/multimodal_assistant/).
- ``lora.py``    LoRA fine-tuning over any mesh, QLoRA over quantized
                 bases (reference: models/Gemma/lora.ipynb recipes).
- ``deploy/``    HelmPipeline operator, chart renderer, compose profiles
                 (reference: deploy/).
"""

__version__ = "0.1.0"
