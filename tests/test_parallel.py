"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

The JAX analogue of the reference's "multi-node without a cluster" envtest
strategy (SURVEY.md §4): numerical parity between sharded and single-device
execution IS the distributed test.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.configs import LlamaConfig
from generativeaiexamples_tpu.parallel import (
    MeshPlan, activation_spec, kv_cache_spec, llama_param_specs, make_mesh,
    shard_params)
from generativeaiexamples_tpu.utils.errors import ShardingError

# Geometry chosen so tp=4 divides heads (8) and kv heads (4).
CFG = LlamaConfig(vocab_size=256, hidden_size=128, intermediate_size=256,
                  num_layers=2, num_heads=8, num_kv_heads=4, head_dim=16,
                  max_position_embeddings=256)


def test_mesh_plan_resolution(cpu_devices):
    plan = MeshPlan(dp=2).resolve(8)
    assert plan.tp == 4 and plan.dp == 2
    with pytest.raises(ShardingError):
        MeshPlan(dp=3).resolve(8)
    with pytest.raises(ShardingError):
        MeshPlan(dp=2, tp=8).resolve(8)


def test_mesh_axes(cpu_devices):
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    assert mesh.shape == {"dp": 2, "pp": 1, "ep": 1, "sp": 1, "tp": 4}


def test_tp_sharded_forward_matches_single_device(cpu_devices):
    params = llama.init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (4, 10), np.int32))
    positions = jnp.broadcast_to(jnp.arange(10, dtype=jnp.int32), (4, 10))

    ref_logits, _ = llama.apply(params, CFG, tokens, positions)

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    specs = llama_param_specs(CFG, mesh)
    sharded = shard_params(params, mesh, specs)
    act = NamedSharding(mesh, activation_spec(mesh))
    tokens_s = jax.device_put(tokens, act)
    pos_s = jax.device_put(positions, act)

    @jax.jit
    def fwd(p, t, pos):
        return llama.apply(p, CFG, t, pos)[0]

    out = fwd(sharded, tokens_s, pos_s)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)


def test_tp_sharded_decode_with_cache(cpu_devices):
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = llama.init_params(CFG, jax.random.key(1), dtype=jnp.float32)
    sharded = shard_params(params, mesh, llama_param_specs(CFG, mesh))
    cache = llama.init_kv_cache(CFG, 4, max_len=32, dtype=jnp.float32)
    cache = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        cache, kv_cache_spec(CFG, mesh))

    tokens = jnp.zeros((4, 4), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(4, dtype=jnp.int32), (4, 4))

    @jax.jit
    def prefill(p, t, pos, c):
        return llama.apply(p, CFG, t, pos, c)

    logits, cache = prefill(sharded, tokens, positions, cache)
    assert logits.shape == (4, 4, 256)

    @jax.jit
    def decode(p, t, pos, c):
        return llama.apply(p, CFG, t, pos, c)

    step_tok = jnp.ones((4, 1), jnp.int32)
    step_pos = jnp.full((4, 1), 4, jnp.int32)
    logits2, cache = decode(sharded, step_tok, step_pos, cache)
    assert logits2.shape == (4, 1, 256)
    assert bool(jnp.isfinite(logits2).all())


def test_engine_on_tp_mesh_greedy_parity(cpu_devices):
    """The full serving engine on a tp=4 mesh (sharded params + paged KV +
    donated state chain) must reproduce the single-device engine's greedy
    output exactly — the TP *serving* path, not just the bare forward
    (VERDICT.md r1 weak #7)."""
    from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                                 SamplingParams)
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    params = llama.init_params(CFG, jax.random.key(3), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_input_length=64, max_output_length=32,
                        prefill_buckets=(32, 64), dtype="float32",
                        page_size=32, steps_per_round=4)
    tok = ByteTokenizer()
    sp = SamplingParams(max_tokens=8, top_k=1, ignore_eos=True)
    prompt = tok.encode("mesh parity probe")

    with Engine(params, CFG, tok, ecfg) as single:
        ref = single.submit(prompt, sp)
        ref.text()

    mesh = make_mesh(MeshPlan(tp=4), jax.devices()[:4])
    with Engine(params, CFG, tok, ecfg, mesh=mesh) as sharded_engine:
        got = sharded_engine.submit(prompt, sp)
        got.text()
        # continuous batching on the mesh: a second wave of requests
        wave = [sharded_engine.submit(tok.encode(f"wave {i}"),
                                      SamplingParams(max_tokens=3 + i,
                                                     ignore_eos=True))
                for i in range(3)]
        for i, s in enumerate(wave):
            s.text()
            assert len(s.token_ids) == 3 + i

    assert got.token_ids == ref.token_ids
    assert got.finish_reason == "length"


def test_engine_on_mesh_gqa_degrade(cpu_devices):
    """tp=8 > kv_heads=4 through the engine: replicated KV projections,
    sharded everything else, still generates."""
    from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                                 SamplingParams)
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    params = llama.init_params(CFG, jax.random.key(4), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_input_length=32, max_output_length=16,
                        prefill_buckets=(32,), dtype="float32", page_size=16,
                        steps_per_round=2)
    mesh = make_mesh(MeshPlan(tp=8))
    with Engine(params, CFG, ByteTokenizer(), ecfg, mesh=mesh) as eng:
        s = eng.submit(eng.tokenizer.encode("gqa"),
                       SamplingParams(max_tokens=5, top_k=1, ignore_eos=True))
        s.text()
        assert len(s.token_ids) == 5


def test_gqa_tp_exceeding_kv_heads_degrades_gracefully(cpu_devices):
    """tp=8 > kv_heads=4: wk/wv fall back to replicated (the XLA version of
    the reference's KV duplication, weight.py:150-157)."""
    mesh = make_mesh(MeshPlan(tp=8))
    specs = llama_param_specs(CFG, mesh)
    assert specs["layers"]["wk"] == P(None, None, None)
    assert specs["layers"]["wq"] == P(None, None, "tp")

    params = llama.init_params(CFG, jax.random.key(2), dtype=jnp.float32)
    sharded = shard_params(params, mesh, specs)
    tokens = jnp.zeros((2, 6), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(6, dtype=jnp.int32), (2, 6))
    logits, _ = jax.jit(lambda p, t, s: llama.apply(p, CFG, t, s))(
        sharded, tokens, positions)
    assert bool(jnp.isfinite(logits).all())


def test_engine_tp_mesh_kernel_path_parity(cpu_devices, monkeypatch):
    """The Pallas decode kernel under a tp mesh (shard_map over KV-head
    shards, interpret mode on CPU): the engine must take the kernel path
    for kernel-supported geometry and reproduce the gather path's greedy
    output exactly (VERDICT r3 weak #3: TP serving fell back to the
    ~10x-slower gather)."""
    from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                                 SamplingParams)
    from generativeaiexamples_tpu.models.configs import LlamaConfig
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    kcfg = LlamaConfig(vocab_size=320, hidden_size=64,
                       intermediate_size=96, num_layers=2, num_heads=4,
                       num_kv_heads=2, head_dim=128,
                       max_position_embeddings=1024)
    params = llama.init_params(kcfg, jax.random.key(11), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_input_length=128,
                        max_output_length=32, prefill_buckets=(128,),
                        dtype="float32", page_size=128,
                        kv_pool_tokens=1024, steps_per_round=4)
    tok = ByteTokenizer()
    sp = SamplingParams(max_tokens=6, top_k=1, ignore_eos=True)
    prompt = tok.encode("kernel under tp")

    # reference: gather path (kernel off), single device
    monkeypatch.setenv("GENAI_TPU_PAGED_KERNEL", "0")
    with Engine(params, kcfg, tok, ecfg) as ref_eng:
        assert not ref_eng._use_kernel
        ref = ref_eng.submit(prompt, sp)
        ref.text()

    # kernel path forced (interpret mode on CPU), tp=2 mesh
    monkeypatch.setenv("GENAI_TPU_PAGED_KERNEL", "1")
    mesh = make_mesh(MeshPlan(tp=2), jax.devices()[:2])
    with Engine(params, kcfg, tok, ecfg, mesh=mesh) as eng:
        assert eng._use_kernel, "tp mesh must take the shard_mapped kernel"
        got = eng.submit(prompt, sp)
        got.text()
    assert got.token_ids == ref.token_ids

    # pp in the mesh is a validated serving rejection (VERDICT r5 #6):
    # every decode round runs all layers as one program, so pipeline
    # stages would idle 1/pp of each round — construction fails loudly
    # at topology validation instead of serving degraded.
    from generativeaiexamples_tpu.utils.errors import ConfigError
    mesh_pp = make_mesh(MeshPlan(pp=2, tp=2), jax.devices()[:4])
    with pytest.raises(ConfigError, match=r"serving requires pp == 1"):
        Engine(params, kcfg, tok, ecfg, mesh=mesh_pp)


def test_engine_tp_mesh_chunked_long_prompt(cpu_devices):
    """Chunked long-prompt admission under a tp mesh: the block-streamed
    prefix attention runs with the pool's KV heads GSPMD-sharded over
    tp, and greedy output matches the meshless chunked engine exactly."""
    from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                                 SamplingParams)
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    params = llama.init_params(CFG, jax.random.key(5), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_input_length=128,
                        max_output_length=16, prefill_buckets=(32,),
                        page_size=16, dtype="float32",
                        kv_pool_tokens=None, steps_per_round=4,
                        max_prefill_bucket=32)
    tok = ByteTokenizer()
    sp = SamplingParams(max_tokens=8, top_k=1, ignore_eos=True)
    prompt = [(i * 11) % 250 + 3 for i in range(100)]   # 100 > bucket 32

    with Engine(params, CFG, tok, ecfg) as ref_eng:
        ref = ref_eng.submit(prompt, sp)
        ref.text()

    mesh = make_mesh(MeshPlan(tp=2), jax.devices()[:2])
    with Engine(params, CFG, tok, ecfg, mesh=mesh) as eng:
        got = eng.submit(prompt, sp)
        got.text()
    assert got.token_ids == ref.token_ids, (got.token_ids, ref.token_ids)
    assert got.finish_reason == "length"


def test_engine_sp_mesh_serving_prefill(cpu_devices):
    """SERVING under a dp×sp mesh: admission prefill runs the
    ring-attention path (activations sequence-sharded — the long-prompt
    admission whose per-device activation budget is 1/sp of the
    prompt), KV lands in the paged pool, and greedy output matches the
    meshless engine exactly (VERDICT r4 weak #9: sp drove only
    score/training)."""
    from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                                 SamplingParams)
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    params = llama.init_params(CFG, jax.random.key(3), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_input_length=128,
                        max_output_length=16, prefill_buckets=(64, 128),
                        page_size=16, dtype="float32",
                        kv_pool_tokens=None, steps_per_round=4)
    tok = ByteTokenizer()
    sp_params = SamplingParams(max_tokens=8, top_k=1, ignore_eos=True)
    prompt = [(i * 13) % 250 + 3 for i in range(100)]

    with Engine(params, CFG, tok, ecfg) as ref_eng:
        ref = ref_eng.submit(prompt, sp_params)
        ref.text()

    mesh = make_mesh(MeshPlan(sp=4), jax.devices()[:4])
    with Engine(params, CFG, tok, ecfg, mesh=mesh) as eng:
        got = eng.submit(prompt, sp_params)
        got.text()
        # a second admission reuses the compiled sp prefill
        again = eng.submit(prompt[:40], sp_params)
        again.text()
    assert got.token_ids == ref.token_ids
    assert got.finish_reason == "length"
    assert len(again.token_ids) == 8


def test_engine_tp_mesh_int8_kv_kernel(cpu_devices, monkeypatch):
    """int8-KV under a tp mesh: the shard_mapped quant kernel (scale
    pools sharded over kv heads with their int8 pools) serves and matches
    the single-device int8 gather path exactly — same quantized pool
    contents, same greedy tokens."""
    from generativeaiexamples_tpu.engine import (Engine, EngineConfig,
                                                 SamplingParams)
    from generativeaiexamples_tpu.models.configs import LlamaConfig
    from generativeaiexamples_tpu.models.tokenizer import ByteTokenizer

    kcfg = LlamaConfig(vocab_size=320, hidden_size=64,
                       intermediate_size=96, num_layers=2, num_heads=4,
                       num_kv_heads=2, head_dim=128,
                       max_position_embeddings=1024)
    params = llama.init_params(kcfg, jax.random.key(11), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_input_length=128,
                        max_output_length=32, prefill_buckets=(128,),
                        dtype="float32", page_size=128,
                        kv_pool_tokens=1024, steps_per_round=4,
                        kv_quant="int8")
    tok = ByteTokenizer()
    sp = SamplingParams(max_tokens=6, top_k=1, ignore_eos=True)
    prompt = tok.encode("int8 kv under tp")

    monkeypatch.setenv("GENAI_TPU_PAGED_KERNEL", "0")
    with Engine(params, kcfg, tok, ecfg) as ref_eng:
        ref = ref_eng.submit(prompt, sp)
        ref.text()

    monkeypatch.setenv("GENAI_TPU_PAGED_KERNEL", "1")
    mesh = make_mesh(MeshPlan(tp=2), jax.devices()[:2])
    with Engine(params, kcfg, tok, ecfg, mesh=mesh) as eng:
        assert eng._use_kernel
        assert eng._state["cache"]["ks"].shape[-2] == 2  # KV dim sharded spec
        got = eng.submit(prompt, sp)
        got.text()
    assert got.token_ids == ref.token_ids
