"""Feedback capture (reference: experimental/multimodal_assistant/utils/
feedback.py — per-response user feedback persisted for later tuning).
JSONL on disk; append-only."""

from __future__ import annotations

import json
import os
import time
from typing import Optional


class FeedbackStore:
    def __init__(self, path: str = "./feedback.jsonl"):
        self.path = path

    def record(self, question: str, answer: str, rating: int,
               comment: str = "", sources: Optional[list[str]] = None,
               ) -> dict:
        entry = {"ts": time.time(), "question": question, "answer": answer,
                 "rating": int(rating), "comment": comment,
                 "sources": sources or []}
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(entry) + "\n")
        return entry

    def load(self) -> list[dict]:
        if not os.path.isfile(self.path):
            return []
        with open(self.path) as f:
            return [json.loads(line) for line in f if line.strip()]
