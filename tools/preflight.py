"""One-stop repo preflight: every committed-artifact and docs-fence
contract in one obvious place.

The repo grew four separate guards — ``tools/check_bench_schema.py``
(the bench output contract), ``tools/check_metrics_docs.py`` (the three
doc-fenced metric tables), ``obs.metrics.lint_prometheus`` (the
/metrics exposition rules), and ``tools/perf_diff.py`` (headline
regression gates over the committed ``BENCH_rNN`` artifacts). Each has
its own CLI and its own tier-1 test, which means a PR that regresses a
committed headline artifact or desyncs a docs fence fails in whichever
corner happens to notice. This module runs ALL of them:

    python tools/preflight.py            # everything; non-zero on any failure
    python tools/preflight.py --list     # enumerate the checks

Checks:

- **bench-schema** — a fully-assembled synthetic bench result (built
  through ``bench.assemble_result``, including the KV-pressure and
  fleet sections) validates against ``tools/bench_schema.json``. The
  committed round artifacts predate newer required sections and are
  deliberately NOT schema-checked; their contract is the perf gate
  below.
- **metrics-docs** — the engine-gauge / router / round-telemetry
  tables in ``docs/observability.md`` match the code surfaces two-way.
- **metrics-lint** — every declared metric surface renders a clean
  Prometheus exposition (HELP lines, family matching, ``_total``
  counters).
- **fleet-obs** — the router's ``GET /debug/fleet`` snapshot and
  ``/debug/requests`` timeline contracts (router/fleet.py schemas)
  validated element-wise over a synthetic-but-real router state built
  through the production table/recorder/window classes.
- **autoscale** — the autoscale controller's decision-record and
  ``GET /debug/autoscale`` contracts (router/autoscale.py schemas):
  a real controller ticks over the synthetic fleet state and every
  decision record + the endpoint payload validate element-wise, with
  the overloaded state required to produce a scale-up decision (an
  all-hold ring would validate while proving nothing).
- **multichip** — the ``BENCH_MESH`` sweep's ``multichip`` section
  contract: schema element-wise plus the semantic invariants (mesh
  labels parse and match ``devices``, every rung carries a positive
  topology-derived round budget, mesh rungs serve the ``fused_tp``
  tail — a ``materialized`` mesh rung is the silent regression this
  PR's tentpole removed).
- **disagg** — the ``BENCH_DISAGG`` scenario's ``disagg`` section
  contract (docs/disaggregation.md): schema element-wise plus the
  semantic invariants (both arms present at EQUAL chip counts, the
  disagg arm's role census actually splits prefill/decode, and its
  handoff accounting shows the two-leg path ran — a disagg arm with
  zero handoffs AND zero fallbacks silently degenerated to unified).
- **alerts** — a REAL ``obs.alerts.AlertEngine`` ticked over a
  synthetic-but-real metric history through a whole episode: the
  watchdog rule must FIRE on climbing stall deltas (``on_fire`` exactly
  once) and must RESOLVE when the breach ages out of the rule window;
  the incident bundle built from the firing validates against the
  ``incident/v1`` contract and renders via ``tools/incident_report.py``.
- **obs-overhead** — the ``BENCH_OBS_OVERHEAD`` scenario's
  ``obs_overhead`` section contract: schema plus the semantic
  invariants (armed arm actually sampling, overhead arithmetic
  consistent with the two arms).
- **perf-gates** — ``tools/perf_diff.py`` over committed artifact
  pairs: each later round must not regress the earlier one's headline
  metrics (the same pairs/thresholds the tier-1 perf_diff test pins).

Tier-1: ``tests/test_preflight.py`` runs ``run_checks`` green, so a
fence desync or artifact regression fails the suite through this one
entry point too.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Callable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Committed artifact pairs the perf gate enforces, with per-metric
#: threshold overrides (p99 tail percentiles over single-digit samples
#: jitter between runs — same widening the tier-1 perf_diff test uses).
PERF_GATE_PAIRS: list[tuple[str, str, dict[str, float]]] = [
    ("BENCH_r04.json", "BENCH_r05.json", {"engine_p99_ttft_ms": 20.0}),
    ("BENCH_r01.json", "BENCH_r05.json", {"engine_p99_ttft_ms": 20.0}),
]


def check_bench_schema() -> list[str]:
    """Validate a fully-populated synthetic result through the real
    emit path (``bench.assemble_result`` -> ``validate_result``)."""
    sys.path.insert(0, REPO)
    import bench
    from tools.check_bench_schema import BenchSchemaError, validate_result

    kv_pressure = {
        "pool_tokens": 2048, "host_pool_tokens": 8192,
        "ratios": [1, 2], "turns": 3,
        "arms": [
            {"ratio": 1, "tiering": False, "sessions": 2,
             "cold_p50_ttft_ms": 50.0, "warm_p50_ttft_ms": 40.0,
             "kv_restore_hit_rate": 0.0, "kv_tier_offload_pages": 0,
             "kv_tier_restore_pages": 0, "kv_restore_skipped_cost": 0,
             "prefix_hit_rate": 0.1},
            {"ratio": 1, "tiering": True, "sessions": 2,
             "cold_p50_ttft_ms": 50.0, "warm_p50_ttft_ms": 20.0,
             "kv_restore_hit_rate": 0.5, "kv_tier_offload_pages": 8,
             "kv_tier_restore_pages": 6, "kv_restore_skipped_cost": 1,
             "prefix_hit_rate": 0.6},
        ],
    }
    fleet = {
        "replicas": 2, "sessions": 3, "turns_per_session": 3,
        "session_rps": 4.0, "slo_ttft_ms": 2000.0, "num_tokens": 4,
        "policies": [
            {"policy": p, "offered_turns": 9, "completed": 9,
             "errors": 0, "slo_attainment": 1.0, "ttft_p50_ms": 10.0,
             "ttft_p99_ms": 12.0, "cold_ttft_p50_ms": 11.0,
             "warm_ttft_p50_ms": 9.0, "prefix_hit_tokens": 100,
             "prefix_hit_rate": 0.5, "placed": {"r0": 5, "r1": 4},
             "affinity_hit_placements": 3, "retries_connect": 0,
             "kv_transfer": p == "affinity_transfer",
             "kv_transfer_pages": 4 if p == "affinity_transfer" else 0}
            for p in ("round_robin", "affinity", "affinity_transfer")],
        "fleet_obs": {
            "slo_attainment": 1.0, "window_requests": 9,
            "ttft_p50_ms": 10.0, "error_rate": 0.0,
            "headroom_tokens_per_sec": 120.0,
            "capacity_tokens_per_sec": 200.0,
            "replicas": [
                {"name": f"r{i}", "slo_attainment": 1.0,
                 "window_requests": 4 + i,
                 "headroom_tokens_per_sec": 60.0}
                for i in range(2)],
        },
    }
    autoscale = {
        "duration_s": 12.0, "trace": [[0.3, 1.0], [0.3, 6.0], [0.4, 1.0]],
        "slo_ttft_ms": 2000.0, "deadline_ms": None, "num_tokens": 8,
        "min_replicas": 1, "max_replicas": 3, "interval_s": 0.3,
        "policies": [
            {"policy": "autoscaled", "replicas_static": None,
             "offered": 40, "completed": 38, "shed": 2, "errors": 0,
             "slo_attainment": 0.9, "ttft_p50_ms": 120.0,
             "replica_minutes": 0.4, "avg_replicas": 2.0,
             "peak_replicas": 3, "scale_ups": 2, "scale_downs": 1,
             "surge_rejections": 0, "decisions": 40},
            {"policy": "static", "replicas_static": 2,
             "offered": 40, "completed": 35, "shed": 5, "errors": 0,
             "slo_attainment": 0.8, "ttft_p50_ms": 200.0,
             "replica_minutes": 0.4, "avg_replicas": 2.0,
             "peak_replicas": 2, "scale_ups": 0, "scale_downs": 0,
             "surge_rejections": 0, "decisions": 0},
        ],
    }
    result = bench.assemble_result(
        kind="engine", model="preflight", headline=10.0,
        engine_p50=8.0, engine_p99=12.0, tput=100.0,
        achieved_bw=1e9, bw_util=0.1, bw_steady=True,
        chat=None, e2e_p50=None, e2e_dist=None, e2e_breakdown=None,
        e2e_tps_p50=None, pipeline=bench.pipeline_snapshot({}),
        quant="none", kv_quant=None, weights="random-init",
        prompt_len=16, out_len=4, slots=2, steps_per_round=4,
        kv_pool_pages=8, device="cpu", rtt_ms=None, n_devices=1,
        bench_seconds=1.0, fleet=fleet, kv_pressure=kv_pressure,
        autoscale=autoscale, multichip=synthetic_multichip(),
        disagg=synthetic_disagg(), obs_overhead=synthetic_obs_overhead())
    try:
        validate_result(result)
    except BenchSchemaError as exc:
        return [str(exc)]
    return []


def synthetic_multichip() -> dict:
    """A fully-populated ``multichip`` bench section (the BENCH_MESH
    sweep's output shape) — shared by the bench-schema synthetic result
    and the multichip check below; returned fresh so the tier-1 test
    can doctor a copy to prove the check fails."""
    return {
        "mesh_sweep": ["tp=1", "tp=2"],
        "prompt_len": 16, "output_len": 4, "requests_per_rung": 2,
        "slots": 2,
        "rungs": [
            {"mesh": "tp=1", "devices": 1,
             "engine_p50_ttft_ms": 20.0, "engine_p99_ttft_ms": 25.0,
             "decode_tokens_per_sec": 100.0,
             "tokens_per_sec_per_device": 100.0,
             "sched_round_budget_tokens": 256,
             "cost_source": "PROFILE_preflight.json",
             "cost_topology": "tp=1", "tail": "fused",
             "engine_downgrades": 0, "spec": None},
            {"mesh": "tp=2", "devices": 2,
             "engine_p50_ttft_ms": 14.0, "engine_p99_ttft_ms": 18.0,
             "decode_tokens_per_sec": 160.0,
             "tokens_per_sec_per_device": 80.0,
             "sched_round_budget_tokens": 384,
             "cost_source": "PROFILE_preflight.json@tp=2",
             "cost_topology": "tp=2", "tail": "fused_tp",
             "engine_downgrades": 0,
             "spec": {"draft_tokens": 8, "accepted_tokens": 5,
                      "verify_rounds": 3, "acceptance_rate": 0.625,
                      "tokens_per_step": 1.6}},
        ],
    }


def validate_multichip_block(block: dict) -> list[str]:
    """Element-wise + semantic validation of one ``multichip`` section:
    schema per rung, parseable mesh labels whose axis product matches
    ``devices``, a positive topology-derived round budget, and a tail
    mode from the known set (a mesh rung reading ``materialized`` means
    the sharded fused tail silently regressed to the fallback)."""
    import re as _re

    sys.path.insert(0, REPO)
    from tools.check_bench_schema import (BenchSchemaError, load_schema,
                                          validate_result)
    errors: list[str] = []
    try:
        validate_result({"multichip": block},
                        schema={**load_schema(),
                                "top_level": {"multichip": ["obj"]}})
    except BenchSchemaError as exc:
        errors.append(str(exc))
    for i, rung in enumerate(block.get("rungs") or []):
        if not isinstance(rung, dict):
            continue
        mesh = str(rung.get("mesh", ""))
        if not _re.fullmatch(r"[a-z]+=\d+(,[a-z]+=\d+)*", mesh):
            errors.append(f"rungs[{i}]: mesh label {mesh!r} is not "
                          f"axis=N[,axis=N...]")
            continue
        product = 1
        for part in mesh.split(","):
            product *= int(part.split("=")[1])
        if product != rung.get("devices"):
            errors.append(
                f"rungs[{i}]: devices={rung.get('devices')} does not "
                f"match mesh {mesh!r} (axis product {product})")
        if not rung.get("sched_round_budget_tokens", 0) > 0:
            errors.append(f"rungs[{i}]: sched_round_budget_tokens must "
                          f"be > 0 (no topology row produced a budget)")
        if rung.get("tail") not in ("fused_tp", "fused", "materialized"):
            errors.append(f"rungs[{i}]: unknown tail mode "
                          f"{rung.get('tail')!r}")
        if rung.get("devices", 1) > 1 and rung.get("tail") != "fused_tp":
            errors.append(
                f"rungs[{i}]: mesh rung {mesh!r} served with tail="
                f"{rung.get('tail')!r} — the tp-sharded fused sampler "
                f"regressed to a fallback")
    return errors


def synthetic_disagg() -> dict:
    """A fully-populated ``disagg`` bench section (the BENCH_DISAGG
    scenario's output shape) — shared by the bench-schema synthetic
    result and the disagg check below; returned fresh so the tier-1
    test can doctor a copy to prove the check fails."""
    return {
        "replicas": 2, "requests": 24, "rps": 4.0, "long_frac": 0.4,
        "long_chars": 4600, "short_chars": 400, "num_tokens": 16,
        "arms": [
            {"arm": "unified", "roles": {"unified": 2},
             "offered": 24, "completed": 24, "errors": 0,
             "ttft_p50_ms": 120.0, "ttft_p99_ms": 400.0,
             "long_ttft_p50_ms": 300.0, "short_ttft_p50_ms": 90.0,
             "tokens_generated": 384, "decode_goodput": 60.0,
             "handoffs": 0, "fallbacks": 0, "kv_export_pages": 0,
             "kv_export_shed": 0, "kv_transfer_pages": 0},
            {"arm": "disagg", "roles": {"prefill": 1, "decode": 1},
             "offered": 24, "completed": 24, "errors": 0,
             "ttft_p50_ms": 80.0, "ttft_p99_ms": 280.0,
             "long_ttft_p50_ms": 200.0, "short_ttft_p50_ms": 60.0,
             "tokens_generated": 384, "decode_goodput": 90.0,
             "handoffs": 9, "fallbacks": 1, "kv_export_pages": 36,
             "kv_export_shed": 0, "kv_transfer_pages": 4},
        ],
    }


def validate_disagg_block(block: dict) -> list[str]:
    """Element-wise + semantic validation of one ``disagg`` section:
    schema per arm, both arms present at EQUAL chip counts, the disagg
    arm's role census genuinely split (>= 1 prefill AND >= 1 decode,
    summing to ``replicas``), and its handoff accounting non-degenerate
    (a disagg arm with zero handoffs and zero fallbacks means the
    router never conducted the two-leg path — the arm silently measured
    unified twice)."""
    sys.path.insert(0, REPO)
    from tools.check_bench_schema import (BenchSchemaError, load_schema,
                                          validate_result)
    errors: list[str] = []
    try:
        validate_result({"disagg": block},
                        schema={**load_schema(),
                                "top_level": {"disagg": ["obj"]}})
    except BenchSchemaError as exc:
        errors.append(str(exc))
    arms = {a.get("arm"): a for a in (block.get("arms") or [])
            if isinstance(a, dict)}
    for want in ("unified", "disagg"):
        if want not in arms:
            errors.append(f"arms: missing the {want!r} arm — the "
                          f"comparison needs both at equal chips")
    if len(arms) < 2:
        return errors
    replicas = block.get("replicas")
    for name, arm in arms.items():
        roles = arm.get("roles") or {}
        if sum(roles.values()) != replicas:
            errors.append(
                f"arms[{name}]: roles {roles} do not sum to replicas="
                f"{replicas} — the equal-chips comparison is broken")
    droles = arms["disagg"].get("roles") or {}
    if not (droles.get("prefill", 0) >= 1 and droles.get("decode", 0) >= 1):
        errors.append(
            f"arms[disagg]: role census {droles} is not a prefill/decode "
            f"split")
    if set((arms["unified"].get("roles") or {})) != {"unified"}:
        errors.append(
            f"arms[unified]: role census "
            f"{arms['unified'].get('roles')} is not all-unified")
    if not (arms["disagg"].get("handoffs", 0)
            or arms["disagg"].get("fallbacks", 0)):
        errors.append(
            "arms[disagg]: zero handoffs AND zero fallbacks — the "
            "router never conducted the two-leg path; the arm measured "
            "unified twice")
    return errors


def check_disagg() -> list[str]:
    """Validate the disagg scenario contract over the synthetic section
    (schema + equal-chips/role-split/handoff invariants) — the same
    validator bench consumers can run over a real BENCH_DISAGG
    artifact."""
    return validate_disagg_block(synthetic_disagg())


def synthetic_failover() -> dict:
    """A fully-populated ``failover`` bench section (the BENCH_FAILOVER
    scenario's output shape) — shared by the bench-schema synthetic
    result and the failover check below; returned fresh so the tier-1
    test can doctor a copy to prove the check fails."""
    return {
        "replicas": 3, "requests": 16, "rps": 3.0, "num_tokens": 32,
        "arms": [
            {"arm": "resume_on", "resume_attempts": 1,
             "offered": 17, "completed": 17, "errors": 0,
             "error_frames": 0, "completed_no_error_rate": 1.0,
             "killed_replica": "r1", "resumes_ok": 2,
             "resumes_failed": 0, "resume_replay_tokens": 18,
             "resumed_p50_ms": 900.0, "unresumed_p50_ms": 620.0,
             "resumed_added_p50_ms": 280.0, "ttft_p50_ms": 140.0,
             "tokens_generated": 544},
            {"arm": "resume_off", "resume_attempts": 0,
             "offered": 17, "completed": 15, "errors": 2,
             "error_frames": 2, "completed_no_error_rate": 0.8824,
             "killed_replica": "r0", "resumes_ok": 0,
             "resumes_failed": 2, "resume_replay_tokens": 0,
             "resumed_p50_ms": None, "unresumed_p50_ms": 610.0,
             "resumed_added_p50_ms": None, "ttft_p50_ms": 138.0,
             "tokens_generated": 480},
        ],
    }


def validate_failover_block(block: dict) -> list[str]:
    """Element-wise + semantic validation of one ``failover`` section:
    schema per arm, both arms present around the same scripted kill,
    every completion rate an actual rate in [0, 1], the resume-on arm
    having actually resumed something (zero resumes means the kill
    never landed mid-stream — the arm measured nothing), and the
    resume-off arm honoring its off switch."""
    sys.path.insert(0, REPO)
    from tools.check_bench_schema import (BenchSchemaError, load_schema,
                                          validate_result)
    errors: list[str] = []
    try:
        validate_result({"failover": block},
                        schema={**load_schema(),
                                "top_level": {"failover": ["obj"]}})
    except BenchSchemaError as exc:
        errors.append(str(exc))
    arms = {a.get("arm"): a for a in (block.get("arms") or [])
            if isinstance(a, dict)}
    for want in ("resume_on", "resume_off"):
        if want not in arms:
            errors.append(f"arms: missing the {want!r} arm — the "
                          f"comparison needs both around the same kill")
    for name, arm in arms.items():
        rate = arm.get("completed_no_error_rate")
        if isinstance(rate, (int, float)) and not isinstance(rate, bool):
            if not 0.0 <= rate <= 1.0:
                errors.append(
                    f"arms[{name}]: completed_no_error_rate {rate!r} "
                    f"is not a rate in [0, 1]")
        if isinstance(arm.get("completed"), int) and \
                isinstance(arm.get("offered"), int) and \
                arm["completed"] > arm["offered"]:
            errors.append(
                f"arms[{name}]: completed {arm['completed']} exceeds "
                f"offered {arm['offered']}")
    if len(arms) < 2:
        return errors
    on, off = arms.get("resume_on", {}), arms.get("resume_off", {})
    if not on.get("resumes_ok", 0):
        errors.append(
            "arms[resume_on]: zero successful resumes — the scripted "
            "kill never landed mid-stream; the arm measured an "
            "uninterrupted fleet, not failover")
    if off.get("resumes_ok", 0):
        errors.append(
            f"arms[resume_off]: {off['resumes_ok']} resumes with the "
            f"budget at 0 — the off switch is not honored")
    if on.get("resume_attempts", 0) < 1 or off.get("resume_attempts", 1):
        errors.append(
            "arms: resume_attempts must be >= 1 on the resume_on arm "
            "and 0 on the resume_off arm")
    return errors


def check_failover() -> list[str]:
    """Validate the failover scenario contract over the synthetic
    section (schema + both-arms/rate-range/resume-accounting
    invariants) — the same validator bench consumers can run over a
    real BENCH_FAILOVER artifact."""
    return validate_failover_block(synthetic_failover())


def synthetic_obs_overhead() -> dict:
    """A fully-populated ``obs_overhead`` bench section (the
    BENCH_OBS_OVERHEAD scenario's output shape: armed history sampler +
    alert engine vs HISTORY_INTERVAL_S=0 disarmed, decode tok/s each
    way) — shared by the bench-schema synthetic result and the
    obs-overhead check below; returned fresh so the tier-1 test can
    doctor a copy to prove the check fails."""
    return {
        "history_interval_s": 0.05, "history_window_s": 10.0,
        "alert_rules": 5, "rounds_per_arm": 8,
        "armed_tokens_per_sec": 99.2, "disarmed_tokens_per_sec": 100.0,
        "armed_samples": 40, "overhead_pct": 0.8,
    }


def validate_obs_overhead_block(block: dict) -> list[str]:
    """Element-wise + semantic validation of one ``obs_overhead``
    section: schema, both arms measured (positive tok/s), the armed arm
    actually sampling (zero samples means the sampler never ran — the
    arm measured a disarmed stack twice), and ``overhead_pct``
    arithmetically consistent with the two arms."""
    sys.path.insert(0, REPO)
    from tools.check_bench_schema import (BenchSchemaError, load_schema,
                                          validate_result)
    errors: list[str] = []
    try:
        validate_result({"obs_overhead": block},
                        schema={**load_schema(),
                                "top_level": {"obs_overhead": ["obj"]}})
    except BenchSchemaError as exc:
        errors.append(str(exc))
    armed = block.get("armed_tokens_per_sec")
    disarmed = block.get("disarmed_tokens_per_sec")
    for name, v in (("armed_tokens_per_sec", armed),
                    ("disarmed_tokens_per_sec", disarmed)):
        if not (isinstance(v, (int, float)) and not isinstance(v, bool)
                and v > 0):
            errors.append(f"{name} must be a positive rate, got {v!r}")
    if not block.get("history_interval_s", 0) > 0:
        errors.append("history_interval_s must be > 0 — the armed arm "
                      "ran with the layer disarmed")
    if not block.get("armed_samples", 0) > 0:
        errors.append("armed_samples is 0 — the sampler never ran; the "
                      "armed arm measured a disarmed stack")
    if isinstance(armed, (int, float)) and isinstance(disarmed,
                                                      (int, float)) \
            and disarmed > 0:
        expect = (disarmed - armed) / disarmed * 100.0
        got = block.get("overhead_pct")
        if not (isinstance(got, (int, float))
                and abs(got - expect) <= 0.5):
            errors.append(
                f"overhead_pct {got!r} does not match the arms "
                f"((disarmed-armed)/disarmed*100 = {expect:.3f})")
    return errors


def check_obs_overhead() -> list[str]:
    """Validate the obs-overhead scenario contract over the synthetic
    section — the same validator bench consumers can run over a real
    BENCH_OBS_OVERHEAD artifact."""
    return validate_obs_overhead_block(synthetic_obs_overhead())


def synthetic_incident_bundle() -> dict:
    """An incident bundle built through the REAL pipeline: a fresh
    registry + history ring sampled over a breaching metric, a real
    AlertEngine firing the watchdog rule, and ``build_bundle`` joining
    history + alert evidence + a flight timeline. Returned fresh so the
    tier-1 test can doctor a copy to prove the validator fails."""
    sys.path.insert(0, REPO)
    from generativeaiexamples_tpu.obs import alerts as obs_alerts
    from generativeaiexamples_tpu.obs import flight as obs_flight
    from generativeaiexamples_tpu.obs import history as obs_history
    from generativeaiexamples_tpu.obs import incidents as obs_incidents
    from generativeaiexamples_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.Registry()
    stalls = reg.gauge("engine_watchdog_stalls",
                       "cumulative watchdog stall count (mirror)")
    hist = obs_history.MetricHistory(registry=reg, window_s=30.0,
                                     interval_s=0.01)
    rule = obs_alerts.AlertRule(
        "engine_watchdog_stall", "engine_watchdog_stalls", "delta", ">",
        0.0, window_s=30.0, severity="critical",
        summary="engine serve loop stalled (watchdog fired)")
    fired: list[dict] = []
    engine = obs_alerts.AlertEngine(
        hist, rules=(rule,), registry=reg,
        on_fire=lambda r, rec: fired.append(rec))
    for v in (0.0, 1.0, 2.0):
        stalls.set(v)
        hist.sample_once()
        engine.tick()
    record = fired[0] if fired else {"state": None, "evidence": {}}
    flight = obs_flight.FlightRecorder()
    tl = flight.begin("preflight-req-1")
    flight.complete(tl)
    trigger = {"kind": "alert", "rule": rule.name,
               "severity": rule.severity, "summary": rule.summary,
               "state": record.get("state"),
               "evidence": record.get("evidence", {})}
    bundle = obs_incidents.build_bundle(
        server="chain", trigger=trigger, history=hist, alerts=engine,
        flight=flight, rounds=None)
    bundle["id"] = "inc-preflight-1-engine_watchdog_stall"
    return bundle


def validate_incident_bundle(bundle: dict) -> list[str]:
    """Element-wise validation of one incident bundle against the
    ``incident/v1`` contract: the joined sections all present, an
    alert-triggered bundle carrying real evidence, a non-empty history
    window, and the markdown renderer able to tell the story."""
    sys.path.insert(0, REPO)
    from generativeaiexamples_tpu.obs.incidents import BUNDLE_SCHEMA
    from tools.incident_report import render_markdown

    errors: list[str] = []
    if bundle.get("schema") != BUNDLE_SCHEMA:
        errors.append(f"schema is {bundle.get('schema')!r}, expected "
                      f"{BUNDLE_SCHEMA!r}")
    for key in ("server", "ts", "trigger", "alerts", "history", "flight",
                "rounds"):
        if key not in bundle:
            errors.append(f"bundle is missing the {key!r} section")
    trigger = bundle.get("trigger") or {}
    if trigger.get("kind") not in ("alert", "manual"):
        errors.append(f"trigger kind {trigger.get('kind')!r} is not "
                      f"alert|manual")
    if trigger.get("kind") == "alert":
        if not trigger.get("rule"):
            errors.append("alert-triggered bundle names no rule")
        if not (trigger.get("evidence") or {}).get("series"):
            errors.append("alert-triggered bundle carries no evidence "
                          "series — capture ran before evaluation?")
    hist = bundle.get("history") or {}
    if not hist.get("window"):
        errors.append("history window is empty — the bundle froze "
                      "nothing")
    agg = hist.get("aggregates") or {}
    if agg and not agg.get("series"):
        errors.append("history aggregates carry no series")
    if errors:
        return errors
    try:
        rendered = render_markdown(bundle)
    except Exception as exc:  # noqa: BLE001 — the check must report
        return [f"incident_report.render_markdown raised: {exc!r}"]
    if trigger.get("rule") and trigger["rule"] not in rendered:
        errors.append("rendered report does not mention the firing rule")
    if bundle.get("id") and bundle["id"] not in rendered:
        errors.append("rendered report does not carry the incident id")
    return errors


def check_alerts() -> list[str]:
    """Drive a REAL AlertEngine over a synthetic-but-real MetricHistory
    through the whole episode — must-fire (watchdog stalls climb →
    firing, on_fire exactly once), no re-capture while it stays firing,
    must-resolve (the breach ages out of the rule window → resolved) —
    then validate the incident bundle the firing built. Both the fire
    leg and the resolve leg are provable-to-fail: the tier-1 test
    doctors the inputs each way."""
    import time as _time

    sys.path.insert(0, REPO)
    from generativeaiexamples_tpu.obs import alerts as obs_alerts
    from generativeaiexamples_tpu.obs import history as obs_history
    from generativeaiexamples_tpu.obs import metrics as obs_metrics

    errors: list[str] = []
    reg = obs_metrics.Registry()
    stalls = reg.gauge("engine_watchdog_stalls",
                       "cumulative watchdog stall count (mirror)")
    hist = obs_history.MetricHistory(registry=reg, window_s=30.0,
                                     interval_s=0.01)
    # A short rule window so the resolve leg can age the breach out in
    # tens of milliseconds instead of minutes.
    rule = obs_alerts.AlertRule(
        "engine_watchdog_stall", "engine_watchdog_stalls", "delta", ">",
        0.0, window_s=0.05, severity="critical",
        summary="engine serve loop stalled (watchdog fired)")
    fired: list[dict] = []
    engine = obs_alerts.AlertEngine(
        hist, rules=(rule,), registry=reg,
        on_fire=lambda r, rec: fired.append(rec))
    for v in (0.0, 1.0, 2.0):
        stalls.set(v)
        hist.sample_once()
        engine.tick()
    if engine.firing() != [rule.name]:
        errors.append(f"must-fire: watchdog deltas did not fire the "
                      f"rule (firing={engine.firing()!r})")
    if len(fired) != 1:
        errors.append(f"on_fire ran {len(fired)} times during the "
                      f"firing transition; the episode contract is "
                      f"exactly once")
    # Staying firing must not re-fire (the no-re-capture pin).
    hist.sample_once()
    engine.tick()
    if len(fired) > 1:
        errors.append("on_fire re-ran while the rule STAYED firing — "
                      "every sustained alert would re-capture a bundle")
    vals = reg.snapshot()
    if vals.get('alerts_firing{rule="engine_watchdog_stall"}') != 1.0:
        errors.append("alerts_firing gauge is not 1 while firing")
    # Must-resolve: let the breach age past the rule window, then
    # sample flat values — the delta collapses and the rule clears.
    _time.sleep(0.08)
    for _ in range(2):
        hist.sample_once()
        engine.tick()
    if engine.firing():
        errors.append(f"must-resolve: rule still firing after the "
                      f"breach aged out (firing={engine.firing()!r})")
    vals = reg.snapshot()
    if vals.get('alerts_firing{rule="engine_watchdog_stall"}') != 0.0:
        errors.append("alerts_firing gauge did not drop to 0 on "
                      "resolve")
    if vals.get('alerts_total{rule="engine_watchdog_stall",'
                'state="resolved"}') != 1.0:
        errors.append("alerts_total did not count the resolved "
                      "transition")
    errors.extend(validate_incident_bundle(synthetic_incident_bundle()))
    return errors


def check_multichip() -> list[str]:
    """Validate the multichip sweep contract over the synthetic section
    (schema + mesh-label/device/budget/tail invariants) — the same
    validator bench consumers can run over a real BENCH_MESH artifact."""
    return validate_multichip_block(synthetic_multichip())


def check_metrics_docs() -> list[str]:
    sys.path.insert(0, REPO)
    from tools.check_metrics_docs import check
    return check()


def check_metrics_lint() -> list[str]:
    """Render every declared metric surface into a fresh registry via
    the same helpers production uses, then lint the exposition."""
    sys.path.insert(0, REPO)
    from generativeaiexamples_tpu.engine.engine import _STATS_TEMPLATE
    from generativeaiexamples_tpu.obs import metrics as obs_metrics
    from generativeaiexamples_tpu.obs.rounds import (ROUND_METRICS,
                                                     ROUND_TOKEN_BUCKETS)
    from generativeaiexamples_tpu.router.metrics import ROUTER_METRICS

    reg = obs_metrics.Registry()
    stats = dict(_STATS_TEMPLATE)
    stats["harvest_rounds"] = 1
    stats["harvest_wait_ms"] = 1.0
    obs_metrics.record_engine_stats(stats, registry=reg)
    obs_metrics.observe_stage("engine_ttft", 0.1, registry=reg)
    timer = obs_metrics.RequestTimer("chain_generate", registry=reg)
    timer.token(2)
    timer.finish()
    for name, (kind, help_txt) in ROUND_METRICS.items():
        if kind == "counter":
            reg.counter(name, help_txt).inc()
        elif kind == "gauge":
            reg.gauge(name, help_txt).set(1.0)
        else:
            buckets = (ROUND_TOKEN_BUCKETS
                       if name == "engine_round_tokens"
                       else obs_metrics.STAGE_BUCKETS)
            reg.histogram(name, help_txt, buckets=buckets).observe(1.0)
    for name, (kind, labels, help_txt) in ROUTER_METRICS.items():
        if kind == "histogram":
            m = reg.histogram(name, help_txt,
                              buckets=obs_metrics.STAGE_BUCKETS,
                              labelnames=labels)
        else:
            m = (reg.counter if kind == "counter" else reg.gauge)(
                name, help_txt, labelnames=labels)
        leaf = m.labels(*(["r0"] * len(labels))) if labels else m
        if kind == "counter":
            leaf.inc()
        elif kind == "gauge":
            leaf.set(1.0)
        else:
            leaf.observe(0.1)
    reg.counter("shed_total", "requests rejected at admission, by reason",
                labelnames=("reason",)).labels("queue_full").inc()
    reg.gauge("breaker_state",
              "circuit breaker state (0 closed, 1 half-open, 2 open)",
              labelnames=("name",)).labels("retrieval").set(0)
    return obs_metrics.lint_prometheus(reg.render_prometheus())


def synthetic_fleet_state():
    """A small but fully-populated router state (table + SLO window +
    flight recorder) built through the REAL production classes — what
    the fleet-obs check below snapshots and validates. Returning the
    parts lets the tier-1 test doctor copies to prove the check can
    fail."""
    from generativeaiexamples_tpu.router.flight import (
        RouterFlightRecorder, SloWindow)
    from generativeaiexamples_tpu.router.table import ReplicaTable

    table = ReplicaTable()
    table.add("r0", "http://r0:8081")
    table.add("r1", "http://r1:8081")
    table.update_health("r0", ok=True, body={
        "draining": False,
        "load": {"in_flight": 2, "queue_depth": 3, "rejected_total": 1,
                 "prefix_hit_rate": 0.6},
        "rounds": {"rounds_completed": 10, "tokens_per_sec": 400.0,
                   "wall_tokens_per_sec": 120.0, "avg_device_ms": 8.0,
                   "avg_bw_util": 0.4, "avg_drift_ratio": 1.1,
                   "interleaved_share": 0.3},
        "capacity": {"slots": 8, "decode_step_ms": 2.0,
                     "model_source": "PROFILE_r09.json",
                     "capacity_tokens_per_sec": 4000.0},
        "kv_tier": {"host_pages": 5, "offload_pages": 9,
                    "restore_pages": 4, "transfer_pages": 2},
    })
    table.update_health("r1", ok=False)   # a partitioned sibling
    slo = SloWindow(window_s=600.0)
    recorder = RouterFlightRecorder(slo=slo)
    tl = recorder.begin_request(
        {"X-Request-ID": "preflight-1", "X-Deadline-Ms": "5000"},
        "/generate")
    recorder.placement(tl, replica="r0", affinity_blocks=2,
                       candidates=[{"replica": "r0", "score": 3.0,
                                    "affinity_blocks": 2,
                                    "queue_depth": 3, "in_flight": 2}],
                       t_start=tl.t_start)
    recorder.attempt_failed(tl, replica="r1", reason="connect",
                            retried=True)
    recorder.first_byte(tl)
    recorder.complete_request(tl, outcome="ok", replica="r0", status=200)
    slo.record(replica="r1", outcome="midstream_loss", ttft_ms=50.0)
    slo.record(replica="r0", outcome="shed")
    return table, slo, recorder, tl


def check_fleet_obs() -> list[str]:
    """Validate the ``GET /debug/fleet`` snapshot and the router
    ``/debug/requests`` timeline contracts over a synthetic-but-real
    router state, element-wise (router/fleet.py schemas)."""
    sys.path.insert(0, REPO)
    from generativeaiexamples_tpu.router import fleet as router_fleet

    table, slo, _recorder, tl = synthetic_fleet_state()
    snap = router_fleet.build_fleet_snapshot(table, slo, heartbeat_s=2.0)
    errors = router_fleet.validate_fleet_snapshot(snap)
    errors.extend(router_fleet.validate_router_timeline(tl.to_dict()))
    # The synthetic state exercises every outcome class: an all-empty
    # window would validate while proving nothing.
    if snap["fleet"]["window_requests"] < 3:
        errors.append("synthetic fleet state produced an empty SLO "
                      "window — the check is no longer exercising the "
                      "outcome path")
    return errors


def check_autoscale() -> list[str]:
    """Tick a REAL AutoscaleController over the synthetic fleet state
    and validate the decision ring + ``GET /debug/autoscale`` payload
    element-wise (router/autoscale.py schemas). The seeded state is
    overloaded (deep queue, utilization past the trigger), so the check
    also requires a ``scale_up`` decision — proving the control law and
    the contract together."""
    import asyncio

    sys.path.insert(0, REPO)
    from generativeaiexamples_tpu.router import autoscale as rauto
    from generativeaiexamples_tpu.router.server import FleetRouter

    table, slo, recorder, _tl = synthetic_fleet_state()
    # Overload r0: the queue is deep and the wall token rate consumes
    # nearly all of the calibrated capacity.
    table.update_health("r0", ok=True, body={
        "draining": False,
        "load": {"in_flight": 6, "queue_depth": 12, "rejected_total": 1,
                 "prefix_hit_rate": 0.6},
        "rounds": {"rounds_completed": 12, "tokens_per_sec": 4000.0,
                   "wall_tokens_per_sec": 3800.0, "avg_device_ms": 8.0,
                   "avg_bw_util": 0.7, "avg_drift_ratio": 1.0,
                   "interleaved_share": 0.3},
        "capacity": {"slots": 8, "decode_step_ms": 2.0,
                     "model_source": "PROFILE_r09.json",
                     "capacity_tokens_per_sec": 4000.0},
    })
    router = FleetRouter(table, flight=recorder)
    controller = rauto.AutoscaleController(
        router, policy=rauto.AutoscalePolicy(min_replicas=1,
                                             max_replicas=4),
        executor=None, surge=router.surge)
    errors: list[str] = []
    try:
        records = [asyncio.run(controller.tick()) for _ in range(3)]
    except Exception as exc:  # noqa: BLE001 — the check must report
        return [f"controller tick raised: {exc!r}"]
    snap = controller.snapshot()
    errors.extend(rauto.validate_autoscale_snapshot(snap))
    if not any(r["action"] in ("scale_up", "blocked")
               and "utilization" in r["reason"] for r in records):
        errors.append(
            "overloaded synthetic fleet produced no utilization-driven "
            "scale decision — the control law is no longer reading the "
            "leading indicators")
    if snap["decisions"] and snap["decisions"][-1]["evidence"][
            "queue_depth"] != 12:
        errors.append("decision evidence does not reflect the fleet "
                      "snapshot's queue depth (the /debug/fleet join is "
                      "broken)")
    return errors


def check_perf_gates(pairs=None) -> list[str]:
    sys.path.insert(0, REPO)
    from tools.perf_diff import diff_files
    errors: list[str] = []
    for base, cand, thresholds in (pairs or PERF_GATE_PAIRS):
        base_p = base if os.path.isabs(base) else os.path.join(REPO, base)
        cand_p = cand if os.path.isabs(cand) else os.path.join(REPO, cand)
        if not (os.path.exists(base_p) and os.path.exists(cand_p)):
            errors.append(f"{base} -> {cand}: artifact missing")
            continue
        try:
            regressions, _ = diff_files(base_p, cand_p,
                                        per_metric_pct=dict(thresholds))
        except (OSError, ValueError) as exc:
            errors.append(f"{base} -> {cand}: {exc}")
            continue
        errors.extend(f"{base} -> {cand}: {r}" for r in regressions)
    return errors


CHECKS: dict[str, Callable[[], list[str]]] = {
    "bench-schema": check_bench_schema,
    "metrics-docs": check_metrics_docs,
    "metrics-lint": check_metrics_lint,
    "fleet-obs": check_fleet_obs,
    "autoscale": check_autoscale,
    "multichip": check_multichip,
    "disagg": check_disagg,
    "failover": check_failover,
    "alerts": check_alerts,
    "obs-overhead": check_obs_overhead,
    "perf-gates": check_perf_gates,
}


def run_checks(names=None) -> dict[str, list[str]]:
    """Run the named checks (default: all). Returns
    ``{check: [errors]}`` — all-empty values mean a clean tree."""
    return {name: CHECKS[name]() for name in (names or CHECKS)}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Run every repo contract check; non-zero exit on "
                    "any failure.")
    parser.add_argument("checks", nargs="*", choices=[[], *CHECKS],
                        help="subset of checks (default: all)")
    parser.add_argument("--list", action="store_true",
                        help="list available checks and exit")
    args = parser.parse_args(argv)
    if args.list:
        for name in CHECKS:
            print(name)
        return 0
    failed = 0
    for name, errors in run_checks(args.checks or None).items():
        if errors:
            failed += 1
            print(f"FAIL {name}:")
            for e in errors:
                print(f"  - {e}")
        else:
            print(f"ok   {name}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main(sys.argv[1:]))
