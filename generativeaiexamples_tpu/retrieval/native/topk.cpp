// Native top-k search kernels (brute-force + IVF-Flat over a CSR layout).
//
// The first-party stand-in for the C++ engines the reference leans on for
// vector search — FAISS and Milvus/knowhere GPU_IVF_FLAT (reference:
// common/utils.py:181-198). OpenMP parallel over queries; per-query
// bounded min-heap selection so k << N costs O(N log k).
//
// Build: g++ -O3 -fopenmp -shared -fPIC topk.cpp -o libgaietopk.so
// (done on demand by native/__init__.py; numpy fallback if unavailable).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

struct Hit {
  float score;
  int64_t id;
};

// Min-heap on score: root = worst of the current top-k.
inline bool worse(const Hit &a, const Hit &b) { return a.score > b.score; }

inline void heap_push(std::vector<Hit> &heap, int64_t k, float score,
                      int64_t id) {
  if ((int64_t)heap.size() < k) {
    heap.push_back({score, id});
    std::push_heap(heap.begin(), heap.end(), worse);
  } else if (score > heap.front().score) {
    std::pop_heap(heap.begin(), heap.end(), worse);
    heap.back() = {score, id};
    std::push_heap(heap.begin(), heap.end(), worse);
  }
}

inline float dot(const float *a, const float *b, int64_t d) {
  float s = 0.f;
  for (int64_t i = 0; i < d; ++i) s += a[i] * b[i];
  return s;
}

// metric: 0 = inner product, 1 = negated squared L2 (argmax == nearest).
inline float score_one(const float *base_row, float base_sq, const float *q,
                       float q_sq, int64_t d, int metric) {
  float dp = dot(base_row, q, d);
  return metric == 0 ? dp : 2.f * dp - base_sq - q_sq;
}

inline void emit(std::vector<Hit> &heap, int64_t k, int64_t *out_idx,
                 float *out_score) {
  // Sort descending by score; pad with id -1.
  std::sort(heap.begin(), heap.end(),
            [](const Hit &a, const Hit &b) { return a.score > b.score; });
  for (int64_t j = 0; j < k; ++j) {
    if (j < (int64_t)heap.size()) {
      out_idx[j] = heap[j].id;
      out_score[j] = heap[j].score;
    } else {
      out_idx[j] = -1;
      out_score[j] = -INFINITY;
    }
  }
}

}  // namespace

extern "C" {

// base: (n, d) row-major. base_sq: (n,) squared norms (may be null for ip).
// live: (n,) 0/1 mask (null == all live). out_*: (nq, k).
void gaie_brute_topk(const float *base, const float *base_sq,
                     const uint8_t *live, int64_t n, int64_t d,
                     const float *queries, int64_t nq, int64_t k, int metric,
                     int64_t *out_idx, float *out_score) {
#pragma omp parallel for schedule(static)
  for (int64_t qi = 0; qi < nq; ++qi) {
    const float *q = queries + qi * d;
    float q_sq = metric == 0 ? 0.f : dot(q, q, d);
    std::vector<Hit> heap;
    heap.reserve(k + 1);
    for (int64_t i = 0; i < n; ++i) {
      if (live && !live[i]) continue;
      heap_push(heap, k,
                score_one(base + i * d, base_sq ? base_sq[i] : 0.f, q, q_sq, d,
                          metric),
                i);
    }
    emit(heap, k, out_idx + qi * k, out_score + qi * k);
  }
}

// IVF-Flat search over a CSR cluster layout:
//   centroids: (nlist, d); offsets: (nlist+1,); items: (n,) vector ids
//   ordered by cluster. Scans the nprobe nearest centroids' postings.
void gaie_ivf_search(const float *base, const float *base_sq,
                     const uint8_t *live, int64_t d, const float *centroids,
                     int64_t nlist, const int64_t *offsets,
                     const int64_t *items, const float *queries, int64_t nq,
                     int64_t k, int64_t nprobe, int metric, int64_t *out_idx,
                     float *out_score) {
  if (nprobe > nlist) nprobe = nlist;
#pragma omp parallel for schedule(static)
  for (int64_t qi = 0; qi < nq; ++qi) {
    const float *q = queries + qi * d;
    float q_sq = dot(q, q, d);
    // Rank centroids by (negated) L2 distance — assignment metric is always
    // euclidean, matching the k-means used to build the lists.
    std::vector<Hit> cheap;
    cheap.reserve(nprobe + 1);
    for (int64_t c = 0; c < nlist; ++c) {
      const float *cr = centroids + c * d;
      float cs = 2.f * dot(cr, q, d) - dot(cr, cr, d) - q_sq;
      heap_push(cheap, nprobe, cs, c);
    }
    std::vector<Hit> heap;
    heap.reserve(k + 1);
    for (const Hit &ch : cheap) {
      int64_t c = ch.id;
      for (int64_t p = offsets[c]; p < offsets[c + 1]; ++p) {
        int64_t i = items[p];
        if (live && !live[i]) continue;
        heap_push(heap, k,
                  score_one(base + i * d, base_sq ? base_sq[i] : 0.f, q, q_sq,
                            d, metric),
                  i);
      }
    }
    emit(heap, k, out_idx + qi * k, out_score + qi * k);
  }
}

int gaie_num_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
