"""Milvus/pgvector connector wire-contract tests (no servers).

The client libraries are not in the image, so fakes are injected at the
import seam and the tests pin exactly what reaches the wire — index and
search parameters matching the reference's store setup
(reference: common/utils.py:143-225 — IVF_FLAT nlist=64 / nprobe=16,
pgvector auto-create) — so a pymilvus/psycopg2 signature drift breaks CI
here instead of shipping silently (VERDICT r3 weak #6).
"""

import sys
import types

import numpy as np
import pytest

from generativeaiexamples_tpu.utils.errors import ConfigError

# ----------------------------------------------------------------- milvus


class FakeMilvusClient:
    created = None

    def __init__(self, uri):
        self.uri = uri
        self.calls = []
        FakeMilvusClient.last = self

    def has_collection(self, name):
        self.calls.append(("has_collection", name))
        return getattr(self, "_exists", False)

    def create_collection(self, **kw):
        self.calls.append(("create_collection", kw))

    def insert(self, collection, rows):
        self.calls.append(("insert", collection, rows))
        return {"ids": list(range(100, 100 + len(rows)))}

    def search(self, collection, data, limit, search_params):
        self.calls.append(("search", collection, data, limit, search_params))
        return [[{"id": 7, "distance": 0.9}, {"id": 3, "distance": 0.5}]
                for _ in data]

    def delete(self, collection, ids):
        self.calls.append(("delete", collection, ids))

    def get_collection_stats(self, collection):
        return {"row_count": 5}

    def flush(self, collection):
        self.calls.append(("flush", collection))


@pytest.fixture
def milvus_store(monkeypatch):
    mod = types.ModuleType("pymilvus")
    mod.MilvusClient = FakeMilvusClient
    monkeypatch.setitem(sys.modules, "pymilvus", mod)
    from generativeaiexamples_tpu.retrieval.connectors import MilvusStore
    return MilvusStore(dim=8, url="http://milvus:19530", collection="rag")


def test_milvus_creates_collection_with_reference_index(milvus_store):
    client = FakeMilvusClient.last
    assert client.uri == "http://milvus:19530"
    create = next(kw for c, kw in
                  [(c[0], c[-1]) for c in client.calls]
                  if c == "create_collection")
    assert create["collection_name"] == "rag"
    assert create["dimension"] == 8
    assert create["auto_id"] is True
    assert create["metric_type"] == "IP"
    assert create["index_params"]["index_type"] == "IVF_FLAT"
    # nlist=64: the reference's GPU_IVF_FLAT build (common/utils.py:181)
    assert create["index_params"]["params"]["nlist"] == 64


def test_milvus_insert_search_delete_wire_shapes(milvus_store):
    client = FakeMilvusClient.last
    ids = milvus_store.add(np.ones((2, 8), np.float32))
    assert ids == [100, 101]
    _, coll, rows = next(c for c in client.calls if c[0] == "insert")
    assert coll == "rag" and list(rows[0]) == ["vector"]
    assert len(rows[0]["vector"]) == 8

    hits = milvus_store.search(np.ones((1, 8), np.float32), k=2)
    _, _, data, limit, params = next(c for c in client.calls
                                     if c[0] == "search")
    assert limit == 2
    # nprobe=16: the reference's search params (common/utils.py:186)
    assert params["params"]["nprobe"] == 16
    assert [h.id for h in hits[0]] == [7, 3]
    assert hits[0][0].score == pytest.approx(0.9)

    milvus_store.delete([7])
    assert ("delete", "rag", [7]) in client.calls
    assert len(milvus_store) == 5
    milvus_store.save("/ignored")
    assert ("flush", "rag") in client.calls


def test_milvus_existing_collection_not_recreated(monkeypatch):
    mod = types.ModuleType("pymilvus")

    class Existing(FakeMilvusClient):
        _exists = True

    mod.MilvusClient = Existing
    monkeypatch.setitem(sys.modules, "pymilvus", mod)
    from generativeaiexamples_tpu.retrieval.connectors import MilvusStore
    MilvusStore(dim=8)
    assert not any(c[0] == "create_collection"
                   for c in Existing.last.calls)


# --------------------------------------------------------------- pgvector


class FakeCursor:
    def __init__(self, log):
        self.log = log
        self._result = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def execute(self, sql, params=None):
        self.log.append((" ".join(sql.split()), params))
        s = sql.strip().upper()
        if s.startswith("SELECT COUNT"):
            self._result = [(3,)]
        elif "RETURNING ID" in s:
            self._result = [(41 + sum(1 for q, _ in self.log
                                      if "RETURNING" in q.upper()),)]
        elif s.startswith("SELECT ID"):
            self._result = [(7, -0.9), (3, 1.5)]
        else:
            self._result = []

    def fetchone(self):
        return self._result[0]

    def fetchall(self):
        return list(self._result)


class FakeConn:
    def __init__(self, log):
        self.log = log
        self.autocommit = False

    def cursor(self):
        return FakeCursor(self.log)


@pytest.fixture
def pg(monkeypatch):
    log = []
    mod = types.ModuleType("psycopg2")
    mod.connect = lambda url: FakeConn(log)
    monkeypatch.setitem(sys.modules, "psycopg2", mod)
    from generativeaiexamples_tpu.retrieval.connectors import PgvectorStore
    return PgvectorStore, log


def test_pgvector_auto_creates_extension_and_table(pg):
    PgvectorStore, log = pg
    PgvectorStore(dim=4)
    assert log[0][0] == "CREATE EXTENSION IF NOT EXISTS vector"
    assert "CREATE TABLE IF NOT EXISTS rag_vectors" in log[1][0]
    assert "vector(4)" in log[1][0]


def test_pgvector_insert_and_ip_search_sql(pg):
    PgvectorStore, log = pg
    store = PgvectorStore(dim=4)
    ids = store.add(np.ones((2, 4), np.float32))
    assert ids == [42, 43]
    inserts = [e for e in log if e[0].startswith("INSERT")]
    assert len(inserts) == 2
    assert inserts[0][1] == ([1.0, 1.0, 1.0, 1.0],)

    hits = store.search(np.zeros((1, 4), np.float32), k=2)
    sel = next(e for e in log if e[0].startswith("SELECT id"))
    # ip metric uses pgvector's <#> (negative inner product) — the score
    # contract negates it back to a real inner product
    assert "<#>" in sel[0] and sel[1][1] == 2
    assert hits[0][0].id == 7 and hits[0][0].score == pytest.approx(0.9)

    store.delete([7, 3])
    dele = next(e for e in log if e[0].startswith("DELETE"))
    assert "= ANY(%s)" in dele[0] and dele[1] == ([7, 3],)
    assert len(store) == 3


def test_pgvector_l2_scores_are_negated_squared(pg):
    PgvectorStore, log = pg
    store = PgvectorStore(dim=4, metric="l2")
    hits = store.search(np.zeros((1, 4), np.float32), k=2)
    sel = next(e for e in log if e[0].startswith("SELECT id"))
    assert "<->" in sel[0]
    # fetchall gives distances (-0.9, 1.5); score = -(d**2)
    assert hits[0][0].score == pytest.approx(-0.81)
    assert hits[0][1].score == pytest.approx(-2.25)


def test_pgvector_rejects_sql_injection_table(pg):
    PgvectorStore, _ = pg
    with pytest.raises(ConfigError, match="table name"):
        PgvectorStore(dim=4, table="rag; DROP TABLE users")
